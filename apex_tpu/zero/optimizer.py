"""ZeroOptimizer: every ZeRO tier behind one switchboard.

Tier map (Rajbhandari et al. SC'20, apex ``contrib.optimizers``):

===========================  ==========================================
``shard_params=False``       ZeRO-1/2 — optimizer state (master fp32,
(tier 1/2, the                m, v) lives as ONE flat ``[total/world]``
``DistributedFusedAdam`` /    shard per rank; params and grads are
``DistributedFusedLAMB``      full: grads arrive whole and are
configuration)                ``psum_scatter``-ed, fresh params are
                              ``all_gather``-ed back every step
                              (optionally e5m2-quantized on the wire).
``shard_params=True``        ZeRO-3 — parameters are ALSO sharded
(tier 3, FSDP semantics)      (per-leaf, ``apex_tpu.zero.core``); the
                              backward hands this optimizer its summed
                              gradient SHARDS (the ``zero_gather``
                              conjugate), the update runs on the local
                              partition only, and no gather happens
                              here at all — the next forward's
                              transient materialization is the only
                              full-param traffic.
===========================  ==========================================

Both tiers run the SAME element math (``zero/update.py``) and the same
accounted collectives (``zero/comm.py``); ``contrib.optimizers``'
``DistributedFusedAdam``/``DistributedFusedLAMB`` are subclasses
pinning ``shard_params=False`` — one implementation, no drift.

Memory per chip (P params, world N, fp32 master+m+v, bf16/fp32 model
dtype d): dense DDP ``(d+12)P``; tier 2 ``dP + 12P/N``; tier 3
``(d+12)P/N`` (+ the transient gathered tree during a step). The
``zero_sharded_step`` bench records the measured version of this table.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu.zero import comm as _comm
from apex_tpu.zero.core import ZeroSpec, pad_to_multiple
from apex_tpu.zero.update import (ShardedAdamState, ShardedLambState,
                                  Zero3State, adam_shard_step,
                                  lamb_shard_term, lamb_trust_ratio)
from apex_tpu.utils.flat import FlatBuffer

__all__ = ["ZeroOptimizer", "ShardedAdamState", "ShardedLambState",
           "Zero3State"]


def _cast_fresh(x, dtype):
    """astype that never aliases (master and model params must stay
    distinct buffers — see ``optimizers/base.py``)."""
    if x.dtype == dtype:
        return jnp.array(x, copy=True)
    return x.astype(dtype)


class ZeroOptimizer:
    """Sharded fused Adam(W)/LAMB over the ``axis_name`` mesh axis.

    Run ``init``/``apply`` inside ``shard_map`` with the axis bound
    (world=1 degrades to a plain fused update). ``kind`` selects the
    update ("adam" or "lamb"); ``shard_params`` selects the tier (see
    the module table). Tier 3 additionally needs the
    :class:`~apex_tpu.zero.core.ZeroSpec` of the resident tree —
    pass it to ``init``/``apply`` or construct with ``spec=``.
    """

    def __init__(self, lr=1e-3, *, kind: str = "adam",
                 shard_params: bool = True,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adam_w_mode: bool = True, gradient_average: bool = True,
                 max_grad_norm: float | None = None,
                 use_nvlamb: bool = False,
                 axis_name: str = "data", overlap_comm: bool = False,
                 compress_allgather: bool | str = False,
                 spec: ZeroSpec | None = None,
                 autotune: str | None = None):
        if kind not in ("adam", "lamb"):
            raise ValueError(f"kind must be 'adam' or 'lamb', got {kind!r}")
        self.kind = kind
        self.shard_params = shard_params
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.gradient_average = gradient_average
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.axis_name = axis_name
        self.overlap_comm = overlap_comm
        # True = the reference's raw e5m2 cast (bitwise-documented);
        # "scaled" = the amp O4 codec (amax-scaled before the cast —
        # survives values outside e5m2's range; zero/comm.py)
        if compress_allgather not in (False, True, "scaled"):
            raise ValueError(
                f"compress_allgather must be False, True or 'scaled', "
                f"got {compress_allgather!r}")
        self.compress_allgather = compress_allgather
        # fused multi-tensor update resolution (zero/fused_update.py):
        # explicit policy > $APEX_TPU_AUTOTUNE > "cache"; no tuned entry
        # (or "off") keeps the historical tree-map/flat-jnp update
        # bit-for-bit. Validated eagerly so a typo fails at construction.
        if autotune is not None:
            from apex_tpu.tune import runtime as _tune_rt
            _tune_rt.resolve_policy(autotune)
        self.autotune = autotune
        self._zspec = spec
        self._spec: FlatBuffer | None = None   # tier-1/2 flat layout

    # -- shared plumbing ----------------------------------------------------
    def _world(self):
        return _comm._world_of(self.axis_name)

    def _hyper(self):
        return dict(betas=self.betas, eps=self.eps,
                    weight_decay=self.weight_decay,
                    adam_w_mode=self.adam_w_mode,
                    bias_correction=self.bias_correction)

    def configure_amp(self, properties, scaler):
        """amp.initialize hook: the fp32 master shard IS the O2 master-
        weight store, so there is nothing to switch on — just keep the
        scaler for the stateful conveniences."""
        self._scaler = scaler

    def _fused_cfg(self, n: int):
        """Tuned ``multi_tensor_update`` chunk config for an ``n``-element
        fp32 sweep, or ``None`` (use the tree-map/flat-jnp path). Runs at
        trace time; resolution order and telemetry are the shared
        ``tune.runtime`` contract the flash/LN/CE kernels use."""
        from apex_tpu.tune import runtime as _tune_rt
        from apex_tpu.zero.fused_update import _resolve_interpret
        policy = _tune_rt.resolve_policy(self.autotune)
        if policy == "off" or n <= 0:
            return None
        return _tune_rt.resolve(
            "multi_tensor_update", {"n": int(n), "itemsize": 4},
            "float32", {"lamb": self.kind == "lamb"}, policy=policy,
            interpret=_resolve_interpret(None))

    # -- dispatch -----------------------------------------------------------
    def init(self, params, spec: ZeroSpec | None = None):
        """Tier 1/2: ``params`` is the full tree. Tier 3: ``params`` is
        the RESIDENT tree from ``zero_shard`` (fp32 — master precision
        is set here) and ``spec`` its ZeroSpec."""
        if self.shard_params:
            return self._init3(params, spec)
        return self._init_flat(params)

    def apply(self, state, params, grads, skip=None, lr=None,
              spec: ZeroSpec | None = None):
        """One sharded step; returns ``(new_params, new_state)``.

        Tier 1/2: full ``params``/``grads`` in, full params out (the
        gather lives here). Tier 3: resident shards and gradient shards
        in, fresh resident shards out (no gather — the update never
        leaves the partition)."""
        if self.shard_params:
            return self._apply3(state, params, grads, skip=skip, lr=lr,
                                spec=spec)
        return self._apply_flat(state, params, grads, skip=skip, lr=lr)

    # ======================================================================
    # tier 1/2: flat [total/world] shard, full params at the boundary
    # ======================================================================
    def _init_flat(self, params):
        self._spec = FlatBuffer.from_tree(params)
        world = self._world()
        flat = pad_to_multiple(
            self._spec.pack(params, dtype=jnp.float32), world)
        per = flat.shape[0] // world
        if world > 1:
            rank = jax.lax.axis_index(self.axis_name)
            shard = jax.lax.dynamic_slice_in_dim(flat, rank * per, per)
        else:
            shard = flat
        cls = ShardedAdamState if self.kind == "adam" else ShardedLambState
        return cls(step=jnp.asarray(0, jnp.int32), master_shard=shard,
                   m_shard=jnp.zeros_like(shard),
                   v_shard=jnp.zeros_like(shard))

    # per-leaf ranges of the flat buffer intersected with the dynamic
    # per-rank shard window — the LAMB trust-ratio machinery
    # (see ``DistributedFusedLAMB``'s docstring for the design notes)
    def _leaf_starts_in_shard(self, base, per):
        """Per-leaf clipped start positions in shard coordinates (the
        piecewise trust-ratio ramp's scatter indices)."""
        offs = jnp.asarray(self._spec.offsets, jnp.int32)
        return jnp.clip(offs - base, 0, per)

    def _range_sums(self, x, base, per):
        """Per-leaf sums of the leaf∩shard ranges, computed EXACTLY.

        Each leaf intersects the shard in a contiguous range of length
        ≤ min(leaf_size, per) — a *static* bound, so a dynamic-start
        static-length window plus an in-window mask gives a plain masked
        reduction per leaf. (A cumsum-difference formulation cancels
        catastrophically in f32: a 256-element leaf after a 2M-element
        prefix summed to exactly 0.)
        """
        sums = []
        for off, size in zip(self._spec.offsets, self._spec.sizes):
            L = min(size, per)
            s = jnp.clip(off - base, 0, per)          # dynamic, in-shard
            e = jnp.clip(off + size - base, 0, per)
            w = jnp.clip(s, 0, per - L)               # window fits: static L
            win = jax.lax.dynamic_slice_in_dim(x, w, L)
            q = w + jnp.arange(L, dtype=jnp.int32)
            mask = (q >= s) & (q < e)
            sums.append(jnp.sum(jnp.where(mask, win, 0.0)))
        return jnp.stack(sums)

    @staticmethod
    def _piecewise(values, starts, per):
        """[per] vector equal to values[i] on leaf i's shard range —
        a delta scatter (n tiny adds) + cumsum; positions past the last
        leaf (alignment padding) carry the last value, harmless because
        pad slots of p/update are zero."""
        deltas = jnp.diff(values, prepend=jnp.zeros((1,), values.dtype))
        d = jnp.zeros((per + 1,), values.dtype).at[starts].add(deltas)
        return jnp.cumsum(d[:per])

    def _apply_flat(self, state, params, grads, skip=None, lr=None):
        if self._spec is None:
            self._spec = FlatBuffer.from_tree(params)
        spec = self._spec
        world = self._world()
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        if skip is None:
            skip = jnp.asarray(False)

        flat_g = pad_to_multiple(spec.pack(grads, dtype=jnp.float32), world)
        per = flat_g.shape[0] // world
        # reduce_scatter: each rank receives the summed shard it owns
        # (distributed_fused_adam.py:409 _pipeline_block_reductions)
        g_shard = _comm.reduce_scatter_flat(flat_g, self.axis_name,
                                            overlap_comm=self.overlap_comm)
        if self.gradient_average and world > 1:
            g_shard = g_shard / world
        if world > 1:
            rank = jax.lax.axis_index(self.axis_name)
        else:
            rank = 0
        base = rank * per if world > 1 else 0

        if self.kind == "lamb":
            starts = self._leaf_starts_in_shard(base, per)
            # global grad norm + clip (distributed_fused_lamb.py:665-699)
            gsq = _comm.psum_flat(jnp.sum(g_shard * g_shard), self.axis_name)
            gnorm = jnp.sqrt(gsq)
            if self.max_grad_norm and self.max_grad_norm > 0:
                g_shard = g_shard / jnp.maximum(
                    1.0, gnorm / self.max_grad_norm)

        fused = self._fused_cfg(per)

        def _do(state=state, g=g_shard, lr=lr):
            step = state.step + 1
            p = state.master_shard
            if self.kind == "adam":
                if fused is not None:
                    from apex_tpu.zero.fused_update import fused_shard_update
                    new_p, m, v = fused_shard_update(
                        p, g, state.m_shard, state.v_shard, step,
                        kind="adam", lr=lr, block_n=fused["block_n"],
                        **self._hyper())
                else:
                    new_p, m, v = adam_shard_step(
                        p, g, state.m_shard, state.v_shard, step, lr=lr,
                        **self._hyper())
                return type(state)(step, new_p, m, v)
            if fused is not None:
                from apex_tpu.zero.fused_update import fused_shard_update
                upd, m, v = fused_shard_update(
                    p, g, state.m_shard, state.v_shard, step,
                    kind="lamb", lr=lr,
                    grad_averaging=self.gradient_average,
                    block_n=fused["block_n"], **self._hyper())
            else:
                upd, m, v = lamb_shard_term(
                    p, g, state.m_shard, state.v_shard, step,
                    grad_averaging=self.gradient_average, **self._hyper())
            # per-tensor norms: shard-local contiguous-range sums +
            # cross-shard psum (the allgather of update norms, :722-778)
            w_sq = _comm.psum_flat(self._range_sums(p * p, base, per),
                                   self.axis_name)
            u_sq = _comm.psum_flat(self._range_sums(upd * upd, base, per),
                                   self.axis_name)
            ratio = lamb_trust_ratio(jnp.sqrt(w_sq), jnp.sqrt(u_sq),
                                     use_nvlamb=self.use_nvlamb,
                                     weight_decay=self.weight_decay)
            new_p = p - lr * self._piecewise(ratio, starts, per) * upd
            return type(state)(step, new_p, m, v)

        new_state = jax.lax.cond(skip, lambda: state, _do)

        # all_gather the fresh params (distributed_fused_adam.py:477),
        # optionally through the e5m2 quantized-broadcast helper
        if self.compress_allgather:
            flat_new = _comm.quantized_all_gather(
                new_state.master_shard, self.axis_name,
                out_dtype=jnp.float32, overlap_comm=self.overlap_comm,
                scaled=(self.compress_allgather == "scaled"))
        else:
            flat_new = _comm.all_gather_flat(
                new_state.master_shard, self.axis_name,
                overlap_comm=self.overlap_comm).astype(jnp.float32)
        return spec.unpack(flat_new[:spec.total]), new_state

    # tier-1/2 elastic checkpointing (contrib.optimizers.zero_state)
    def gather_state(self, state):
        """Topology-independent full state for checkpointing (inside
        ``shard_map``); see ``apex_tpu.contrib.optimizers.zero_state``."""
        from apex_tpu.contrib.optimizers.zero_state import gather_zero_state
        return gather_zero_state(self, state)

    def shard_state(self, full_state, params=None):
        """Local shard of a gathered state under the CURRENT mesh — the
        dp=8 -> dp=4 resume path (``distributed_fused_lamb.py:139``)."""
        from apex_tpu.contrib.optimizers.zero_state import shard_zero_state
        return shard_zero_state(self, full_state, params)

    # ======================================================================
    # tier 3: per-leaf resident shards, no gather anywhere in the step
    # ======================================================================
    def _spec3(self, spec: ZeroSpec | None) -> ZeroSpec:
        if spec is not None:
            self._zspec = spec
        if self._zspec is None:
            raise ValueError(
                "ZeroOptimizer(shard_params=True) needs the ZeroSpec of "
                "the resident tree — pass spec= here or at construction "
                "(ZeroShardedModel.shard builds it)")
        return self._zspec

    @staticmethod
    def _is_float(x) -> bool:
        return jnp.issubdtype(x.dtype, jnp.floating)

    def _init3(self, shards, spec: ZeroSpec | None = None):
        spec = self._spec3(spec)

        def master(x):
            return _cast_fresh(x, jnp.float32) if self._is_float(x) else x

        def slot(x):
            return jnp.zeros(x.shape, jnp.float32) if self._is_float(x) \
                else jnp.zeros((0,), jnp.float32)

        return Zero3State(
            step=jnp.asarray(0, jnp.int32),
            master=jax.tree.map(master, shards),
            m=jax.tree.map(slot, shards),
            v=jax.tree.map(slot, shards),
        )

    def _masked_psum_merge(self, partials: list, spec: ZeroSpec):
        """Exact cross-rank per-leaf reductions in ONE psum: sharded
        leaves' partial sums need the cross-shard psum, replicated
        leaves' are already whole (every rank computed the identical
        value) and must be counted ONCE — merge by the static mask."""
        stacked = jnp.stack(partials)
        summed = _comm.psum_flat(stacked, self.axis_name)
        mask = jnp.asarray(np.asarray(spec.sharded, bool))
        return jnp.where(mask, summed, stacked)

    def _apply3(self, state: Zero3State, shards, grads, skip=None, lr=None,
                spec: ZeroSpec | None = None):
        spec = self._spec3(spec)
        world = self._world()
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        if skip is None:
            skip = jnp.asarray(False)

        p_leaves = jax.tree.leaves(shards)
        g_leaves = [g.astype(jnp.float32) if self._is_float(g) else g
                    for g in jax.tree.leaves(grads)]
        if self.gradient_average and world > 1:
            g_leaves = [g / world if self._is_float(g) else g
                        for g in g_leaves]
        m_leaves = jax.tree.leaves(state.m)
        v_leaves = jax.tree.leaves(state.v)
        mast_leaves = jax.tree.leaves(state.master)
        is_float = [self._is_float(g) for g in g_leaves]
        floats = [i for i, f in enumerate(is_float) if f]

        if self.kind == "lamb":
            gsq = self._masked_psum_merge(
                [jnp.sum(g_leaves[i] * g_leaves[i]) if is_float[i]
                 else jnp.zeros((), jnp.float32)
                 for i in range(len(g_leaves))], spec)
            gnorm = jnp.sqrt(jnp.sum(gsq))
            if self.max_grad_norm and self.max_grad_norm > 0:
                clip = jnp.maximum(1.0, gnorm / self.max_grad_norm)
                g_leaves = [g_leaves[i] / clip if is_float[i]
                            else g_leaves[i] for i in range(len(g_leaves))]

        # the fused multi-tensor path sweeps ALL float leaves as one
        # concatenated flat buffer — one kernel instead of a tree-map of
        # per-leaf op chains (elementwise, so concatenation preserves
        # bit-parity with the per-leaf form under compilation)
        fused = self._fused_cfg(sum(mast_leaves[i].size for i in floats)) \
            if floats else None

        def _fused_leaves(kind, step, lr):
            from apex_tpu.zero.fused_update import fused_shard_update
            def cat(ls):
                return jnp.concatenate([ls[i].reshape(-1) for i in floats])
            fo, fm, fv = fused_shard_update(
                cat(mast_leaves), cat(g_leaves), cat(m_leaves),
                cat(v_leaves), step, kind=kind, lr=lr,
                grad_averaging=self.gradient_average,
                block_n=fused["block_n"], **self._hyper())
            out, off = {}, 0
            for i in floats:
                sz = mast_leaves[i].size
                shp = mast_leaves[i].shape
                out[i] = (fo[off:off + sz].reshape(shp),
                          fm[off:off + sz].reshape(shp),
                          fv[off:off + sz].reshape(shp))
                off += sz
            return out

        def _do():
            step = state.step + 1
            new_master = list(mast_leaves)
            new_m, new_v = list(m_leaves), list(v_leaves)
            if self.kind == "adam":
                if fused is not None:
                    for i, (o, nm, nv) in _fused_leaves("adam", step,
                                                        lr).items():
                        new_master[i], new_m[i], new_v[i] = o, nm, nv
                else:
                    for i in floats:
                        new_master[i], new_m[i], new_v[i] = adam_shard_step(
                            mast_leaves[i], g_leaves[i], m_leaves[i],
                            v_leaves[i], step, lr=lr, **self._hyper())
            else:
                upds = {}
                if fused is not None:
                    for i, (o, nm, nv) in _fused_leaves("lamb", step,
                                                        lr).items():
                        upds[i], new_m[i], new_v[i] = o, nm, nv
                else:
                    for i in floats:
                        upds[i], new_m[i], new_v[i] = lamb_shard_term(
                            mast_leaves[i], g_leaves[i], m_leaves[i],
                            v_leaves[i], step,
                            grad_averaging=self.gradient_average,
                            **self._hyper())
                # whole-logical-tensor norms from shard partials
                zero = jnp.zeros((), jnp.float32)
                w_sq = self._masked_psum_merge(
                    [jnp.sum(mast_leaves[i] ** 2) if is_float[i] else zero
                     for i in range(len(g_leaves))], spec)
                u_sq = self._masked_psum_merge(
                    [jnp.sum(upds[i] ** 2) if is_float[i] else zero
                     for i in range(len(g_leaves))], spec)
                ratio = lamb_trust_ratio(jnp.sqrt(w_sq), jnp.sqrt(u_sq),
                                         use_nvlamb=self.use_nvlamb,
                                         weight_decay=self.weight_decay)
                for i in floats:
                    new_master[i] = mast_leaves[i] - lr * ratio[i] * upds[i]
            t = spec.treedef
            return Zero3State(step,
                              jax.tree.unflatten(t, new_master),
                              jax.tree.unflatten(t, new_m),
                              jax.tree.unflatten(t, new_v))

        new_state = jax.lax.cond(skip, lambda: state, _do)

        # fresh resident shards in the MODEL dtypes (fp32 master ->
        # bf16/fp16 under amp O2) — the tier-3 analog of the param
        # all_gather is: nothing. The next forward's transient
        # zero_gather is the only full-param traffic.
        new_shards = jax.tree.unflatten(spec.treedef, [
            _cast_fresh(nm, p.dtype) if self._is_float(p) else p
            for nm, p in zip(jax.tree.leaves(new_state.master), p_leaves)])
        return new_shards, new_state
