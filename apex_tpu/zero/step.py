"""The ZeRO-3 train step: amp loss scaling + overflow skip over sharded
parameters, in one traced program.

The amp hot loop (``amp.make_train_step``) with two ZeRO twists:

- gradients arrive as SHARDS (``zero_gather``'s conjugate backward), so
  the unscale/overflow detection runs on 1/world of the gradient bytes
  per rank — but each rank then only sees its own partition's infs, so
  the ``found_inf`` flag is OR-reduced over the zero axis before the
  skip decision (the exact ``sync_found_inf`` argument from
  ``amp/scaler.py``: a rank-divergent skip would desynchronize step
  counters and scaler state forever);
- the optimizer update is the tier-3 shard update — no parameter
  all-gather anywhere in the step; the next forward's transient
  materialization is the only full-param traffic.

Composes with ``amp.initialize(..., opt_level="O2", zero=...)``: the
returned :class:`~apex_tpu.zero.core.ZeroShardedModel` wraps the
``AmpModel`` (inputs cast, O2 output recast) and the armed
``LossScaler`` is picked up from the optimizer's amp stash, so the
overflow/skip/regrowth machinery is byte-for-byte the dense one.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.amp import scaler as _scaler_mod
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.monitor import hooks as _mon
from apex_tpu.monitor import profile as _prof
from apex_tpu.zero import comm as _comm
from apex_tpu.zero.core import ZeroShardedModel

__all__ = ["make_train_step"]


def make_train_step(
    loss_fn: Callable,
    zero_model: ZeroShardedModel | None = None,
    optimizer=None,
    *,
    scaler: LossScaler | None = None,
    has_aux: bool = False,
    grad_dtype=jnp.float32,
    donate: bool = True,
    sync_axes: tuple = (),
):
    """Build the jitted ZeRO-3 step (call it inside ``shard_map`` over
    the zero axis).

    ``loss_fn(full_params, *batch) -> loss`` — written against ORDINARY
    parameters; the materialization is inserted here, so the same loss
    function drives the dense and the sharded path (the parity tests
    literally share it). ``optimizer`` is a
    :class:`~apex_tpu.zero.optimizer.ZeroOptimizer` with
    ``shard_params=True``. ``zero_model`` may be omitted when
    ``amp.initialize(..., zero=...)`` built the wrapper — it is picked
    up from ``optimizer._zero_model``. ``sync_axes``: extra mesh axes
    (tensor, pipeline) whose ranks shard gradients and must agree on
    the skip.

    The returned ``step(shards, opt_state, scaler_state, *batch)``
    performs: scaled-loss grad (gather behind forward, reduce-scatter
    behind backward), per-shard unscale + overflow detect, cross-rank
    OR of ``found_inf``, conditional shard update, dynamic scale update
    — zero host syncs, zero full-gradient materializations.
    """
    if optimizer is None:
        raise TypeError("make_train_step: optimizer is required")
    if zero_model is None:
        zero_model = getattr(optimizer, "_zero_model", None)
        if zero_model is None:
            raise ValueError(
                "make_train_step: pass zero_model, or build it through "
                "amp.initialize(..., zero=...) so the optimizer carries "
                "it (optimizer._zero_model)")
    opt_axis = getattr(optimizer, "axis_name", None)
    if opt_axis is not None and opt_axis != zero_model.axis_name:
        raise ValueError(
            f"make_train_step: optimizer.axis_name={opt_axis!r} does not "
            f"match zero_model.axis_name={zero_model.axis_name!r}. The "
            "shard update's collectives would see an unbound axis and "
            "silently degrade to world=1 (no gradient averaging, identity "
            "norm psums) while gradients reduce over "
            f"{zero_model.axis_name!r} — construct the optimizer with "
            f"axis_name={zero_model.axis_name!r}.")
    scaler = scaler or (optimizer._amp_stash.loss_scalers[0]
                        if hasattr(optimizer, "_amp_stash")
                        else LossScaler(1.0))

    def scaled_loss_fn(shards, scaler_state, *batch):
        out = loss_fn(zero_model.materialize(shards), *batch)
        loss, aux = (out if has_aux else (out, None))
        return _scaler_mod.scale_value(loss, scaler_state), (loss, aux)

    grad_fn = jax.grad(scaled_loss_fn, has_aux=True)

    def step(_mon_on, shards, opt_state, scaler_state: ScalerState, *batch):
        # profile scopes (monitor.profile): metadata-only, jaxpr-pure —
        # per-phase attribution of the sharded hot loop
        with _prof.scope("zero_grad"):
            grads, (loss, aux) = grad_fn(shards, scaler_state, *batch)
        with _prof.scope("zero_unscale"):
            grads, found_inf = _scaler_mod.unscale(grads, scaler_state,
                                                   out_dtype=grad_dtype)
        with _prof.scope("zero_inf_sync"):
            # each rank inspected only its own shards: OR the flag over
            # the zero axis (and any model-parallel axes) before deciding
            axes = (zero_model.axis_name,) + tuple(sync_axes)
            flag = found_inf.astype(jnp.int32)
            for ax in axes:
                flag = _comm.psum_flat(flag, ax)
            found_inf = flag > 0
        # zero_model.spec is read at trace time, inside the call: the
        # usual flow builds it (zm.shard) in the same traced program
        with _prof.scope("zero_update"):
            new_shards, new_opt_state = optimizer.apply(
                opt_state, shards, grads, skip=found_inf,
                spec=zero_model.spec)
        with _prof.scope("zero_scaler"):
            new_scaler_state = scaler.update_state(scaler_state, found_inf)
        outs = (new_shards, new_opt_state, new_scaler_state, loss)
        return outs + ((aux,) if has_aux else ())

    jitted = jax.jit(step, static_argnums=(0,),
                     donate_argnums=(1, 2, 3) if donate else ())

    @functools.wraps(step)
    def run(shards, opt_state, scaler_state: ScalerState, *batch):
        return jitted(_mon.traced_enabled(), shards, opt_state,
                      scaler_state, *batch)

    run._jitted = jitted
    return run
