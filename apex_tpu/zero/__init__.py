"""apex_tpu.zero — parameter-sharded (ZeRO-3/FSDP) training.

The sharded-optimizer family behind one subsystem (ROADMAP item 1):

- :mod:`~apex_tpu.zero.rules`      — regex rule table: param path ->
  shard/replicate, with a small-leaf size threshold.
- :mod:`~apex_tpu.zero.core`       — :class:`ZeroSpec`,
  :func:`zero_shard`, :func:`zero_gather` (all-gather hidden behind the
  forward, conjugate reduce-scatter behind the backward — ``custom_vjp``),
  :class:`ZeroShardedModel`.
- :mod:`~apex_tpu.zero.optimizer`  — :class:`ZeroOptimizer`: ZeRO-1/2
  (``shard_params=False``, the ``contrib.optimizers`` configuration) and
  ZeRO-3 (``shard_params=True``) on shared update math and accounted
  collectives.
- :mod:`~apex_tpu.zero.elastic`    — topology-independent gather /
  reshard of tier-3 params + state (dp=8 saves, dp=4 resumes,
  bit-exactly) for ``apex_tpu.checkpoint``.
- :mod:`~apex_tpu.zero.step`       — :func:`make_train_step`: the amp
  O2 + LossScaler overflow/skip composition over shards.

Imports here do no jax work (APX001 discipline).
"""

from apex_tpu.zero.rules import (  # noqa: F401
    DEFAULT_MIN_SHARD_SIZE,
    DEFAULT_RULES,
    REPLICATE,
    SHARD,
    match_zero_rules,
)
from apex_tpu.zero.core import (  # noqa: F401
    ZeroShardedModel,
    ZeroSpec,
    build_spec,
    params_resident_bytes,
    zero_gather,
    zero_shard,
)
from apex_tpu.zero.optimizer import (  # noqa: F401
    ShardedAdamState,
    ShardedLambState,
    Zero3State,
    ZeroOptimizer,
)
from apex_tpu.zero.elastic import (  # noqa: F401
    gather_zero3_params,
    gather_zero3_state,
    shard_zero3_params,
    shard_zero3_state,
)
from apex_tpu.zero.step import make_train_step  # noqa: F401
from apex_tpu.zero import comm  # noqa: F401
