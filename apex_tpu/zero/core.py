"""ZeRO-3 parameter sharding: spec, shard/materialize, and the
gather-behind-forward / scatter-behind-backward ``custom_vjp``.

Design (Rajbhandari et al. SC'20 §5; PyTorch FSDP, Zhao et al.
VLDB'23): each rank keeps 1/world of every (large, floating) parameter
resident — a 1-D slice of the zero-padded flattened leaf — and the full
parameter exists only transiently, materialized by :func:`zero_gather`
at the top of the forward. The gather carries a ``custom_vjp`` whose
backward is the CONJUGATE collective (the transpose of an all-gather is
a reduce-scatter — the same conjugate-ring property
``parallel/overlap.py`` established for the collective matmuls), so the
cotangent of the full parameter leaves the backward already
reduce-scattered: each rank receives exactly the summed gradient shard
its optimizer partition needs, and the full gradient is never resident.
Replicated (small) leaves take a plain ``psum`` in the backward — the
dense-DDP gradient exchange — so after one backward EVERY leaf's
gradient is cross-rank summed, whatever its placement.

``overlap_comm=False`` (default) uses the blocking
``all_gather``/``psum_scatter`` collectives — the program is
byte-identical to a hand-written gather/scatter (asserted in tests).
``overlap_comm=True`` ring-decomposes both directions into tp-1
ppermutes (``overlap.ring_all_gather`` / ``ring_psum_scatter``) so the
hops of one leaf's gather schedule underneath other leaves' compute —
the ``all_gather_matmul``-style decomposition, with the bare ring as
the fallback for leaves whose consumer needs the whole array (fused
collective-matmul only works when the consumer IS a matmul).

Everything runs inside ``shard_map`` with ``axis_name`` bound (the
``contrib.optimizers`` contract); at world=1 every function degrades to
the identity with zero collectives.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.monitor import hooks as _mon
from apex_tpu.zero import comm as _comm
from apex_tpu.zero.rules import (DEFAULT_MIN_SHARD_SIZE, match_zero_rules)

__all__ = [
    "ZeroSpec", "build_spec", "zero_shard", "zero_gather",
    "params_resident_bytes", "ZeroShardedModel",
]


@dataclass(frozen=True)
class ZeroSpec:
    """Static description of a ZeRO-3 sharding of a parameter pytree.

    Hashable (it rides ``custom_vjp`` ``nondiff_argnums``); everything
    here is a trace-time constant — axis sizes are static inside
    ``shard_map``."""

    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]          # np.dtype per leaf (original params)
    sharded: tuple[bool, ...]
    world: int
    axis_name: str

    @property
    def sizes(self) -> tuple[int, ...]:
        return tuple(int(np.prod(s)) if s else 1 for s in self.shapes)

    @property
    def padded(self) -> tuple[int, ...]:
        """Flattened leaf length rounded up to a multiple of world
        (sharded leaves; the zero tail is the ``total % world != 0``
        slack)."""
        return tuple(n + (-n) % self.world for n in self.sizes)

    def shard_len(self, i: int) -> int:
        return self.padded[i] // self.world

    @property
    def n_leaves(self) -> int:
        return len(self.shapes)

    def local_offsets(self) -> tuple[int, ...]:
        """Start offset of each SHARDED leaf's shard in the per-rank
        flat optimizer buffer (tree order, replicated leaves skipped);
        identical on every rank — per-leaf ranges of the local shard
        are static slices."""
        offs, acc = [], 0
        for i, sh in enumerate(self.sharded):
            offs.append(acc)
            if sh:
                acc += self.shard_len(i)
        return tuple(offs)


def build_spec(
    params: Any,
    rules: Sequence[tuple[str, str]] | None = None,
    *,
    axis_name: str = "data",
    min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
) -> ZeroSpec:
    """Derive the static sharding spec for ``params`` under the rule
    table (see :mod:`apex_tpu.zero.rules`). Call inside ``shard_map``
    (the world size is read from the bound axis; unbound -> world=1,
    where nothing shards)."""
    world = _comm._world_of(axis_name)
    decisions = jax.tree.leaves(
        match_zero_rules(rules, params, min_shard_size=min_shard_size))
    leaves, treedef = jax.tree.flatten(params)
    sharded = tuple(bool(d) and world > 1 for d in decisions)
    return ZeroSpec(
        treedef=treedef,
        shapes=tuple(tuple(x.shape) for x in leaves),
        dtypes=tuple(np.dtype(x.dtype) for x in leaves),
        sharded=sharded,
        world=world,
        axis_name=axis_name,
    )


def _pad_flat(flat, padded: int):
    if flat.shape[0] != padded:
        flat = jnp.concatenate(
            [flat, jnp.zeros((padded - flat.shape[0],), flat.dtype)])
    return flat


def pad_to_multiple(flat, mult: int):
    """Zero-pad a 1-D buffer to a multiple of ``mult`` — the flat-shard
    layout invariant (every rank's slice is equal length). The single
    pad helper for the tier-1/2 flat buffers AND the tier-3 per-leaf
    shards (via :attr:`ZeroSpec.padded`)."""
    return _pad_flat(flat, flat.shape[0] + (-flat.shape[0]) % mult)


def shard_tree(tree: Any, spec: ZeroSpec) -> Any:
    """Per-leaf local shards of a full tree under ``spec`` —
    dtype-preserving (works on params and fp32 optimizer slots alike),
    no gauge. Inside ``shard_map``; world=1 is the identity. This is
    the one slicing loop: :func:`zero_shard` (residency) and
    ``elastic.shard_zero3_*`` (resume) both run it, so shard-time
    layout and elastic re-slicing can never drift apart."""
    if spec.world == 1:
        return tree
    rank = jax.lax.axis_index(spec.axis_name)
    out = []
    for i, x in enumerate(jax.tree.leaves(tree)):
        if not spec.sharded[i]:
            out.append(x)
            continue
        flat = _pad_flat(x.reshape(-1), spec.padded[i])
        per = spec.shard_len(i)
        out.append(jax.lax.dynamic_slice_in_dim(flat, rank * per, per))
    return jax.tree.unflatten(spec.treedef, out)


def params_resident_bytes(spec: ZeroSpec, dtypes=None) -> int:
    """Per-rank resident parameter bytes under ``spec`` — the quantity
    ZeRO-3 divides by world. ``dtypes`` overrides the spec's (the amp
    O2 case: bf16 resident shards, fp32 in the spec)."""
    dts = spec.dtypes if dtypes is None else tuple(np.dtype(d) for d in dtypes)
    total = 0
    for i, sh in enumerate(spec.sharded):
        n = spec.shard_len(i) if sh else spec.sizes[i]
        total += n * dts[i].itemsize
    return total


def zero_shard(params: Any, spec: ZeroSpec) -> Any:
    """This rank's resident tree: sharded leaves become their 1-D local
    slice ``[padded/world]``, replicated leaves pass through. Inside
    ``shard_map``. Emits the ``zero/params_resident_bytes`` gauge when
    a monitor recorder is attached (a trace-time static, like the
    collective table)."""
    leaves = jax.tree.leaves(params)
    if len(leaves) != spec.n_leaves:
        raise ValueError(
            f"zero_shard: tree has {len(leaves)} leaves, spec describes "
            f"{spec.n_leaves}")
    if _mon.enabled():
        _mon.gauge("zero/params_resident_bytes", params_resident_bytes(
            spec, dtypes=tuple(x.dtype for x in leaves)))
    return shard_tree(params, spec)


def gather_tree(shards: Any, spec: ZeroSpec,
                overlap_comm: bool = False) -> Any:
    """The primal gather: full tree from per-leaf shards (all_gather,
    unpad, reshape; replicated leaves pass through). Dtype-preserving —
    the conjugate of :func:`shard_tree`, and likewise the ONE gather
    loop: :func:`zero_gather`'s forward and ``elastic.gather_zero3_*``
    (the checkpoint form) both run it."""
    out = []
    for i, x in enumerate(jax.tree.leaves(shards)):
        if not spec.sharded[i]:
            out.append(x)
            continue
        full = _comm.all_gather_flat(x, spec.axis_name,
                                     overlap_comm=overlap_comm)
        out.append(full[:spec.sizes[i]].reshape(spec.shapes[i]))
    return jax.tree.unflatten(spec.treedef, out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def zero_gather(shards, spec: ZeroSpec, overlap_comm: bool = False):
    """Materialize the full parameter tree from per-rank shards.

    Forward: per-leaf flat all-gather (bitwise — values are moved, not
    combined), unpad, reshape. Backward: the conjugate — sharded leaves'
    cotangents are zero-padded and reduce-scattered into summed gradient
    SHARDS; replicated leaves' cotangents are psummed whole. The full
    gradient tree therefore never exists: the backward hands the
    optimizer exactly its partition, already reduced (ZeRO-3's "no
    full-gradient materialization").
    """
    return gather_tree(shards, spec, overlap_comm)


def _zero_gather_fwd(shards, spec, overlap_comm):
    return gather_tree(shards, spec, overlap_comm), None


def _zero_gather_bwd(spec, overlap_comm, _res, ct):
    out = []
    for i, g in enumerate(jax.tree.leaves(ct)):
        dtype = getattr(g, "dtype", None)
        if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
            out.append(g)          # float0 / symbolic-zero cotangent
            continue
        if not spec.sharded[i]:
            out.append(_comm.psum_flat(g, spec.axis_name))
            continue
        flat = _pad_flat(g.reshape(-1), spec.padded[i])
        out.append(_comm.reduce_scatter_flat(flat, spec.axis_name,
                                             overlap_comm=overlap_comm))
    return (jax.tree.unflatten(spec.treedef, out),)


zero_gather.defvjp(_zero_gather_fwd, _zero_gather_bwd)


class ZeroShardedModel:
    """Forward wrapper giving a params-first callable FSDP semantics.

    ``zm = ZeroShardedModel(apply_fn, rules=..., axis_name="data")``
    then, inside ``shard_map`` over the axis::

        shards = zm.shard(params)          # fp32; builds zm.spec
        out    = zm(shards, *args)         # gather -> apply_fn(full, ...)

    ``apply_fn`` is anything ``fn(params, *args, **kwargs)`` — a flax
    ``.apply``, an :class:`apex_tpu.amp.AmpModel` (the O2 composition:
    ``amp.initialize(..., zero=...)`` builds exactly this wrapper), or
    a plain function. ``shard``/``materialize`` are the setup and
    checkpoint paths; the hot path is ``__call__``'s transient gather.
    """

    def __init__(self, apply_fn: Callable,
                 rules: Sequence[tuple[str, str]] | None = None,
                 *, axis_name: str = "data",
                 min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
                 overlap_comm: bool = False):
        self.apply_fn = apply_fn.apply if hasattr(apply_fn, "apply") \
            else apply_fn
        self.rules = rules
        self.axis_name = axis_name
        self.min_shard_size = min_shard_size
        self.overlap_comm = overlap_comm
        self.spec: ZeroSpec | None = None

    def shard(self, params):
        """Build (and remember) the spec, return this rank's resident
        tree. Call inside ``shard_map`` on the ORIGINAL (fp32) params —
        the optimizer's master shards must come from full precision;
        cast the returned tree down afterwards for O2/O3 residence
        (``cast_params``)."""
        self.spec = build_spec(params, self.rules, axis_name=self.axis_name,
                               min_shard_size=self.min_shard_size)
        return zero_shard(params, self.spec)

    def materialize(self, shards):
        """The differentiable gather (see :func:`zero_gather`)."""
        if self.spec is None:
            raise ValueError("ZeroShardedModel: call shard(params) first "
                             "(the spec is built there)")
        return zero_gather(shards, self.spec, self.overlap_comm)

    def cast_params(self, shards):
        """Opt-level cast of the RESIDENT tree (delegates to the wrapped
        AmpModel when amp built this wrapper; identity otherwise). Tree
        paths are unchanged by sharding, so amp's name-based
        keep-fp32 predicates apply unmodified."""
        inner = getattr(self, "_amp_model", None)
        if inner is not None:
            return inner.cast_params(shards)
        return shards

    def __call__(self, shards, *args, **kwargs):
        return self.apply_fn(self.materialize(shards), *args, **kwargs)
