"""Regex-driven parameter sharding rules: param path -> shard/replicate.

The rule table follows the ``match_partition_rules`` shape (SNIPPETS.md
[2]): an ordered sequence of ``(regex, decision)`` pairs matched with
``re.search`` against the leaf's slash-joined tree path; the FIRST match
wins, and a leaf no rule matches is an error (a silent default would
hide typos in the table). Decisions here are ZeRO decisions, not
PartitionSpecs: ``"shard"`` (1/world of the flattened leaf resident per
rank) or ``"replicate"`` (full copy per rank).

Two structural overrides run before the table, mirroring what every
FSDP implementation hard-codes:

- non-floating leaves (step counters, integer tables) replicate — a
  sharded int has no gradient to reduce-scatter and saves nothing worth
  the gather;
- floating leaves smaller than ``min_shard_size`` elements replicate —
  below that, the per-leaf all-gather latency costs more than world-1
  copies of a bias vector (the ``np.prod(shape) == 1`` scalar exemption
  of ``match_partition_rules``, widened to a tunable threshold).
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

SHARD = "shard"
REPLICATE = "replicate"

#: Shard every (large, floating) leaf — the ZeRO-3 default, matching
#: ``DistributedFusedAdam``'s everything-in-the-flat-buffer policy.
DEFAULT_RULES: tuple = ((".*", SHARD),)

#: Leaves under this many ELEMENTS replicate regardless of the table
#: (biases, norm scales). 2**11 * 4 B = 8 KiB of fp32 — comfortably
#: below the point where a gather is worth scheduling.
DEFAULT_MIN_SHARD_SIZE = 2 ** 11


def leaf_path_names(path) -> tuple[str, ...]:
    """Tree-path entries as plain strings (dict keys, attr names,
    sequence indices)."""
    return tuple(str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
                 for p in path)


def first_match(rules: Sequence[tuple[str, str]], name: str):
    """Index of the first rule whose regex matches ``name`` — THE
    first-match-wins resolution every rules table in the package uses
    (zero, serve, and the ``lint.rules_tables`` validator that audits
    them for dead/shadowed entries). Returns None when nothing matches.
    """
    for i, (rx, _) in enumerate(rules):
        if re.search(rx, name) is not None:
            return i
    return None


def match_zero_rules(
    rules: Sequence[tuple[str, str]] | None,
    params: Any,
    *,
    min_shard_size: int = DEFAULT_MIN_SHARD_SIZE,
    validate: bool | str = True,
) -> Any:
    """Pytree of python bools (shard this leaf?) matching ``params``.

    ``rules``: ordered ``(regex, "shard"|"replicate")`` pairs;
    ``None`` means :data:`DEFAULT_RULES`. Paths are joined with ``/``
    (``{"block_0": {"kernel": ...}}`` -> ``"block_0/kernel"``).

    ``validate``: run the apexlint APXR table checks
    (:mod:`apex_tpu.lint.rules_tables`) against THIS tree at
    config-build time, raising with the finding text on shadowed rules
    (APXR202) or bad decisions (APXR203). ``"strict"`` additionally
    rejects dead rules and uncovered leaves (APXR201); ``False`` opts
    out for exploratory tables.
    """
    rules = DEFAULT_RULES if rules is None else tuple(rules)
    for rx, decision in rules:
        if decision not in (SHARD, REPLICATE):
            raise ValueError(
                f"zero rule ({rx!r}, {decision!r}): decision must be "
                f"{SHARD!r} or {REPLICATE!r}")
    if validate:
        from apex_tpu.lint.rules_tables import constructor_validate
        constructor_validate(rules, [params],
                             table_name="match_zero_rules", kind="zero",
                             strict=validate == "strict")

    def decide(path, leaf) -> bool:
        name = "/".join(leaf_path_names(path))
        dtype = getattr(leaf, "dtype", None)
        # jnp.issubdtype, not np: bfloat16/float8 are ml_dtypes
        # extension types that numpy does not class as floating
        if dtype is None or not jnp.issubdtype(np.dtype(dtype),
                                               jnp.floating):
            return False
        if int(np.prod(np.shape(leaf) or (1,))) < min_shard_size:
            return False
        idx = first_match(rules, name)
        if idx is None:
            raise ValueError(
                f"no zero sharding rule matched param {name!r} — add a "
                f"rule (a catch-all ('.*', 'shard') is the ZeRO-3 default)")
        return rules[idx][1] == SHARD

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [decide(p, x) for p, x in flat])
