"""True multi-tensor optimizer update: ONE Pallas kernel sweeping the
flat ZeRO shard in blocked chunks (ISSUE 13 tentpole c).

``zero/update.py`` is the element MATH every tier runs; this module is
its kernel twin. The tree-map/flat-jnp form lowers to a chain of
elementwise HLO ops that XLA fuses per leaf — each tier-3 leaf still
pays its own kernel launch and the fp32 state (p, g, m, v -> p, m, v)
makes seven HBM round trips per fusion boundary. The fused form views
the whole shard as ``[rows, 128]`` fp32 and walks it in ``block_n``-
element chunks: each program reads its p/g/m/v blocks once, runs the
complete Adam(W) (or pre-trust-ratio LAMB term) update in registers,
and writes the three outputs once — the TPU analog of apex's
``multi_tensor_apply`` chunking (``csrc/multi_tensor_apply.cuh``: many
tensors, one kernel launch, one sweep).

Numerics contract: the kernel body is the SAME sequence of elementwise
fp32 ops as :func:`apex_tpu.zero.update.adam_shard_step` /
:func:`lamb_shard_term` (the scalar bias-correction denominators are
computed outside with the identical expression and passed in through
SMEM), so in the compiled step the fused update is BIT-identical to the
tree-map on every tier — asserted across tiers 1/2/3 and the elastic
dp=8→4→8 round trip in ``tests/test_fused_kernels.py``. (Compared OUT
of the step context, the final ``p - lr*upd`` axpy can differ by one
fp32 ULP: XLA's mul+add contraction choice is per-fusion-cluster, and a
bare elementwise chain and a pallas loop body are different clusters.)

Resolution: :class:`~apex_tpu.zero.optimizer.ZeroOptimizer` (and the
``DistributedFusedAdam``/``DistributedFusedLAMB`` subclasses) consult
the tuned cache for a ``multi_tensor_update`` entry at the shard's
bucket; no entry (or ``autotune="off"``) keeps the historical tree-map
path bit-for-bit. ``python -m apex_tpu.ops tune --kernel
multi_tensor_update`` sweeps the chunk size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _resolve_interpret(interpret):
    # ONE interpret-resolution policy for every kernel (lazy: this
    # module must stay importable before ops finishes initializing)
    from apex_tpu.ops.flash_attention import _resolve_interpret as _ri
    return _ri(interpret)


def _mtu_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, o_ref, mo_ref,
                vo_ref, *, kind: str, betas, eps: float,
                weight_decay: float, adam_w_mode: bool,
                bias_correction: bool, grad_averaging: bool):
    """One ``[block_n/128, 128]`` chunk of the flat shard: the complete
    update term in one read of (p, g, m, v), one write of (out, m, v).
    The op sequence mirrors ``zero/update.py`` exactly (bit-parity
    contract, module docstring); ``scal_ref`` holds the traced scalars
    ``[lr, 1-b1^t, 1-b2^t]`` in SMEM."""
    b1, b2 = betas
    lr = scal_ref[0]
    p = p_ref[...]
    g = g_ref[...]
    m = m_ref[...]
    v = v_ref[...]
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p
    if kind == "adam":
        m = b1 * m + (1 - b1) * g
    else:
        beta3 = (1 - b1) if grad_averaging else 1.0
        m = b1 * m + beta3 * g
    v = b2 * v + (1 - b2) * g * g
    if bias_correction:
        mhat = m / scal_ref[1]
        vhat = v / scal_ref[2]
    else:
        mhat, vhat = m, v
    upd = mhat / (jnp.sqrt(vhat) + eps)
    if adam_w_mode and weight_decay:
        upd = upd + weight_decay * p
    o_ref[...] = (p - lr * upd) if kind == "adam" else upd
    mo_ref[...] = m
    vo_ref[...] = v


def fused_shard_update(p, g, m, v, step, *, kind: str, lr, betas, eps,
                       weight_decay, adam_w_mode, bias_correction,
                       grad_averaging: bool = True, block_n: int,
                       interpret=None):
    """Fused twin of ``adam_shard_step`` (``kind="adam"``: returns
    ``(new_p, new_m, new_v)``) / ``lamb_shard_term`` (``kind="lamb"``:
    returns ``(upd, new_m, new_v)`` — trust-ratio norms stay with the
    caller, whose layout knows the leaf ranges). ``p/g/m/v`` are fp32
    arrays of any shape; the sweep runs over the raveled buffer."""
    if kind not in ("adam", "lamb"):
        raise ValueError(f"kind must be 'adam' or 'lamb', got {kind!r}")
    if block_n % (8 * _LANES) != 0:
        raise ValueError(
            f"block_n must cover whole fp32 (8, {_LANES}) tiles "
            f"(a multiple of {8 * _LANES}), got {block_n}")
    shape = p.shape
    n = p.size
    lr = jnp.asarray(lr, jnp.float32)
    b1, b2 = betas
    if bias_correction:
        # the identical expressions zero/update.py evaluates inline —
        # computed ONCE per step here instead of per leaf
        sf = step.astype(jnp.float32)
        c1 = 1 - jnp.power(b1, sf)
        c2 = 1 - jnp.power(b2, sf)
    else:
        c1 = c2 = jnp.asarray(1.0, jnp.float32)
    scal = jnp.stack([lr, c1, c2]).astype(jnp.float32)

    from apex_tpu.tune.vmem import ceil_to
    n_pad = ceil_to(n, block_n)
    rows = n_pad // _LANES
    block_rows = block_n // _LANES

    def _blocked(x):
        x = x.reshape(-1)
        if n_pad != n:
            # padded slots run the update on zeros (rsqrt-free math:
            # sqrt(0)+eps is finite) and are sliced off below
            x = jnp.pad(x, (0, n_pad - n))
        return x.reshape(rows, _LANES)

    kern = functools.partial(
        _mtu_kernel, kind=kind, betas=betas, eps=eps,
        weight_decay=weight_decay, adam_w_mode=adam_w_mode,
        bias_correction=bias_correction, grad_averaging=grad_averaging)
    blk = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    # profile scope (monitor.profile): the fused sweep attributed as one
    # module beside the zero step's update phase; metadata-only
    from apex_tpu.monitor import profile as _prof
    with _prof.scope("multi_tensor_update"):
        out, mo, vo = pl.pallas_call(
            kern,
            grid=(rows // block_rows,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [blk] * 4,
            out_specs=[blk] * 3,
            out_shape=[jax.ShapeDtypeStruct((rows, _LANES),
                                            jnp.float32)] * 3,
            interpret=_resolve_interpret(interpret),
        )(scal, _blocked(p), _blocked(g), _blocked(m), _blocked(v))

    def _unblocked(x):
        return x.reshape(-1)[:n].reshape(shape)

    return _unblocked(out), _unblocked(mo), _unblocked(vo)
