"""``amp.scale_loss`` context manager and legacy handles.

Reference: ``apex/amp/handle.py:16-281``. Apex's context manager yields
``loss * scale`` and, on exit, unscales the ``.grad`` attributes the user's
``backward()`` populated, then patches ``optimizer.step`` to skip on
overflow. JAX gradients are values, not attributes, so the contract here
is:

    with amp.scale_loss(loss, optimizer) as scaled_loss:
        grads = <grads of the scaled loss>              # user-side
        optimizer.step(grads)   # unscales + skips-on-overflow internally

i.e. the context manager scales the loss and arms the optimizer's scaler;
the unscale/skip logic runs inside the optimizer step (mirroring
``_post_amp_backward``, ``apex/amp/_process_optimizer.py:161-202``), and
``step`` is called *inside* the context so the exit-time overflow report
reflects this iteration. This eager API pays one host sync per iteration
for the report; the fully-jitted zero-sync path is ``amp.make_train_step``.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from apex_tpu.amp import scaler as _scaler_mod
from apex_tpu.amp._amp_state import _amp_state, maybe_print
from apex_tpu.monitor import hooks as _mon


@contextlib.contextmanager
def scale_loss(loss, optimizers, loss_id: int = 0, model=None,
               delay_unscale: bool = False,
               delay_overflow_check: bool = False):
    """Yield the scaled loss; on exit update the scaler from observed state.

    ``delay_unscale`` mirrors ``apex/amp/handle.py:67-79`` (gradient
    accumulation: skip unscale/update this iteration).
    ``delay_overflow_check`` (``apex/amp/handle.py:80-84``) exists for
    signature parity: it deferred the CUDA-stream overflow readback; the
    TPU scaler's overflow check is already a device-side ``lax.cond``
    with no host sync to defer, so the flag is accepted and inert.
    """
    if not _amp_state.enabled or not _amp_state.loss_scalers:
        # amp disabled (initialize(enabled=False)) or not initialized →
        # passthrough, like handle.py:21-29
        yield loss
        return

    loss_scaler = _amp_state.loss_scalers[loss_id]
    opt_list = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
    for opt in opt_list:
        if hasattr(opt, "arm_scaler"):
            opt.arm_scaler(loss_scaler, delay_unscale=delay_unscale)

    yield _scaler_mod.scale_value(jnp.asarray(loss), loss_scaler.state)

    if delay_unscale:
        return
    # If the user called optimizer.step(grads) inside the context (the
    # documented flow), the scaler state now reflects this iteration;
    # surface the skip message like handle.py:138-140.
    if bool(loss_scaler.state.overflow):
        _mon.counter("amp/scale_loss_overflows", loss_id=loss_id)
        maybe_print(
            f"Gradient overflow.  Skipping step, loss scaler {loss_id} reducing "
            f"loss scale to {float(loss_scaler.state.loss_scale)}")
    if _mon.enabled():
        # loss_id 0 (the common case) shares the traced path's gauge
        # name; extra loss scalers get a namespaced column
        name = "amp/loss_scale" if loss_id == 0 \
            else f"amp/loss_scale/{loss_id}"
        _mon.gauge(name, float(loss_scaler.state.loss_scale))


@contextlib.contextmanager
def disable_casts():
    """``amp.handle.disable_casts`` parity (``apex/amp/handle.py:156-164``)."""
    from apex_tpu.amp.policy import autocast
    with autocast(False):
        yield


class AmpHandle:
    """Legacy handle API (``apex/amp/handle.py:170-251``)."""

    def __init__(self, loss_scale="dynamic", enable_caching=True, verbose=False):
        self._enable_caching = enable_caching
        self._verbose = verbose
        from apex_tpu.amp.scaler import LossScaler
        self._default_scaler = LossScaler(loss_scale)
        self._is_active = True
        self._all_wrappers = []

    def is_active(self):
        return self._is_active

    @contextlib.contextmanager
    def _disable_casts(self):
        with disable_casts():
            yield

    def scale_loss(self, loss, optimizer):
        return scale_loss(loss, optimizer)

    @property
    def has_cache(self):
        return self._enable_caching

    def _clear_cache(self):
        pass  # XLA CSE makes the weight-cast cache unnecessary


class NoOpHandle:
    """``apex/amp/handle.py:254-281``."""

    def is_active(self):
        return False

    @contextlib.contextmanager
    def _disable_casts(self):
        yield

    def scale_loss(self, loss, optimizer):
        return contextlib.nullcontext(loss)

    @property
    def has_cache(self):
        return False

    def _clear_cache(self):
        pass
