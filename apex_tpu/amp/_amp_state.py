"""Process-global amp state + rank-aware printing.

Reference: ``apex/amp/_amp_state.py:7-50``.
"""

from __future__ import annotations

import jax


class AmpState:
    def __init__(self):
        self.hard_override = False
        # amp.initialize(enabled=False) flips this; scale_loss consults
        # it (with the empty-loss_scalers fallback) to pass the loss
        # through unscaled (apex/amp/frontend.py:198,209)
        self.enabled = True
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.handle = None
        self.opt_properties = None
        self.loss_scalers: list = []


_amp_state = AmpState()


def master_only() -> bool:
    return jax.process_index() == 0


def maybe_print(msg: str, rank0: bool = False):
    """Verbosity-gated, optionally rank-0-only printing
    (``apex/amp/_amp_state.py:38-50``)."""
    if _amp_state.verbosity > 0 and (not rank0 or master_only()):
        print(msg)


def warn_or_err(msg: str):
    if _amp_state.hard_override:
        maybe_print("Warning: " + msg)
    else:
        raise RuntimeError(msg)
