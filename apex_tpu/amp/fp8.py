"""fp8 training primitives: the O4 opt level's delayed-scaling codec.

Reference recipe: Transformer Engine's ``DelayedScaling``
(Micikevicius et al., "FP8 Formats for Deep Learning", 2022; NVIDIA
TransformerEngine ``common/recipe.py``) — forward tensors (activations,
weights) are quantized to **e4m3** (max 448, 3 mantissa bits), backward
cotangents to **e5m2** (max 57344, fp16-exponent range), and every
quantized tensor carries its own scale derived from a ring buffer of
recent amax (max-abs) observations: the scale used on step *t* comes
from the history of steps ``< t`` — *delayed* scaling — so quantization
never needs a same-step host sync or a second pass over the tensor.

TPU translation (pure, APX005-clean — nothing here mutates Python state
under jit):

- :class:`Fp8Meta` is a tiny device-resident state pytree (amax-history
  ring + current scale) per quantized tensor; :class:`Fp8DotMeta` packs
  the three metas of one matmul site (x / w / cotangent).
- :func:`fp8_matmul` / :func:`fp8_dot` are ``custom_vjp`` primitives:
  the forward quantizes both operands to e4m3 (saturating — an
  out-of-range cast to e4m3 produces NaN, not inf, so the clip is
  correctness, not a nicety) and contracts them with fp32 MXU
  accumulation; the backward quantizes the arriving cotangent to e5m2
  and computes both input grads from the *quantized* operands (the fp8
  residuals are the memory win: 1 byte/elt instead of 2).
- **amax recording rides the cotangent**: the backward's "gradient" for
  each :class:`Fp8Meta` input is a meta-shaped pytree whose ``scale``
  slot carries the tensor's recorded amax (x and w measured in the
  forward, the cotangent measured in the backward) and whose
  ``amax_history`` slot is zeros. ``jax.grad(loss, argnums=(params,
  fp8_state))`` therefore returns every recorded amax alongside the
  parameter grads — no aux plumbing, no host round trip, and the whole
  step stays one jitted program. (If one meta feeds several matmuls the
  cotangents *sum*; a sum of amaxes over-estimates the true max, which
  only makes the next scale more conservative.)
- :func:`update_state` applies the delayed-scaling update: push the
  recorded amax into the ring, take the history max, recompute the
  scale from the format's representable max and the safety ``margin``.
  ``amp.make_train_step(..., fp8=True)`` threads and donates this state
  alongside the scaler state, and skips the update on overflow steps
  (the amax history stays untouched, same contract as the O2
  master-weight skip).

The quantize/dequantize/compute-scale helpers below are the ONE fp8
codec in the package: ``parallel/overlap.py``'s fp8-compressed gradient
buckets and ``zero/comm.py``'s scaled parameter gather reuse them, so
wire numerics are identical wherever fp8 bytes move.

``amp.initialize(..., enabled=False)`` flips the module-level
:func:`set_enabled` guard (the same lifecycle as ``_amp_state.enabled``)
and every primitive here goes inert-but-present: :func:`fp8_matmul`
becomes the plain fp32-accumulated matmul, :func:`update_state` the
identity — code written against the O4 API runs at full precision with
the same signatures. The flag is read at trace time; like the amp
enable flag, re-jit after toggling.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "E4M3", "E5M2", "E4M3_MAX", "E5M2_MAX", "fp8_max",
    "Fp8Meta", "Fp8DotMeta", "init_meta", "init_dot_meta", "init_state",
    "amax", "compute_scale", "quantize", "dequantize",
    "fp8_dot", "fp8_matmul", "update_meta", "update_state",
    "set_enabled", "is_enabled",
]

# the two wire formats of the TE recipe (jnp aliases of ml_dtypes):
# e4m3fn = "finite NaN" variant — NO inf encoding, which is why every
# cast below saturates explicitly
E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2

# representable maxima (ml_dtypes.finfo(...).max — hardcoded as plain
# floats so they are usable as static trace-time constants and default
# args; asserted against finfo in tests/test_fp8.py)
E4M3_MAX = 448.0
E5M2_MAX = 57344.0

_FP8_MAX = {np.dtype(E4M3): E4M3_MAX, np.dtype(E5M2): E5M2_MAX}

# module guard flipped by amp.initialize (enabled= lifecycle); read at
# trace time only — never from inside a traced function body
_STATE = {"enabled": True}


def set_enabled(flag: bool) -> None:
    """Arm/disarm the fp8 primitives (called by ``amp.initialize``;
    ``enabled=False`` renders the whole O4 surface inert-but-present)."""
    _STATE["enabled"] = bool(flag)


def is_enabled() -> bool:
    return _STATE["enabled"]


def fp8_max(dtype) -> float:
    """Representable max of an fp8 wire dtype (the saturation bound)."""
    key = np.dtype(dtype)
    if key not in _FP8_MAX:
        raise ValueError(f"not an fp8 wire dtype: {dtype}")
    return _FP8_MAX[key]


# ---------------------------------------------------------------------------
# per-tensor delayed-scaling state
# ---------------------------------------------------------------------------


class Fp8Meta(NamedTuple):
    """Delayed-scaling state of ONE quantized tensor.

    ``amax_history``: f32 ``[history_len]`` ring, newest observation at
    index 0. ``scale``: f32 scalar — the multiplier applied *before*
    the fp8 cast (``q = clip(x * scale)``); dequantize divides it back
    out. In a recorded-amax cotangent (see module docstring) the
    ``scale`` slot carries the observed amax instead.
    """

    amax_history: jax.Array
    scale: jax.Array


class Fp8DotMeta(NamedTuple):
    """The three tensor metas of one matmul site: ``x`` (e4m3 forward
    activation), ``w`` (e4m3 weight), ``g`` (e5m2 backward cotangent)."""

    x: Fp8Meta
    w: Fp8Meta
    g: Fp8Meta


def init_meta(history_len: int = 16, scale: float = 1.0) -> Fp8Meta:
    return Fp8Meta(
        amax_history=jnp.zeros((int(history_len),), jnp.float32),
        scale=jnp.asarray(scale, jnp.float32))


def init_dot_meta(history_len: int = 16) -> Fp8DotMeta:
    return Fp8DotMeta(x=init_meta(history_len), w=init_meta(history_len),
                      g=init_meta(history_len))


def init_state(sites: Sequence[str], history_len: int = 16) -> dict:
    """One :class:`Fp8DotMeta` per named matmul site — the state tree
    ``amp.make_train_step(..., fp8=True)`` threads and donates. Plain
    f32 arrays throughout, so ``checkpoint.save_checkpoint`` /
    ``load_checkpoint`` round-trip it bitwise with no special casing."""
    return {name: init_dot_meta(history_len) for name in sites}


# ---------------------------------------------------------------------------
# the codec (shared with parallel/overlap.py and zero/comm.py)
# ---------------------------------------------------------------------------


def amax(x) -> jax.Array:
    """f32 max-abs of a tensor — the statistic the recipe tracks."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)))


def compute_scale(amax_val, fmt_max: float, margin: float = 0.0) -> jax.Array:
    """TE ``DelayedScaling`` scale: ``fmt_max / (amax * 2**margin)`` —
    the largest multiplier that keeps ``amax`` (plus ``margin`` powers
    of two of headroom) inside the format. Zero / non-finite amax
    (untrained history, an inf that slipped past the overflow skip)
    falls back to scale 1.0 rather than poisoning the codec."""
    amax_val = jnp.asarray(amax_val, jnp.float32)
    s = fmt_max / (amax_val * (2.0 ** float(margin)))
    # an inf amax yields s == 0.0 — finite, but a zero scale poisons
    # both quantize (all zeros) and dequantize (divide by zero), so the
    # amax itself must be finite too
    ok = (amax_val > 0) & jnp.isfinite(amax_val) & jnp.isfinite(s)
    return jnp.where(ok, s, jnp.float32(1.0))


def quantize(x, scale, wire_dtype=E5M2) -> jax.Array:
    """Saturating cast to an fp8 wire dtype: ``clip(x*scale, ±max)``.

    The clip is load-bearing for e4m3: ml_dtypes' ``float8_e4m3fn`` has
    no inf encoding, so an unclipped out-of-range cast produces NaN
    (measured) and one hot activation would poison the whole tensor."""
    m = fp8_max(wire_dtype)
    scaled = x.astype(jnp.float32) * scale
    return jnp.clip(scaled, -m, m).astype(wire_dtype)


def dequantize(q, scale, out_dtype=jnp.float32) -> jax.Array:
    """Invert :func:`quantize` (up to the format's rounding)."""
    return (q.astype(jnp.float32) / scale).astype(out_dtype)


# ---------------------------------------------------------------------------
# fp8 matmul with amax-recording custom_vjp
# ---------------------------------------------------------------------------


def _zeros_meta_cot(meta: Fp8Meta, recorded_amax) -> Fp8Meta:
    """Recorded-amax cotangent: history slot zeros, scale slot = amax."""
    return Fp8Meta(amax_history=jnp.zeros_like(meta.amax_history),
                   scale=recorded_amax)


@functools.lru_cache(maxsize=None)
def _fp8_matmul_prim(x_dtype_str: str, w_dtype_str: str):
    """The ``custom_vjp`` primitive, specialized per operand-dtype pair
    (residuals must be pure array pytrees, so the cotangent dtypes are
    baked in statically; the cache is bounded by the handful of
    floating dtypes in play)."""
    x_dtype = jnp.dtype(x_dtype_str)
    w_dtype = jnp.dtype(w_dtype_str)

    @jax.custom_vjp
    def prim(x, w, meta: Fp8DotMeta):
        qx = quantize(x, meta.x.scale, E4M3)
        qw = quantize(w, meta.w.scale, E4M3)
        y = jnp.dot(qx, qw, preferred_element_type=jnp.float32)
        return y / (meta.x.scale * meta.w.scale)

    def fwd(x, w, meta):
        qx = quantize(x, meta.x.scale, E4M3)
        qw = quantize(w, meta.w.scale, E4M3)
        y = jnp.dot(qx, qw, preferred_element_type=jnp.float32)
        y = y / (meta.x.scale * meta.w.scale)
        # residuals hold the QUANTIZED operands (1 byte/elt — the fp8
        # memory property) plus the forward amax observations
        return y, (qx, qw, meta, amax(x), amax(w))

    def bwd(res, dy):
        qx, qw, meta, amax_x, amax_w = res
        amax_g = amax(dy)
        qg = quantize(dy, meta.g.scale, E5M2)
        inv_gw = 1.0 / (meta.g.scale * meta.w.scale)
        inv_gx = 1.0 / (meta.g.scale * meta.x.scale)
        # dx = dy @ w^T and dw = x^T @ dy, both from the fp8 residuals
        # with fp32 accumulation (the e5m2 cotangent is the recipe's
        # gradient wire format)
        dx = (jnp.dot(qg, qw.T, preferred_element_type=jnp.float32)
              * inv_gw).astype(x_dtype)
        nbatch = qg.ndim - 1
        dw = (jnp.tensordot(
            qx, qg, axes=(tuple(range(nbatch)), tuple(range(nbatch))),
            preferred_element_type=jnp.float32) * inv_gx).astype(w_dtype)
        meta_cot = Fp8DotMeta(x=_zeros_meta_cot(meta.x, amax_x),
                              w=_zeros_meta_cot(meta.w, amax_w),
                              g=_zeros_meta_cot(meta.g, amax_g))
        return dx, dw, meta_cot

    prim.defvjp(fwd, bwd)
    return prim


def fp8_matmul(x, w, meta: Fp8DotMeta, out_dtype=None):
    """``x @ w`` through the fp8 codec: operands quantized e4m3 with
    their per-tensor delayed scales, fp32 MXU accumulation, cotangent
    quantized e5m2 in the backward; amax recorded on both passes and
    returned as the ``meta`` cotangent (module docstring).

    ``x``: ``[..., k]`` (any leading dims), ``w``: ``[k, n]``. Output
    dtype defaults to ``x.dtype`` (bf16 under the O4 patched forward).
    When the module guard is off (``amp.initialize(enabled=False)``)
    this is the plain fp32-accumulated matmul — same signature, same
    state threading, full precision.
    """
    if w.ndim != 2:
        raise ValueError(f"fp8_matmul: weight must be 2D [k, n], got "
                         f"{w.shape}")
    if x.shape[-1] != w.shape[0]:
        raise ValueError(f"fp8_matmul: contraction mismatch, "
                         f"x[..., {x.shape[-1]}] @ w[{w.shape[0]}, ...]")
    out_dtype = jnp.dtype(x.dtype if out_dtype is None else out_dtype)
    if not is_enabled():
        return jnp.dot(x, w,
                       preferred_element_type=jnp.float32).astype(out_dtype)
    prim = _fp8_matmul_prim(str(jnp.dtype(x.dtype)), str(jnp.dtype(w.dtype)))
    # the primitive returns fp32 (the accumulate dtype); the output cast
    # sits outside the custom_vjp so its transpose (a cast back to f32)
    # composes with the e5m2 cotangent quantization inside
    return prim(x, w, meta).astype(out_dtype)


# docs and the issue speak of both names; ``fp8_dot`` is the same
# contraction (last axis of x against first of w)
fp8_dot = fp8_matmul


# ---------------------------------------------------------------------------
# delayed-scaling update (the once-per-step state transition)
# ---------------------------------------------------------------------------


def update_meta(meta: Fp8Meta, recorded_amax, fmt_max: float,
                margin: float = 0.0) -> Fp8Meta:
    """Push one amax observation and recompute the scale.

    The ring shifts (newest at 0, oldest falls off), the reference amax
    is the max over the updated history (``amax_compute_algo="max"``),
    and the new scale positions that amax ``margin`` powers of two
    below the format max. A non-finite observation is recorded as 0 —
    it must not zero the scale (the overflow path in
    ``make_train_step`` normally skips this update entirely)."""
    obs = jnp.asarray(recorded_amax, jnp.float32)
    obs = jnp.where(jnp.isfinite(obs), obs, 0.0)
    hist = jnp.concatenate([obs[None], meta.amax_history[:-1]])
    ref = jnp.max(hist)
    return Fp8Meta(amax_history=hist,
                   scale=compute_scale(ref, fmt_max, margin))


def update_dot_meta(meta: Fp8DotMeta, recorded: Fp8DotMeta,
                    margin: float = 0.0) -> Fp8DotMeta:
    """Delayed-scaling update of one matmul site from its recorded-amax
    cotangent (x/w against the e4m3 max, g against e5m2)."""
    return Fp8DotMeta(
        x=update_meta(meta.x, recorded.x.scale, E4M3_MAX, margin),
        w=update_meta(meta.w, recorded.w.scale, E4M3_MAX, margin),
        g=update_meta(meta.g, recorded.g.scale, E5M2_MAX, margin))


def update_state(state: Any, recorded: Any, *, margin: float = 0.0) -> Any:
    """Apply :func:`update_dot_meta` across a state tree and its
    recorded cotangent tree (the fp8 half of ``jax.grad``'s output in
    ``make_train_step(fp8=True)``). Identity when the module guard is
    off — the inert-but-present contract."""
    if not is_enabled():
        return state
    return jax.tree.map(
        functools.partial(update_dot_meta, margin=margin),
        state, recorded,
        is_leaf=lambda n: isinstance(n, Fp8DotMeta))
