"""Opt-level properties: O0–O4 precision policies.

Reference: ``apex/amp/frontend.py:7-191`` — a ``Properties`` object with
per-property consistency validation in ``__setattr__`` plus four canned opt
levels, overridable by explicit kwargs (``frontend.py:336-356``).

TPU deltas (documented, deliberate):
- the default half type is **bfloat16** (no loss scaling needed for range,
  so bf16 opt levels default ``loss_scale=1.0``); ``float16`` is fully
  supported for parity and then defaults to dynamic scaling like apex.
- ``patch_torch_functions`` becomes ``cast_ops`` — O1 per-op casting is a
  trace-time dtype policy applied through the ``apex_tpu.amp.policy``
  registry, not namespace monkey-patching (JAX has no safely patchable op
  namespace; see SURVEY §7 hard parts).
"""

from __future__ import annotations

import jax.numpy as jnp


class Properties:
    """Mutable options bag with mutual-consistency handling.

    Mirrors ``apex/amp/frontend.py:7-97``: options may be set before or
    after an opt level is chosen; setting an opt level stamps its defaults
    over unset options, and explicit user overrides win.
    """

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,       # dtype params are cast to (O2/O3)
            "cast_ops": False,             # O1 per-op autocast policy
            "cast_model_outputs": None,    # force outputs to this dtype
            "keep_batchnorm_fp32": None,   # exempt norm params from the cast
            "master_weights": None,        # keep fp32 master params in optimizer
            "loss_scale": 1.0,             # float or "dynamic"
            "half_dtype": jnp.bfloat16,    # what "half" means on this device
            "fp8_history_len": 16,         # O4: amax ring length per tensor
            "fp8_margin": 0.0,             # O4: scale headroom, powers of two
        }

    def _update_options_dict(self, new_options: dict):
        for k, v in new_options.items():
            if k in self.options:
                self.options[k] = v
            else:
                raise ValueError(f"Tried to set unexpected option {k}")

    def __getattr__(self, name):
        if "options" in self.__dict__ and name in self.__dict__["options"]:
            return self.options[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if "options" in self.__dict__ and name in self.options:
            if name == "loss_scale" and value != "dynamic" and value is not None:
                value = float(value)
            if name == "keep_batchnorm_fp32" and isinstance(value, str):
                # apex accepts the strings "True"/"False" here
                # (apex/amp/frontend.py:269-278)
                if value not in ("True", "False"):
                    raise ValueError(f"keep_batchnorm_fp32 string must be 'True'/'False', got {value}")
                value = value == "True"
            self.options[name] = value
        else:
            super().__setattr__(name, value)


class O3:
    """Pure half. ``cast_model_type=half, master_weights=False, loss_scale=1``.

    Reference: ``apex/amp/frontend.py:100-116``.
    """

    brief = "O3: Pure half precision (speed-of-light baseline)."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = properties.half_dtype
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2:
    """Half model + fp32 batchnorm + fp32 master weights + loss scaling.

    Reference: ``apex/amp/frontend.py:118-143``. With bf16 the default
    ``loss_scale`` is 1.0 (bf16 shares fp32 exponent range); with fp16 it
    is "dynamic" exactly like apex.
    """

    brief = "O2: 'Almost half' — half model, fp32 batchnorm and master weights."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = properties.half_dtype
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = (
            "dynamic" if properties.half_dtype == jnp.float16 else 1.0
        )
        return properties


class O1:
    """Per-op cast policy; fp32 weights; dynamic scaling for fp16.

    Reference: ``apex/amp/frontend.py:145-167`` — instead of patching the
    torch namespace, O1 here activates the trace-time autocast policy
    consulted by apex_tpu ops and ``half_function``-registered functions.
    """

    brief = "O1: per-op mixed precision via the autocast policy registry."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.cast_ops = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = (
            "dynamic" if properties.half_dtype == jnp.float16 else 1.0
        )
        return properties


class O0:
    """Pure fp32 baseline. Reference: ``apex/amp/frontend.py:169-191``."""

    brief = "O0: Pure fp32 (accuracy baseline)."

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O4:
    """fp8 matmuls with per-tensor delayed scaling, on the O2 recipe.

    No apex analog — the Transformer-Engine ``DelayedScaling`` recipe
    (e4m3 forward activations/weights, e5m2 cotangents, per-tensor amax
    history) grafted onto this package's opt-level frame: everything the
    model does NOT route through ``amp.fp8.fp8_matmul`` runs exactly
    like O2 (half storage, fp32 batchnorm, fp32 master weights), and the
    fp8 sites carry their own :class:`~apex_tpu.amp.fp8.Fp8DotMeta`
    state threaded by ``make_train_step(..., fp8=True)``.

    ``loss_scale``: every *fp8-consumed* gradient is governed by its
    tensor's own e5m2 delayed scale, so the global loss scale is
    redundant for those leaves; it exists purely for the NON-fp8 leaves
    (norm params, biases, embeddings outside fp8 sites), and therefore
    defaults exactly like O2 — ``"dynamic"`` iff the half dtype is fp16
    (bf16 shares the fp32 exponent range and needs no scaling). The
    overflow skip never touches the amax history (tested).
    """

    brief = ("O4: fp8 matmuls (e4m3 fwd / e5m2 grads, delayed scaling) "
             "over the O2 master-weight recipe.")

    def __call__(self, properties: Properties) -> Properties:
        properties.enabled = True
        properties.opt_level = "O4"
        properties.cast_model_type = properties.half_dtype
        properties.cast_ops = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = (
            "dynamic" if properties.half_dtype == jnp.float16 else 1.0
        )
        return properties


opt_levels = {"O4": O4(), "O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}
