"""Loss scaling: static or dynamic, with device-resident overflow state.

Reference: ``apex/amp/scaler.py:33-217``. Apex keeps a GPU ``_overflow_buf``
written by the multi-tensor kernels and performs exactly one D2H sync per
step in ``update_scale`` (:197-200); on overflow it halves the scale, and
doubles every ``scale_window=2000`` clean steps.

TPU design: the scaler is a pure function over a small state pytree that
lives on device. ``update`` is branch-free (``jnp.where``), so the whole
(scale → backward → unscale → check → update → maybe-skip-step) loop stays
inside one jitted program with **zero** host syncs — strictly better than
the reference's one sync. The host can still read ``state.loss_scale`` for
logging/checkpointing whenever it wants.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.monitor import hooks as _mon
from apex_tpu.utils.tree import tree_all_finite


class ScalerState(NamedTuple):
    """Device-resident dynamic loss-scaler state."""

    loss_scale: jax.Array   # f32 scalar, current scale
    unskipped: jax.Array    # i32 scalar, clean steps since last change
    overflow: jax.Array     # bool scalar, last step overflowed


def init_state(init_scale: float = 2.0 ** 16) -> ScalerState:
    return ScalerState(
        loss_scale=jnp.asarray(init_scale, jnp.float32),
        unskipped=jnp.asarray(0, jnp.int32),
        overflow=jnp.asarray(False),
    )


def scale_value(loss: jax.Array, state: ScalerState) -> jax.Array:
    """``loss.float() * loss_scale`` (``apex/amp/handle.py:113``)."""
    return loss.astype(jnp.float32) * state.loss_scale


def unscale(grads: Any, state: ScalerState, out_dtype=jnp.float32):
    """Unscale a gradient pytree and detect overflow.

    Mirrors ``LossScaler.unscale`` (``apex/amp/scaler.py:94-150``): the
    model grads are multiplied by ``1/scale`` into (possibly new-dtype)
    output grads, with inf/nan detection folded in. Returns
    ``(unscaled_grads, found_inf)``.
    """
    inv = jnp.where(state.loss_scale > 0, 1.0 / state.loss_scale, 1.0)
    if any(g.dtype == jnp.float16 for g in jax.tree.leaves(grads)):
        # fp16 on TPU is emulated with EXCESS PRECISION and rounding is
        # applied per-fusion: without a barrier the overflow reduction
        # and the downstream unscale/apply can be fused into different
        # consumers seeing DIFFERENT values — measured on a v5e RN50
        # fp16-O2 step: found_inf=False while the grads the optimizer
        # consumed held inf, poisoning params with no skip (caught by
        # the r5 convergence tier at step 0). The barrier pins ONE
        # materialization of the fp16 grads that both the detection and
        # the update then share, and is applied PER LEAF to only the
        # fp16 leaves: bf16/fp32 leaves in a mixed tree (master-weight
        # setups, fp32-pinned batchnorm grads) have no fp16 rounding
        # ambiguity, and barriering them would only block their fusion.
        grads = jax.tree.map(
            lambda g: jax.lax.optimization_barrier(g)
            if g.dtype == jnp.float16 else g, grads)
    found_inf = ~tree_all_finite(grads)
    out = jax.tree.map(
        lambda g: (g.astype(jnp.float32) * inv).astype(out_dtype)
        if jnp.issubdtype(g.dtype, jnp.floating) else g,
        grads,
    )
    return out, found_inf


def sync_found_inf(found_inf: jax.Array, *axis_names: str) -> jax.Array:
    """OR the overflow flag across model-parallel mesh axes.

    Under tensor (or any model) parallelism each rank sees only its own
    gradient shards, so ranks can disagree on ``found_inf``; if one rank
    skips the step while another applies it, the replicated params, step
    counters, and scaler state diverge permanently. Megatron all-reduces
    the overflow flag over the model-parallel group before the skip
    decision — call this with every mesh axis that shards gradients
    (NOT the data axis: grads are summed over dp before unscale, which
    already propagates inf). Unbound axis names are ignored, so the same
    train step works at tp=1 outside shard_map.
    """
    from apex_tpu.transformer import parallel_state as _ps  # lazy: no cycle
    x = found_inf.astype(jnp.int32)
    for ax in axis_names:
        x = _ps.psum_if_bound(x, ax)
    return x > 0


def update(
    state: ScalerState,
    found_inf: jax.Array,
    *,
    dynamic: bool,
    scale_factor: float = 2.0,
    scale_window: int = 2000,
    min_loss_scale: float | None = None,
    max_loss_scale: float = 2.0 ** 24,
) -> ScalerState:
    """Pure version of ``LossScaler.update_scale`` (``apex/amp/scaler.py:197-217``).

    On overflow: scale /= scale_factor (clamped to ``min_loss_scale``),
    counter resets. Every ``scale_window`` clean steps: scale *= factor
    (clamped to ``max_loss_scale``). Static scaling is the identity.
    """
    if not dynamic:
        new_state = ScalerState(state.loss_scale, state.unskipped, found_inf)
    else:
        min_scale = jnp.asarray(min_loss_scale if min_loss_scale is not None else 0.0, jnp.float32)
        shrunk = jnp.maximum(state.loss_scale / scale_factor, jnp.maximum(min_scale, 1.0e-8))
        unskipped = jnp.where(found_inf, 0, state.unskipped + 1)
        grow = unskipped >= scale_window
        grown = jnp.minimum(state.loss_scale * scale_factor, max_loss_scale)
        new_scale = jnp.where(found_inf, shrunk, jnp.where(grow, grown, state.loss_scale))
        unskipped = jnp.where(grow, 0, unskipped)
        new_state = ScalerState(new_scale, unskipped.astype(jnp.int32), found_inf)
    # telemetry: loss-scale value + overflow flag per executed update
    # (no-op unless a monitor.Recorder is attached — no inserted ops,
    # identical jaxpr in the disabled path)
    _mon.traced_scalar("amp/loss_scale", new_state.loss_scale)
    _mon.traced_scalar("amp/overflow", found_inf)
    return new_state


class LossScaler:
    """Stateful wrapper mirroring the apex object API.

    Reference: ``apex/amp/scaler.py:33`` — construction with
    ``loss_scale="dynamic"`` or a float, plus ``scale_window`` etc.; exposes
    ``loss_scale()``, ``update_scale()``, ``clear_overflow_state()`` and
    state-dict helpers used by ``amp.state_dict``
    (``apex/amp/frontend.py:361-400``).

    All compute methods are jit-safe; only the convenience properties pull
    values to the host.
    """

    warned_unscaling_non_fp32_grad = False

    def __init__(
        self,
        loss_scale: float | str = "dynamic",
        init_scale: float = 2.0 ** 16,
        scale_factor: float = 2.0,
        scale_window: int = 2000,
        min_loss_scale: float | None = None,
        max_loss_scale: float = 2.0 ** 24,
    ):
        self.dynamic = loss_scale == "dynamic"
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_loss_scale = min_loss_scale
        self._max_loss_scale = max_loss_scale
        self._skipped_steps = 0     # host-visible total (eager path only)
        self._growth_resets = 0     # scale_window expiries seen eagerly
        init = init_scale if self.dynamic else float(loss_scale)
        self.state = init_state(init)

    # -- jit-safe functional API -------------------------------------------
    def scale_value(self, loss, state: ScalerState | None = None):
        return scale_value(loss, state if state is not None else self.state)

    def unscale_tree(self, grads, state: ScalerState | None = None, out_dtype=jnp.float32):
        return unscale(grads, state if state is not None else self.state, out_dtype)

    def update_state(self, state: ScalerState, found_inf) -> ScalerState:
        return update(
            state,
            found_inf,
            dynamic=self.dynamic,
            scale_factor=self._scale_factor,
            scale_window=self._scale_window,
            min_loss_scale=self._min_loss_scale,
            max_loss_scale=self._max_loss_scale,
        )

    # -- stateful conveniences (host-side, eager) --------------------------
    def loss_scale(self) -> float:
        return float(self.state.loss_scale)

    def state_summary(self) -> dict:
        """Public snapshot of the scaler's knobs and counters — use this
        instead of reaching for private attrs. (Named ``state_summary``
        because ``state`` is the device-resident :class:`ScalerState`
        attribute, part of the stable API.)

        ``skipped_steps``/``growth_interval_resets`` count what the
        *eager* ``update_scale`` path observed; a fully-jitted loop that
        calls :func:`update` directly keeps its counters on device (read
        ``unskipped``/``overflow`` from its ScalerState, or attach a
        ``apex_tpu.monitor`` recorder for per-step telemetry).
        """
        return {
            "scale": float(self.state.loss_scale),
            "growth_counter": int(self.state.unskipped),
            "overflow": bool(self.state.overflow),
            "skipped_steps": self._skipped_steps,
            "growth_interval_resets": self._growth_resets,
            "dynamic": self.dynamic,
            "scale_factor": self._scale_factor,
            "scale_window": self._scale_window,
            "min_loss_scale": self._min_loss_scale,
            "max_loss_scale": self._max_loss_scale,
        }

    def update_scale(self, found_inf=None) -> bool:
        """Eager update; returns True if the step should be skipped.

        The host read here is the analog of apex's single D2H sync
        (``apex/amp/scaler.py:199-200``); the fully-jitted path avoids it.
        """
        if found_inf is None:
            found_inf = self.state.overflow
        self.state = self.update_state(self.state, jnp.asarray(found_inf))
        skipped = bool(self.state.overflow)
        if skipped:
            self._skipped_steps += 1
            _mon.counter("amp/skipped_steps")
        elif self.dynamic and int(self.state.unskipped) == 0:
            # on a clean dynamic step the counter is where(grow, 0,
            # prev+1) with prev+1 >= 1, so 0 iff a growth-interval
            # expiry just reset it — no pre-update read needed
            self._growth_resets += 1
            _mon.counter("amp/growth_interval_resets")
        return skipped

    def clear_overflow_state(self):
        self.state = ScalerState(self.state.loss_scale, self.state.unskipped, jnp.asarray(False))

    # -- checkpointing (apex/amp/scaler.py state via frontend:361-400) -----
    def state_dict(self) -> dict:
        return {
            "loss_scale": float(self.state.loss_scale),
            "unskipped": int(self.state.unskipped),
            "dynamic": self.dynamic,
            "skipped_steps": self._skipped_steps,
            "growth_interval_resets": self._growth_resets,
        }

    def load_state_dict(self, sd: dict):
        self.dynamic = sd.get("dynamic", self.dynamic)
        self._skipped_steps = int(sd.get("skipped_steps", 0))
        self._growth_resets = int(sd.get("growth_interval_resets", 0))
        self.state = ScalerState(
            jnp.asarray(sd["loss_scale"], jnp.float32),
            jnp.asarray(sd.get("unskipped", 0), jnp.int32),
            jnp.asarray(False),
        )
