"""apex_tpu.amp — mixed precision with O0–O4 opt levels on TPU.

Reference package: ``apex/amp`` (``apex/amp/__init__.py:1-5``); the O4
fp8 level follows the Transformer-Engine delayed-scaling recipe
(``apex_tpu/amp/fp8.py``).
"""

from apex_tpu.amp.frontend import (  # noqa: F401
    initialize,
    state_dict,
    load_state_dict,
    master_state_dict,
    load_master_state_dict,
    make_train_step,
    AmpModel,
)
from apex_tpu.amp.lists import (  # noqa: F401
    register_half_module,
    register_float_module,
)
from apex_tpu.amp.handle import scale_loss, disable_casts, AmpHandle, NoOpHandle  # noqa: F401
from apex_tpu.amp.policy import (  # noqa: F401
    autocast,
    half_function,
    float_function,
    promote_function,
    register_half_function,
    register_float_function,
    register_promote_function,
    autocast_enabled,
)
from apex_tpu.amp.properties import Properties, opt_levels  # noqa: F401
from apex_tpu.amp.scaler import LossScaler, ScalerState, init_state  # noqa: F401
from apex_tpu.amp import scaler  # noqa: F401
from apex_tpu.amp import fp8  # noqa: F401
from apex_tpu.amp.fp8 import fp8_dot, fp8_matmul, Fp8Meta, Fp8DotMeta  # noqa: F401
