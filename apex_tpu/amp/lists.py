"""O1 default cast coverage for arbitrary flax models.

Reference: apex O1 monkey-patches ~200 torch-namespace functions through
curated cast lists (``apex/amp/lists/functional_overrides.py:17-80``
FP16_FUNCS/FP32_FUNCS, ``torch_overrides.py:7-115``,
``tensor_overrides.py:12-63``), so *any* model gets per-op mixed precision
with no model changes. JAX has no mutable op namespace, but flax has the
equivalent seam: ``nn.intercept_methods`` sees every module call of an
``apply``. The table below maps module *classes* (the flax analog of the
reference's function lists) to a cast action:

- ``half``: matmul-class modules (Dense/Conv/Einsum/attention — the
  FP16_FUNCS row: conv1-3d, linear, matmul, bmm, mm, …) run with compute
  dtype = the policy half dtype. Parameters keep fp32 *storage*
  (``param_dtype`` untouched — O1 master weights); flax's ``promote_dtype``
  casts them per-op at trace time, which XLA CSEs, exactly the reference's
  weight-cast cache (``apex/amp/utils.py:97-158``) for free.
- ``float``: normalization / reduction-sensitive modules (the FP32_FUNCS
  row: *norm, softmax, pow, sum, …) run with compute dtype fp32.

Anything not listed runs untouched (the MATCH_INPUT / promote default —
elementwise ops follow their input dtypes, which is what the reference's
casts_after promotion achieves).

The interceptor overrides the module's ``dtype`` field for the duration of
the call (flax modules are per-call bound clones, so the mutation is
trace-local) and also casts floating *array* arguments, so chains of
listed modules don't bounce through fp32.
"""

from __future__ import annotations

from typing import Any, Callable, Literal, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.amp import policy as _policy_mod

Action = Literal["half", "float"]


def _collect(names):
    out = []
    for n in names:
        cls = getattr(nn, n, None)
        if isinstance(cls, type):
            out.append(cls)
    return out


# FP16_FUNCS analog (functional_overrides.py:17-42: conv*, linear, matmul…)
_HALF_MODULES: list[type] = _collect([
    "Dense", "DenseGeneral", "Einsum",
    "Conv", "ConvTranspose", "ConvLocal",
    "MultiHeadDotProductAttention", "MultiHeadAttention", "SelfAttention",
])

# FP32_FUNCS analog (functional_overrides.py:44-62: *norm, softmax, …)
_FLOAT_MODULES: list[type] = _collect([
    "BatchNorm", "LayerNorm", "GroupNorm", "RMSNorm", "InstanceNorm",
    "SpectralNorm", "WeightNorm",
])


def register_half_module(cls: type) -> None:
    """Add a flax module class to the O1 half list
    (``apex.amp.register_half_function`` analog for modules)."""
    if cls not in _HALF_MODULES:
        _HALF_MODULES.append(cls)


def register_float_module(cls: type) -> None:
    if cls not in _FLOAT_MODULES:
        _FLOAT_MODULES.append(cls)


def module_cast_action(mod: Any) -> Optional[Action]:
    # exact-class and subclass matches; FLOAT wins on diamond ancestry
    # (safety first, mirroring the reference's banned/FP32 priority)
    for cls in _FLOAT_MODULES:
        if isinstance(mod, cls):
            return "float"
    for cls in _HALF_MODULES:
        if isinstance(mod, cls):
            return "half"
    return None


def _cast_float_arrays(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x, tree)


def o1_interceptor(next_fun: Callable, args, kwargs, context):
    """``nn.intercept_methods`` interceptor applying the cast table."""
    p = _policy_mod.current_policy()
    # "attend" is the embedding-transpose logits matmul (flax nn.Embed /
    # VocabParallelEmbedding) — matmul-class with a float input, so it
    # must see the half policy like __call__ does (it is the single
    # largest matmul of a GPT step).
    if (p is None or not p.enabled
            or context.method_name not in ("__call__", "attend")):
        return next_fun(*args, **kwargs)
    mod = context.module
    action = module_cast_action(mod)
    if action is None:
        return next_fun(*args, **kwargs)
    target = p.half_dtype if action == "half" else jnp.float32
    args = _cast_float_arrays(args, target)
    kwargs = _cast_float_arrays(kwargs, target)
    has_dtype = hasattr(mod, "dtype")
    if not has_dtype:
        return next_fun(*args, **kwargs)
    prev = mod.dtype
    # flax modules are frozen dataclasses; the bound clone is private to
    # this call, so a scoped override of the *compute* dtype is safe
    object.__setattr__(mod, "dtype", target)
    try:
        return next_fun(*args, **kwargs)
    finally:
        object.__setattr__(mod, "dtype", prev)
