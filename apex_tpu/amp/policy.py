"""O1 autocast: a trace-time dtype policy with a function registry.

Reference: apex O1 monkey-patches ~200 functions on the torch namespace via
white/black/promote lists (``apex/amp/amp.py:68-177``, cast lists in
``apex/amp/lists/*.py``) and exposes ``register_half_function`` etc.
(``apex/amp/amp.py:26-66``). JAX has no mutable op namespace that can be
patched safely under tracing, so the same *capability* is provided as:

- an ``autocast(...)`` context manager setting a trace-time policy
  (ContextVar — safe under nested jit tracing since tracing is
  single-threaded per trace);
- decorators ``half_function`` / ``float_function`` / ``promote_function``
  that wrap any callable with the corresponding input-cast behavior,
  active only while a policy is enabled;
- registration helpers mirroring the apex module API
  (``amp.register_half_function(module, name)``), which *rebind the
  attribute on the owning module object* — the JAX-safe equivalent of the
  reference's patching, applied to user/apex_tpu modules (never to jax
  itself);
- the weight-cast **cache** semantics of apex (``apex/amp/utils.py:97-158``)
  are unnecessary: under jit, casting the same param twice is CSE'd by XLA.

All apex_tpu fused layers consult this policy, so O1 gives per-op mixed
precision across the library out of the box.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp


class CastPolicy:
    def __init__(self, enabled: bool, half_dtype=jnp.bfloat16):
        self.enabled = enabled
        self.half_dtype = half_dtype


_policy: contextvars.ContextVar[CastPolicy | None] = contextvars.ContextVar(
    "apex_tpu_amp_policy", default=None
)


def current_policy() -> CastPolicy | None:
    return _policy.get()


def autocast_enabled() -> bool:
    p = _policy.get()
    return p is not None and p.enabled


@contextlib.contextmanager
def autocast(enabled: bool = True, dtype=jnp.bfloat16):
    """Enable the O1 cast policy for ops traced inside the context.

    Analog of entering an amp-O1-initialized region; also the analog of
    ``amp.disable_casts`` (``apex/amp/handle.py:156-164``) when called with
    ``enabled=False``.
    """
    token = _policy.set(CastPolicy(enabled, dtype))
    try:
        yield
    finally:
        _policy.reset(token)


disable_casts = functools.partial(autocast, False)


def _cast_tree(args: Any, dtype) -> Any:
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
        else x,
        args,
    )


def half_function(fn: Callable) -> Callable:
    """Run ``fn`` in half precision when autocast is active.

    Analog of ``apex.amp.half_function`` (``apex/amp/amp.py:56-58``);
    matmul-class ops (dense, conv, attention, MLP) are registered with this.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = _policy.get()
        if p is not None and p.enabled:
            args = _cast_tree(args, p.half_dtype)
            kwargs = _cast_tree(kwargs, p.half_dtype)
        return fn(*args, **kwargs)

    wrapper.__amp_cast__ = "half"
    return wrapper


def float_function(fn: Callable) -> Callable:
    """Run ``fn`` in fp32 when autocast is active (softmax/log/loss class).

    Analog of ``apex.amp.float_function`` (``apex/amp/amp.py:60-62``).
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = _policy.get()
        if p is not None and p.enabled:
            args = _cast_tree(args, jnp.float32)
            kwargs = _cast_tree(kwargs, jnp.float32)
        return fn(*args, **kwargs)

    wrapper.__amp_cast__ = "float"
    return wrapper


def promote_function(fn: Callable) -> Callable:
    """Promote all floating args to the widest present dtype.

    Analog of ``apex.amp.promote_function`` (``apex/amp/amp.py:64-66``,
    promotion logic ``apex/amp/wrap.py:76-119``).
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = _policy.get()
        if p is not None and p.enabled:
            leaves = [
                x for x in jax.tree.leaves((args, kwargs))
                if hasattr(x, "dtype") and jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            ]
            if leaves:
                widest = functools.reduce(jnp.promote_types, [x.dtype for x in leaves])
                args = _cast_tree(args, widest)
                kwargs = _cast_tree(kwargs, widest)
        return fn(*args, **kwargs)

    wrapper.__amp_cast__ = "promote"
    return wrapper


def dtype_transparent(reason: str) -> Callable:
    """Mark an op as deliberately NOT cast under autocast.

    The reference puts softmax/norm/loss ops on FP32_FUNCS
    (``apex/amp/lists/functional_overrides.py:44-62``) because their CUDA
    kernels are precision-fragile in fp16. The apex_tpu equivalents
    upcast *internally* (stats/exp/log-sum-exp accumulate in fp32
    regardless of input dtype), so input casts would only add HBM
    round trips without changing numerics. This decorator records that
    audited decision on the function (``__amp_cast__ = "match_input"``)
    so the O1 coverage audit (`tests/test_amp.py`) can tell "deliberately
    transparent" from "forgot to register".
    """

    def deco(fn: Callable) -> Callable:
        fn.__amp_cast__ = "match_input"
        fn.__amp_cast_reason__ = reason
        return fn

    return deco


def _register(module, name, deco):
    fn = getattr(module, name)
    if getattr(fn, "__amp_cast__", None) is None:
        setattr(module, name, deco(fn))


def register_half_function(module, name: str):
    """``apex.amp.register_half_function`` parity (``apex/amp/amp.py:26-35``)."""
    _register(module, name, half_function)


def register_float_function(module, name: str):
    _register(module, name, float_function)


def register_promote_function(module, name: str):
    _register(module, name, promote_function)


# Functions banned under autocast for numerical-safety, mirroring apex's
# treatment of fp16 binary_cross_entropy (``apex/amp/lists/functional_overrides.py:63-77``).
def err_if_autocast(fn: Callable, name: str, hint: str) -> Callable:
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if autocast_enabled():
            leaves = [x for x in jax.tree.leaves((args, kwargs)) if hasattr(x, "dtype")]
            if any(jnp.asarray(x).dtype in (jnp.float16, jnp.bfloat16) for x in leaves):
                raise NotImplementedError(
                    f"amp does not work out-of-the-box with `{name}`; {hint}"
                )
        return fn(*args, **kwargs)

    return wrapper
