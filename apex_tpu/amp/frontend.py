"""amp frontend: ``initialize``, opt-level application, checkpoint state.

Reference: ``apex/amp/frontend.py:195-400`` + ``apex/amp/_initialize.py:145-263``.

Apex mutates a live torch model (casts modules, patches ``forward``,
monkey-patches the optimizer instance). The TPU-native translation keeps
the same *decision logic* (opt-level defaults + explicit-override
validation) but applies it functionally:

- ``initialize(model, optimizers, opt_level, ...)`` returns an
  :class:`AmpModel` wrapper (casts inputs/outputs, applies the O1 autocast
  policy around the forward) and the optimizer(s) with amp state attached
  (scaler + properties; our optimizers consult this in ``step`` for
  master-weight and skip-on-overflow behavior).
- parameter casting is explicit: ``amp_model.cast_params(params)`` —
  params are data in JAX, not module state.
- ``make_train_step`` builds the fully-jitted hot path (scale → grad →
  unscale → cond-skip step → scale update) with zero host syncs.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.amp import fp8 as _fp8_mod
from apex_tpu.amp import policy as _policy_mod
from apex_tpu.amp.lists import o1_interceptor
from apex_tpu.amp import scaler as _scaler_mod
from apex_tpu.amp._amp_state import _amp_state, maybe_print, warn_or_err
from apex_tpu.amp.properties import Properties, opt_levels
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.monitor import hooks as _mon
from apex_tpu.monitor import profile as _prof
from apex_tpu.utils.tree import cast_floating


def _is_norm_param(path_names: tuple[str, ...]) -> bool:
    """Name-based analog of ``isinstance(module, _BatchNorm)``
    (``apex/fp16_utils/fp16util.py:27-39``): flax/haiku BN scopes are named
    ``BatchNorm*`` / ``bn*`` / ``batch_stats``."""
    joined = "/".join(path_names).lower()
    return any(k in joined for k in ("batchnorm", "batch_norm", "batch_stats", "/bn", "bn_", "sync_bn", "syncbn"))


def _batch_stats_scopes(variables: Any) -> frozenset:
    """Scope paths that own running statistics — the STRUCTURAL
    ``isinstance(_BatchNorm)`` signal: every flax BatchNorm/SyncBatchNorm
    stores (mean, var) in the ``batch_stats`` collection under its own
    scope, whatever the user named it. Returns () when ``variables`` is
    a bare params tree (no collections to inspect)."""
    if not isinstance(variables, dict) or "batch_stats" not in variables:
        return frozenset()
    scopes = set()
    for path, _ in jax.tree_util.tree_flatten_with_path(
            variables["batch_stats"])[0]:
        names = tuple(str(getattr(p, "key", getattr(p, "name", p)))
                      for p in path)
        scopes.add(names[:-1])   # drop the (mean|var) leaf name
    return frozenset(scopes)


class AmpModel:
    """Forward-pass wrapper produced by :func:`initialize`.

    Mirrors the patched ``model.forward`` of ``apex/amp/_initialize.py:190-201``
    (cast inputs to the model dtype, optionally cast outputs) plus the O1
    autocast context. Callable as ``amp_model(params, *args, **kwargs)``
    where the underlying model is ``apply_fn(params, *args, **kwargs)``.
    """

    def __init__(self, apply_fn: Callable, properties: Properties,
                 keep_fp32_predicate: Callable | None = None):
        self.apply_fn = apply_fn
        self.properties = properties
        self._keep_fp32_is_default = keep_fp32_predicate is None
        self._keep_fp32 = keep_fp32_predicate or (
            (lambda names, x: not _is_norm_param(names))
            if properties.keep_batchnorm_fp32 else None
        )

    def cast_params(self, params: Any) -> Any:
        """Cast a parameter pytree per the opt level.

        O2/O3: floating leaves → half (batchnorm leaves exempt under O2,
        cf. ``convert_network`` ``apex/fp16_utils/fp16util.py:60``).
        O0: → fp32. O1: untouched (weights stay fp32; ops cast).

        BN detection is structural when possible: pass the FULL
        ``variables`` dict (with its ``batch_stats`` collection) and any
        scope owning running stats keeps fp32 params, whatever its name
        — the ``isinstance(_BatchNorm)`` guarantee. The name heuristic
        remains as a fallback for bare params trees, and an explicit
        ``keep_fp32_predicate`` overrides both.
        """
        ct = self.properties.cast_model_type
        if ct is None:
            return params
        keep = self._keep_fp32
        if (keep is not None and self._keep_fp32_is_default
                and self.properties.keep_batchnorm_fp32):
            bn_scopes = _batch_stats_scopes(params)
            if bn_scopes:
                def keep(names, x, _scopes=bn_scopes):
                    # names[0] is the collection ("params"/"batch_stats")
                    return not (names[1:-1] in _scopes
                                or _is_norm_param(names))
        return cast_floating(params, ct, keep)

    def init_fp8_state(self, sites) -> dict:
        """Fresh O4 delayed-scaling state: one
        :class:`~apex_tpu.amp.fp8.Fp8DotMeta` per named matmul site,
        with the opt level's ``fp8_history_len``. Valid on any opt
        level (the metas are inert unless the model routes matmuls
        through ``amp.fp8.fp8_matmul``)."""
        return _fp8_mod.init_state(
            sites, history_len=self.properties.fp8_history_len)

    def __call__(self, params, *args, **kwargs):
        p = self.properties
        if p.cast_model_type is not None and p.cast_model_type != jnp.float32:
            args = cast_floating(args, p.cast_model_type)
            kwargs = cast_floating(kwargs, p.cast_model_type)
        if p.cast_ops:
            # the autocast policy drives the apex_tpu op registry; the flax
            # interceptor gives default O1 coverage to arbitrary flax
            # modules (the reference's cast-lists, apex/amp/amp.py:68-177)
            with _policy_mod.autocast(True, p.half_dtype), \
                    nn.intercept_methods(o1_interceptor):
                out = self.apply_fn(params, *args, **kwargs)
        else:
            out = self.apply_fn(params, *args, **kwargs)
        if p.cast_model_outputs is not None:
            out = cast_floating(out, p.cast_model_outputs)
        elif p.cast_model_type is not None and p.cast_model_type != jnp.float32:
            # O2/O3 patched forward casts outputs back to fp32
            # (apex/amp/_initialize.py:198-201 applier(out, to_type(fp32)))
            out = cast_floating(out, jnp.float32)
        return out


class _AmpStash:
    """Attached to each optimizer, mirroring ``optimizer._amp_stash``
    (``apex/amp/_process_optimizer.py:324-339``)."""

    def __init__(self, properties: Properties, loss_scalers: list[LossScaler]):
        self.properties = properties
        self.loss_scalers = loss_scalers
        self.already_patched = True


def _wrap_zero(zero, model_list, opt_list, amp_model=None):
    """Wrap the (single) model in a :class:`ZeroShardedModel` and point
    each optimizer at it — ``zero.make_train_step`` defaults its model
    argument from ``opt._zero_model`` (the scaler rides the usual
    ``opt._amp_stash``)."""
    from apex_tpu.zero import ZeroShardedModel
    if len(model_list) != 1:
        raise ValueError(
            "initialize(zero=...) supports exactly one model (the "
            f"sharded parameter tree belongs to one forward); got "
            f"{len(model_list)}")
    if isinstance(zero, ZeroShardedModel):
        zm = zero
        zm.apply_fn = model_list[0]
    else:
        kw = {} if zero is True else dict(zero)
        zm = ZeroShardedModel(model_list[0], **kw)
    if amp_model is not None:
        # cast_params on the wrapper routes through the AmpModel's
        # opt-level cast (shard paths == param paths, so the
        # keep-fp32 predicates apply unchanged)
        zm._amp_model = amp_model
    for opt in opt_list:
        ax = getattr(opt, "axis_name", None)
        if ax is not None and ax != zm.axis_name:
            raise ValueError(
                f"initialize(zero=...): optimizer.axis_name={ax!r} does "
                f"not match the zero axis {zm.axis_name!r} — a mismatch "
                "silently degrades the shard update to world=1; construct "
                f"the optimizer with axis_name={zm.axis_name!r}")
        opt._zero_model = zm
    return zm


class _InertFp8Model:
    """The O4 face of ``initialize(enabled=False)``: a pass-through
    apply that still carries :meth:`init_fp8_state`, so the documented
    O4 recipe (``model.init_fp8_state(sites)`` → ``make_train_step
    (fp8=True)``) runs at full precision with unchanged call sites
    (``amp.fp8``'s primitives are inert under the same flag)."""

    def __init__(self, apply_fn, history_len: int):
        self._apply = apply_fn
        self._history_len = int(history_len)

    def __call__(self, params, *args, **kwargs):
        return self._apply(params, *args, **kwargs)

    def init_fp8_state(self, sites) -> dict:
        return _fp8_mod.init_state(sites, history_len=self._history_len)


def initialize(
    models,
    optimizers=None,
    enabled: bool = True,
    opt_level: str = "O1",
    *,
    half_dtype=None,
    cast_model_type=None,
    cast_ops=None,
    patch_torch_functions=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    cast_model_outputs=None,
    num_losses: int = 1,
    verbosity: int = 1,
    min_loss_scale: float | None = None,
    max_loss_scale: float = 2.0 ** 24,
    keep_fp32_predicate: Callable | None = None,
    zero=None,
    fp8_history_len: int | None = None,
    fp8_margin: float | None = None,
):
    """Initialize amp. Reference: ``amp.initialize`` ``apex/amp/frontend.py:195-358``.

    ``models``: an ``apply_fn(params, *args)``, an object with ``.apply``
    (flax ``nn.Module``), or a list of either. ``optimizers``: apex_tpu
    optimizer instance(s) (may be None for inference, frontend.py:298-306).

    Returns ``(models, optimizers)`` with the same list-ness as the inputs
    (frontend.py:342-358).

    ``zero=`` composes ZeRO-3 parameter sharding with the opt level
    (``apex_tpu.zero``; most useful under O2, where the fp32 master
    lives as the optimizer's shard): pass ``True`` (default rules), a
    kwargs dict for :class:`apex_tpu.zero.ZeroShardedModel` (``rules``,
    ``axis_name``, ``min_shard_size``, ``overlap_comm``), or a
    pre-built ``ZeroShardedModel``. The returned model is then that
    wrapper — ``model(shards, *args)`` materializes transiently and
    runs the amp-cast forward — and each optimizer learns the wrapper
    (``opt._zero_model``), which ``zero.make_train_step`` uses as its
    default model (the armed scaler rides ``opt._amp_stash`` as usual).
    Single model only (the sharded tree belongs to one forward).

    ``enabled=False`` renders amp inert (``apex/amp/frontend.py:195-215``):
    no casting, no scaler arming, and ``amp.scale_loss`` yields the loss
    unscaled — code written against the amp API runs at full precision
    with zero overhead. Models come back with the SAME calling
    convention as the enabled path (``fn(params, *args)``): a flax
    Module input returns its ``.apply`` rather than the unbound module,
    so ``m = initialize(module, ..., enabled=flag)`` is callable either
    way. ``zero=`` also survives disablement: the model still comes back
    as a :class:`~apex_tpu.zero.ZeroShardedModel` (full precision — no
    cast, no scaler arming) so FSDP code runs unchanged. Optimizers are
    otherwise returned untouched. ``enabled`` sits third positionally,
    exactly like the reference.
    """
    _amp_state.verbosity = verbosity
    if isinstance(enabled, str):
        # someone ported OUR pre-r5 positional order (opt_level third)
        raise TypeError(
            f"initialize() got {enabled!r} for 'enabled' (3rd positional "
            f"arg, matching apex). Pass opt_level as a keyword: "
            f"initialize(models, optimizers, opt_level={enabled!r})")
    if not enabled:
        _amp_state.enabled = False
        _amp_state.opt_properties = None
        _amp_state.loss_scalers = []
        # the fp8 (O4) surface survives disablement inert-but-present:
        # fp8_matmul degrades to the plain fp32-accumulated matmul and
        # update_state to the identity, so O4-written steps run at full
        # precision with unchanged signatures (the same class of
        # contract as the zero= wrapper surviving below — PR 6's
        # enabled=False wrapper-drop bug, now for fp8-meta callers)
        _fp8_mod.set_enabled(False)
        maybe_print("amp disabled (enabled=False): pass-through", True)

        def _plain(m):
            fn = m.apply if hasattr(m, "apply") else m
            if opt_level == "O4":
                # O4 callers are written against model.init_fp8_state
                # (docs/amp.md recipe) — returning the bare function
                # would crash them, the PR-6 wrapper-drop bug class.
                # Everything else keeps apex's unmodified-model parity.
                return _InertFp8Model(fn, fp8_history_len or 16)
            return fn
        if isinstance(models, (list, tuple)):
            out_models = type(models)(_plain(m) for m in models)
        else:
            out_models = _plain(models)
        if zero is not None and zero is not False:
            # amp is inert, but the zero= surface must survive: callers
            # are written against ZeroShardedModel (shard/materialize/
            # make_train_step), so wrap the plain apply with no amp cast
            # attached — full-precision FSDP, same calling convention.
            model_list = (list(out_models)
                          if isinstance(out_models, (list, tuple))
                          else [out_models])
            opt_list = (list(optimizers)
                        if isinstance(optimizers, (list, tuple))
                        else [optimizers] if optimizers is not None else [])
            zm = _wrap_zero(zero, model_list, opt_list)
            out_models = (type(models)([zm])
                          if isinstance(models, (list, tuple)) else zm)
        if optimizers is None:
            return out_models
        return out_models, optimizers
    _amp_state.enabled = True
    _fp8_mod.set_enabled(True)   # re-arm after any earlier enabled=False
    if patch_torch_functions is not None and cast_ops is None:
        # the reference's O1 knob name (apex/amp/frontend.py:201): there
        # is no torch namespace to patch on TPU — the equivalent scope
        # is the op-registry autocast, i.e. cast_ops
        cast_ops = patch_torch_functions
    if opt_level not in opt_levels:
        raise RuntimeError(f"Unexpected optimization level {opt_level}. Options are 'O0', 'O1', 'O2', 'O3', 'O4'.")

    properties = Properties()
    if half_dtype is not None:
        properties.half_dtype = half_dtype
    properties = opt_levels[opt_level](properties)
    maybe_print(f"Selected optimization level {opt_level}: {opt_levels[opt_level].brief}", True)

    # Explicit overrides win over opt-level defaults (frontend.py:336-356).
    overrides = dict(
        cast_model_type=cast_model_type,
        cast_ops=cast_ops,
        keep_batchnorm_fp32=keep_batchnorm_fp32,
        master_weights=master_weights,
        loss_scale=loss_scale,
        cast_model_outputs=cast_model_outputs,
        # the O4 delayed-scaling knobs (TE DelayedScaling's
        # amax_history_len / margin; live on any opt level, consumed by
        # init_fp8_state and make_train_step(fp8=True))
        fp8_history_len=fp8_history_len,
        fp8_margin=fp8_margin,
    )
    for k, v in overrides.items():
        if v is not None:
            maybe_print(f"Overriding {k}: {v}", True)
            setattr(properties, k, v)

    # Consistency checks analogous to Properties.__setattr__ validation
    # (apex/amp/frontend.py:40-97).
    if properties.keep_batchnorm_fp32 and properties.cast_model_type is None:
        warn_or_err("keep_batchnorm_fp32 only makes sense with a cast_model_type (O2/O3).")
    if properties.master_weights and properties.cast_model_type is None:
        warn_or_err("master_weights requires cast_model_type (O2).")
    if properties.cast_ops:
        maybe_print(
            "O1 scope: casts cover flax module calls (the default cast "
            "lists incl. apex_tpu layer classes), apex_tpu.ops, and "
            "functions you register — NOT raw jnp.*/lax.* calls in your "
            "own code (no patchable namespace in JAX; docs/amp.md). "
            "Raw-jnp models should use O2/O3 or amp.half_function.", True)

    _amp_state.opt_properties = properties

    models_was_list = isinstance(models, (list, tuple))
    model_list = list(models) if models_was_list else [models]
    amp_models = []
    for m in model_list:
        apply_fn = m.apply if hasattr(m, "apply") else m  # flax Module or callable
        amp_models.append(AmpModel(apply_fn, properties, keep_fp32_predicate))

    scalers = [
        LossScaler(
            properties.loss_scale,
            min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale,
        )
        for _ in range(num_losses)
    ]
    _amp_state.loss_scalers = scalers

    opts_was_list = isinstance(optimizers, (list, tuple))
    opt_list = list(optimizers) if opts_was_list else ([optimizers] if optimizers is not None else [])
    for opt in opt_list:
        opt._amp_stash = _AmpStash(properties, scalers)
        if hasattr(opt, "configure_amp"):
            opt.configure_amp(properties, scalers[0])

    if zero is not None and zero is not False:
        amp_models = [_wrap_zero(zero, amp_models, opt_list,
                                 amp_model=amp_models[0])]

    out_models = amp_models if models_was_list else amp_models[0]
    if optimizers is None:
        return out_models
    out_opts = opt_list if opts_was_list else opt_list[0]
    return out_models, out_opts


# ---------------------------------------------------------------------------
# Checkpointing: amp.state_dict / amp.load_state_dict
# (apex/amp/frontend.py:361-400 — serializes every loss scaler's scale and
# unskipped count)
# ---------------------------------------------------------------------------

def master_state_dict(optimizer, opt_state, params=None):
    """fp32 model checkpoint under O2 (``O2StateDictHook`` analog,
    ``apex/amp/_initialize.py:133-142``): always returns fp32 parameters,
    read from the optimizer's master buffer when present."""
    return optimizer.master_params(opt_state, params)


def load_master_state_dict(optimizer, opt_state, fp32_params):
    """Restore an fp32 checkpoint: ``(model_params, opt_state)`` with
    params recast to their run dtypes and the master replaced bitwise."""
    return optimizer.restore_master(opt_state, fp32_params)


def state_dict(destination: dict | None = None) -> dict:
    """``destination`` fills a caller-supplied dict, like the reference
    (``apex/amp/frontend.py:361-372``)."""
    d = {} if destination is None else destination
    for i, s in enumerate(_amp_state.loss_scalers):
        d[f"loss_scaler{i}"] = s.state_dict()
    return d


def load_state_dict(state_dict: dict):
    sd = state_dict
    if len(sd) != len(_amp_state.loss_scalers):
        maybe_print(
            f"Warning: state_dict has {len(sd)} entries but amp has "
            f"{len(_amp_state.loss_scalers)} scalers", True)
    for key, v in sd.items():
        idx = int(key.replace("loss_scaler", ""))
        if idx < len(_amp_state.loss_scalers):
            _amp_state.loss_scalers[idx].load_state_dict(v)


# ---------------------------------------------------------------------------
# The fully-jitted hot path (SURVEY §7 hard-parts: dynamic loss scaling
# under jit with zero per-step host syncs).
# ---------------------------------------------------------------------------

def make_train_step(
    loss_fn: Callable,
    optimizer,
    *,
    scaler: LossScaler | None = None,
    has_aux: bool = False,
    grad_dtype=jnp.float32,
    donate: bool = True,
    fp8: bool = False,
    fp8_margin: float | None = None,
):
    """Build a jitted training step with amp semantics.

    ``loss_fn(params, *batch) -> loss`` (or ``(loss, aux)``). ``optimizer``
    is an apex_tpu fused optimizer (functional core: ``init``/``apply``).

    The returned ``step(params, opt_state, scaler_state, *batch)`` performs
    the whole of apex's hot loop (``apex/amp/handle.py:16-158`` +
    ``_process_optimizer.py:161-202``): scaled-loss grad, unscale with
    overflow detect, conditional skip of the optimizer step on overflow
    (apex patches ``optimizer.step`` to a no-op; here it is a ``jnp.where``
    on the update), and dynamic scale update — all inside one XLA program.

    ``fp8=True`` (the O4 hot loop): ``loss_fn(params, fp8_state, *batch)``
    and the step becomes ``step(params, opt_state, scaler_state,
    fp8_state, *batch)`` returning the updated fp8 state fourth — the
    delayed-scaling amax tree is threaded and DONATED alongside the
    scaler state. The gradient pass records every fp8 tensor's amax as
    the cotangent of its meta (``amp.fp8`` module doc), the step applies
    the delayed-scaling update, and an overflow skip leaves the amax
    history bitwise untouched (an inf backward must not enter the
    statistics — the same contract as the O2 master-weight skip).
    ``fp8_margin`` defaults from the optimizer's amp properties
    (``Properties.fp8_margin``, settable via ``initialize``), else 0.

    The monitoring guard rides along as a static jit argument (a bool:
    is a traced-hooks recorder attached?): attaching or detaching a
    ``apex_tpu.monitor`` recorder switches between exactly two cached
    programs — instrumented and uninstrumented — so each flip costs at
    most one trace and repeated attach/detach cycles never grow the
    cache. Device telemetry routes to whichever recorder is attached
    when a step *runs*; trace-time accounting (collective counts) lands
    in the recorder attached when the instrumented variant was first
    traced.
    """
    scaler = scaler or (optimizer._amp_stash.loss_scalers[0]
                        if hasattr(optimizer, "_amp_stash") else LossScaler(1.0))

    if fp8:
        return _make_fp8_train_step(loss_fn, optimizer, scaler,
                                    has_aux=has_aux, grad_dtype=grad_dtype,
                                    donate=donate, fp8_margin=fp8_margin)
    if fp8_margin is not None:
        raise ValueError(
            "make_train_step: fp8_margin is only meaningful with "
            "fp8=True (the O4 delayed-scaling step); without it the "
            "margin would be silently ignored")

    def scaled_loss_fn(params, scaler_state, *batch):
        out = loss_fn(params, *batch)
        loss, aux = (out if has_aux else (out, None))
        return _scaler_mod.scale_value(loss, scaler_state), (loss, aux)

    grad_fn = jax.grad(scaled_loss_fn, has_aux=True)

    def step(_mon_on, params, opt_state, scaler_state: ScalerState,
             *batch):
        # profile scopes (monitor.profile): metadata-only tags — the
        # jaxpr is byte-identical with or without them — that make the
        # whole hot loop attributable per phase in the per-module table
        with _prof.scope("amp_grad"):
            grads, (loss, aux) = grad_fn(params, scaler_state, *batch)
        with _prof.scope("amp_unscale"):
            grads, found_inf = _scaler_mod.unscale(
                grads, scaler_state, out_dtype=grad_dtype)
        with _prof.scope("amp_optimizer"):
            new_params, new_opt_state = optimizer.apply(
                opt_state, params, grads, skip=found_inf
            )
        with _prof.scope("amp_scaler"):
            new_scaler_state = scaler.update_state(scaler_state, found_inf)
        outs = (new_params, new_opt_state, new_scaler_state, loss)
        return outs + ((aux,) if has_aux else ())

    jitted = jax.jit(step, static_argnums=(0,),
                     donate_argnums=(1, 2, 3) if donate else ())

    @functools.wraps(step)
    def run(params, opt_state, scaler_state: ScalerState, *batch):
        return jitted(_mon.traced_enabled(), params, opt_state,
                      scaler_state, *batch)

    run._jitted = jitted   # escape hatch: .lower()/.trace() on the inner fn
    return run


def _make_fp8_train_step(loss_fn, optimizer, scaler, *, has_aux,
                         grad_dtype, donate, fp8_margin):
    """The O4 variant of the hot loop (see :func:`make_train_step`,
    ``fp8=True``): one ``jax.grad`` over ``(params, fp8_state)`` yields
    the parameter grads AND the recorded amaxes, so the whole
    scale → grad → unscale → cond-skip → delayed-scaling-update →
    scale-update pipeline is still a single XLA program with zero host
    syncs."""
    if fp8_margin is None:
        stash = getattr(optimizer, "_amp_stash", None)
        fp8_margin = (stash.properties.fp8_margin if stash is not None
                      else 0.0)

    def scaled_loss_fn(params, fp8_state, scaler_state, *batch):
        out = loss_fn(params, fp8_state, *batch)
        loss, aux = (out if has_aux else (out, None))
        return _scaler_mod.scale_value(loss, scaler_state), (loss, aux)

    grad_fn = jax.grad(scaled_loss_fn, argnums=(0, 1), has_aux=True)

    def step(_mon_on, params, opt_state, scaler_state: ScalerState,
             fp8_state, *batch):
        with _prof.scope("amp_grad"):
            (grads, recorded), (loss, aux) = grad_fn(
                params, fp8_state, scaler_state, *batch)
        with _prof.scope("amp_unscale"):
            grads, found_inf = _scaler_mod.unscale(grads, scaler_state,
                                                   out_dtype=grad_dtype)
        with _prof.scope("amp_optimizer"):
            new_params, new_opt_state = optimizer.apply(
                opt_state, params, grads, skip=found_inf
            )
        updated = _fp8_mod.update_state(fp8_state, recorded,
                                        margin=fp8_margin)
        # overflow: the recorded amaxes came from an inf/nan backward —
        # keep the history bitwise untouched (the O2 master-skip
        # contract, tests/test_fp8.py)
        new_fp8 = jax.tree.map(
            lambda new, old: jnp.where(found_inf, old, new),
            updated, fp8_state)
        new_scaler_state = scaler.update_state(scaler_state, found_inf)
        outs = (new_params, new_opt_state, new_scaler_state, new_fp8, loss)
        return outs + ((aux,) if has_aux else ())

    jitted = jax.jit(step, static_argnums=(0,),
                     donate_argnums=(1, 2, 3, 4) if donate else ())

    @functools.wraps(step)
    def run(params, opt_state, scaler_state: ScalerState, fp8_state,
            *batch):
        return jitted(_mon.traced_enabled(), params, opt_state,
                      scaler_state, fp8_state, *batch)

    run._jitted = jitted
    return run
