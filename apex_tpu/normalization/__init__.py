"""apex_tpu.normalization — FusedLayerNorm / FusedRMSNorm modules.

Reference: ``apex/normalization/__init__.py`` (FusedLayerNorm,
MixedFusedLayerNorm).
"""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    MixedFusedLayerNorm,
    FusedRMSNorm,
    MixedFusedRMSNorm,
)
