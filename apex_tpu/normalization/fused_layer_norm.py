"""FusedLayerNorm / MixedFusedLayerNorm flax modules.

Reference: ``apex/normalization/fused_layer_norm.py:102-219`` —
``FusedLayerNorm`` mirrors ``torch.nn.LayerNorm`` backed by the fused
kernel (CPU fallback to unfused math, :147-151 — here the jnp form under
jit IS the fused form; a hand-written Pallas LN measured no faster, see
``ops/layer_norm.py``);
``MixedFusedLayerNorm`` (:202) keeps params in the input dtype so output
dtype == param dtype (Megatron-compatible).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
)


def _as_shape(normalized_shape) -> tuple[int, ...]:
    if isinstance(normalized_shape, int):
        return (normalized_shape,)
    return tuple(normalized_shape)


class FusedLayerNorm(nn.Module):
    normalized_shape: Sequence[int] | int
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32
    # output dtype override; None = param_dtype. Set to the compute dtype
    # (e.g. bf16) to get bf16 in -> bf16 out with fp32 params and no
    # call-site casts.
    dtype: jnp.dtype | None = None
    # Pallas-kernel resolution for the affine path (ops/layer_norm.py):
    # explicit block_r > tuned cache (per `autotune` policy) > jnp shim.
    # Defaults leave callers bit-for-bit on the pre-kernel program.
    autotune: str | None = None
    block_r: int | None = None

    @nn.compact
    def __call__(self, x):
        shape = _as_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param(
                "weight", nn.initializers.ones, shape, self.param_dtype)
            bias = self.param(
                "bias", nn.initializers.zeros, shape, self.param_dtype)
            return fused_layer_norm_affine(x, weight, bias, shape, self.eps,
                                           self.dtype, block_r=self.block_r,
                                           autotune=self.autotune)
        y = fused_layer_norm(x, shape, self.eps)
        return y if self.dtype is None else y.astype(self.dtype)


class MixedFusedLayerNorm(FusedLayerNorm):
    """Params stored in (and output cast to) the compute dtype — the
    ``memory_efficient``/mixed-dtype Megatron variant
    (``apex/normalization/fused_layer_norm.py:202-219``)."""

    @nn.compact
    def __call__(self, x):
        shape = _as_shape(self.normalized_shape)
        weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, shape, self.param_dtype)
        # inherited `dtype` still overrides the output (x.dtype otherwise)
        return fused_layer_norm_affine(
            x, weight.astype(x.dtype), bias.astype(x.dtype), shape, self.eps,
            self.dtype, block_r=self.block_r, autotune=self.autotune)


class FusedRMSNorm(nn.Module):
    """RMSNorm module (upstream apex ``FusedRMSNorm`` API parity)."""

    normalized_shape: Sequence[int] | int
    eps: float = 1e-5
    elementwise_affine: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _as_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            return fused_rms_norm_affine(x, weight, shape, self.eps)
        return fused_rms_norm(x, shape, self.eps)


class MixedFusedRMSNorm(FusedRMSNorm):
    @nn.compact
    def __call__(self, x):
        shape = _as_shape(self.normalized_shape)
        weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
        return fused_rms_norm_affine(x, weight.astype(x.dtype), shape, self.eps)

# O1 default-cast coverage: norms are FP32-class under autocast (the
# reference's FP32_FUNCS row) — inputs cast up, compute dtype pinned fp32.
from apex_tpu.amp import lists as _amp_lists  # noqa: E402
_amp_lists.register_float_module(FusedLayerNorm)
_amp_lists.register_float_module(FusedRMSNorm)
