"""Per-op trace tables — re-export shim over
:mod:`apex_tpu.monitor.xprof` (the implementation's new home).

Kept so the reference-shaped ``pyprof.parse.op_stats`` pipeline (and
its callers in bench/tests/docs) keep working; new code should import
``apex_tpu.monitor.xprof`` directly or use the CLI
``python -m apex_tpu.monitor report``.
"""

from apex_tpu.monitor.xprof import (  # noqa: F401
    _COLUMNS, _gviz_tables, _xplane_paths, format_table, op_stats,
    op_stats_from_raw, top_ops)
