"""Trace annotations (the NVTX analog).

Reference: ``apex/pyprof/nvtx/nvmarker.py`` monkey-patches every torch
function to push an NVTX range encoding op + args + call stack. JAX
equivalence: ``jax.named_scope`` tags the HLO (visible in XProf per-op),
``jax.profiler.TraceAnnotation`` tags host timeline ranges; ``wrap``
decorates any callable with both, including arg shapes like the
reference's marker payload.
"""

from __future__ import annotations

import contextlib
import functools
import json

import jax


def init(enable: bool = True):
    """Parity shim for ``pyprof.nvtx.init()``: JAX needs no global
    patching — annotation is opt-in via :func:`annotate`/:func:`wrap`."""
    return enable


@contextlib.contextmanager
def annotate(name: str, **metadata):
    """Named range visible in the XProf host timeline and HLO op names."""
    payload = name if not metadata else f"{name}|{json.dumps(metadata, default=str)}"
    with jax.profiler.TraceAnnotation(payload):
        with jax.named_scope(name):
            yield


def _describe_args(args, kwargs):
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return f"{x.dtype}{list(x.shape)}"
        return type(x).__name__
    return {
        "args": [one(a) for a in args],
        "kwargs": {k: one(v) for k, v in kwargs.items()},
    }


def wrap(fn, name: str | None = None):
    """Decorate ``fn`` with an annotation carrying the op name and arg
    shapes (the ``add_wrapper`` payload, ``nvmarker.py:206``)."""
    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with annotate(label, **_describe_args(args, kwargs)):
            return fn(*args, **kwargs)

    return wrapper
