"""Trace annotations (the NVTX analog) — re-export shim.

The implementation moved to :mod:`apex_tpu.monitor.trace` (the monitor
subsystem's trace layer subsumes pyprof); ``init``/``annotate``/``wrap``
keep the reference parity API (``apex/pyprof/nvtx/nvmarker.py``).
"""

from apex_tpu.monitor.trace import annotate, init, wrap  # noqa: F401
