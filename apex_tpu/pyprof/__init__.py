"""apex_tpu.pyprof — profiling/annotation layer on jax.profiler + XLA.

Reference: ``apex/pyprof`` (deprecated in apex) — three parts:
``nvtx`` (annotate every op with name/args/callstack,
``apex/pyprof/nvtx/nvmarker.py:67-108,206``), ``parse`` (read the nvprof
SQLite DB), ``prof`` (map kernels to op semantics, compute FLOPs/bytes,
``apex/pyprof/prof/*.py``).

TPU mapping: annotation = ``jax.profiler`` trace annotations (visible in
TensorBoard/XProf, replacing NVTX); parse/prof = XLA's own cost analysis
on the compiled executable (FLOPs/bytes per program without re-deriving
them from kernel names).
"""

from apex_tpu.pyprof.nvtx import annotate, init, wrap  # noqa: F401
from apex_tpu.pyprof.prof import cost_analysis, flop_report, trace  # noqa: F401
from apex_tpu.pyprof import parse  # noqa: F401
from apex_tpu.pyprof.parse import format_table, op_stats, top_ops  # noqa: F401
