"""apex_tpu.pyprof — parity shim over :mod:`apex_tpu.monitor`.

Reference: ``apex/pyprof`` (deprecated in apex) — ``nvtx`` (annotate
ops), ``parse`` (read the nvprof SQLite DB), ``prof`` (map kernels to
op semantics with FLOPs/bytes). The implementations now live in the
monitor subsystem, which extends them with recorder-integrated
telemetry (docs/observability.md); this package re-exports the historic
names so ported code and the parity API keep working:

- ``pyprof.annotate/init/wrap``      → ``monitor.trace``
- ``pyprof.trace/cost_analysis/flop_report`` → ``monitor.trace``
- ``pyprof.parse`` (op_stats, top_ops, format_table) → ``monitor.xprof``

The per-step training report the reference's ``pyprof.prof`` CLI
approximated per-kernel is now ``python -m apex_tpu.monitor report``.
"""

from apex_tpu.monitor.trace import annotate, init, wrap  # noqa: F401
from apex_tpu.monitor.trace import cost_analysis, flop_report, trace  # noqa: F401
from apex_tpu.pyprof import nvtx  # noqa: F401
from apex_tpu.pyprof import parse  # noqa: F401
from apex_tpu.pyprof import prof  # noqa: F401
from apex_tpu.monitor.xprof import format_table, op_stats, top_ops  # noqa: F401
