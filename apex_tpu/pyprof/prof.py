"""FLOPs/bytes accounting — re-export shim over
:mod:`apex_tpu.monitor.trace` (the implementation's new home).

``cost_analysis``/``flop_report`` ask the compiled executable for XLA's
own cost analysis (exact post-fusion, unlike the reference's name-based
reconstruction); ``trace`` captures an XProf session.
"""

from apex_tpu.monitor.trace import cost_analysis, flop_report, trace  # noqa: F401
