"""FLOPs/bytes accounting from XLA (the ``pyprof.prof`` analog).

Reference: ``apex/pyprof/prof/*.py`` reconstructs per-kernel FLOPs and
bytes from parsed nvprof records with one class per op family. XLA
already computes this during compilation, so the TPU version just asks
the compiled executable — exact for the program actually run, including
fusion (which the reference's name-based reconstruction cannot see).
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable

import jax


def cost_analysis(fn: Callable, *args, **kwargs) -> dict:
    """Compile ``fn`` and return XLA's cost analysis dict
    (``flops``, ``bytes accessed``, per-memory-space breakdowns)."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def flop_report(fn: Callable, *args, step_time_s: float | None = None,
                peak_flops: float | None = None, **kwargs) -> dict:
    """FLOPs/bytes + arithmetic intensity (+ MFU when timings given) —
    the summary ``pyprof.prof`` prints per kernel, at whole-program
    granularity."""
    ca = cost_analysis(fn, *args, **kwargs)
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    rep = {
        "flops": flops,
        "bytes_accessed": byts,
        "arithmetic_intensity": flops / byts if byts else float("inf"),
    }
    if step_time_s:
        rep["achieved_flops_per_s"] = flops / step_time_s
        if peak_flops:
            rep["mfu"] = flops / step_time_s / peak_flops
    return rep


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture an XProf trace of the block (the nvprof-session analog);
    view with TensorBoard's profile plugin."""
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
