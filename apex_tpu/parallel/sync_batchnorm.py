"""Synchronized BatchNorm over mesh collectives.

Reference: ``apex/parallel/optimized_sync_batchnorm.py:9-85`` +
``optimized_sync_batchnorm_kernel.py:7-119`` (CUDA Welford local stats,
``all_gather`` + parallel Welford merge across processes, hand-written
backward allreducing ``sum_dy``/``sum_dy_xmu``) and the python fallback
(``apex/parallel/sync_batchnorm.py:9``).

TPU design: local (sum, sumsq, count) in fp32 are ``psum``-merged over the
``axis_name`` (count-weighted — supports different per-device batch sizes,
cf. ``tests/distributed/synced_batchnorm/two_gpu_test_different_batch_size.py``).
The backward needs no hand-written kernel: JAX differentiates through the
collectives, producing exactly the reference's allreduced
``sum_dy``/``sum_dy_xmu`` terms. "Process groups"
(``apex/parallel/__init__.py:58-97``) map to ``axis_index_groups`` of the
psum, so BN can sync over sub-groups of the data axis.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from apex_tpu._compat import axis_size as _axis_size


def create_syncbn_process_group(group_size: int, world_size: int):
    """Partition ``world_size`` devices into contiguous groups of
    ``group_size`` for grouped-BN sync — returns ``axis_index_groups`` for
    ``lax.psum`` (reference: ``apex/parallel/__init__.py:58-97`` builds one
    NCCL group per partition)."""
    if group_size == 0 or group_size == world_size:
        return None
    if world_size % group_size != 0:
        raise ValueError("world_size must be divisible by group_size")
    return [
        list(range(i, i + group_size)) for i in range(0, world_size, group_size)
    ]


def _grouped_psum(x, axis_name, groups):
    """psum over ``axis_name``, optionally restricted to index groups.

    Implemented as all_gather + a static membership mask so it works under
    ``shard_map`` on every backend (grouped ``psum`` lowering is not
    universally available) and stays differentiable.
    """
    if groups is None:
        return jax.lax.psum(x, axis_name)
    world = _axis_size(axis_name)
    gathered = jax.lax.all_gather(x, axis_name)          # [world, ...]
    import numpy as np
    m = np.zeros((world, world), np.float32)
    for g in groups:
        for i in g:
            for j in g:
                m[i, j] = 1.0
    row = jnp.asarray(m)[jax.lax.axis_index(axis_name)]  # [world]
    return jnp.tensordot(row, gathered.astype(jnp.float32), axes=1)


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm that reduces statistics across a mesh axis.

    Mirrors the reference module args (``optimized_sync_batchnorm.py:9``):
    ``momentum`` uses the torch convention (new = (1-m)*old + m*batch),
    ``channel_last`` is the natural JAX layout (feature axis = -1).
    ``axis_name=None`` degrades to ordinary BatchNorm (single process,
    like the reference outside distributed mode).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = "data"
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None  # process_group analog
    fuse_relu: bool = False   # reference's fuse_relu variant (syncbn ext)
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, z=None, use_running_average: bool = False):
        """``z``: optional residual added before the (optional) fused relu —
        the ``bn_add_relu`` fusion of the group-BN extension
        (``apex/contrib/csrc/groupbn/interface.cpp``)."""
        c = self.num_features
        if x.shape[-1] != c:
            raise ValueError(f"expected feature axis -1 of size {c}, got {x.shape}")

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            x32 = x.astype(jnp.float32)
            red_axes = tuple(range(x.ndim - 1))
            local_count = jnp.asarray(
                jnp.prod(jnp.asarray([x.shape[a] for a in red_axes])), jnp.float32)
            local_sum = jnp.sum(x32, axis=red_axes)
            local_sumsq = jnp.sum(x32 * x32, axis=red_axes)
            in_mapped_ctx = True
            if self.axis_name is not None:
                try:
                    _axis_size(self.axis_name)
                except NameError:
                    in_mapped_ctx = False  # e.g. Module.init outside shard_map
            if self.axis_name is not None and in_mapped_ctx:
                # count-weighted cross-device merge == parallel Welford
                # combine (welford.cu:566-600) in fp32
                stats = jnp.concatenate(
                    [local_sum, local_sumsq, local_count[None]])
                stats = _grouped_psum(stats, self.axis_name, self.axis_index_groups)
                g_sum, g_sumsq, g_count = (
                    stats[:c], stats[c:2 * c], stats[2 * c])
            else:
                g_sum, g_sumsq, g_count = local_sum, local_sumsq, local_count
            mean = g_sum / g_count
            var = g_sumsq / g_count - mean * mean  # biased, like BN training

            if self.track_running_stats and not self.is_initializing():
                # unbiased var for running stats (torch semantics)
                unbiased = var * g_count / jnp.maximum(g_count - 1.0, 1.0)
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * jax.lax.stop_gradient(mean)
                ra_var.value = (1 - m) * ra_var.value + m * jax.lax.stop_gradient(unbiased)

        inv = jax.lax.rsqrt(var + self.eps)
        y = (x.astype(jnp.float32) - mean) * inv
        if self.affine:
            weight = self.param("weight", nn.initializers.ones, (c,), self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, (c,), self.param_dtype)
            y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
        if z is not None:
            y = y + z.astype(jnp.float32)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(x.dtype)


def convert_syncbn_model(module, process_group=None, channel_last=True):
    """Swap ``nn.BatchNorm``-typed dataclass fields for :class:`SyncBatchNorm`.

    Reference: ``apex/parallel/__init__.py:21-56`` recursively replaces
    ``_BatchNorm`` children. Flax modules declared inline in ``@nn.compact``
    cannot be swapped post-hoc; apex_tpu models therefore take a
    ``norm`` factory argument (see ``apex_tpu.models``) and this converter
    handles the dataclass-field case plus returns a factory for compact use.
    """
    import dataclasses

    if module is None or module is nn.BatchNorm:
        def factory(num_features, **kw):
            return SyncBatchNorm(num_features=num_features,
                                 axis_index_groups=process_group, **kw)
        return factory

    if dataclasses.is_dataclass(module):
        changes = {}
        for f in dataclasses.fields(module):
            v = getattr(module, f.name, None)
            if isinstance(v, nn.BatchNorm):
                changes[f.name] = SyncBatchNorm(
                    num_features=v.num_features if hasattr(v, "num_features") else 0,
                    momentum=1.0 - v.momentum if hasattr(v, "momentum") else 0.1,
                    eps=v.epsilon if hasattr(v, "epsilon") else 1e-5,
                    axis_index_groups=process_group)
            elif isinstance(v, nn.Module):
                changes[f.name] = convert_syncbn_model(v, process_group)
        if changes:
            return module.replace(**changes) if hasattr(module, "replace") else module
    return module

# O1 default-cast coverage: BN runs fp32 under autocast (FP32_FUNCS row).
from apex_tpu.amp import lists as _amp_lists  # noqa: E402
_amp_lists.register_float_module(SyncBatchNorm)
