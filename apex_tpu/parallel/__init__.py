"""apex_tpu.parallel — data parallelism + synchronized BatchNorm on a mesh.

Reference: ``apex/parallel/__init__.py`` (DistributedDataParallel,
Reducer, SyncBatchNorm, convert_syncbn_model, LARC, ReduceOp re-export).
"""

from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    Reducer,
    allreduce_gradients,
    flat_dist_call,
)
from apex_tpu.parallel.overlap import (  # noqa: F401
    accumulate_gradients,
    all_gather_matmul,
    bucketed_allreduce,
    matmul_reduce_scatter,
)
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
    create_syncbn_process_group,
)
from apex_tpu.optimizers.larc import LARC  # noqa: F401
from apex_tpu.parallel.multiproc import init_distributed  # noqa: F401


class ReduceOp:
    """Mesh-collective reduce-op names (parity with the
    ``torch.distributed.ReduceOp`` re-export, ``apex/parallel/__init__.py:3-8``)."""

    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"
