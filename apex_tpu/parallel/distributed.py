"""Data-parallel gradient synchronization over mesh collectives.

Reference: ``apex/parallel/distributed.py:129-639`` — a module wrapper
installing per-param backward hooks that greedily bucket gradients and
allreduce each bucket on side CUDA streams, with options for predivision,
fp32 allreduce, and delayed (accumulation-friendly) allreduce.

TPU-native translation: gradient exchange is a ``psum`` over a named mesh
axis. Bucketing/streams/hook ordering disappear — XLA's latency-hiding
scheduler overlaps the (single, fused) collective with computation, which
is the *policy outcome* apex's machinery hand-builds. What survives is the
**option surface** (``apex/parallel/distributed.py:129-170``):

- ``gradient_average``          → divide by world size after the sum
- ``gradient_predivide_factor`` → divide by f before, world/f after (:247)
- ``allreduce_always_fp32``     → cast grads to fp32 for the reduction (:245)
- ``delay_allreduce``           → skip sync (gradient accumulation), call
  the sync explicitly at the end — here just: don't call it.

Use inside ``shard_map``/``pmap`` (axis must exist), or rely on GSPMD
(sharded batch + replicated params makes XLA insert the same reduction
automatically — the zero-code path recommended for new code).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.monitor import hooks as _mon
from apex_tpu.utils.flat import flatten_tensors, unflatten_tensors
from apex_tpu.utils.parity import warn_inert_once as _warn_inert_once
from apex_tpu._compat import axis_size as _axis_size


def allreduce_gradients(
    grads: Any,
    axis_name: str = "data",
    *,
    gradient_average: bool = True,
    allreduce_always_fp32: bool = False,
    gradient_predivide_factor: float = 1.0,
) -> Any:
    """psum a gradient pytree over ``axis_name`` with apex's scaling options
    (``apex/parallel/distributed.py:425-468`` allreduce_bucket +
    allreduce_maybe_retain)."""
    world = _axis_size(axis_name)
    if _mon.traced_enabled():
        # trace-time accounting: one psum per floating leaf (XLA may
        # fuse them, but the wire volume is the same), sized at the
        # dtype actually reduced — allreduce_always_fp32 upcasts bf16/
        # fp16 leaves before the collective, doubling their bytes
        floats = [g for g in jax.tree.leaves(grads)
                  if jnp.issubdtype(g.dtype, jnp.floating)]
        if allreduce_always_fp32:
            nbytes = sum(g.size * 4 for g in floats)
        else:
            nbytes = _mon.tree_bytes(floats)
        _mon.collective("psum", axis_name, nbytes=nbytes,
                        count=len(floats))

    def _one(g):
        if not jnp.issubdtype(g.dtype, jnp.floating):
            return g
        orig = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            post = world / gradient_predivide_factor if gradient_predivide_factor != 1.0 else world
            g = g / post
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        return g.astype(orig)

    return jax.tree.map(_one, grads)


def flat_dist_call(tensors: Sequence[jax.Array], op: Callable, axis_name: str = "data"):
    """Flatten → one collective → unflatten
    (``apex/parallel/distributed.py:36-75``). ``op`` is e.g.
    ``lambda t: jax.lax.psum(t, axis_name)``."""
    flat = flatten_tensors(list(tensors))
    if _mon.traced_enabled():
        # one fused collective over the flat buffer; op is opaque, so
        # account it under its own name rather than guessing psum
        _mon.collective("flat_dist_call", axis_name,
                        nbytes=_mon.tree_bytes(flat), count=1)
    flat = op(flat)
    return unflatten_tensors(flat, list(tensors))


class DistributedDataParallel:
    """Wrapper giving the apex DDP call shape on top of mesh collectives.

    ``ddp = DistributedDataParallel(amp_model_or_apply_fn, ...)`` then
    inside a shard_mapped/pmapped step: ``out = ddp(params, x)`` and
    ``grads = ddp.sync(grads)``. ``delay_allreduce=True`` makes ``sync`` a
    no-op until ``ddp.flush(grads)`` is called (gradient accumulation,
    ``apex/parallel/distributed.py:161,559-607``).
    """

    def __init__(self, module: Callable, axis_name: str = "data",
                 message_size: int = 10_000_000, delay_allreduce: bool = False,
                 shared_param: bool | None = None, allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators=None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 gradient_average_split_factor=None,
                 prof: bool = False):
        self.module = module
        self.axis_name = axis_name
        self.delay_allreduce = delay_allreduce
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        # message_size / streams / communicators are accepted for API
        # parity; XLA owns fusion & overlap of the collective on TPU.
        # Ported code deserves a one-time heads-up when it sets them to
        # non-defaults expecting CUDA-stream behavior.
        inert = []
        if message_size != 10_000_000:
            inert.append(f"message_size={message_size}")
        if num_allreduce_streams != 1:
            inert.append(f"num_allreduce_streams={num_allreduce_streams}")
        if allreduce_communicators is not None:
            inert.append("allreduce_communicators")
        if gradient_average_split_factor is not None:
            # legacy knob (apex/parallel/distributed.py): split the
            # average across the two allreduce halves — no split halves
            # exist here, psum + one scale is exact
            inert.append("gradient_average_split_factor="
                         f"{gradient_average_split_factor}")
        if inert:
            _warn_inert_once(
                "DistributedDataParallel: "
                + ", ".join(inert)
                + " accepted for API parity but a no-op on TPU (XLA "
                "fuses, buckets and overlaps the gradient all-reduce "
                "itself; there are no CUDA streams or NCCL "
                "communicators to configure)")

    def __call__(self, params, *args, **kwargs):
        return self.module(params, *args, **kwargs)

    def sync(self, grads):
        if self.delay_allreduce:
            return grads
        return self.flush(grads)

    def flush(self, grads):
        return allreduce_gradients(
            grads, self.axis_name,
            gradient_average=self.gradient_average,
            allreduce_always_fp32=self.allreduce_always_fp32,
            gradient_predivide_factor=self.gradient_predivide_factor)


class Reducer:
    """Manual-sync variant (``apex/parallel/distributed.py:89-127``): user
    calls ``reducer.reduce(params_or_grads)`` when desired."""

    def __init__(self, module_or_grads_list=None, axis_name: str = "data"):
        self.axis_name = axis_name

    def reduce(self, tree):
        world = _axis_size(self.axis_name)
        if _mon.traced_enabled():
            floats = [g for g in jax.tree.leaves(tree)
                      if jnp.issubdtype(g.dtype, jnp.floating)]
            _mon.collective("psum", self.axis_name,
                            nbytes=_mon.tree_bytes(floats),
                            count=len(floats))
        return jax.tree.map(
            lambda g: jax.lax.psum(g, self.axis_name) / world
            if jnp.issubdtype(g.dtype, jnp.floating) else g, tree)
