"""Data-parallel gradient synchronization over mesh collectives.

Reference: ``apex/parallel/distributed.py:129-639`` — a module wrapper
installing per-param backward hooks that greedily bucket gradients and
allreduce each bucket on side CUDA streams, with options for predivision,
fp32 allreduce, and delayed (accumulation-friendly) allreduce.

TPU-native translation: gradient exchange is a ``psum`` over a named mesh
axis. Streams/hook ordering disappear — XLA's latency-hiding scheduler
overlaps the collective with computation, which is the *policy outcome*
apex's machinery hand-builds. Bucketing, however, survives with real
semantics: ``overlap_comm=True`` routes ``flush``/``sync`` through
``parallel/overlap.py``'s bucketed all-reduce (one fused psum per
``message_size``-byte bucket, issued data-independent of the next
microbatch's compute in the ``accumulate`` loop). What also survives is
the **option surface** (``apex/parallel/distributed.py:129-170``):

- ``gradient_average``          → divide by world size after the sum
- ``gradient_predivide_factor`` → divide by f before, world/f after (:247)
- ``allreduce_always_fp32``     → cast grads to fp32 for the reduction (:245)
- ``delay_allreduce``           → skip sync (gradient accumulation), call
  the sync explicitly at the end — here just: don't call it.

Use inside ``shard_map``/``pmap`` (axis must exist), or rely on GSPMD
(sharded batch + replicated params makes XLA insert the same reduction
automatically — the zero-code path recommended for new code).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.monitor import hooks as _mon
from apex_tpu.utils.flat import flatten_tensors, unflatten_tensors
from apex_tpu.utils.parity import warn_inert_once as _warn_inert_once
from apex_tpu._compat import axis_size as _axis_size


def _prescale_leaf(g, allreduce_always_fp32: bool,
                   gradient_predivide_factor: float):
    """Per-leaf transform before the collective: optional fp32 upcast,
    optional predivide (overflow headroom). ONE implementation shared by
    the per-leaf path below and ``overlap.bucketed_allreduce`` — the
    numeric-parity contract between the two paths depends on it."""
    if allreduce_always_fp32:
        g = g.astype(jnp.float32)
    if gradient_predivide_factor != 1.0:
        g = g / gradient_predivide_factor
    return g


def _postscale_leaf(g, orig_dtype, world, gradient_average: bool,
                    gradient_predivide_factor: float):
    """Per-leaf transform after the psum: the average (or the predivide
    compensation) and the cast back to the stored dtype. Shared with
    ``overlap.bucketed_allreduce`` like :func:`_prescale_leaf`."""
    if gradient_average:
        post = (world / gradient_predivide_factor
                if gradient_predivide_factor != 1.0 else world)
        g = g / post
    elif gradient_predivide_factor != 1.0:
        g = g * gradient_predivide_factor
    return g.astype(orig_dtype)


def allreduce_gradients(
    grads: Any,
    axis_name: str = "data",
    *,
    gradient_average: bool = True,
    allreduce_always_fp32: bool = False,
    gradient_predivide_factor: float = 1.0,
) -> Any:
    """psum a gradient pytree over ``axis_name`` with apex's scaling options
    (``apex/parallel/distributed.py:425-468`` allreduce_bucket +
    allreduce_maybe_retain)."""
    world = _axis_size(axis_name)
    if _mon.traced_enabled():
        # trace-time accounting: one psum per floating leaf (XLA may
        # fuse them, but the wire volume is the same), sized at the
        # dtype actually reduced — allreduce_always_fp32 upcasts bf16/
        # fp16 leaves before the collective, doubling their bytes
        floats = [g for g in jax.tree.leaves(grads)
                  if jnp.issubdtype(g.dtype, jnp.floating)]
        if allreduce_always_fp32:
            nbytes = sum(g.size * 4 for g in floats)
        else:
            nbytes = _mon.tree_bytes(floats)
        _mon.collective("psum", axis_name, nbytes=nbytes,
                        count=len(floats))

    def _one(g):
        if not jnp.issubdtype(g.dtype, jnp.floating):
            return g
        orig = g.dtype
        g = _prescale_leaf(g, allreduce_always_fp32,
                           gradient_predivide_factor)
        g = jax.lax.psum(g, axis_name)
        return _postscale_leaf(g, orig, world, gradient_average,
                               gradient_predivide_factor)

    return jax.tree.map(_one, grads)


def flat_dist_call(tensors: Sequence[jax.Array], op: Callable, axis_name: str = "data"):
    """Flatten → one collective → unflatten
    (``apex/parallel/distributed.py:36-75``). ``op`` is e.g.
    ``lambda t: jax.lax.psum(t, axis_name)``."""
    flat = flatten_tensors(list(tensors))
    if _mon.traced_enabled():
        # one fused collective over the flat buffer; op is opaque, so
        # account it under its own name rather than guessing psum
        _mon.collective("flat_dist_call", axis_name,
                        nbytes=_mon.tree_bytes(flat), count=1)
    flat = op(flat)
    return unflatten_tensors(flat, list(tensors))


class DistributedDataParallel:
    """Wrapper giving the apex DDP call shape on top of mesh collectives.

    ``ddp = DistributedDataParallel(amp_model_or_apply_fn, ...)`` then
    inside a shard_mapped/pmapped step: ``out = ddp(params, x)`` and
    ``grads = ddp.sync(grads)``. ``delay_allreduce=True`` makes ``sync`` a
    no-op until ``ddp.flush(grads)`` is called (gradient accumulation,
    ``apex/parallel/distributed.py:161,559-607``).
    """

    def __init__(self, module: Callable, axis_name: str = "data",
                 message_size: int = 10_000_000, delay_allreduce: bool = False,
                 shared_param: bool | None = None, allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators=None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 gradient_average_split_factor=None,
                 overlap_comm: bool = False,
                 compress: str | None = None,
                 prof: bool = False):
        self.module = module
        self.axis_name = axis_name
        self.message_size = message_size
        self.delay_allreduce = delay_allreduce
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.overlap_comm = overlap_comm
        # ``compress="fp8"`` — the amp O4 gradient-comm path: each
        # message_size bucket is pmax-amax'd, cast to float8_e5m2
        # through the shared amp.fp8 codec, psummed in the wire dtype
        # and rescaled (overlap.bucketed_allreduce). Opt-in and lossy
        # (e5m2 has 2 mantissa bits) — never a default.
        if compress not in (None, "fp8"):
            raise ValueError(
                f"DistributedDataParallel: compress must be None or "
                f"'fp8', got {compress!r}")
        if compress and not overlap_comm:
            raise ValueError(
                "DistributedDataParallel: compress='fp8' requires "
                "overlap_comm=True — the fp8 codec scales per "
                "message_size bucket (parallel/overlap.py), so there "
                "is no bucket to scale on the per-leaf path")
        if compress and allreduce_always_fp32:
            raise ValueError(
                "DistributedDataParallel: compress='fp8' contradicts "
                "allreduce_always_fp32=True (one narrows the wire to "
                "1 byte/elt, the other widens it to 4)")
        self.compress = compress
        # ``overlap_comm=True`` gives ``message_size`` real TPU semantics:
        # ``flush``/``sync``/``accumulate`` partition the grad tree into
        # message_size-byte buckets and issue one fused psum per bucket
        # (``parallel/overlap.py``), the explicit translation of apex's
        # side-stream bucket all-reduce. With the flag off (default, the
        # jaxpr-identical path) message_size stays a parity no-op — XLA
        # owns fusion & overlap of the per-leaf collectives — and ported
        # code that sets it still deserves the one-time heads-up. Stream
        # and communicator knobs have no TPU analog in either mode.
        if message_size != 10_000_000 and not overlap_comm:
            # its own warning, NOT the no-op-on-TPU list below: unlike
            # the stream/communicator knobs this one CAN be made live
            _warn_inert_once(
                f"DistributedDataParallel: message_size={message_size} is "
                "inert because overlap_comm=False — pass "
                "overlap_comm=True to enable the bucketed-psum path that "
                "honors it (parallel/overlap.py)")
        inert = []
        if num_allreduce_streams != 1:
            inert.append(f"num_allreduce_streams={num_allreduce_streams}")
        if allreduce_communicators is not None:
            inert.append("allreduce_communicators")
        if gradient_average_split_factor is not None:
            # legacy knob (apex/parallel/distributed.py): split the
            # average across the two allreduce halves — no split halves
            # exist here, psum + one scale is exact
            inert.append("gradient_average_split_factor="
                         f"{gradient_average_split_factor}")
        if inert:
            _warn_inert_once(
                "DistributedDataParallel: "
                + ", ".join(inert)
                + " accepted for API parity but a no-op on TPU (XLA "
                "fuses, buckets and overlaps the gradient all-reduce "
                "itself; there are no CUDA streams or NCCL "
                "communicators to configure)")

    def __call__(self, params, *args, **kwargs):
        return self.module(params, *args, **kwargs)

    def _scaling(self):
        return dict(
            gradient_average=self.gradient_average,
            allreduce_always_fp32=self.allreduce_always_fp32,
            gradient_predivide_factor=self.gradient_predivide_factor)

    def sync(self, grads):
        if self.delay_allreduce:
            return grads
        return self.flush(grads)

    def flush(self, grads):
        if self.overlap_comm:
            from apex_tpu.parallel.overlap import bucketed_allreduce
            return bucketed_allreduce(grads, self.axis_name,
                                      message_size=self.message_size,
                                      compress=self.compress,
                                      **self._scaling())
        return allreduce_gradients(grads, self.axis_name, **self._scaling())

    def accumulate(self, grad_fn, params, microbatches):
        """Gradient-accumulation loop with the reduction placed by this
        wrapper's config: ``overlap_comm=True, delay_allreduce=False``
        streams each microbatch's bucket psums so they overlap the next
        microbatch's compute; ``delay_allreduce=True`` flushes once at
        the end (bucketed when ``overlap_comm``). See
        :func:`apex_tpu.parallel.overlap.accumulate_gradients`."""
        from apex_tpu.parallel.overlap import accumulate_gradients
        return accumulate_gradients(
            grad_fn, params, microbatches, axis_name=self.axis_name,
            message_size=self.message_size, overlap_comm=self.overlap_comm,
            delay_allreduce=self.delay_allreduce, compress=self.compress,
            **self._scaling())


class Reducer:
    """Manual-sync variant (``apex/parallel/distributed.py:89-127``): user
    calls ``reducer.reduce(params_or_grads)`` when desired."""

    def __init__(self, module_or_grads_list=None, axis_name: str = "data"):
        self.axis_name = axis_name

    def reduce(self, tree):
        world = _axis_size(self.axis_name)
        if _mon.traced_enabled():
            floats = [g for g in jax.tree.leaves(tree)
                      if jnp.issubdtype(g.dtype, jnp.floating)]
            _mon.collective("psum", self.axis_name,
                            nbytes=_mon.tree_bytes(floats),
                            count=len(floats))
        return jax.tree.map(
            lambda g: jax.lax.psum(g, self.axis_name) / world
            if jnp.issubdtype(g.dtype, jnp.floating) else g, tree)
