"""Explicit communication/computation overlap: chunked collective matmul
and bucketed gradient all-reduce.

Reference: apex's two flagship overlap mechanisms —

- DDP's greedy gradient bucketing with side-stream all-reduce
  (``apex/parallel/distributed.py:425-468``): gradients are packed into
  ``message_size``-byte buckets and each bucket's all-reduce is kicked
  off on a communication stream while the backward keeps producing the
  next bucket.
- Megatron's interleaved tensor-parallel collectives (the
  async-allreduce-in-backward column linear,
  ``apex/transformer/tensor_parallel/layers.py:206-234``).

Elsewhere in this package those are "ported" by *policy*: XLA's
latency-hiding scheduler is left to overlap the one fused collective
with compute. That works when the dependency structure permits it — but
the hot TP patterns are **blocking by construction**: a sequence-parallel
``ColumnParallelLinear`` cannot start its matmul until the full
``all_gather`` of the activation lands, and a sequence-parallel
``RowParallelLinear``'s ``reduce_scatter`` cannot start until the full
matmul finishes. No scheduler can overlap ops that depend on each other.

The collective-matmul literature ("Overlapping Communication with
Dependent Computation via Decomposition", Wang et al.; the Megatron-LM
sequence-parallel work — PAPERS.md) breaks the dependency by hand: ring-
decompose the collective into ``tp`` per-shard steps so that step *k*'s
partial matmul is data-independent of step *k+1*'s ``ppermute``, which
the scheduler then runs concurrently. This module implements both ring
directions plus the bucketed gradient-allreduce path that finally gives
apex's ``message_size`` knob real TPU semantics:

- :func:`all_gather_matmul`   — ``dot(all_gather(x), w)`` as a ppermute
  ring, each hop overlapped with the previous shard's partial matmul.
- :func:`matmul_reduce_scatter` — ``psum_scatter(dot(x, w))`` as the
  transpose ring: per-destination-block partial matmuls overlapping the
  travelling accumulator's hops.
- both carry a ``custom_vjp`` whose backward **uses the conjugate
  overlapped form** (the cotangent of an all-gather→matmul is exactly a
  matmul→reduce-scatter, and vice versa), so fwd and bwd each hide their
  collective. The backward re-rings the *local shard* instead of saving
  the gathered activation — the Megatron-SP memory property.
- :func:`bucketed_allreduce` / :func:`accumulate_gradients` — partition
  a gradient tree into ``message_size``-byte buckets, one fused ``psum``
  per bucket; in the gradient-accumulation loop each microbatch's bucket
  psums are issued data-independent of the next microbatch's compute.
  ``compress="fp8"`` (the amp O4 comm path) quantizes each bucket to
  float8_e5m2 through the shared ``amp.fp8`` codec before the psum, so
  the collective's operands — and the accounted wire bytes — are 1
  byte/element: half of bf16, a quarter of fp32.

Numerics: ``all_gather_matmul`` is *bitwise* identical to the gather-
then-matmul program (each output row block is the same full-contraction
dot). ``matmul_reduce_scatter`` and the bucketed psums reassociate the
cross-rank additions, so they match the fused forms to dtype-appropriate
tolerance only (fp32 ~1e-6, bf16 ~1e-2 relative).

Everything here takes ``axis_name`` explicitly and must run inside
``shard_map``/``pmap`` with that axis bound (same contract as
``transformer/tensor_parallel/mappings.py``). At axis size 1 every
function degrades to its local form with zero collectives.

Trace-time ``ppermute`` byte/count accounting is threaded through
``apex_tpu.monitor`` (the collective table previously only saw
psum/all_gather/psum_scatter).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from apex_tpu._compat import axis_size as _axis_size
from apex_tpu.monitor import hooks as _mon

__all__ = [
    "all_gather_matmul",
    "matmul_reduce_scatter",
    "ring_all_gather",
    "ring_psum_scatter",
    "bucket_partition",
    "bucketed_allreduce",
    "accumulate_gradients",
]


# ---------------------------------------------------------------------------
# ring building blocks
# ---------------------------------------------------------------------------


def _ring_perm(tp: int):
    """The +1 ring: rank j sends to (j+1) % tp, so after each hop rank i
    holds what rank i-1 held."""
    return [(j, (j + 1) % tp) for j in range(tp)]


def _dot(a, w, out_dtype):
    """The layers' matmul convention: fp32 MXU accumulation, activation
    storage dtype (``tensor_parallel/layers.py``)."""
    return jnp.dot(a, w, preferred_element_type=jnp.float32).astype(out_dtype)


def _account_ring(axis_name, chunk, hops: int):
    """Trace-time ppermute accounting: ``hops`` permutes of ``chunk``."""
    if hops > 0 and _mon.traced_enabled():
        _mon.collective("ppermute", axis_name,
                        nbytes=hops * _mon.tree_bytes(chunk), count=hops)


def _ring_all_gather_matmul(x, w, axis_name, gather_dim: int):
    """``dot(all_gather(x, gather_dim), w)`` as tp ring steps.

    Step k matmuls the shard currently held (originally from rank
    ``idx - k``) into its output row block while the next shard is in
    flight on the ring — the two are data-independent, so XLA overlaps
    them. Each block is a complete contraction, so the result is bitwise
    equal to the blocking gather-then-matmul form.
    """
    tp = _axis_size(axis_name)
    if tp == 1:
        return _dot(x, w, x.dtype)
    gather_dim = gather_dim % x.ndim
    idx = jax.lax.axis_index(axis_name)
    s_local = x.shape[gather_dim]
    out_shape = list(x.shape[:-1]) + [w.shape[-1]]
    out_shape[gather_dim] = s_local * tp
    y = jnp.zeros(tuple(out_shape), x.dtype)
    perm = _ring_perm(tp)
    _account_ring(axis_name, x, tp - 1)
    chunk = x
    for k in range(tp):
        part = _dot(chunk, w, x.dtype)
        src = (idx - k) % tp
        y = jax.lax.dynamic_update_slice_in_dim(
            y, part, src * s_local, axis=gather_dim)
        if k < tp - 1:
            chunk = jax.lax.ppermute(chunk, axis_name, perm)
    return y


def _ring_matmul_reduce_scatter(x, w, axis_name, scatter_dim: int):
    """``psum_scatter(dot(x, w), scatter_dim)`` as tp ring steps.

    A partial-sum accumulator travels the ring; at step t rank i slices
    the row block destined for rank ``i - t - 1``, matmuls it, and adds
    it to the arriving accumulator. The slice+matmul for step t is
    independent of step t-1's hop, so compute hides the permute. After
    tp-1 hops each rank holds its own fully-reduced output block.
    """
    tp = _axis_size(axis_name)
    if tp == 1:
        return _dot(x, w, x.dtype)
    scatter_dim = scatter_dim % x.ndim
    idx = jax.lax.axis_index(axis_name)
    s_full = x.shape[scatter_dim]
    if s_full % tp != 0:
        raise ValueError(
            f"matmul_reduce_scatter: dim {scatter_dim} of size {s_full} is "
            f"not divisible by axis '{axis_name}' size {tp}")
    s_local = s_full // tp
    perm = _ring_perm(tp)
    acc = None
    for t in range(tp):
        b = (idx - t - 1) % tp
        blk = jax.lax.dynamic_slice_in_dim(
            x, b * s_local, s_local, axis=scatter_dim)
        part = _dot(blk, w, x.dtype)
        if acc is None:
            acc = part
        else:
            acc = jax.lax.ppermute(acc, axis_name, perm) + part
    _account_ring(axis_name, acc, tp - 1)
    return acc


def _ring_weight_grad(travelling, resident, axis_name, block_dim: int,
                      *, resident_on_left: bool):
    """The shared dw-accumulation ring of both backwards: ``travelling``
    (a per-rank shard — ``x`` in the gather backward, the cotangent in
    the scatter backward) circulates on the ring while each arriving
    chunk is contracted over all non-feature dims with its origin rank's
    row block of the resident full-length array. ``resident_on_left``
    picks the contraction order (``dw = resident_blk^T @ chunk`` vs
    ``chunk^T @ resident_blk``). Accumulates in fp32 (the MXU
    convention) and returns fp32 — the caller casts."""
    nd = travelling.ndim
    axes = (tuple(range(nd - 1)),) * 2

    def term(chunk, blk):
        a, b = (blk, chunk) if resident_on_left else (chunk, blk)
        return jnp.tensordot(a, b, axes=axes,
                             preferred_element_type=jnp.float32)

    tp = _axis_size(axis_name)
    if tp == 1:
        return term(travelling, resident)
    block_dim = block_dim % nd
    idx = jax.lax.axis_index(axis_name)
    s_local = travelling.shape[block_dim]
    perm = _ring_perm(tp)
    _account_ring(axis_name, travelling, tp - 1)
    chunk = travelling
    dw = None
    for k in range(tp):
        src = (idx - k) % tp
        blk = jax.lax.dynamic_slice_in_dim(
            resident, src * s_local, s_local, axis=block_dim)
        part = term(chunk, blk)
        dw = part if dw is None else dw + part
        if k < tp - 1:
            chunk = jax.lax.ppermute(chunk, axis_name, perm)
    return dw


# ---------------------------------------------------------------------------
# bare ring collectives (no fused compute): the ZeRO-3 parameter
# gather/scatter building blocks (``apex_tpu.zero``). Decomposing a
# parameter all-gather into tp-1 ppermutes makes each hop an independent
# eqn, so XLA's scheduler can run leaf A's remaining hops underneath the
# layers that only consume leaf B — the per-leaf analog of the fused
# collective-matmul rings above, for consumers that need the whole leaf
# (embedding lookups, norms, bias adds) and therefore cannot fuse the
# matmul into the ring.
# ---------------------------------------------------------------------------


def ring_all_gather(x, axis_name, gather_dim: int = 0):
    """``all_gather(x, axis=gather_dim, tiled=True)`` as tp-1 ppermute
    hops. Each arriving chunk is written straight into its origin rank's
    block of the output, so the values (and the result) are *bitwise*
    identical to the blocking all_gather — only the schedulability
    changes."""
    tp = _axis_size(axis_name)
    if tp == 1:
        return x
    gather_dim = gather_dim % x.ndim
    idx = jax.lax.axis_index(axis_name)
    s_local = x.shape[gather_dim]
    out_shape = list(x.shape)
    out_shape[gather_dim] = s_local * tp
    y = jnp.zeros(tuple(out_shape), x.dtype)
    perm = _ring_perm(tp)
    _account_ring(axis_name, x, tp - 1)
    chunk = x
    for k in range(tp):
        src = (idx - k) % tp
        y = jax.lax.dynamic_update_slice_in_dim(
            y, chunk, src * s_local, axis=gather_dim)
        if k < tp - 1:
            chunk = jax.lax.ppermute(chunk, axis_name, perm)
    return y


def ring_psum_scatter(x, axis_name, scatter_dim: int = 0):
    """``psum_scatter(x, scatter_dimension=scatter_dim, tiled=True)`` as
    a travelling partial-sum accumulator: at step t rank i slices the
    block destined for rank ``i - t - 1`` and adds it to the arriving
    accumulator; after tp-1 hops each rank holds its own fully-reduced
    block. The cross-rank additions are reassociated relative to the
    fused collective, so parity is dtype-tolerance (fp32 ~1e-6), same
    as :func:`matmul_reduce_scatter`."""
    tp = _axis_size(axis_name)
    if tp == 1:
        return x
    scatter_dim = scatter_dim % x.ndim
    s_full = x.shape[scatter_dim]
    if s_full % tp != 0:
        raise ValueError(
            f"ring_psum_scatter: dim {scatter_dim} of size {s_full} is "
            f"not divisible by axis '{axis_name}' size {tp}")
    idx = jax.lax.axis_index(axis_name)
    s_local = s_full // tp
    perm = _ring_perm(tp)
    acc = None
    for t in range(tp):
        b = (idx - t - 1) % tp
        blk = jax.lax.dynamic_slice_in_dim(
            x, b * s_local, s_local, axis=scatter_dim)
        if acc is None:
            acc = blk
        else:
            acc = jax.lax.ppermute(acc, axis_name, perm) + blk
    _account_ring(axis_name, acc, tp - 1)
    return acc


# ---------------------------------------------------------------------------
# collective matmul primitives (custom_vjp: overlapped fwd AND bwd)
# ---------------------------------------------------------------------------


def _check_operands(x, w, dim: int, what: str):
    if w.ndim != 2:
        raise ValueError(f"{what}: weight must be 2D [in, out], got {w.shape}")
    if x.shape[-1] != w.shape[0]:
        raise ValueError(
            f"{what}: contraction mismatch, x[..., {x.shape[-1]}] @ "
            f"w[{w.shape[0]}, ...]")
    if not (-x.ndim <= dim < x.ndim - 1) or (dim % x.ndim) == x.ndim - 1:
        raise ValueError(
            f"{what}: ring dim {dim} must be a non-contraction axis of "
            f"x with shape {x.shape}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def all_gather_matmul(x, w, axis_name, gather_dim: int = 0):
    """``dot(all_gather(x, axis=gather_dim, tiled=True), w)`` with the
    gather ring-decomposed so each hop overlaps a per-shard matmul.

    ``x``: the local sequence shard ``[..., s/tp at gather_dim, ..., h]``;
    ``w``: the local weight shard ``[h, n_local]``. Returns
    ``[..., s, ..., n_local]``. Bitwise-equal to the blocking form.

    Backward: ``dx`` is the conjugate :func:`matmul_reduce_scatter` of
    ``dy @ w^T`` (overlapped), ``dw`` re-rings the saved *local* shard
    (no gathered activation is stored — the Megatron-SP memory property).
    """
    _check_operands(x, w, gather_dim, "all_gather_matmul")
    return _ring_all_gather_matmul(x, w, axis_name, gather_dim)


def _agm_fwd(x, w, axis_name, gather_dim):
    _check_operands(x, w, gather_dim, "all_gather_matmul")
    return _ring_all_gather_matmul(x, w, axis_name, gather_dim), (x, w)


def _agm_bwd(axis_name, gather_dim, res, dy):
    x, w = res
    # d(gathered x) = dy @ w^T, and the gather's transpose re-shards while
    # summing cross-rank partials: exactly matmul→reduce-scatter.
    dx = _ring_matmul_reduce_scatter(
        dy, jnp.swapaxes(w, 0, 1).astype(dy.dtype), axis_name, gather_dim)
    dw = _ring_weight_grad(x, dy, axis_name, gather_dim,
                           resident_on_left=False).astype(w.dtype)
    return dx.astype(x.dtype), dw


all_gather_matmul.defvjp(_agm_fwd, _agm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_reduce_scatter(x, w, axis_name, scatter_dim: int = 0):
    """``psum_scatter(dot(x, w), scatter_dim, tiled=True)`` with the
    reduce-scatter ring-decomposed: per-destination-block partial matmuls
    overlap the travelling accumulator's hops.

    ``x``: the full-sequence activation holding this rank's contraction
    shard ``[..., s at scatter_dim, ..., h_local]``; ``w``: the local
    weight shard ``[h_local, n]``. Returns ``[..., s/tp, ..., n]``.
    Matches the fused form to dtype tolerance (the cross-rank additions
    are reassociated).

    Backward: ``dx`` is the conjugate :func:`all_gather_matmul` of the
    scattered cotangent (overlapped); ``dw`` rings the cotangent shard
    against the saved local activation.
    """
    _check_operands(x, w, scatter_dim, "matmul_reduce_scatter")
    return _ring_matmul_reduce_scatter(x, w, axis_name, scatter_dim)


def _mrs_fwd(x, w, axis_name, scatter_dim):
    _check_operands(x, w, scatter_dim, "matmul_reduce_scatter")
    return _ring_matmul_reduce_scatter(x, w, axis_name, scatter_dim), (x, w)


def _mrs_bwd(axis_name, scatter_dim, res, dy):
    x, w = res
    # d(x @ w) = all_gather(dy) — and folding the following @ w^T into the
    # gather ring is exactly the conjugate collective matmul.
    dx = _ring_all_gather_matmul(
        dy, jnp.swapaxes(w, 0, 1).astype(dy.dtype), axis_name, scatter_dim)
    dw = _ring_weight_grad(dy, x, axis_name, scatter_dim,
                           resident_on_left=True).astype(w.dtype)
    return dx.astype(x.dtype), dw


matmul_reduce_scatter.defvjp(_mrs_fwd, _mrs_bwd)


# ---------------------------------------------------------------------------
# bucketed gradient all-reduce (apex message_size semantics, live on TPU)
# ---------------------------------------------------------------------------


def _is_float(g) -> bool:
    return jnp.issubdtype(g.dtype, jnp.floating)


def bucket_partition(leaves: Sequence, message_size: int,
                     *, allreduce_always_fp32: bool = False) -> list:
    """Greedy in-order partition of the floating leaves of a flattened
    gradient tree into buckets of ~``message_size`` bytes.

    Mirrors apex's bucketing (``apex/parallel/distributed.py:425-468``):
    leaves are appended whole (never split) in tree order and a bucket
    closes once it reaches the byte target, so a leaf may straddle the
    nominal boundary and a bucket holds at least one leaf regardless of
    its size. ``allreduce_always_fp32`` sizes bf16/fp16 leaves at the 4
    bytes they occupy on the wire after the upcast. Returns a list of
    index lists into ``leaves``; non-floating leaves appear in no bucket.
    """
    if message_size <= 0:
        raise ValueError(f"message_size must be > 0, got {message_size}")
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, g in enumerate(leaves):
        if not _is_float(g):
            continue
        itemsize = 4 if allreduce_always_fp32 else jnp.dtype(g.dtype).itemsize
        cur.append(i)
        cur_bytes += int(g.size) * itemsize
        if cur_bytes >= message_size:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def bucketed_allreduce(
    grads: Any,
    axis_name: str = "data",
    *,
    message_size: int = 10_000_000,
    gradient_average: bool = True,
    allreduce_always_fp32: bool = False,
    gradient_predivide_factor: float = 1.0,
    compress: str | None = None,
) -> Any:
    """``allreduce_gradients`` with apex's bucket semantics made real:
    one fused ``psum`` *per bucket* instead of one per leaf.

    Each bucket's psum is a single collective eqn over that bucket's
    leaves, data-independent of every other bucket's — XLA pipelines the
    bucket collectives against each other and against whatever consumes
    the already-reduced buckets (per-bucket optimizer math, the next
    microbatch's compute in :func:`accumulate_gradients`). Scaling
    options match :func:`apex_tpu.parallel.allreduce_gradients` exactly;
    per-leaf numerics are identical to the unbucketed path (bucketing
    changes grouping, not any leaf's reduction).

    ``compress="fp8"`` — the amp O4 gradient-comm path (the ONE fp8
    codec, ``apex_tpu.amp.fp8``; ``zero.comm.quantized_all_gather
    (scaled=True)`` is the parameter-gather face of the same helpers):
    each bucket takes one cross-rank amax (a scalar ``pmax``), scales by
    ``E5M2_MAX / (amax * world)`` — the ``world`` predivide guarantees
    no partial sum of the psum can exceed the e5m2 max, so accumulation
    in the wire dtype cannot saturate — casts to float8_e5m2, psums the
    fp8 operands in ONE eqn, and rescales. Wire (and accounted) bytes
    per bucket are 1 byte/element vs 2 for bf16 / 4 for fp32; numerics
    are e5m2-lossy (2 mantissa bits — relative error ~2^-2 per leaf
    value; gradient *direction* is preserved, see docs/perf.md), so this
    is an opt-in, never a default. Incompatible with
    ``allreduce_always_fp32`` (the knobs contradict: one widens the
    wire, the other narrows it).
    """
    from apex_tpu.parallel.distributed import (_postscale_leaf,
                                               _prescale_leaf)

    if compress not in (None, "fp8"):
        raise ValueError(f"compress must be None or 'fp8', got {compress!r}")
    if compress == "fp8":
        from apex_tpu.amp import fp8 as _fp8
    if compress and allreduce_always_fp32:
        raise ValueError(
            "compress='fp8' contradicts allreduce_always_fp32=True: one "
            "narrows the wire to 1 byte/elt, the other widens it to 4")

    world = _axis_size(axis_name)
    leaves, treedef = jax.tree.flatten(grads)
    buckets = bucket_partition(leaves, message_size,
                               allreduce_always_fp32=allreduce_always_fp32)
    out = list(leaves)
    for bucket in buckets:
        ops = [_prescale_leaf(leaves[i], allreduce_always_fp32,
                              gradient_predivide_factor) for i in bucket]
        if compress == "fp8":
            # one delayed-scaling-style scale per bucket, agreed across
            # ranks (pmax of the local amaxes — a 4-byte scalar, counted
            # in the accounting so the byte comparison stays honest)
            local_amax = jnp.max(jnp.stack([_fp8.amax(g) for g in ops]))
            bucket_amax = jax.lax.pmax(local_amax, axis_name)
            if _mon.traced_enabled():
                _mon.collective("pmax", axis_name, nbytes=4, count=1)
            scale = _fp8.compute_scale(bucket_amax * world, _fp8.E5M2_MAX)
            wire = tuple(_fp8.quantize(g, scale, _fp8.E5M2) for g in ops)
            if _mon.traced_enabled():
                _mon.collective("psum", axis_name,
                                nbytes=_mon.tree_bytes(wire), count=1)
            summed = jax.lax.psum(wire, axis_name)   # fp8 on the wire
            reduced = [_fp8.dequantize(q, scale, jnp.float32)
                       for q in summed]
        else:
            if _mon.traced_enabled():
                _mon.collective("psum", axis_name,
                                nbytes=_mon.tree_bytes(ops), count=1)
            reduced = jax.lax.psum(tuple(ops), axis_name)  # ONE eqn/bucket
        for i, g in zip(bucket, reduced):
            out[i] = _postscale_leaf(g, leaves[i].dtype, world,
                                     gradient_average,
                                     gradient_predivide_factor)
    return jax.tree.unflatten(treedef, out)


def accumulate_gradients(
    grad_fn: Callable,
    params: Any,
    microbatches: Sequence,
    *,
    axis_name: str = "data",
    message_size: int = 10_000_000,
    overlap_comm: bool = True,
    delay_allreduce: bool = False,
    gradient_average: bool = True,
    allreduce_always_fp32: bool = False,
    gradient_predivide_factor: float = 1.0,
    compress: str | None = None,
) -> Any:
    """Gradient accumulation with the reduction placed for overlap.

    ``grad_fn(params, microbatch) -> grad_tree``; the loop is unrolled
    (``len(microbatches)`` is static), grads are **summed** across
    microbatches and all-reduced over ``axis_name``:

    - ``overlap_comm=True, delay_allreduce=False`` (apex's default DDP
      regime): each microbatch's grads are bucket-psummed immediately.
      Bucket *b* of microbatch *i* is data-independent of microbatch
      *i+1*'s forward/backward, so XLA overlaps the collectives with the
      next microbatch's compute — the TPU translation of apex's
      side-stream bucket all-reduce. Same wire volume as apex's
      per-backward all-reduce; the overlap is what pays for it.
    - ``overlap_comm=True, delay_allreduce=True``: accumulate locally,
      bucket-psum once at the end (minimum wire volume; the bucket psums
      still pipeline against each other and the consumer).
    - ``overlap_comm=False``: accumulate locally and flush through the
      per-leaf :func:`apex_tpu.parallel.allreduce_gradients` — byte-
      identical to the hand-written accumulate-then-allreduce loop this
      helper replaces (asserted in tests).

    All three modes compute the same value (psum is linear; per-leaf
    tolerance only from fp reassociation in the streamed mode).
    ``compress="fp8"`` rides the bucketed paths (see
    :func:`bucketed_allreduce`; requires ``overlap_comm=True`` — the
    per-leaf fallback has no bucket to scale).
    """
    if not len(microbatches):
        raise ValueError("accumulate_gradients: need at least 1 microbatch")
    if compress and not overlap_comm:
        raise ValueError(
            "compress='fp8' requires overlap_comm=True: the fp8 codec "
            "scales per message_size bucket (bucketed_allreduce)")
    scaling = dict(gradient_average=gradient_average,
                   allreduce_always_fp32=allreduce_always_fp32,
                   gradient_predivide_factor=gradient_predivide_factor)
    acc = None
    for mb in microbatches:
        g = grad_fn(params, mb)
        if overlap_comm and not delay_allreduce:
            g = bucketed_allreduce(g, axis_name, message_size=message_size,
                                   compress=compress, **scaling)
        acc = g if acc is None else jax.tree.map(jnp.add, acc, g)
    if overlap_comm and delay_allreduce:
        acc = bucketed_allreduce(acc, axis_name, message_size=message_size,
                                 compress=compress, **scaling)
    elif not overlap_comm:
        from apex_tpu.parallel.distributed import allreduce_gradients
        acc = allreduce_gradients(acc, axis_name, **scaling)
    return acc
