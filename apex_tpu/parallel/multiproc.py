"""Single-node multi-process launcher.

Reference: ``apex/parallel/multiproc.py:12-35`` — spawn one training
process per GPU with ``--rank``/``--world-size`` appended.

TPU reality: one process drives all local chips (SPMD), and multi-host
jobs are launched by the TPU infrastructure with
``jax.distributed.initialize()``. This launcher exists for parity and for
multi-process CPU simulation: it spawns ``world_size`` processes with the
coordinator env set so ``jax.distributed.initialize`` connects them.

Usage: ``python -m apex_tpu.parallel.multiproc [--world-size N] script.py args...``
"""

from __future__ import annotations

import os
import subprocess
import sys


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None):
    """Connect this process to the JAX distributed runtime.

    Reads the env contract this launcher sets (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) unless given explicitly —
    the multi-host analog of the reference's ``--rank``/``--world-size``
    plumbing into ``torch.distributed.init_process_group``
    (``apex/parallel/multiproc.py:12-35``). On real TPU pods the args are
    auto-detected and this reduces to ``jax.distributed.initialize()``.

    After this, ``jax.devices()`` spans all hosts;
    ``parallel_state.initialize_model_parallel`` then builds the global
    mesh with the data axis outermost, so DP crosses hosts (DCN) while
    tp/pp/cp ride intra-host ICI.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    # rank-tag any attached recorder so its JSONL shard self-identifies
    # (monitor.merge reads process_index/process_count from the header)
    from apex_tpu import monitor
    rec = monitor.get_recorder()
    if rec is not None:
        rec.meta.setdefault("process_index", jax.process_index())
        rec.meta.setdefault("process_count", jax.process_count())
        rec.gauge("dist/process_index", jax.process_index())


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    world_size = None
    if argv and argv[0] == "--world-size":
        world_size = int(argv[1])
        argv = argv[2:]
    if not argv:
        print(__doc__)
        return 1
    if world_size is None:
        try:
            import jax
            world_size = jax.local_device_count()
        except Exception:
            world_size = 1

    port = int(os.environ.get("APEX_TPU_COORD_PORT", "12355"))
    procs = []
    for rank in range(world_size):
        env = dict(os.environ)
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(world_size),
            "JAX_PROCESS_ID": str(rank),
        })
        cmd = [sys.executable] + argv + ["--rank", str(rank),
                                         "--world-size", str(world_size)]
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
