"""Shared utilities: pytree/dtype helpers used across apex_tpu."""

from apex_tpu.utils.tree import (  # noqa: F401
    cast_floating,
    tree_all_finite,
    tree_map_with_path_names,
    is_floating,
)
from apex_tpu.utils.flat import FlatBuffer, flatten_tensors, unflatten_tensors  # noqa: F401
from apex_tpu.utils.parity import warn_inert_once  # noqa: F401
