"""One-time notices for reference-API knobs that are inert on TPU.

The reference exposes CUDA-runtime tuning options (NCCL stream counts,
bucket byte sizes, packed-output modes) that have no TPU analog — XLA
owns collective fusion/overlap and static-shape compute. apex_tpu keeps
the option surfaces for drop-in parity (``apex/parallel/distributed.py:
129-170``) but ported code that sets them to non-defaults deserves one
loud heads-up instead of silent acceptance."""

from __future__ import annotations

import warnings

_seen: set = set()


def warn_inert_once(msg: str, key: str | None = None) -> None:
    """Emit ``msg`` as a UserWarning once per ``key`` (default: the
    message itself) for the life of the process."""
    k = key or msg
    if k in _seen:
        return
    _seen.add(k)
    warnings.warn(msg, UserWarning, stacklevel=3)
