"""Shared remat-policy resolution for model configs."""

from __future__ import annotations

from typing import Optional

import jax


def resolve_remat_policy(name: Optional[str]):
    """Map a config-level remat policy name to a jax.checkpoint policy.

    ``None`` = full recompute; ``"dots"`` = save matmul outputs and
    recompute the elementwise/LN chains in backward
    (``jax.checkpoint_policies.checkpoint_dots``).
    """
    if name is None:
        return None
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    raise ValueError(f"unknown remat_policy {name!r}; expected None or 'dots'")
