"""Flat-buffer utilities: the TPU analog of ``apex_C.flatten/unflatten``.

The reference flattens bucket tensor lists into one contiguous buffer so a
single NCCL call / CUDA kernel covers many small tensors
(``csrc/flatten_unflatten.cpp:1-18``, used by
``apex/parallel/distributed.py:426``). On TPU the same trick pays off for a
different reason: one large 1-D array gives XLA a single fused elementwise
loop (optimizer update, scaling) and a single collective instead of
hundreds of tiny ones.

``FlatBuffer`` captures the static structure (shapes/sizes/offsets) once so
the pack/unpack is cheap to retrace and fully shape-static under ``jit``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class FlatBuffer:
    """Static description of a flattening of a pytree of arrays."""

    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[Any, ...]
    sizes: tuple[int, ...]
    offsets: tuple[int, ...]  # start offset of each leaf in the flat buffer
    total: int

    @staticmethod
    def from_tree(tree: Any) -> "FlatBuffer":
        leaves, treedef = jax.tree.flatten(tree)
        shapes = tuple(tuple(x.shape) for x in leaves)
        dtypes = tuple(x.dtype for x in leaves)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        offsets = tuple(int(o) for o in np.cumsum((0,) + sizes)[:-1])
        return FlatBuffer(treedef, shapes, dtypes, sizes, offsets, int(sum(sizes)))

    def pack(self, tree: Any, dtype: Any = None) -> jax.Array:
        """Concatenate all leaves into one 1-D array (optionally casting)."""
        leaves = jax.tree.leaves(tree)
        parts = [x.reshape(-1) for x in leaves]
        if dtype is not None:
            parts = [p.astype(dtype) for p in parts]
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def unpack(self, flat: jax.Array, dtype_from_spec: bool = True) -> Any:
        """Split a flat buffer back into the original pytree."""
        leaves = []
        for shape, dt, size, off in zip(self.shapes, self.dtypes, self.sizes, self.offsets):
            part = jax.lax.dynamic_slice_in_dim(flat, off, size).reshape(shape)
            if dtype_from_spec:
                part = part.astype(dt)
            leaves.append(part)
        return jax.tree.unflatten(self.treedef, leaves)


def flatten_tensors(tensors: Sequence[jax.Array]) -> jax.Array:
    """``apex_C.flatten`` equivalent: list of arrays -> one 1-D array."""
    return jnp.concatenate([t.reshape(-1) for t in tensors])


def unflatten_tensors(flat: jax.Array, like: Sequence[jax.Array]) -> list[jax.Array]:
    """``apex_C.unflatten`` equivalent: split ``flat`` to match ``like``."""
    sizes = [int(np.prod(t.shape)) if t.shape else 1 for t in like]
    splits = list(np.cumsum(sizes)[:-1])
    parts = jnp.split(flat, splits)
    return [p.reshape(t.shape) for p, t in zip(parts, like)]
