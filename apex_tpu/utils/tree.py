"""Pytree + dtype helpers.

These replace the host-side tensor bookkeeping the reference does with
python loops over ``torch.nn.Module`` state (e.g.
``apex/fp16_utils/fp16util.py:60`` ``convert_network``) with pure pytree
transforms.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def is_floating(x: Any) -> bool:
    """True if ``x`` is a floating-point JAX/numpy array."""
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def cast_floating(tree: Any, dtype: Any, predicate: Callable[..., bool] | None = None) -> Any:
    """Cast every floating leaf of ``tree`` to ``dtype``.

    ``predicate(path_names, leaf) -> bool`` can exempt leaves (returning
    False keeps the leaf untouched) — used for ``keep_batchnorm_fp32``
    semantics (reference: ``apex/fp16_utils/fp16util.py:60-77`` keeps
    ``_BatchNorm`` modules in fp32 while halving the rest).
    """
    if predicate is None:
        return jax.tree.map(lambda x: x.astype(dtype) if is_floating(x) else x, tree)

    def _cast(path, x):
        names = _path_names(path)
        if is_floating(x) and predicate(names, x):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map_with_path(_cast, tree)


def _path_names(path) -> tuple[str, ...]:
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
        elif hasattr(p, "idx"):
            names.append(str(p.idx))
        else:
            names.append(str(p))
    return tuple(names)


def tree_map_with_path_names(fn: Callable, tree: Any) -> Any:
    """``jax.tree_util.tree_map_with_path`` but passing string path tuples."""
    return jax.tree_util.tree_map_with_path(lambda p, x: fn(_path_names(p), x), tree)


def tree_all_finite(tree: Any) -> jax.Array:
    """Single boolean: are ALL floating leaves finite?

    The TPU equivalent of the reference's on-device overflow ``noop_flag``
    set by every multi-tensor kernel (``csrc/multi_tensor_scale_kernel.cu``):
    a pure reduction that stays on device; the caller decides when (if
    ever) to sync it to the host.
    """
    leaves = [x for x in jax.tree.leaves(tree) if is_floating(x)]
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finite).all()
