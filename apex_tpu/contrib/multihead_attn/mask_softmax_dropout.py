"""Standalone fused mask+softmax+dropout.

Reference: ``apex/contrib/multihead_attn/mask_softmax_dropout_func.py`` +
``fast_mask_softmax_dropout_cuda`` (setup.py:369-487 variant list): the
softmax stage of attention as its own fused op, with pad mask and
probability dropout, keeping the dropout mask for exact backward.

TPU: one jit region; dropout uses an explicit key; backward follows from
the ops' custom VJPs. The dropout keep mask is saved by autodiff as a
residual (like the reference, which stores the mask); mask-free
regeneration-in-backward exists only in the Pallas flash-attention
kernel (``ops/flash_attention.py``), where the counter-based RNG runs
in-kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import scaled_masked_softmax


def fast_mask_softmax_dropout(inputs, pad_mask=None, *, is_training=True,
                              dropout_prob=0.0, key=None, scale=1.0):
    probs = scaled_masked_softmax(inputs, pad_mask, scale)
    if is_training and dropout_prob > 0.0:
        if key is None:
            raise ValueError("dropout requires a PRNG key")
        keep = jax.random.bernoulli(key, 1.0 - dropout_prob, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_prob), 0.0).astype(probs.dtype)
    return probs


class MaskSoftmaxDropout:
    """Module-style wrapper mirroring the reference class API."""

    def __init__(self, dropout: float = 0.0, scale: float = 1.0):
        self.dropout = dropout
        self.scale = scale

    def __call__(self, inputs, pad_mask=None, is_training=True, key=None):
        return fast_mask_softmax_dropout(
            inputs, pad_mask, is_training=is_training,
            dropout_prob=self.dropout, key=key, scale=self.scale)
