"""Encoder-decoder (cross) multihead attention.

Reference: ``apex/contrib/multihead_attn/encdec_multihead_attn.py`` — Q
from the decoder stream, K/V from the encoder stream (fused KV GEMM),
same fusion menu as the self-attention variants
(``csrc/multihead_attn/encdec_multihead_attn_*.cu``).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.multihead_attn._fused_prep import prep_fast_path
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine


class EncdecMultiheadAttn(nn.Module):
    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    use_bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key, value=None, key_padding_mask=None,
                 attn_mask=None, is_training: bool = True,
                 deterministic: Optional[bool] = None):
        deterministic = (not is_training) if deterministic is None else deterministic
        e, h = self.embed_dim, self.num_heads
        d = e // h
        sq, b, _ = query.shape
        sk = key.shape[0]
        residual = query
        x = query

        if self.include_norm_add:
            lnw = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (e,), self.param_dtype)
            lnb = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (e,), self.param_dtype)
            x = fused_layer_norm_affine(x, lnw.astype(x.dtype), lnb.astype(x.dtype), (e,))

        wq = self.param("q_weight", nn.initializers.lecun_normal(), (e, e), self.param_dtype)
        wkv = self.param("kv_weight", nn.initializers.lecun_normal(), (2 * e, e), self.param_dtype)
        q = x @ wq.T.astype(x.dtype)
        kv = key @ wkv.T.astype(key.dtype)
        k, v = jnp.split(kv, 2, axis=-1)

        qh = q.reshape(sq, b, h, d).transpose(1, 2, 0, 3)
        kh = k.reshape(sk, b, h, d).transpose(1, 2, 0, 3)
        vh = v.reshape(sk, b, h, d).transpose(1, 2, 0, 3)
        scale = d ** -0.5

        if self.impl == "fast":
            # stays fused under padding/additive masks and dropout, like
            # the self-attention variant (VERDICT r1 weak #6)
            sid_q, sid_kv, bias, drop, seed = prep_fast_path(
                key_padding_mask, attn_mask, b, sq, self.dropout,
                deterministic, self.make_rng)
            ctx = flash_attention(qh, kh, vh, segment_ids_q=sid_q,
                                  segment_ids_kv=sid_kv, scale=scale,
                                  bias=bias, dropout_rate=drop,
                                  dropout_seed=seed)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                                kh.astype(jnp.float32)) * scale
            if attn_mask is not None:
                scores = scores + attn_mask.astype(jnp.float32)
            if key_padding_mask is not None:
                scores = jnp.where(key_padding_mask[:, None, None, :], -10000.0, scores)
            probs = jax.nn.softmax(scores, axis=-1)
            if self.dropout > 0 and not deterministic:
                probs = nn.Dropout(self.dropout, deterministic=False)(
                    probs, rng=self.make_rng("dropout"))
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs,
                             vh.astype(jnp.float32)).astype(qh.dtype)

        ctx = ctx.transpose(2, 0, 1, 3).reshape(sq, b, e)
        wo = self.param("out_proj_weight", nn.initializers.lecun_normal(),
                        (e, e), self.param_dtype)
        out = ctx @ wo.T.astype(ctx.dtype)
        if self.use_bias:
            ob = self.param("out_proj_bias", nn.initializers.zeros, (e,), self.param_dtype)
            out = out + ob.astype(out.dtype)
        if self.include_norm_add:
            # dropout-add epilogue exists only in the norm_add variant
            # (reference jit_dropout_add)
            if self.dropout > 0 and not deterministic:
                out = nn.Dropout(self.dropout, deterministic=False)(
                    out, rng=self.make_rng("dropout"))
            out = out + residual
        return out
