"""Self multihead attention with optional fused pre-LN residual add.

Reference: ``apex/contrib/multihead_attn/self_multihead_attn.py:26`` —
``SelfMultiheadAttn(embed_dim, num_heads, dropout, bias,
include_norm_add, separate_qkv_params, impl='fast'|'default')``; the
'fast' impl is the fully fused CUDA path (QKV GEMM + strided-batch GEMMs
+ softmax + dropout + out-proj, optionally pre-LN + residual,
``csrc/multihead_attn/self_multihead_attn_*.cu``), 'default' composes
torch ops.

TPU: 'fast' routes through the Pallas flash-attention kernel — including
under ``key_padding_mask`` (expressed as segment ids) and additive
``attn_mask`` (the kernel's bias operand), with probability dropout
applied *inside* the kernel (counter-based hash mask, regenerated — not
stored — in the backward), matching the reference's softmax-dropout
placement; 'default' uses the unfused reference
composition (useful for numerics checks, like the reference's impl
switch). ``include_norm_add`` fuses layernorm before QKV and adds the
residual after the projection (the ``norm_add`` CUDA variants).

Layout: inputs are [seq, batch, embed] like the reference modules.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.multihead_attn._fused_prep import prep_fast_path
from apex_tpu.ops.flash_attention import flash_attention, mha_reference
from apex_tpu.ops.layer_norm import fused_layer_norm_affine


class SelfMultiheadAttn(nn.Module):
    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    use_bias: bool = False
    include_norm_add: bool = False
    separate_qkv_params: bool = False
    impl: str = "fast"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key=None, value=None, key_padding_mask=None,
                 attn_mask=None, is_training: bool = True,
                 deterministic: Optional[bool] = None):
        if self.embed_dim % self.num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        deterministic = (not is_training) if deterministic is None else deterministic
        e = self.embed_dim
        h = self.num_heads
        d = e // h
        s, b, _ = query.shape
        residual = query
        x = query

        if self.include_norm_add:
            lnw = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (e,), self.param_dtype)
            lnb = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (e,), self.param_dtype)
            x = fused_layer_norm_affine(x, lnw.astype(x.dtype), lnb.astype(x.dtype), (e,))

        if self.separate_qkv_params:
            wq = self.param("q_weight", nn.initializers.lecun_normal(), (e, e), self.param_dtype)
            wk = self.param("k_weight", nn.initializers.lecun_normal(), (e, e), self.param_dtype)
            wv = self.param("v_weight", nn.initializers.lecun_normal(), (e, e), self.param_dtype)
            q = x @ wq.T.astype(x.dtype)
            k = x @ wk.T.astype(x.dtype)
            v = x @ wv.T.astype(x.dtype)
        else:
            w = self.param("qkv_weight", nn.initializers.lecun_normal(), (3 * e, e), self.param_dtype)
            qkv = x @ w.T.astype(x.dtype)
            if self.use_bias:
                qb = self.param("qkv_bias", nn.initializers.zeros, (3 * e,), self.param_dtype)
                qkv = qkv + qb.astype(qkv.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)

        # [s, b, e] -> [b, h, s, d]
        def to_bhsd(t):
            return t.reshape(s, b, h, d).transpose(1, 2, 0, 3)

        qh, kh, vh = to_bhsd(q), to_bhsd(k), to_bhsd(v)
        scale = d ** -0.5

        causal = isinstance(attn_mask, str) and attn_mask == "causal"
        if self.impl == "fast":
            sid_q, sid_kv, bias, drop, seed = prep_fast_path(
                key_padding_mask, attn_mask, b, s, self.dropout,
                deterministic, self.make_rng, causal=causal)
            ctx = flash_attention(qh, kh, vh, segment_ids_q=sid_q,
                                  segment_ids_kv=sid_kv, causal=bool(causal),
                                  scale=scale, bias=bias, dropout_rate=drop,
                                  dropout_seed=seed)
        else:
            scores = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                                kh.astype(jnp.float32)) * scale
            if causal:
                cm = jnp.arange(s)[None, :] > jnp.arange(s)[:, None]
                scores = jnp.where(cm, -10000.0, scores)
            elif attn_mask is not None:
                scores = scores + attn_mask.astype(jnp.float32)  # additive mask
            if key_padding_mask is not None:
                # [b, sk] True = pad
                scores = jnp.where(key_padding_mask[:, None, None, :], -10000.0, scores)
            probs = jax.nn.softmax(scores, axis=-1)
            if self.dropout > 0 and not deterministic:
                probs = nn.Dropout(self.dropout, deterministic=False)(
                    probs, rng=self.make_rng("dropout"))
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs,
                             vh.astype(jnp.float32)).astype(qh.dtype)

        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, e)
        wo = self.param("out_proj_weight", nn.initializers.lecun_normal(),
                        (e, e), self.param_dtype)
        out = ctx @ wo.T.astype(ctx.dtype)
        if self.use_bias:
            ob = self.param("out_proj_bias", nn.initializers.zeros, (e,), self.param_dtype)
            out = out + ob.astype(out.dtype)
        if self.include_norm_add:
            # dropout-add epilogue (reference jit_dropout_add,
            # self_multihead_attn.py:19-21,165) — output dropout exists
            # only in the norm_add variant
            if self.dropout > 0 and not deterministic:
                out = nn.Dropout(self.dropout, deterministic=False)(
                    out, rng=self.make_rng("dropout"))
            out = out + residual
        return out
