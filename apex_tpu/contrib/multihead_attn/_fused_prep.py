"""Shared fast-path prep for the fused multihead-attention variants:
masks/dropout arguments → flash-attention kernel operands."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def prep_fast_path(key_padding_mask, attn_mask, b, sq, dropout,
                   deterministic, make_rng, *, causal=False):
    """Returns (sid_q, sid_kv, bias, dropout_rate, dropout_seed).

    - ``key_padding_mask`` [b, sk] True=pad → kv segment ids (-1 = pad);
    - additive ``attn_mask`` → kernel bias, [sq, sk] (reference layout)
      or explicit [b|1, h|1, sq, sk] (3-D is ambiguous per-batch vs
      per-head and rejected);
    - dropout seed drawn from the module's 'dropout' RNG stream.
    """
    sid_q = sid_kv = None
    if key_padding_mask is not None:
        sid_kv = jnp.where(key_padding_mask, -1, 0).astype(jnp.int32)
        sid_q = jnp.zeros((b, sq), jnp.int32)
    bias = None
    if attn_mask is not None and not causal:
        bias = jnp.asarray(attn_mask)
        if bias.ndim == 2:              # [sq, sk], the reference layout
            bias = bias[None, None]
        elif bias.ndim != 4:
            raise ValueError(
                "attn_mask must be [sq, sk] (reference layout) or an "
                f"explicit [b|1, h|1, sq, sk]; got {bias.shape} — 3-D "
                "masks are ambiguous (per-batch vs per-head)")
    drop = dropout if (dropout > 0 and not deterministic) else 0.0
    seed = None
    if drop > 0.0:
        seed = jax.random.randint(make_rng("dropout"), (), 0, 2 ** 31 - 1,
                                  jnp.int32)
    return sid_q, sid_kv, bias, drop, seed
