"""apex_tpu.contrib.multihead_attn — fused multihead attention modules.

Reference: ``apex/contrib/multihead_attn/__init__.py`` (SelfMultiheadAttn,
EncdecMultiheadAttn, MaskSoftmaxDropout) over 8 CUDA variant extensions
(``apex/contrib/csrc/multihead_attn/*``). Here all variants collapse onto
one Pallas flash-attention kernel plus fused LN/bias epilogues.
"""

from apex_tpu.contrib.multihead_attn.self_multihead_attn import SelfMultiheadAttn  # noqa: F401
from apex_tpu.contrib.multihead_attn.encdec_multihead_attn import EncdecMultiheadAttn  # noqa: F401
from apex_tpu.contrib.multihead_attn.mask_softmax_dropout import MaskSoftmaxDropout  # noqa: F401
