"""RNN-T joint + loss.

Reference semantics:
- ``TransducerJoint`` (``apex/contrib/transducer/transducer.py:5``):
  joint[b,t,u,:] = f[b,t,:] + g[b,u,:], with optional fused relu+dropout
  and packed output that drops per-sample (T,U) padding
  (``transducer_joint_kernel.cu`` packing path).
- ``TransducerLoss`` (``:68``): RNN-T alpha/beta dynamic program over the
  (T,U) lattice on log-probs [B,T,U,V] with per-sample lengths
  (``transducer_loss_kernel.cu`` wavefront kernels).

TPU design: the joint is a broadcast add XLA fuses with its epilogue; the
loss runs the alpha recursion as a ``lax.scan`` over anti-diagonal
wavefronts (the same parallel order as the CUDA kernel's per-diagonal
waves), with gradients via autodiff of the scan (mathematically the beta
recursion, so no hand-written backward). Packing is unnecessary on TPU —
masking handles ragged (T,U); the packed API is kept for parity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG = -1e30


def transducer_joint(f, g, f_len=None, g_len=None, *, relu=False,
                     dropout_prob=0.0, key=None):
    """joint[b,t,u,:] = f[b,t,:] + g[b,u,:] (+ relu + dropout)."""
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_prob > 0.0:
        if key is None:
            raise ValueError("dropout requires a PRNG key")
        keep = jax.random.bernoulli(key, 1.0 - dropout_prob, out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_prob), 0.0).astype(out.dtype)
    return out


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T negative log likelihood per batch element.

    ``log_probs``: [B, T, U, V] log-softmax outputs of the joint network
    (U = max label length + 1); ``labels``: [B, U-1] int targets;
    ``f_len``: [B] encoder lengths; ``y_len``: [B] label lengths.
    """
    B, T, U, V = log_probs.shape
    lp = log_probs.astype(jnp.float32)

    # blank and emit log-probs per lattice cell
    blank_lp = lp[..., blank_idx]                                  # [B,T,U]
    pad_labels = jnp.concatenate(
        [labels, jnp.zeros((B, 1), labels.dtype)], axis=1)[:, :U]  # [B,U]
    emit_lp = jnp.take_along_axis(
        lp, pad_labels[:, None, :, None], axis=-1)[..., 0]         # [B,T,U]

    # mask invalid emit transitions (u >= y_len cannot emit)
    u_idx = jnp.arange(U)[None, :]
    emit_valid = u_idx < y_len[:, None]                            # [B,U]
    emit_lp = jnp.where(emit_valid[:, None, :], emit_lp, _NEG)

    # alpha over anti-diagonal wavefronts: cell (t,u) on diagonal t+u
    alpha0 = jnp.full((B, T, U), _NEG).at[:, 0, 0].set(0.0)

    def wave(alpha, d):
        from_t = jnp.concatenate(
            [jnp.full((B, 1, U), _NEG),
             alpha[:, :-1, :] + blank_lp[:, :-1, :]], axis=1)
        from_u = jnp.concatenate(
            [jnp.full((B, T, 1), _NEG),
             alpha[:, :, :-1] + emit_lp[:, :, :-1]], axis=2)
        cand = jnp.logaddexp(from_t, from_u)
        t_idx = jnp.arange(T)[:, None]
        on_diag = (t_idx + jnp.arange(U)[None, :]) == d
        return jnp.where(on_diag[None], cand, alpha), None

    alpha, _ = jax.lax.scan(wave, alpha0, jnp.arange(1, T + U - 1))

    # total log prob: alpha at (f_len-1, y_len) + final blank
    bidx = jnp.arange(B)
    t_last = f_len - 1
    u_last = y_len
    ll = (alpha[bidx, t_last, u_last] + blank_lp[bidx, t_last, u_last])
    return -ll


class TransducerJoint:
    """Module-style wrapper (``apex/contrib/transducer/transducer.py:5``)."""

    def __init__(self, pack_output=False, relu=False, dropout=False,
                 dropout_prob=0.0, probe_mask=False):
        if pack_output:
            # packing exists to skip padded compute on CUDA; on TPU static
            # shapes + masking win — keep the flag but compute unpacked.
            from apex_tpu.utils.parity import warn_inert_once
            warn_inert_once(
                "TransducerJoint(pack_output=True) accepted for API "
                "parity but a no-op on TPU: outputs stay unpacked "
                "(static shapes + masking beat packed varlen compute "
                "under XLA)")
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_batch=0, key=None):
        return transducer_joint(
            f, g, f_len, g_len, relu=self.relu,
            dropout_prob=self.dropout_prob if self.dropout else 0.0, key=key)


class TransducerLoss:
    """Module-style wrapper (``apex/contrib/transducer/transducer.py:68``)."""

    def __init__(self, fuse_softmax_backward=True, opt=1, packed_input=False):
        del fuse_softmax_backward, opt, packed_input  # fused by construction

    def __call__(self, x, label, f_len, y_len, blank_idx=0, batch_offset=None,
                 max_f_len=None, debug_list=None):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
