"""apex_tpu.contrib.transducer — RNN-T joint and loss.

Reference: ``apex/contrib/transducer/transducer.py:5,68`` backed by
``transducer_joint_kernel.cu`` (972 LoC) and ``transducer_loss_kernel.cu``
(766 LoC).
"""

from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)
