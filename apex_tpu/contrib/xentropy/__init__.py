"""apex_tpu.contrib.xentropy — fused softmax cross entropy.

Reference: ``apex/contrib/xentropy/__init__.py`` exposing
``SoftmaxCrossEntropyLoss`` backed by ``xentropy_cuda``
(``apex/contrib/xentropy/softmax_xentropy.py:4-31``).
"""

from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_with_smoothing,
)
