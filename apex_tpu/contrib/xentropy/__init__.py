"""apex_tpu.contrib.xentropy — fused softmax cross entropy.

Reference: ``apex/contrib/xentropy/__init__.py`` exposing
``SoftmaxCrossEntropyLoss`` backed by ``xentropy_cuda``
(``apex/contrib/xentropy/softmax_xentropy.py:4-31``).

DEPRECATED pointer: this is a thin re-export over the ONE fused CE
implementation in :mod:`apex_tpu.ops.fused_ce` (Pallas kernels + XLA
reference twin, tuner-resolved); import from there in new code.
"""

from apex_tpu.ops.fused_ce import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_with_smoothing,
)
