"""Bottleneck block + spatial (H-dim) parallelism with halo exchange.

Reference: ``apex/contrib/bottleneck/bottleneck.py`` —
``Bottleneck`` (:52) is the conv1x1-conv3x3-conv1x1 residual block fused
through the cudnn-frontend v8 engine; ``SpatialBottleneck`` (:218-512)
shards the H dimension over ``spatial_group_size`` GPUs, hand-managing
NCCL halo pushes around every 3x3 conv.

TPU: block fusion is XLA's job — ``Bottleneck`` is the plain graph (see
``apex_tpu.models.resnet.Bottleneck``). Spatial parallelism maps to an H-
sharded ``shard_map`` where :func:`halo_exchange` swaps 1-row halos with
ring neighbors via two ``ppermute``s before each 3x3 conv — the explicit
form of what GSPMD inserts automatically when you simply shard H in a
sharding constraint (both are supported; the explicit module exists for
parity and for fine control).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.resnet import Bottleneck  # the fused-block graph
from apex_tpu._compat import axis_size as _axis_size


def halo_exchange(x, axis_name: str, halo: int = 1):
    """Exchange ``halo`` rows (H axis = dim 1 of NHWC) with ring neighbors.

    Returns x padded to [N, H_local + 2*halo, W, C]; the first/last rank
    get zero halos (edge padding), matching the reference's halo handling
    at the volume boundary (``bottleneck.py:218+``).
    """
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    top = x[:, :halo]        # rows to send upward (to rank-1)
    bot = x[:, -halo:]       # rows to send downward (to rank+1)
    # receive bottom neighbor's top rows as our lower halo, and vice versa
    from_next = jax.lax.ppermute(top, axis_name, [(i, (i - 1) % n) for i in range(n)])
    from_prev = jax.lax.ppermute(bot, axis_name, [(i, (i + 1) % n) for i in range(n)])
    zero = jnp.zeros_like(top)
    upper = jnp.where(idx == 0, zero, from_prev)
    lower = jnp.where(idx == n - 1, zero, from_next)
    return jnp.concatenate([upper, x, lower], axis=1)


class SpatialBottleneck(nn.Module):
    """Bottleneck whose 3x3 conv runs on H-sharded activations.

    Run inside ``shard_map`` with inputs sharded [N, H/spatial, W, C] over
    ``axis_name``. Only stride-1 blocks support spatial sharding (the
    reference's spatial path has the same constraint for the halo math).
    """

    filters: int
    strides: int = 1
    expansion: int = 4
    axis_name: str = "data"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.strides != 1:
            raise ValueError("SpatialBottleneck supports stride 1 (reference parity)")
        from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm
        conv = lambda f, k, name, **kw: nn.Conv(
            f, k, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            name=name, **kw)
        # BN stats synced over the spatial axis so sharded == full-volume
        # (the reference's spatial path shares BN stats via its bn_group)
        norm = lambda f, name: SyncBatchNorm(num_features=f,
                                             axis_name=self.axis_name, name=name)
        ura = not train
        residual = x
        y = conv(self.filters, (1, 1), "conv1")(x)
        y = jax.nn.relu(norm(self.filters, "n1")(y, use_running_average=ura))
        # 3x3 with halo: pad H with neighbor rows, conv VALID on H
        y = halo_exchange(y, self.axis_name, 1)
        y = conv(self.filters, (3, 3), "conv2",
                 padding=[(0, 0), (1, 1)])(y)
        y = jax.nn.relu(norm(self.filters, "n2")(y, use_running_average=ura))
        y = conv(self.filters * self.expansion, (1, 1), "conv3")(y)
        y = norm(self.filters * self.expansion, "n3")(y, use_running_average=ura)
        if residual.shape[-1] != self.filters * self.expansion:
            residual = conv(self.filters * self.expansion, (1, 1), "proj")(x)
            residual = norm(self.filters * self.expansion, "n4")(
                residual, use_running_average=ura)
        return jax.nn.relu(y + residual)
