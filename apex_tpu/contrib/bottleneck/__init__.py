"""apex_tpu.contrib.bottleneck — fused bottleneck + spatial parallelism.

Reference: ``apex/contrib/bottleneck/bottleneck.py:52-512`` — a
cudnn-frontend-fused ResNet bottleneck and ``SpatialBottleneck``, which
splits the H dimension across ``spatial_group_size`` GPUs with explicit
halo transfers around each 3x3 conv.
"""

from apex_tpu.contrib.bottleneck.bottleneck import (  # noqa: F401
    Bottleneck,
    SpatialBottleneck,
    halo_exchange,
)
