"""apex.contrib.layer_norm parity surface.

Reference: ``apex/contrib/layer_norm/layer_norm.py`` — ``FastLayerNorm``
(hidden_size, eps) over the ``fast_layer_norm`` CUDA extension
(``ln_fwd``/``ln_bwd``, ``apex/contrib/csrc/layer_norm/``), apex's
second, faster LN for large hidden sizes.

TPU disposition: ONE LN implementation serves both of apex's
(``apex_tpu.ops.layer_norm``), and since ISSUE 13 it is kernel-or-shim
resolved — a real Pallas fwd+bwd pair engages where a tuned cache entry
(``python -m apex_tpu.ops tune --kernel fused_layer_norm``) or an
explicit ``block_r`` says it wins, and the jnp shim (which the r2
measurement showed XLA fuses with its neighbors) remains the default.
This module re-exports that one implementation under the reference's
``FastLayerNorm`` module API so ported code imports unchanged; kernel
knobs (``block_r=``, ``autotune=``) pass through.
"""

from __future__ import annotations

from apex_tpu.normalization.fused_layer_norm import FusedLayerNorm
from apex_tpu.ops.layer_norm import fused_layer_norm_affine


def FastLayerNorm(hidden_size, eps: float = 1e-5, **kw) -> FusedLayerNorm:
    """``FastLayerNorm(hidden_size, eps)`` (reference ``layer_norm.py:31``)
    — same params (weight=ones, bias=zeros) and forward contract as the
    CUDA module, backed by the single fused LN implementation (factory,
    since flax modules are frozen dataclasses)."""
    return FusedLayerNorm(normalized_shape=hidden_size, eps=eps, **kw)


def ln_fwd(x, gamma, beta, epsilon: float = 1e-5, **kw):
    """Functional fwd (the ``fast_layer_norm.ln_fwd`` entry): returns the
    normalized output (row stats are autodiff residuals here, not
    caller-managed). Kernel knobs (``block_r=``, ``autotune=``,
    ``interpret=``) pass through to the resolved implementation."""
    return fused_layer_norm_affine(x, gamma, beta, gamma.shape, epsilon,
                                   **kw)
