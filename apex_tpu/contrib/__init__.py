"""apex_tpu.contrib — specialized fused components.

Reference: ``apex/contrib`` (multihead attention, FMHA, xentropy, group
BN, transducer, sparsity, bottleneck, distributed optimizers). Each
subpackage here is the TPU-native counterpart; see SURVEY §2.2 for the
kernel-by-kernel mapping.
"""

from apex_tpu.contrib import xentropy  # noqa: F401
from apex_tpu.contrib import multihead_attn  # noqa: F401
from apex_tpu.contrib import fmha  # noqa: F401
from apex_tpu.contrib import optimizers  # noqa: F401
from apex_tpu.contrib import transducer  # noqa: F401
from apex_tpu.contrib import groupbn  # noqa: F401
from apex_tpu.contrib import sparsity  # noqa: F401
from apex_tpu.contrib import bottleneck  # noqa: F401
