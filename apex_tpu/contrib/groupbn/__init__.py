"""apex_tpu.contrib.groupbn — NHWC BatchNorm with cross-device BN groups.

Reference: ``apex/contrib/groupbn/batch_norm.py`` (``BatchNorm2d_NHWC``)
over ``apex/contrib/csrc/groupbn/*`` (~5.1k LoC: NHWC kernels,
add+relu fusion, multi-GPU ``bn_group`` via CUDA IPC peer buffers).

TPU: NHWC is the native layout and cross-chip stat exchange is a psum —
the whole extension reduces to :class:`apex_tpu.parallel.SyncBatchNorm`
configured with a group; this module provides the reference's class API.
"""

from apex_tpu.contrib.groupbn.batch_norm import BatchNorm2d_NHWC  # noqa: F401
