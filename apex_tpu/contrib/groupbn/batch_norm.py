"""BatchNorm2d_NHWC: group BN with fused add+relu.

Reference API (``apex/contrib/groupbn/batch_norm.py``): constructor takes
``(planes, fuse_relu=False, bn_group=1)``; forward takes ``(x, z=None)``
where ``z`` is a residual fused into the normalize+add+relu kernel
(``bn_add_relu``). ``bn_group > 1`` syncs stats across that many devices.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, create_syncbn_process_group
from apex_tpu._compat import axis_size as _axis_size


class BatchNorm2d_NHWC(nn.Module):
    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    world_size: Optional[int] = None   # inferred from the mesh axis if unset
    momentum: float = 0.1              # torch convention, as SyncBatchNorm
    eps: float = 1e-5
    axis_name: Optional[str] = "data"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, z=None, use_running_average: bool = False):
        groups = None
        axis = self.axis_name if self.bn_group > 1 else None
        if self.bn_group > 1:
            ws = self.world_size
            if ws is None:
                try:
                    # static axis size at trace time
                    ws = _axis_size(self.axis_name)
                except NameError:
                    # e.g. Module.init outside shard_map — single device,
                    # no group construction (same guard as SyncBatchNorm)
                    ws = 1
                    axis = None
            if ws > self.bn_group:
                groups = create_syncbn_process_group(self.bn_group, ws)
        bn = SyncBatchNorm(
            num_features=self.num_features,
            eps=self.eps,
            momentum=self.momentum,
            axis_name=axis,
            axis_index_groups=groups,
            fuse_relu=self.fuse_relu,
            param_dtype=self.param_dtype,
            name="bn")
        return bn(x, z=z, use_running_average=use_running_average)
