"""DistributedFusedLAMB: ZeRO-sharded two-phase LAMB.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:82-160,
556-778`` — pipelined reduce-scatter of flat grad blocks during backward
(``_pipeline_block_reductions``:640), global grad-norm with clipping,
sharded ``multi_tensor_lamb_compute_update_term``, allgather of
per-tensor update norms, sharded weight update, allgather of new params
(``_pipeline_step``:722).

TPU: the same dataflow in one jitted region: psum_scatter grads → global
norm (psum of shard partials) → sharded Adam-style update term →
per-tensor norms + psum → trust-ratio-scaled sharded update → all_gather
params. Since ``apex_tpu.zero`` landed this class IS
``ZeroOptimizer(kind="lamb", shard_params=False)``; the layout-specific
trust-ratio machinery below is documented here and implemented on the
shared base.

Per-tensor reductions exploit that each leaf occupies a CONTIGUOUS range
of the flat buffer, so every leaf∩shard intersection is a contiguous
(dynamic) range: shard-local per-leaf sums are masked static-length
window reductions (exact — see ``ZeroOptimizer._range_sums``), and the
per-position trust ratio is a piecewise-constant ramp built by one tiny
scatter + cumsum — no ``segment_sum`` scatter and no flat-sized gather,
both of which lower poorly on TPU (a BERT-base LAMB step went ~100x
slower than its matmuls through them). (The ZeRO-3 tier's per-leaf
layout makes every range STATIC and skips all of this — see
``zero/optimizer.py``.)
"""

from __future__ import annotations

from apex_tpu.zero.optimizer import ZeroOptimizer
from apex_tpu.zero.update import ShardedLambState  # noqa: F401  (re-export)


class DistributedFusedLAMB(ZeroOptimizer):
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, max_grad_norm=1.0,
                 adam_w_mode=True, grad_averaging=True, use_nvlamb=False,
                 axis_name: str = "data", overlap_comm: bool = False,
                 autotune: str | None = None):
        super().__init__(
            lr, kind="lamb", shard_params=False,
            bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            gradient_average=grad_averaging, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb, axis_name=axis_name,
            overlap_comm=overlap_comm, autotune=autotune)

    @property
    def grad_averaging(self):
        """apex's LAMB knob name (drives both the dp mean and beta3 —
        the reference conflates them the same way)."""
        return self.gradient_average
