"""DistributedFusedLAMB: ZeRO-sharded two-phase LAMB.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:82-160,
556-778`` — pipelined reduce-scatter of flat grad blocks during backward
(``_pipeline_block_reductions``:640), global grad-norm with clipping,
sharded ``multi_tensor_lamb_compute_update_term``, allgather of
per-tensor update norms, sharded weight update, allgather of new params
(``_pipeline_step``:722).

TPU: the same dataflow in one jitted region: psum_scatter grads → global
norm (psum of shard partials) → sharded Adam-style update term →
per-tensor norms + psum → trust-ratio-scaled sharded update → all_gather
params.

Per-tensor reductions exploit that each leaf occupies a CONTIGUOUS range
of the flat buffer, so every leaf∩shard intersection is a contiguous
(dynamic) range: shard-local per-leaf sums are masked static-length
window reductions (exact — see ``_range_sums``), and the per-position
trust ratio is a piecewise-constant ramp built by one tiny scatter +
cumsum — no ``segment_sum`` scatter and no flat-sized gather, both of
which lower poorly on TPU (a BERT-base LAMB step went ~100x slower than
its matmuls through them).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu.utils.flat import FlatBuffer
from apex_tpu._compat import axis_size as _axis_size


class ShardedLambState(NamedTuple):
    step: jax.Array
    master_shard: jax.Array
    m_shard: jax.Array
    v_shard: jax.Array


class DistributedFusedLAMB:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, max_grad_norm=1.0,
                 adam_w_mode=True, grad_averaging=True, use_nvlamb=False,
                 axis_name: str = "data"):
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.axis_name = axis_name
        self._spec: FlatBuffer | None = None

    def _world(self):
        try:
            return _axis_size(self.axis_name)
        except NameError:
            return 1

    def _prepare(self, params):
        self._spec = FlatBuffer.from_tree(params)

    def _padded(self, flat, world):
        pad = (-flat.shape[0]) % world
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    def _leaf_starts_in_shard(self, base, per):
        """Per-leaf clipped start positions in shard coordinates (the
        piecewise trust-ratio ramp's scatter indices)."""
        offs = jnp.asarray(self._spec.offsets, jnp.int32)
        return jnp.clip(offs - base, 0, per)

    def _range_sums(self, x, base, per):
        """Per-leaf sums of the leaf∩shard ranges, computed EXACTLY.

        Each leaf intersects the shard in a contiguous range of length
        ≤ min(leaf_size, per) — a *static* bound, so a dynamic-start
        static-length window plus an in-window mask gives a plain masked
        reduction per leaf. (A cumsum-difference formulation cancels
        catastrophically in f32: a 256-element leaf after a 2M-element
        prefix summed to exactly 0.)
        """
        sums = []
        for off, size in zip(self._spec.offsets, self._spec.sizes):
            L = min(size, per)
            s = jnp.clip(off - base, 0, per)          # dynamic, in-shard
            e = jnp.clip(off + size - base, 0, per)
            w = jnp.clip(s, 0, per - L)               # window fits: static L
            win = jax.lax.dynamic_slice_in_dim(x, w, L)
            q = w + jnp.arange(L, dtype=jnp.int32)
            mask = (q >= s) & (q < e)
            sums.append(jnp.sum(jnp.where(mask, win, 0.0)))
        return jnp.stack(sums)

    @staticmethod
    def _piecewise(values, starts, per):
        """[per] vector equal to values[i] on leaf i's shard range —
        a delta scatter (n tiny adds) + cumsum; positions past the last
        leaf (alignment padding) carry the last value, harmless because
        pad slots of p/update are zero."""
        deltas = jnp.diff(values, prepend=jnp.zeros((1,), values.dtype))
        d = jnp.zeros((per + 1,), values.dtype).at[starts].add(deltas)
        return jnp.cumsum(d[:per])

    def init(self, params) -> ShardedLambState:
        self._prepare(params)
        world = self._world()
        flat = self._padded(self._spec.pack(params, dtype=jnp.float32), world)
        per = flat.shape[0] // world
        if world > 1:
            rank = jax.lax.axis_index(self.axis_name)
            shard = jax.lax.dynamic_slice_in_dim(flat, rank * per, per)
        else:
            shard = flat
        return ShardedLambState(jnp.asarray(0, jnp.int32), shard,
                                jnp.zeros_like(shard), jnp.zeros_like(shard))

    def gather_state(self, state: ShardedLambState) -> ShardedLambState:
        """Topology-independent full state for checkpointing (inside
        ``shard_map``); see ``apex_tpu.contrib.optimizers.zero_state``."""
        from apex_tpu.contrib.optimizers.zero_state import gather_zero_state
        return gather_zero_state(self, state)

    def shard_state(self, full_state: ShardedLambState,
                    params=None) -> ShardedLambState:
        """Local shard of a gathered state under the CURRENT mesh — the
        resume path of ``_resume_from_checkpoint`` (lamb.py:139)."""
        from apex_tpu.contrib.optimizers.zero_state import shard_zero_state
        return shard_zero_state(self, full_state, params)

    def apply(self, state: ShardedLambState, params, grads, skip=None, lr=None):
        if self._spec is None:
            self._prepare(params)
        spec = self._spec
        world = self._world()
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        if skip is None:
            skip = jnp.asarray(False)
        b1, b2 = self.betas

        flat_g = self._padded(spec.pack(grads, dtype=jnp.float32), world)
        per = flat_g.shape[0] // world
        if world > 1:
            g_shard = jax.lax.psum_scatter(flat_g, self.axis_name, tiled=True)
            if self.grad_averaging:
                g_shard = g_shard / world
            rank = jax.lax.axis_index(self.axis_name)
        else:
            g_shard = flat_g
            rank = 0

        base = rank * per if world > 1 else 0
        starts = self._leaf_starts_in_shard(base, per)

        # global grad norm + clip (distributed_fused_lamb.py:665-699)
        gsq = jnp.sum(g_shard * g_shard)
        if world > 1:
            gsq = jax.lax.psum(gsq, self.axis_name)
        gnorm = jnp.sqrt(gsq)
        if self.max_grad_norm and self.max_grad_norm > 0:
            g_shard = g_shard / jnp.maximum(1.0, gnorm / self.max_grad_norm)

        def _do(state=state, g=g_shard):
            step = state.step + 1
            p = state.master_shard
            beta3 = (1 - b1) if self.grad_averaging else 1.0
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p
            m = b1 * state.m_shard + beta3 * g
            v = b2 * state.v_shard + (1 - b2) * g * g
            if self.bias_correction:
                sf = step.astype(jnp.float32)
                mhat = m / (1 - jnp.power(b1, sf))
                vhat = v / (1 - jnp.power(b2, sf))
            else:
                mhat, vhat = m, v
            upd = mhat / (jnp.sqrt(vhat) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                upd = upd + self.weight_decay * p

            # per-tensor norms: shard-local contiguous-range sums +
            # cross-shard psum (the allgather of update norms, :722-778)
            w_sq = self._range_sums(p * p, base, per)
            u_sq = self._range_sums(upd * upd, base, per)
            if world > 1:
                w_sq = jax.lax.psum(w_sq, self.axis_name)
                u_sq = jax.lax.psum(u_sq, self.axis_name)
            w_n = jnp.sqrt(w_sq)
            u_n = jnp.sqrt(u_sq)
            ratio = jnp.where((w_n > 0) & (u_n > 0), w_n / jnp.maximum(u_n, 1e-30), 1.0)
            if not self.use_nvlamb and self.weight_decay == 0.0:
                ratio = jnp.ones_like(ratio)
            new_p = p - lr * self._piecewise(ratio, starts, per) * upd
            return ShardedLambState(step, new_p, m, v)

        new_state = jax.lax.cond(skip, lambda: state, _do)
        if world > 1:
            flat_new = jax.lax.all_gather(new_state.master_shard, self.axis_name, tiled=True)
        else:
            flat_new = new_state.master_shard
        return spec.unpack(flat_new[:spec.total]), new_state
