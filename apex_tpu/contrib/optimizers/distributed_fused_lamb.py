"""DistributedFusedLAMB: ZeRO-sharded two-phase LAMB.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:82-160,
556-778`` — pipelined reduce-scatter of flat grad blocks during backward
(``_pipeline_block_reductions``:640), global grad-norm with clipping,
sharded ``multi_tensor_lamb_compute_update_term``, allgather of
per-tensor update norms, sharded weight update, allgather of new params
(``_pipeline_step``:722).

TPU: the same dataflow in one jitted region: psum_scatter grads → global
norm (psum of shard partials) → sharded Adam-style update term →
per-tensor norms via shard-local ``segment_sum`` + psum (the shard
boundaries cut tensors; the static flat→tensor segment map handles it) →
trust-ratio-scaled sharded update → all_gather params.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu.utils.flat import FlatBuffer


class ShardedLambState(NamedTuple):
    step: jax.Array
    master_shard: jax.Array
    m_shard: jax.Array
    v_shard: jax.Array


class DistributedFusedLAMB:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, max_grad_norm=1.0,
                 adam_w_mode=True, grad_averaging=True, use_nvlamb=False,
                 axis_name: str = "data"):
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.axis_name = axis_name
        self._spec: FlatBuffer | None = None
        self._segment_ids: np.ndarray | None = None

    def _world(self):
        try:
            return jax.lax.axis_size(self.axis_name)
        except NameError:
            return 1

    def _prepare(self, params):
        self._spec = FlatBuffer.from_tree(params)
        ids = np.concatenate([
            np.full(size, i, dtype=np.int32)
            for i, size in enumerate(self._spec.sizes)]) if self._spec.sizes else np.zeros(0, np.int32)
        self._segment_ids = ids

    def _padded(self, flat, world):
        pad = (-flat.shape[0]) % world
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    def _shard_segments(self, world, per):
        """Static full segment map padded with a sink id for pad slots."""
        n = len(self._spec.sizes)
        ids = self._segment_ids
        pad = world * per - ids.shape[0]
        if pad:
            ids = np.concatenate([ids, np.full(pad, n, np.int32)])
        return jnp.asarray(ids), n

    def init(self, params) -> ShardedLambState:
        self._prepare(params)
        world = self._world()
        flat = self._padded(self._spec.pack(params, dtype=jnp.float32), world)
        per = flat.shape[0] // world
        if world > 1:
            rank = jax.lax.axis_index(self.axis_name)
            shard = jax.lax.dynamic_slice_in_dim(flat, rank * per, per)
        else:
            shard = flat
        return ShardedLambState(jnp.asarray(0, jnp.int32), shard,
                                jnp.zeros_like(shard), jnp.zeros_like(shard))

    def apply(self, state: ShardedLambState, params, grads, skip=None, lr=None):
        if self._spec is None:
            self._prepare(params)
        spec = self._spec
        world = self._world()
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        if skip is None:
            skip = jnp.asarray(False)
        b1, b2 = self.betas

        flat_g = self._padded(spec.pack(grads, dtype=jnp.float32), world)
        per = flat_g.shape[0] // world
        if world > 1:
            g_shard = jax.lax.psum_scatter(flat_g, self.axis_name, tiled=True)
            if self.grad_averaging:
                g_shard = g_shard / world
            rank = jax.lax.axis_index(self.axis_name)
        else:
            g_shard = flat_g
            rank = 0

        all_ids, n_tensors = self._shard_segments(world, per)
        seg_shard = jax.lax.dynamic_slice_in_dim(all_ids, rank * per, per)

        # global grad norm + clip (distributed_fused_lamb.py:665-699)
        gsq = jnp.sum(g_shard * g_shard)
        if world > 1:
            gsq = jax.lax.psum(gsq, self.axis_name)
        gnorm = jnp.sqrt(gsq)
        if self.max_grad_norm and self.max_grad_norm > 0:
            g_shard = g_shard / jnp.maximum(1.0, gnorm / self.max_grad_norm)

        def _do(state=state, g=g_shard):
            step = state.step + 1
            p = state.master_shard
            beta3 = (1 - b1) if self.grad_averaging else 1.0
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p
            m = b1 * state.m_shard + beta3 * g
            v = b2 * state.v_shard + (1 - b2) * g * g
            if self.bias_correction:
                sf = step.astype(jnp.float32)
                mhat = m / (1 - jnp.power(b1, sf))
                vhat = v / (1 - jnp.power(b2, sf))
            else:
                mhat, vhat = m, v
            upd = mhat / (jnp.sqrt(vhat) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                upd = upd + self.weight_decay * p

            # per-tensor norms: shard-local segment sums + cross-shard psum
            # (the allgather of update norms, :722-778)
            w_sq = jax.ops.segment_sum(p * p, seg_shard, num_segments=n_tensors + 1)
            u_sq = jax.ops.segment_sum(upd * upd, seg_shard, num_segments=n_tensors + 1)
            if world > 1:
                w_sq = jax.lax.psum(w_sq, self.axis_name)
                u_sq = jax.lax.psum(u_sq, self.axis_name)
            w_n = jnp.sqrt(w_sq)
            u_n = jnp.sqrt(u_sq)
            ratio = jnp.where((w_n > 0) & (u_n > 0), w_n / jnp.maximum(u_n, 1e-30), 1.0)
            if not self.use_nvlamb and self.weight_decay == 0.0:
                ratio = jnp.ones_like(ratio)
            new_p = p - lr * ratio[seg_shard] * upd
            return ShardedLambState(step, new_p, m, v)

        new_state = jax.lax.cond(skip, lambda: state, _do)
        if world > 1:
            flat_new = jax.lax.all_gather(new_state.master_shard, self.axis_name, tiled=True)
        else:
            flat_new = new_state.master_shard
        return spec.unpack(flat_new[:spec.total]), new_state
