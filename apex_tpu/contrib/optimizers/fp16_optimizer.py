"""Legacy FP16_Optimizer wrapper (contrib flavor).

Reference: ``apex/contrib/optimizers/fp16_optimizer.py`` — wraps a fused
optimizer with fp32 master weights and (dynamic) loss scaling for users
not on the amp frontend; exposes ``state_dict``/``load_state_dict``
(:179-230).

TPU: thin composition of an apex_tpu fused optimizer (which already does
master weights) with a ``LossScaler``; step() unscales, skip-on-overflow,
and updates the scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp import scaler as scaler_mod
from apex_tpu.amp.scaler import LossScaler


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=False):
        self.optimizer = init_optimizer
        self.optimizer.master_weights = True
        args = dynamic_loss_args or {}
        self.loss_scaler = (LossScaler("dynamic", **args) if dynamic_loss_scale
                            else LossScaler(static_loss_scale))
        self.verbose = verbose

    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale()

    def scale_loss(self, loss):
        return scaler_mod.scale_value(jnp.asarray(loss), self.loss_scaler.state)

    def backward(self, loss):  # API-parity: user computes grads explicitly in JAX
        raise NotImplementedError(
            "JAX has no .backward(); compute grads of self.scale_loss(loss) "
            "and call step(grads)")

    def step(self, grads=None, closure=None):
        if self.optimizer.state is None:
            self.optimizer.initialize_state()
        self.optimizer.arm_scaler(self.loss_scaler)
        return self.optimizer.step(grads)

    def zero_grad(self, set_grads_to_None=True):
        pass

    def state_dict(self) -> dict:
        return {
            "loss_scaler": self.loss_scaler.state_dict(),
            "optimizer_state_dict": self.optimizer.state_dict(),
        }

    def load_state_dict(self, sd: dict):
        self.loss_scaler.load_state_dict(sd["loss_scaler"])
        self.optimizer.load_state_dict(sd["optimizer_state_dict"])
