"""DistributedFusedAdam: ZeRO-style sharded Adam over the data axis.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:55-118,
409,477`` — the flat gradient buffer is reduce-scattered so each rank owns
1/world of the gradients, the Adam update runs only on that shard (with
sharded m/v/master state), and the new parameters are all-gathered back
(optionally e5m2-compressed). Overlap is pipelined per block during
backward.

Since the ``apex_tpu.zero`` subsystem landed, this class IS
``ZeroOptimizer(kind="adam", shard_params=False)`` — the ZeRO-1/2 tier:
optimizer state sharded, parameters replicated, one ``psum_scatter`` +
sharded fused update + one ``all_gather`` inside the jitted step (XLA
overlaps the collectives with surrounding compute; ``overlap_comm=True``
opts into the explicit ppermute rings instead). The update math and the
accounted collectives are the shared ``zero/update.py`` /
``zero/comm.py`` implementations — the same code ZeRO-3 runs on per-leaf
shards — and ``compress_allgather`` rides
``zero.comm.quantized_all_gather`` (the reference's e5m2 trick: master
state stays exact, only the *broadcast* copy is quantized).

Run ``init``/``apply`` inside ``shard_map`` over the shard axis. At
world=1 it degrades to plain fused Adam.
"""

from __future__ import annotations

from apex_tpu.zero.optimizer import ZeroOptimizer
from apex_tpu.zero.update import ShardedAdamState  # noqa: F401  (re-export)


class DistributedFusedAdam(ZeroOptimizer):
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, adam_w_mode=True,
                 gradient_average=True, axis_name: str = "data",
                 compress_allgather: bool = False,
                 overlap_comm: bool = False,
                 autotune: str | None = None):
        super().__init__(
            lr, kind="adam", shard_params=False,
            bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            gradient_average=gradient_average, axis_name=axis_name,
            compress_allgather=compress_allgather,
            overlap_comm=overlap_comm, autotune=autotune)
