"""DistributedFusedAdam: ZeRO-style sharded Adam over the data axis.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:55-118,
409,477`` — the flat gradient buffer is reduce-scattered so each rank owns
1/world of the gradients, the Adam update runs only on that shard (with
sharded m/v/master state), and the new parameters are all-gathered back
(optionally e5m2-compressed). Overlap is pipelined per block during
backward.

TPU design: one ``psum_scatter`` + sharded fused update + one
``all_gather`` inside the jitted step — XLA overlaps the collectives with
surrounding compute (the hand-built per-block pipelining of the reference
is the scheduler's job here). Optional ``compress_allgather`` casts the
gathered params to float8_e5m2 (the reference's e5m2 trick) — master
state stays exact, so compression only quantizes the *broadcast* copy.

Run ``init``/``apply`` inside ``shard_map`` over the shard axis. At
world=1 it degrades to plain fused Adam.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.utils.flat import FlatBuffer
from apex_tpu._compat import axis_size as _axis_size


class ShardedAdamState(NamedTuple):
    step: jax.Array
    master_shard: jax.Array   # [total/world] fp32
    m_shard: jax.Array
    v_shard: jax.Array


def _pad_to(x, mult):
    pad = (-x.shape[0]) % mult
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x


class DistributedFusedAdam:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, weight_decay=0.0, adam_w_mode=True,
                 gradient_average=True, axis_name: str = "data",
                 compress_allgather: bool = False):
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.gradient_average = gradient_average
        self.axis_name = axis_name
        self.compress_allgather = compress_allgather
        self._spec: FlatBuffer | None = None

    def _world(self):
        try:
            return _axis_size(self.axis_name)
        except NameError:
            return 1

    def init(self, params) -> ShardedAdamState:
        self._spec = FlatBuffer.from_tree(params)
        world = self._world()
        flat = _pad_to(self._spec.pack(params, dtype=jnp.float32), world)
        per = flat.shape[0] // world
        if world > 1:
            rank = jax.lax.axis_index(self.axis_name)
            shard = jax.lax.dynamic_slice_in_dim(flat, rank * per, per)
        else:
            shard = flat
        return ShardedAdamState(
            step=jnp.asarray(0, jnp.int32),
            master_shard=shard,
            m_shard=jnp.zeros_like(shard),
            v_shard=jnp.zeros_like(shard),
        )

    def gather_state(self, state: ShardedAdamState) -> ShardedAdamState:
        """Topology-independent full state for checkpointing (inside
        ``shard_map``); see ``apex_tpu.contrib.optimizers.zero_state``."""
        from apex_tpu.contrib.optimizers.zero_state import gather_zero_state
        return gather_zero_state(self, state)

    def shard_state(self, full_state: ShardedAdamState,
                    params=None) -> ShardedAdamState:
        """Local shard of a gathered state under the CURRENT mesh — the
        dp=8 -> dp=4 resume path (``distributed_fused_lamb.py:139``)."""
        from apex_tpu.contrib.optimizers.zero_state import shard_zero_state
        return shard_zero_state(self, full_state, params)

    def apply(self, state: ShardedAdamState, params, grads, skip=None, lr=None):
        """One sharded step; returns (new_params, new_state)."""
        if self._spec is None:
            self._spec = FlatBuffer.from_tree(params)
        spec = self._spec
        world = self._world()
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        if skip is None:
            skip = jnp.asarray(False)

        flat_g = _pad_to(spec.pack(grads, dtype=jnp.float32), world)
        if world > 1:
            # reduce_scatter: each rank receives the summed shard it owns
            # (distributed_fused_adam.py:409 _pipeline_block_reductions)
            g_shard = jax.lax.psum_scatter(flat_g, self.axis_name, tiled=True)
            if self.gradient_average:
                g_shard = g_shard / world
        else:
            g_shard = flat_g

        def _do(state=state, g=g_shard, lr=lr):
            b1, b2 = self.betas
            step = state.step + 1
            p = state.master_shard
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p
            m = b1 * state.m_shard + (1 - b1) * g
            v = b2 * state.v_shard + (1 - b2) * g * g
            if self.bias_correction:
                sf = step.astype(jnp.float32)
                mhat = m / (1 - jnp.power(b1, sf))
                vhat = v / (1 - jnp.power(b2, sf))
            else:
                mhat, vhat = m, v
            upd = mhat / (jnp.sqrt(vhat) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                upd = upd + self.weight_decay * p
            return ShardedAdamState(step, p - lr * upd, m, v)

        new_state = jax.lax.cond(skip, lambda: state, _do)

        # all_gather the fresh params (distributed_fused_adam.py:477),
        # optionally through the e5m2 compressed path
        shard_out = new_state.master_shard
        if self.compress_allgather:
            shard_out = shard_out.astype(jnp.float8_e5m2)
        if world > 1:
            flat_new = jax.lax.all_gather(shard_out, self.axis_name, tiled=True)
        else:
            flat_new = shard_out
        flat_new = flat_new.astype(jnp.float32)[:spec.total]
        return spec.unpack(flat_new), new_state
