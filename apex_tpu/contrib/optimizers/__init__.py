"""apex_tpu.contrib.optimizers — ZeRO-sharded optimizers + legacy wrappers.

Reference: ``apex/contrib/optimizers/`` (DistributedFusedAdam,
DistributedFusedLAMB, FP16_Optimizer, deprecated FusedAdam/FusedSGD).
"""

from apex_tpu.contrib.optimizers.distributed_fused_adam import DistributedFusedAdam  # noqa: F401
from apex_tpu.contrib.optimizers.distributed_fused_lamb import DistributedFusedLAMB  # noqa: F401
from apex_tpu.contrib.optimizers.fp16_optimizer import FP16_Optimizer  # noqa: F401
