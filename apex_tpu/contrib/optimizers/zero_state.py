"""Checkpoint hooks for ZeRO-sharded optimizer state: gather to a full
(topology-independent) form for saving, re-shard on load under a
possibly DIFFERENT world size.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:139``
``_resume_from_checkpoint`` re-slices a gathered flat buffer into the
local shard. Here the same two moves are explicit functions usable with
both ``DistributedFusedAdam`` and ``DistributedFusedLAMB`` (their states
share the (step, master_shard, m_shard, v_shard) layout):

- ``gather_zero_state`` runs inside ``shard_map`` on the OLD mesh: one
  ``all_gather`` per buffer, unpadded to the logical parameter count —
  the result is identical on every rank and is what
  ``apex_tpu.checkpoint.save_checkpoint`` writes.
- ``shard_zero_state`` runs inside ``shard_map`` on the NEW mesh: re-pad
  to the new world size, slice the local shard. dp=8 state resumes on
  dp=4 (or any world) bit-exactly, because padding is zeros and the
  sharded update all-gathers identical params regardless of topology.

The ZeRO-3 tier shards *parameters* too; its gather/reshard — the same
moves per leaf, params and (step, master, m, v) alike — lives in
``apex_tpu.zero.elastic`` and is re-exported here so the checkpoint
entry points for every tier share one module. Collectives route through
``zero/comm.py`` so the monitor's trace-time table accounts them.
"""

from __future__ import annotations

import jax

from apex_tpu.zero import comm as _comm
from apex_tpu.zero.core import pad_to_multiple as _pad_to
from apex_tpu.zero.elastic import (  # noqa: F401  (tier-3 re-exports)
    gather_zero3_params,
    gather_zero3_state,
    shard_zero3_params,
    shard_zero3_state,
)


def gather_zero_state(opt, state):
    """Full (unsharded) state from a per-rank sharded one; call inside
    ``shard_map`` over ``opt.axis_name``. ``opt`` must know its flat
    spec (after ``init``/``apply``)."""
    if opt._spec is None:
        raise ValueError("optimizer has no flat spec yet — call init() "
                         "(or pass the state through apply once) first")

    def g(x):
        full = _comm.all_gather_flat(x, opt.axis_name)
        return full[:opt._spec.total]

    return type(state)(state.step, g(state.master_shard),
                       g(state.m_shard), g(state.v_shard))


def shard_zero_state(opt, full_state, params=None):
    """Local shard of a full (gathered) state under the CURRENT mesh;
    call inside ``shard_map`` over ``opt.axis_name``. Pass ``params``
    when the optimizer is fresh (sets its flat spec)."""
    if opt._spec is None:
        if params is None:
            raise ValueError("fresh optimizer: pass params so the flat "
                             "spec can be derived")
        opt.init(params)  # sets the spec; the returned state is discarded
    world = opt._world()

    def s(x):
        flat = _pad_to(x, world)
        per = flat.shape[0] // world
        if world > 1:
            rank = jax.lax.axis_index(opt.axis_name)
            return jax.lax.dynamic_slice_in_dim(flat, rank * per, per)
        return flat

    return type(full_state)(full_state.step, s(full_state.master_shard),
                            s(full_state.m_shard), s(full_state.v_shard))
