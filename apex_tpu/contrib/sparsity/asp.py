"""ASP: automatic sparsity for fine-tuning.

Reference: ``apex/contrib/sparsity/asp.py`` — ``ASP.init_model_for_pruning``
whitelists layer types/min sizes, ``compute_sparse_masks`` builds 2:4
masks, and the optimizer is patched so masks are re-applied after every
step (pruned weights stay zero). Restore via ``restore_pruned_weights``.

TPU: masks are a pytree of the same structure as params; application is
``params * masks``; "patching the optimizer" is a functional wrapper
around ``apply``. State (masks) lives on the ASP object or flows
explicitly.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask
from apex_tpu.utils.tree import tree_map_with_path_names


def _default_whitelist(path_names, leaf) -> bool:
    """Matrix-shaped weights with dims divisible by 4 (the reference
    whitelists Linear/Conv weights with min features, ``asp.py``)."""
    if not hasattr(leaf, "ndim") or leaf.ndim < 2:
        return False
    name = path_names[-1].lower() if path_names else ""
    if name in ("bias", "scale"):
        return False
    # the mask is cut along the reduction dim (axis -2 of JAX kernels)
    return leaf.shape[-2] % 4 == 0 and leaf.shape[-2] >= 16


class ASP:
    """Class-method API mirroring the reference; also usable as an instance."""

    _masks: Any = None
    _whitelist: Callable = staticmethod(_default_whitelist)
    _pattern: str = "2:4"

    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator: str = "m4n2_1d",
                               whitelist: Optional[Callable] = None,
                               allow_recompute_mask: bool = False):
        if whitelist is not None:
            cls._whitelist = staticmethod(whitelist)
        if "4" in mask_calculator:
            cls._pattern = "2:4"
        return params

    @classmethod
    def compute_sparse_masks(cls, params):
        def one(path, leaf):
            if cls._whitelist(path, leaf):
                return create_mask(leaf, cls._pattern)
            return jnp.ones_like(leaf, dtype=bool)

        cls._masks = tree_map_with_path_names(one, params)
        return cls._masks

    @classmethod
    def apply_masks(cls, params, masks=None):
        masks = masks if masks is not None else cls._masks
        if masks is None:
            raise RuntimeError("compute_sparse_masks first")
        return jax.tree.map(lambda p, m: p * m.astype(p.dtype), params, masks)

    @classmethod
    def init_optimizer_for_pruning(cls, optimizer):
        """Wrap ``optimizer.apply`` so masks re-apply after every step
        (the reference patches ``optimizer.step``, ``asp.py``)."""
        inner_apply = optimizer.apply

        def masked_apply(state, params, grads, skip=None, **kw):
            new_params, new_state = inner_apply(state, params, grads, skip=skip, **kw)
            if cls._masks is not None:
                new_params = cls.apply_masks(new_params)
            return new_params, new_state

        optimizer.apply = masked_apply
        return optimizer

    @classmethod
    def restore_pruned_weights(cls, params):
        """Masks off — nothing to restore in the functional design (the
        dense weights were never mutated in place); returns params."""
        cls._masks = None
        return params

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        return cls._masks is not None
