"""Structured-sparsity mask construction.

Reference: ``apex/contrib/sparsity/sparse_masklib.py`` — builds n:m masks
(default 2:4 along the input dimension) by magnitude, via enumerated
permutation patterns. TPU: a top-k over contiguous groups of m — one
vectorized op, jit-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def m4n2_1d(w, *_args, **_kw):
    """2:4 mask along the reduction dim (keep the 2 largest |w| of each 4)."""
    return create_mask(w, pattern="2:4")


def create_mask(w, pattern: str = "2:4", axis: int = -2):
    """N:M mask by magnitude along ``axis``.

    The reference prunes along the *input/reduction* dimension — torch
    weights are [out, in] so it groups the last dim; JAX kernels are
    [..., in, out], so the reduction dim is ``-2`` here. That is the dim a
    sparse dot-product contraction actually skips.
    """
    n, m = (int(s) for s in pattern.split(":"))
    axis = axis % w.ndim
    if w.shape[axis] % m:
        raise ValueError(
            f"dim {axis} of size {w.shape[axis]} not divisible by group size {m}")
    wt = jnp.moveaxis(w, axis, -1)
    g = wt.reshape(*wt.shape[:-1], wt.shape[-1] // m, m)
    mag = jnp.abs(g.astype(jnp.float32))
    # rank within each group; keep the n largest magnitudes
    order = jnp.argsort(mag, axis=-1)            # ascending
    ranks = jnp.argsort(order, axis=-1)          # rank of each element
    mask = ranks >= (m - n)
    return jnp.moveaxis(mask.reshape(wt.shape), -1, axis)
