"""Structured-sparsity mask construction.

Reference: ``apex/contrib/sparsity/sparse_masklib.py`` — builds n:m masks
(default 2:4 along the input dimension) by magnitude, via enumerated
permutation patterns. TPU: a top-k over contiguous groups of m — one
vectorized op, jit-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def m4n2_1d(w, *_args, **_kw):
    """2:4 mask along the last dim (keep the 2 largest |w| of each 4)."""
    return create_mask(w, pattern="2:4")


def create_mask(w, pattern: str = "2:4"):
    n, m = (int(s) for s in pattern.split(":"))
    *lead, last = w.shape
    if last % m:
        raise ValueError(f"last dim {last} not divisible by group size {m}")
    g = w.reshape(*lead, last // m, m)
    mag = jnp.abs(g.astype(jnp.float32))
    # rank within each group; keep the n largest magnitudes
    order = jnp.argsort(mag, axis=-1)            # ascending
    ranks = jnp.argsort(order, axis=-1)          # rank of each element
    mask = ranks >= (m - n)
    return mask.reshape(w.shape)
