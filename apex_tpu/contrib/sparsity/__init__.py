"""apex_tpu.contrib.sparsity — ASP (automatic structured sparsity).

Reference: ``apex/contrib/sparsity/asp.py`` + ``sparse_masklib.py``:
2:4 structured sparsity masks computed from weight magnitudes, applied to
whitelisted layers and re-applied after each optimizer step so pruned
weights stay zero through fine-tuning.
"""

from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.sparse_masklib import create_mask, m4n2_1d  # noqa: F401
