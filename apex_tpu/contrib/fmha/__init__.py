"""apex_tpu.contrib.fmha — packed variable-length fused attention.

Reference: ``apex/contrib/fmha/fmha.py:32-58`` — ``fmha.fwd(qkv,
cu_seqlens, p_dropout, max_s, ...)`` on a packed [total, 3, h, d] batch,
seqlen ≤ 512, sm80-only. TPU: cu_seqlens → segment ids feeding the Pallas
flash-attention kernel; no seqlen cap, any chip.
"""

from apex_tpu.contrib.fmha.fmha import fmha_varlen, FMHAFun, cu_seqlens_to_segment_ids  # noqa: F401
