"""Packed-varlen attention via segment ids.

Reference call shape (``apex/contrib/fmha/fmha.py:32-58``): QKV packed as
[total_tokens, 3, heads, head_dim] with ``cu_seqlens`` [batch+1]
prefix-sum boundaries. The CUDA kernels specialize on max seqlen
(128/256/384/512); the TPU kernel has no such cap — one code path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


def cu_seqlens_to_segment_ids(cu_seqlens, total: int):
    """[b+1] prefix sums -> int32 [total] segment ids (static total)."""
    return jnp.searchsorted(cu_seqlens[1:], jnp.arange(total), side="right").astype(jnp.int32)


def fmha_varlen(qkv, cu_seqlens, *, causal: bool = False,
                scale: float | None = None, block: int = 512,
                dropout_rate: float = 0.0, dropout_seed=None):
    """qkv: [total, 3, h, d] packed batch. Returns [total, h, d].

    ``total`` should be padded to a block multiple; pad tokens get a
    segment id of their own trailing segment and attend only themselves
    (their outputs are garbage to be masked by the caller, same contract
    as the reference's packed layout).

    ``dropout_rate``/``dropout_seed``: in-kernel attention dropout
    (reference p_dropout plumbing, ``fmha_api.cpp:67-110``).
    """
    total, three, h, d = qkv.shape
    if three != 3:
        raise ValueError("qkv must be [total, 3, heads, head_dim]")
    sids = cu_seqlens_to_segment_ids(cu_seqlens, total)[None]  # [1, total]
    q = qkv[:, 0].transpose(1, 0, 2)[None]   # [1, h, total, d]
    k = qkv[:, 1].transpose(1, 0, 2)[None]
    v = qkv[:, 2].transpose(1, 0, 2)[None]
    blk = min(block, total)
    # backward blocks stated explicitly: inheritance is intended here
    # (blocks must stay <= total), and saying so keeps flash_attention's
    # inherited-backward-blocks warning — and its once-per-process key —
    # for end users who actually left the backward tiling implicit
    out = flash_attention(q, k, v, segment_ids_q=sids, causal=causal,
                          scale=scale, block_q=blk, block_k=blk,
                          block_q_bwd=blk, block_k_bwd=blk,
                          dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)
    return out[0].transpose(1, 0, 2)          # [total, h, d]


class FMHAFun:
    """API-parity shim for ``FMHAFun.apply`` (``apex/contrib/fmha/fmha.py:9``)."""

    @staticmethod
    def apply(qkv, cu_seqlens, p_dropout=0.0, max_s=None, is_training=True,
              zero_tensors=False, dropout_seed=None):
        del max_s, zero_tensors
        rate = float(p_dropout) if is_training else 0.0
        if rate > 0.0 and dropout_seed is None:
            # the reference draws from the global philox stream per call;
            # the stateless TPU kernel needs an explicit per-step seed
            raise ValueError(
                "p_dropout > 0 requires dropout_seed (pass a fresh int32 "
                "per training step)")
        return fmha_varlen(qkv, cu_seqlens, dropout_rate=rate,
                           dropout_seed=dropout_seed if rate > 0.0 else None)
