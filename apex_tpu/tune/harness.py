"""Timed sweep harness: measure candidate configs, rank, pick.

Design constraints (ISSUE 8 tentpole b):

- **compile excluded** — each candidate's runner is built and warmed
  before its first timed call, so compile time never pollutes the
  ranking (it is recorded separately as ``build_s``);
- **median-of-k steady state** — every timed call is also recorded
  through the monitor timer path (``tune/sweep/<label>`` timer events),
  so a sweep leaves the same JSONL evidence as a bench section;
- **per-config timeout** — one pathological compile (or a config
  Mosaic rejects only at the end of a long pipeline) cannot eat the
  sweep: the config is marked failed and the sweep moves on;
- **injectable timer** — ``timer(fn, config) -> seconds`` replaces the
  wall clock. Tests and the bench smoke section inject a deterministic
  fake clock (a pure function of the config), making cache resolution,
  ranking, and persistence testable on CPU without a TPU: same grid +
  same fake timings => same chosen config, bit for bit.

Determinism: ranking is ``min`` over medians with ties broken by
candidate order (the generator emits coarsest-first), via a stable sort
on ``(median, index)``.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable, Optional

from apex_tpu.monitor import hooks


class SweepTimeout(Exception):
    """A candidate exceeded its per-config budget."""


def wall_timer(fn: Callable[[], None], config: dict) -> float:
    """Default timer: run ``fn`` once, return elapsed seconds."""
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _call_with_timeout(fn: Callable[[], object],
                       timeout_s: Optional[float]):
    """Run ``fn`` under a SIGALRM budget when one is available (main
    thread, a positive budget); otherwise run it unguarded. SIGALRM is
    the only way to interrupt a native XLA compile; worker threads fall
    back to unguarded calls — the sweep still skips the config on any
    exception, it just cannot preempt a hang there.

    ITIMER_REAL is process-global, so an enclosing alarm budget (e.g.
    bench.py's per-section SIGALRM) is suspended for the duration and
    re-armed with its REMAINING time afterwards — if it would have
    expired while ours was live, it fires (almost) immediately under
    its restored handler instead of being silently cancelled."""
    if (timeout_s is None or timeout_s <= 0
            or threading.current_thread() is not threading.main_thread()):
        return fn()

    def _alarm(signum, frame):
        raise SweepTimeout(f"config exceeded {timeout_s:.1f}s budget")

    prev_handler = signal.signal(signal.SIGALRM, _alarm)
    prev_remaining, prev_interval = signal.getitimer(signal.ITIMER_REAL)
    t0 = time.monotonic()
    signal.setitimer(signal.ITIMER_REAL, timeout_s)
    try:
        return fn()
    finally:
        # handler first, then re-arm: an already-due outer budget must
        # fire under ITS handler, not ours
        signal.signal(signal.SIGALRM, prev_handler)
        if prev_remaining > 0:
            elapsed = time.monotonic() - t0
            signal.setitimer(signal.ITIMER_REAL,
                             max(prev_remaining - elapsed, 1e-6),
                             prev_interval)
        else:
            signal.setitimer(signal.ITIMER_REAL, 0.0)


def sweep(candidates: list[dict], build: Callable[[dict], Callable[[], None]],
          *, timer: Optional[Callable[[Callable[[], None], dict], float]]
          = None, median_of: int = 5, warmup: int = 1,
          config_timeout_s: Optional[float] = None,
          label: str = "sweep") -> dict:
    """Measure every candidate, return the ranked result.

    ``build(config)`` returns a zero-arg callable running ONE steady-
    state iteration (it must block until the work is done, e.g. via
    ``jax.block_until_ready``); build + ``warmup`` calls happen before
    timing, so compilation is excluded. ``timer(fn, config)`` returns
    seconds for one iteration (default: wall clock).

    Returns ``{"best": config|None, "best_s": float|None,
    "results": [...], "failed": [...]}`` where each result row is
    ``{config, median_s, timings_s, build_s}`` (results sorted
    best-first) and each failed row is ``{config, error}``.
    """
    timer = timer or wall_timer
    results, failed = [], []
    for idx, config in enumerate(candidates):
        try:
            t_build0 = time.perf_counter()

            def _prepare(config=config):
                fn = build(config)
                for _ in range(max(0, warmup)):
                    fn()
                return fn

            fn = _call_with_timeout(_prepare, config_timeout_s)
            build_s = time.perf_counter() - t_build0
            timings = []
            for _ in range(max(1, median_of)):
                s = _call_with_timeout(
                    lambda: timer(fn, config), config_timeout_s)
                s = float(s)
                timings.append(s)
                hooks.timer_event(f"tune/sweep/{label}", s, config=config)
            timings_sorted = sorted(timings)
            median = timings_sorted[len(timings_sorted) // 2]
            results.append({"config": dict(config), "median_s": median,
                            "timings_s": timings, "build_s": build_s,
                            "_idx": idx})
        except Exception as e:      # a failed config is data; BaseException
            # control-flow (KeyboardInterrupt, SystemExit, bench.py's
            # SectionTimeout — a BaseException precisely so broad
            # excepts can't eat it) must propagate out of the sweep
            failed.append({"config": dict(config),
                           "error": f"{type(e).__name__}: {e}"})
            hooks.counter("tune/sweep_config_failed")
    results.sort(key=lambda r: (r["median_s"], r["_idx"]))
    for r in results:
        del r["_idx"]
    best = results[0] if results else None
    return {"best": best["config"] if best else None,
            "best_s": best["median_s"] if best else None,
            "results": results, "failed": failed}
