"""apex_tpu.tune — measure-and-cache Pallas kernel autotuning.

The reference hard-codes launch geometry per CUDA architecture
(``csrc/`` warp/block constants baked per SM); our Pallas kernels expose
block knobs instead (``flash_attention``'s ``block_q/block_k`` +
``block_q_bwd/block_k_bwd``, ``fused_lm_head_cross_entropy``'s
``block_t/block_v``). This package replaces the hand-tuning scripts
with one measure-and-cache autotuner:

- :mod:`~apex_tpu.tune.vmem` — the shared VMEM-envelope model
  (promoted from ``lm_head_ce._pick_blocks`` + the flash tile-cost
  accounting) that prunes illegal configs before compile;
- :mod:`~apex_tpu.tune.space` — legal block grids from static
  shape/dtype;
- :mod:`~apex_tpu.tune.harness` — compile-excluded median-of-k sweep
  with per-config timeout and an injectable timer (tests run a
  deterministic fake clock on CPU);
- :mod:`~apex_tpu.tune.cache` — persistent atomic-write JSON keyed by
  ``(device_kind, kernel, shape-bucket, dtype, flags)``; corrupt/stale
  entries degrade to heuristics;
- :mod:`~apex_tpu.tune.runtime` — the lookup the kernels call when
  their block knobs are ``None`` (``autotune="off"/"cache"/"online"``).

Offline entry point::

    python -m apex_tpu.ops tune --kernel flash_attention \\
        --shapes "b=8,h=16,s=1024,d=64,dtype=bf16,causal=1"

Telemetry: every runtime resolution lands as monitor
``tune/cache_hit``/``tune/cache_miss`` counters, a ``tune/cache_hit``
gauge, and a typed ``tune`` event; sweep measurements ride the
``tune/sweep/<kernel>`` timer path. Docs: docs/perf.md §autotuning.
"""

from apex_tpu.tune.cache import (  # noqa: F401
    TuneCache, cache_key, default_cache_dir, shape_bucket)
from apex_tpu.tune.harness import sweep, wall_timer  # noqa: F401
from apex_tpu.tune.runtime import (  # noqa: F401
    invalidate, override_cache_dir, resolve, resolve_policy)
from apex_tpu.tune.space import config_space  # noqa: F401
from apex_tpu.tune.vmem import budget_for, fits, vmem_estimate  # noqa: F401
