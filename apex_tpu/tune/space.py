"""Per-kernel config-space generation for the autotuner.

Enumerates legal block grids from static shape/dtype information alone,
pruned by the shared VMEM-envelope model (:mod:`apex_tpu.tune.vmem`)
so illegal configs never reach a compile. The enumeration is
deterministic: candidates come out in a fixed order (coarsest blocks
first), which makes sweep tie-breaking reproducible.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.tune import vmem

# power-of-two block menu shared by both flash phases; Mosaic wants the
# trailing dims (8, 128)-aligned and every real sweep to date has only
# ever ranked powers of two (scripts/fa_microbench.py history)
_FLASH_BLOCKS = (1024, 512, 256, 128)
_CE_BLOCK_T = (1024, 512, 256, 128)
_CE_BLOCK_V = (8192, 4096, 2048, 1024, 512, 256, 128)
# KV-cache page sizes for the serve decode kernel: the page is the
# kernel's block (one page of one head per program), AND the pool's
# allocation granule — smaller pages waste less tail capacity per
# sequence, larger pages cut program count. 8-sublane aligned.
_DECODE_BLOCKS = (512, 256, 128, 64, 32, 16)
# row blocks for the fused LayerNorm kernel pair (fwd+bwd share the
# knob): bigger blocks amortize per-program overhead, smaller ones trade
# VMEM for h — the envelope prunes per shape
_LN_BLOCKS = (2048, 1024, 512, 256, 128, 64, 32, 16, 8)
# flat-shard chunks for the multi-tensor optimizer update; must stay a
# multiple of one fp32 VMEM tile (8 sublanes x 128 lanes = 1024 elts)
# because the kernel views the flat buffer as [rows, 128]
_MTU_BLOCKS = (262144, 131072, 65536, 32768, 16384, 8192, 4096, 2048,
               1024)
# contraction/output tiles for the fp8 dequant-matmul: block_k rides
# both x's lane dim and the e4m3 weight's sublane dim (fp8 tiling wants
# 32-sublane multiples — every 128 qualifies), block_n the output lanes
_FP8MM_BLOCKS_K = (512, 256, 128)
_FP8MM_BLOCKS_N = (2048, 1024, 512, 256, 128)


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, (int(x) - 1).bit_length())


def _clip_menu(menu, limit: int):
    """Menu entries no larger than the (power-of-two-rounded) limit —
    blocks clamp to the sequence inside the kernels, so anything past
    the padded extent is a duplicate of the clamped config."""
    cap = _pow2_ceil(limit)
    out = [m for m in menu if m <= cap]
    return out or [menu[-1]]


def flash_attention_space(*, sq: int, sk: int, d: int, itemsize: int = 2,
                          phase: str = "fwd", bias: bool = False,
                          dropout: bool = False,
                          segments: bool = False) -> list[dict]:
    """Legal ``{"block_q", "block_k"}`` candidates for one flash phase.

    ``phase`` is ``"fwd"`` or ``"bwd"`` — the two are tuned
    independently (their measured optima differ: the r5 retune landed
    (1024, 1024) forward / (512, 512) backward at the causal GPT shape).
    """
    if phase not in ("fwd", "bwd"):
        raise ValueError(f"phase must be 'fwd' or 'bwd', got {phase!r}")
    kernel = f"flash_attention_{phase}"
    out = []
    for bq in _clip_menu(_FLASH_BLOCKS, sq):
        for bk in _clip_menu(_FLASH_BLOCKS, sk):
            if vmem.fits(kernel, block_q=bq, block_k=bk, d=d,
                         itemsize=itemsize, bias=bias, dropout=dropout,
                         segments=segments):
                out.append({"block_q": bq, "block_k": bk})
    return out


def lm_head_ce_space(*, n: int, v: int, h: int,
                     itemsize: int = 2) -> list[dict]:
    """Legal ``{"block_t", "block_v"}`` candidates for the fused
    LM-head CE kernels (forward and backward share the tiling knobs)."""
    out = []
    for bt in _clip_menu(_CE_BLOCK_T, n):
        for bv in _clip_menu(_CE_BLOCK_V, v):
            if vmem.fits("lm_head_ce", block_t=bt, block_v=bv, h=h,
                         itemsize=itemsize):
                out.append({"block_t": bt, "block_v": bv})
    return out


def decode_attention_space(*, s: int, d: int, group: int = 1,
                           itemsize: int = 2) -> list[dict]:
    """Legal ``{"block_kv"}`` (KV-cache page size) candidates for the
    paged decode kernel. ``s`` is the context length the sweep measures
    at — pages are clipped to it like flash blocks clip to the
    sequence."""
    out = []
    for bkv in _clip_menu(_DECODE_BLOCKS, max(s, _DECODE_BLOCKS[-1])):
        if vmem.fits("decode_attention", block_kv=bkv, d=d, group=group,
                     itemsize=itemsize):
            out.append({"block_kv": bkv})
    return out


def fused_layer_norm_space(*, n: int, h: int,
                           itemsize: int = 2) -> list[dict]:
    """Legal ``{"block_r"}`` row-block candidates for the fused LN
    kernel pair (forward and single-pass backward share the knob)."""
    out = []
    for br in _clip_menu(_LN_BLOCKS, n):
        if vmem.fits("fused_layer_norm", block_r=br, h=h,
                     itemsize=itemsize):
            out.append({"block_r": br})
    return out


def xentropy_space(*, n: int, v: int, itemsize: int = 2) -> list[dict]:
    """Legal ``{"block_t", "block_v"}`` candidates for the fused
    softmax-CE kernels (fwd/bwd share the tiling, like lm_head_ce)."""
    out = []
    for bt in _clip_menu(_CE_BLOCK_T, n):
        for bv in _clip_menu(_CE_BLOCK_V, v):
            if vmem.fits("xentropy", block_t=bt, block_v=bv,
                         itemsize=itemsize):
                out.append({"block_t": bt, "block_v": bv})
    return out


def multi_tensor_update_space(*, n: int, itemsize: int = 4) -> list[dict]:
    """Legal ``{"block_n"}`` flat-shard chunk candidates for the fused
    multi-tensor optimizer update."""
    out = []
    for bn in _clip_menu(_MTU_BLOCKS, max(n, _MTU_BLOCKS[-1])):
        if vmem.fits("multi_tensor_update", block_n=bn,
                     itemsize=itemsize):
            out.append({"block_n": bn})
    return out


def fp8_matmul_space(*, m: int, k: int, n: int,
                     itemsize: int = 2) -> list[dict]:
    """Legal ``{"block_k", "block_n"}`` candidates for the fused fp8
    dequant-matmul (serve weight-streaming)."""
    out = []
    for bk in _clip_menu(_FP8MM_BLOCKS_K, k):
        for bn in _clip_menu(_FP8MM_BLOCKS_N, n):
            if vmem.fits("fp8_matmul", block_k=bk, block_n=bn,
                         group=max(m, 1), itemsize=itemsize):
                out.append({"block_k": bk, "block_n": bn})
    return out


def config_space(kernel: str, shape: dict,
                 flags: Optional[dict] = None) -> list[dict]:
    """Dispatch on the cache's kernel naming: ``flash_attention_fwd``,
    ``flash_attention_bwd``, ``lm_head_ce``, ``decode_attention``.
    ``shape``/``flags`` use the same field names the cache key is built
    from."""
    flags = flags or {}
    if kernel == "decode_attention":
        return decode_attention_space(
            s=shape["s"], d=shape["d"], group=shape.get("group", 1),
            itemsize=shape.get("itemsize", 2))
    if kernel in ("flash_attention_fwd", "flash_attention_bwd"):
        return flash_attention_space(
            sq=shape["sq"], sk=shape["sk"], d=shape["d"],
            itemsize=shape.get("itemsize", 2),
            phase=kernel.rsplit("_", 1)[1],
            bias=bool(flags.get("bias")), dropout=bool(flags.get("dropout")),
            segments=bool(flags.get("segments")))
    if kernel == "lm_head_ce":
        return lm_head_ce_space(n=shape["n"], v=shape["v"], h=shape["h"],
                                itemsize=shape.get("itemsize", 2))
    if kernel == "fused_layer_norm":
        return fused_layer_norm_space(n=shape["n"], h=shape["h"],
                                      itemsize=shape.get("itemsize", 2))
    if kernel == "xentropy":
        return xentropy_space(n=shape["n"], v=shape["v"],
                              itemsize=shape.get("itemsize", 2))
    if kernel == "multi_tensor_update":
        return multi_tensor_update_space(
            n=shape["n"], itemsize=shape.get("itemsize", 4))
    if kernel == "fp8_matmul":
        return fp8_matmul_space(
            m=shape.get("m", 8), k=shape["k"], n=shape["n"],
            itemsize=shape.get("itemsize", 2))
    raise ValueError(f"unknown kernel {kernel!r}; known: {vmem.KERNELS}")
