"""Runtime block resolution: the piece the kernels call.

``flash_attention`` and ``fused_lm_head_cross_entropy`` call
:func:`resolve` when their block knobs are left at ``None``. Resolution
order (tentpole d):

    explicit user blocks  >  tuned cache entry  >  heuristic default

with an ``autotune=`` policy:

- ``"off"``    — no lookup at all; bit-for-bit today's heuristics
  (asserted jaxpr-identical in tests);
- ``"cache"``  — the default: use a tuned entry when one exists for
  this (device_kind, kernel, shape-bucket, dtype, flags), fall back to
  the heuristic otherwise. A miss costs one ``os.stat``.
- ``"online"`` — tune-on-first-miss: a miss triggers an in-process
  sweep over the legal config space on synthetic operands of the same
  shape/dtype, stores the winner, and uses it. First call at a new
  bucket pays the whole sweep (seconds to minutes on hardware) — see
  docs/perf.md for when that is safe.

Every resolution emits monitor telemetry (``tune/cache_hit`` /
``tune/cache_miss`` counters + the ``tune/cache_hit`` gauge + a typed
``tune`` event) so tests and the bench can assert cache behavior
without reaching into the resolver. ``"off"`` emits nothing.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

from apex_tpu.monitor import hooks
from apex_tpu.tune.cache import TuneCache, cache_key

ENV_POLICY = "APEX_TPU_AUTOTUNE"
POLICIES = ("off", "cache", "online")

_caches: dict = {}          # (dir, device_kind) -> TuneCache
_device_kind: Optional[str] = None     # memo — jax.devices() is not free


def resolve_policy(autotune: Optional[str]) -> str:
    """Explicit argument > $APEX_TPU_AUTOTUNE > "cache"."""
    policy = autotune if autotune is not None else \
        os.environ.get(ENV_POLICY, "cache")
    if policy not in POLICIES:
        raise ValueError(
            f"autotune policy must be one of {POLICIES}, got {policy!r}")
    return policy


def _cache_for(cache_dir: Optional[str]) -> TuneCache:
    global _device_kind
    if _device_kind is None:
        from apex_tpu.tune.cache import current_device_kind
        _device_kind = current_device_kind()
    from apex_tpu.tune.cache import default_cache_dir
    directory = cache_dir or default_cache_dir()
    key = (directory, _device_kind)
    cached = _caches.get(key)
    if cached is None:
        cached = _caches[key] = TuneCache(directory=directory,
                                          device_kind=_device_kind)
    return cached


def invalidate() -> None:
    """Drop the process-level cache handles and the device-kind memo
    (tests; after an offline sweep into a fresh directory the mtime
    check already reloads)."""
    global _device_kind
    _caches.clear()
    _device_kind = None


@contextlib.contextmanager
def override_cache_dir(directory: str):
    """Point runtime resolution at ``directory`` for the duration —
    env var + process-level memos, both restored after. The one place
    for the save/set/invalidate/restore dance the lint entrypoint,
    bench section and tests all need."""
    from apex_tpu.tune.cache import ENV_CACHE_DIR
    prev = os.environ.get(ENV_CACHE_DIR)
    os.environ[ENV_CACHE_DIR] = directory
    invalidate()
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(ENV_CACHE_DIR, None)
        else:
            os.environ[ENV_CACHE_DIR] = prev
        invalidate()


def _config_sane(kernel: str, cfg: dict, shape: dict, flags: dict) -> bool:
    """Value-level screen of a cache-resolved config: Mosaic wants
    (8, 128)-aligned tiles and the VMEM envelope must fit — a
    hand-edited or bit-rotted entry degrades to the heuristic rather
    than failing at compile time. Never raises."""
    try:
        from apex_tpu.tune import vmem
        if any(v % 8 != 0 for v in cfg.values()):
            return False
        itemsize = int(shape.get("itemsize", 2))
        if kernel in ("flash_attention_fwd", "flash_attention_bwd"):
            return vmem.fits(kernel, block_q=cfg["block_q"],
                             block_k=cfg["block_k"], d=shape["d"],
                             itemsize=itemsize,
                             bias=bool(flags.get("bias")),
                             dropout=bool(flags.get("dropout")),
                             segments=bool(flags.get("segments")))
        if kernel == "lm_head_ce":
            return vmem.fits(kernel, block_t=cfg["block_t"],
                             block_v=cfg["block_v"], h=shape["h"],
                             itemsize=itemsize)
        if kernel == "decode_attention":
            return vmem.fits(kernel, block_kv=cfg["block_kv"],
                             d=shape["d"], group=shape.get("group", 1),
                             itemsize=itemsize)
        if kernel == "fused_layer_norm":
            return vmem.fits(kernel, block_r=cfg["block_r"],
                             h=shape["h"], itemsize=itemsize)
        if kernel == "xentropy":
            return vmem.fits(kernel, block_t=cfg["block_t"],
                             block_v=cfg["block_v"], itemsize=itemsize)
        if kernel == "multi_tensor_update":
            # the kernel views the flat shard as [rows, 128]: a chunk
            # must cover whole fp32 (8, 128) tiles
            return (cfg["block_n"] % 1024 == 0
                    and vmem.fits(kernel, block_n=cfg["block_n"],
                                  itemsize=itemsize))
        if kernel == "fp8_matmul":
            # both tiles ride a 128-lane extent (block_k is also the
            # e4m3 weight's sublane dim — 128 covers the (32, 128) tile)
            return (cfg["block_k"] % 128 == 0
                    and cfg["block_n"] % 128 == 0
                    and vmem.fits(kernel, block_k=cfg["block_k"],
                                  block_n=cfg["block_n"],
                                  group=shape.get("m", 8),
                                  itemsize=itemsize))
        return False
    except Exception:
        return False


def resolve(kernel: str, shape: dict, dtype: str, flags: dict, *,
            policy: str, cache_dir: Optional[str] = None,
            interpret: bool = False) -> Optional[dict]:
    """Tuned config for one kernel call site, or ``None`` (use the
    heuristic). ``policy`` comes from :func:`resolve_policy`. Never
    raises on cache trouble — a bad cache is a miss."""
    if policy == "off":
        return None
    key = cache_key(kernel, shape, dtype, flags)
    cache = _cache_for(cache_dir)
    cfg = cache.lookup(key)
    if cfg is not None and not _config_sane(kernel, cfg, shape, flags):
        cfg = None                      # drifted VALUES: a miss, not a crash
    if cfg is not None:
        hooks.tune_event(kernel, key, hit=True, source="cache", config=cfg)
        return cfg
    if policy == "online":
        cfg = _tune_online(kernel, shape, dtype, flags, cache, key,
                           interpret=interpret)
        hooks.tune_event(kernel, key, hit=False, source="online",
                         config=cfg)
        return cfg
    hooks.tune_event(kernel, key, hit=False, source="cache", config=None)
    return None


def _tune_online(kernel: str, shape: dict, dtype: str, flags: dict,
                 cache: TuneCache, key: str, *,
                 interpret: bool) -> Optional[dict]:
    """Tune-on-first-miss. Runs host-side on synthetic operands built
    from the static shape/dtype (so it also works when the kernel call
    is being traced — the sweep's own jits execute eagerly), stores the
    winner, returns it. Any failure degrades to the heuristic."""
    try:
        from apex_tpu.tune import kernels as tk
        result = tk.tune_one(kernel, shape, dtype, flags,
                             interpret=interpret)
        best = result.get("best")
        if best:
            cache.put(key, best, ms=result.get("best_s", 0) * 1e3,
                      swept=len(result.get("results", [])))
        return best
    except Exception:
        hooks.counter("tune/online_failed")
        return None
