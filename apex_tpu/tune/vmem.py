"""Shared VMEM-envelope model for the Pallas kernel autotuner.

One place for the budget math that used to live in two: the
``lm_head_ce._pick_blocks`` docstring (fp32 dE block + double-buffered
operand blocks + the logits tile, against the raised 64 MB kernel
budget) and the flash-attention module docstring's tile-cost accounting
(the [block_q, block_k] fp32 score tile, plus one more tile each for an
additive bias block and the regenerated dropout keep mask, against
Mosaic's scoped-VMEM default). The config-space generator calls
:func:`vmem_estimate` to prune illegal block grids *before* anything is
compiled, so a sweep never burns its timeout on a config Mosaic would
reject.

These are calibrated ENVELOPES, not byte-exact Mosaic accounting (which
depends on liveness analysis and buffer reuse the compiler owns). The
budgets are set so that every hardware-verified shipping config passes
and every hardware-verified failing config is pruned — the calibration
points are quoted next to each constant. A config that passes the
envelope can still, in principle, fail to compile on a future compiler;
the sweep harness treats a compile failure as a skipped config, never an
error.
"""

from __future__ import annotations

# Mosaic's scoped-VMEM default is 16 MB/core. The flash kernels run
# under it unraised; the envelope budget leaves headroom for the
# compiler's own double-buffering and transients. Calibration (module
# docstring of ops/flash_attention.py, all measured on v5e):
#   pass: (1024, 1024) plain/causal/bias-only/dropout-only at d=64..128
#   fail: (2048, 2048) any flavor; (1024, 1024) with bias AND dropout
FLASH_VMEM_BUDGET = 12 * 1024 * 1024

# ops/lm_head_ce.py requests a raised 64 MB scoped-VMEM limit (v5e has
# 128 MB): the backward's resident set at the swept-optimal tiles is
# ~24 MB standalone but grows to ~42 MB when the kernel sits inside a
# remat/scan body that shares the scope. The envelope prunes configs
# whose standalone resident set already exceeds the raised limit.
LM_HEAD_VMEM_LIMIT = 64 * 1024 * 1024

KERNELS = ("flash_attention_fwd", "flash_attention_bwd", "lm_head_ce",
           "decode_attention", "fused_layer_norm", "xentropy",
           "multi_tensor_update", "fp8_matmul")

# Donation-worthiness threshold for the APXJ105 lint check (and anyone
# else asking "is this state big enough that an undonated round trip
# hurts"): one flash-kernel VMEM working set. State smaller than a
# single kernel's on-chip budget is noise next to activations; state at
# or past it doubles real HBM when a jitted step threads it undonated
# (input buffers stay alive while the outputs are written).
DONATION_BYTES_MIN = FLASH_VMEM_BUDGET


def ceil_to(x: int, m: int) -> int:
    """Smallest multiple of ``m`` >= ``x`` — the ONE ceil-to-multiple
    used by the kernel wrappers' block/pad alignment math (jax-free)."""
    return -(-x // m) * m


def aval_nbytes(aval) -> int:
    """Byte size of an abstract value (aval / ShapeDtypeStruct / array):
    the ONE sizing rule the lint donation checks and capacity accounting
    share. Returns 0 for unshaped/untyped objects rather than raising."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        n *= int(s)
    try:
        return n * dtype.itemsize
    except AttributeError:
        import numpy as np
        return n * np.dtype(dtype).itemsize


def tree_nbytes(tree) -> int:
    """Total :func:`aval_nbytes` over a pytree's leaves."""
    import jax
    return sum(aval_nbytes(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def budget_for(kernel: str) -> int:
    if kernel in ("flash_attention_fwd", "flash_attention_bwd",
                  "decode_attention", "fused_layer_norm", "xentropy",
                  "multi_tensor_update", "fp8_matmul"):
        # the r13 kernels run under Mosaic's unraised scoped-VMEM
        # default, so they share the flash envelope budget
        return FLASH_VMEM_BUDGET
    if kernel == "lm_head_ce":
        return LM_HEAD_VMEM_LIMIT
    raise ValueError(f"unknown kernel {kernel!r}; known: {KERNELS}")


def _flash_common(block_q: int, block_k: int, d: int, itemsize: int) -> int:
    # double-buffered operand blocks (q + k + v) in their native dtype,
    # the output block, and the fp32 accumulator scratch
    operands = 2 * (block_q + 2 * block_k) * d * itemsize
    out = 2 * block_q * d * itemsize
    acc = block_q * d * 4
    return operands + out + acc


def vmem_estimate(kernel: str, *, block_q: int = 0, block_k: int = 0,
                  d: int = 0, block_t: int = 0, block_v: int = 0,
                  h: int = 0, itemsize: int = 2, bias: bool = False,
                  dropout: bool = False, segments: bool = False,
                  block_kv: int = 0, group: int = 8,
                  block_r: int = 0, block_n: int = 0) -> int:
    """Estimated resident VMEM bytes for one kernel program at the given
    block config. Flash kernels take ``block_q/block_k/d``; ``lm_head_ce``
    takes ``block_t/block_v/h``. ``itemsize`` is the operand dtype's.
    """
    if kernel == "flash_attention_fwd":
        tile = block_q * block_k * 4
        # one fp32 score/probability tile (Mosaic reuses the buffer
        # across the s -> p passes), +1 tile for a resident bias block,
        # +1 for the regenerated dropout keep mask; segment-id vectors
        # are lane-thin and disappear into the headroom
        n_tiles = 1 + (1 if bias else 0) + (1 if dropout else 0)
        return n_tiles * tile + _flash_common(block_q, block_k, d, itemsize)
    if kernel == "flash_attention_bwd":
        tile = block_q * block_k * 4
        # p and ds live simultaneously (dp folds into ds in-place);
        # bias/dropout each add a resident tile exactly as forward
        n_tiles = 2 + (1 if bias else 0) + (1 if dropout else 0)
        # do block + the dq/dkdv fp32 accumulators
        extra = 2 * block_q * d * itemsize + 2 * block_k * d * 4
        return (n_tiles * tile + extra
                + _flash_common(block_q, block_k, d, itemsize))
    if kernel == "decode_attention":
        # the serve decode kernel: ``block_kv`` is the KV-cache page
        # size (one page of one head resident per program). Double-
        # buffered k+v page blocks in the pool dtype (1 B in fp8-KV
        # mode), the padded-group q/out blocks, the fp32 accumulator
        # trio, and one fp32 score tile; block-table/seq-len scalars
        # and the fp8 page scales ride SMEM and disappear into the
        # headroom.
        g8 = max(8, -(-int(group) // 8) * 8)
        kv_blocks = 2 * 2 * block_kv * d * itemsize
        q_out = 2 * g8 * d * itemsize
        acc = g8 * d * 4 + 2 * g8 * 4
        tile = g8 * block_kv * 4
        return kv_blocks + q_out + acc + tile
    if kernel == "fused_layer_norm":
        # single-pass backward dominates: double-buffered x + dy operand
        # blocks, the dx output block, ~4 fp32 row-block temps the
        # compiler keeps live (x32/dy32/xhat/dxhat before reuse), and
        # the [1, h] fp32 dgamma/dbeta accumulators + weight row
        operands = 2 * 2 * block_r * h * itemsize
        dx = 2 * block_r * h * itemsize
        temps = 4 * block_r * h * 4
        rows = 3 * h * 4
        return operands + dx + temps + rows
    if kernel == "xentropy":
        # backward dominates: two fp32 [block_t, block_v] tiles (the
        # recomputed probabilities and the gradient tile live together),
        # double-buffered logits operand + dlogits output blocks, and
        # the lane-thin per-token vectors (m/l/dl/tgt) in the headroom
        tiles = 2 * block_t * block_v * 4
        operands = 2 * block_t * block_v * itemsize
        dlogits = 2 * block_t * block_v * itemsize
        vectors = 8 * block_t * 4
        return tiles + operands + dlogits + vectors
    if kernel == "multi_tensor_update":
        # one blocked chunk of the flat shard: 4 double-buffered fp32
        # inputs (p/g/m/v), 3 double-buffered fp32 outputs, plus ~4
        # elementwise temps before Mosaic's buffer reuse kicks in
        return (2 * 4 + 2 * 3 + 4) * block_n * 4
    if kernel == "fp8_matmul":
        # the serve weight-streaming dequant-matmul: ``group`` is the
        # padded activation row count (decode batches are tiny — 16
        # covers the bf16 sublane tile). Double-buffered activation
        # blocks in their native dtype, 1-byte e4m3 weight blocks, the
        # in-VMEM fp32 dequant temp, the fp32 x cast, and the revisited
        # fp32 output block + one partial-product tile; the scalar
        # scale rides SMEM and disappears into the headroom.
        g16 = max(16, -(-int(group) // 16) * 16)
        x_blocks = 2 * g16 * block_k * itemsize
        w_blocks = 2 * block_k * block_n * 1
        deq = block_k * block_n * 4
        x32 = g16 * block_k * 4
        out = 2 * g16 * block_n * 4
        return x_blocks + w_blocks + deq + x32 + out
    if kernel == "lm_head_ce":
        # the _pick_blocks budget math, promoted: fp32 dE accumulator
        # block + fp32 logits tile + double-buffered E/x operand blocks
        # + the dx output tile (backward dominates the forward, which
        # shares every term except dE/dx)
        de = block_v * h * 4
        logits = block_t * block_v * 4
        operands = 2 * (block_v * h + block_t * h) * itemsize
        dx = block_t * h * 4
        return de + logits + operands + dx
    raise ValueError(f"unknown kernel {kernel!r}; known: {KERNELS}")


def fits(kernel: str, **kw) -> bool:
    """Whether a config's envelope fits the kernel's budget."""
    return vmem_estimate(kernel, **kw) <= budget_for(kernel)
