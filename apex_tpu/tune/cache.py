"""Persistent per-device autotune cache.

One JSON file per ``device_kind`` under the cache directory
(``$APEX_TPU_TUNE_CACHE``, an explicit argument, or
``~/.cache/apex_tpu/tune``), schema::

    {"schema": 1, "device_kind": "TPU v5e",
     "entries": {"<key>": {"config": {...}, "ms": 1.17, "swept": 9,
                            "ts": 1722600000}}}

Keys are ``kernel|shape-bucket|dtype|flags`` strings
(:func:`cache_key`). Shapes are BUCKETED — batch*heads and sequence
extents round up to powers of two — so one tuned entry serves the whole
bucket: block choice is governed by tile geometry (sequence extent,
head/hidden dim, dtype, feature flags), not by the exact batch size,
and bucketing keeps the cache (and the offline sweep matrix) small.
Inside the kernels blocks still clamp to the actual sequence, so a
bucket-resolved block is always legal for the concrete shape.

Robustness contract (ISSUE 8 tentpole c): corrupt JSON, an unknown
schema version, or a ``device_kind`` that does not match the running
device all degrade to heuristic defaults — a lookup returns ``None``
(gauged as a cache miss by the runtime layer), never raises. Writes are
atomic: serialize to a ``.tmp.<pid>`` sibling, ``os.replace`` onto the
canonical name — a crash mid-write leaves either the old file or the
new one, and a stray partial tmp file is never read (loads open only
the canonical name).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

SCHEMA = 1
ENV_CACHE_DIR = "APEX_TPU_TUNE_CACHE"

# the exact config-dict key set per kernel — lookup() rejects entries
# whose names drifted (hand-edited file, schema evolution) so a resolved
# config can be indexed by the kernels without a KeyError
CONFIG_KEYS = {"flash_attention_fwd": frozenset(("block_q", "block_k")),
               "flash_attention_bwd": frozenset(("block_q", "block_k")),
               "lm_head_ce": frozenset(("block_t", "block_v")),
               "decode_attention": frozenset(("block_kv",)),
               "fused_layer_norm": frozenset(("block_r",)),
               "xentropy": frozenset(("block_t", "block_v")),
               "multi_tensor_update": frozenset(("block_n",)),
               "fp8_matmul": frozenset(("block_k", "block_n"))}


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, (int(x) - 1).bit_length())


def default_cache_dir() -> str:
    env = os.environ.get(ENV_CACHE_DIR)
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "apex_tpu", "tune")


def current_device_kind() -> str:
    """The running backend's device kind (``"TPU v5e"``, ``"cpu"``,
    ...). Imported lazily — cache/key code must work jax-free."""
    try:
        import jax
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def _flags_str(flags: Optional[dict]) -> str:
    active = sorted(k for k, v in (flags or {}).items() if v)
    return "+".join(active) if active else "plain"


def shape_bucket(kernel: str, shape: dict) -> str:
    """The bucketed-shape component of a cache key."""
    if kernel in ("flash_attention_fwd", "flash_attention_bwd"):
        bh = _pow2_ceil(shape.get("b", 1) * shape.get("h", 1))
        return (f"bh{bh}_sq{_pow2_ceil(shape['sq'])}"
                f"_sk{_pow2_ceil(shape['sk'])}_d{shape['d']}")
    if kernel == "lm_head_ce":
        return (f"n{_pow2_ceil(shape['n'])}_v{_pow2_ceil(shape['v'])}"
                f"_h{shape['h']}")
    if kernel == "decode_attention":
        # bucket batch and context (pow2), pin head geometry exactly —
        # the page-size optimum tracks d/group, not the exact batch
        bkv = _pow2_ceil(shape.get("b", 1) * shape.get("kv", 1))
        return (f"bkv{bkv}_s{_pow2_ceil(shape['s'])}_d{shape['d']}"
                f"_g{shape.get('group', 1)}")
    if kernel == "fused_layer_norm":
        # rows bucket pow2; the hidden size is pinned exactly (it is
        # the lane extent the row block trades VMEM against)
        return f"n{_pow2_ceil(shape['n'])}_h{shape['h']}"
    if kernel == "xentropy":
        return f"n{_pow2_ceil(shape['n'])}_v{_pow2_ceil(shape['v'])}"
    if kernel == "multi_tensor_update":
        return f"n{_pow2_ceil(shape['n'])}"
    if kernel == "fp8_matmul":
        # rows bucket pow2 (the decode batch); the weight geometry is
        # pinned exactly — it IS the tile-extent the blocks trade against
        return (f"m{_pow2_ceil(shape.get('m', 1))}_k{shape['k']}"
                f"_n{shape['n']}")
    raise ValueError(f"unknown kernel {kernel!r}")


def cache_key(kernel: str, shape: dict, dtype: str,
              flags: Optional[dict] = None) -> str:
    return "|".join((kernel, shape_bucket(kernel, shape), str(dtype),
                     _flags_str(flags)))


def _kind_filename(device_kind: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", device_kind) + ".json"


class TuneCache:
    """mtime-checked view over one device-kind cache file.

    Lookups stat the file and reload only when (mtime_ns, size) moved,
    so a per-kernel-call lookup costs one ``os.stat``. All failure
    modes return ``None``/no-op; the runtime layer turns them into
    gauged heuristic fallbacks.
    """

    def __init__(self, directory: Optional[str] = None,
                 device_kind: Optional[str] = None):
        self.directory = directory or default_cache_dir()
        self.device_kind = device_kind or current_device_kind()
        self.path = os.path.join(self.directory,
                                 _kind_filename(self.device_kind))
        self._entries: dict = {}
        self._stat = None      # (mtime_ns, size) of the loaded file
        self._valid = False

    # -- load ---------------------------------------------------------------
    def _refresh(self):
        try:
            st = os.stat(self.path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            self._entries, self._stat, self._valid = {}, None, False
            return
        if sig == self._stat:
            return
        self._stat = sig
        self._entries, self._valid = {}, False
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return                      # corrupt/unreadable: stay empty
        if not isinstance(data, dict) or data.get("schema") != SCHEMA:
            return                      # unknown schema: stay empty
        if data.get("device_kind") != self.device_kind:
            return                      # foreign device's entries: ignore
        entries = data.get("entries")
        if isinstance(entries, dict):
            self._entries = entries
            self._valid = True

    def lookup(self, key: str) -> Optional[dict]:
        """The tuned config for ``key``, or None. Never raises."""
        try:
            self._refresh()
            ent = self._entries.get(key)
            if not isinstance(ent, dict):
                return None
            cfg = ent.get("config")
            want = CONFIG_KEYS.get(key.split("|", 1)[0])
            if (isinstance(cfg, dict) and cfg
                    and (want is None or set(cfg) == want)
                    and all(isinstance(v, int) and v > 0
                            for v in cfg.values())):
                return dict(cfg)
            return None
        except Exception:
            return None

    def entries(self) -> dict:
        self._refresh()
        return {k: dict(v) for k, v in self._entries.items()}

    # -- store --------------------------------------------------------------
    def put(self, key: str, config: dict, *, ms: Optional[float] = None,
            swept: Optional[int] = None) -> None:
        """Merge one entry and atomically rewrite the cache file."""
        self._refresh()
        entries = dict(self._entries) if self._valid else {}
        row = {"config": {k: int(v) for k, v in config.items()},
               "ts": int(time.time())}
        if ms is not None:
            row["ms"] = round(float(ms), 6)
        if swept is not None:
            row["swept"] = int(swept)
        entries[key] = row
        self._write(entries)

    def _write(self, entries: dict) -> None:
        os.makedirs(self.directory, exist_ok=True)
        data = {"schema": SCHEMA, "device_kind": self.device_kind,
                "entries": entries}
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._stat = None               # force reload on next lookup
