"""Kernel-specific sweep builders: the bridge between the generic
harness and the two tunable Pallas kernel families.

Everything here builds synthetic operands from static shape/dtype
(numpy RNG — no PRNG key plumbing, and it works at trace time for the
``"online"`` policy: the sweep's own jits execute eagerly on concrete
arrays). The flash backward is tuned INDEPENDENTLY of the forward: its
runner times only the vjp closure (the forward runs once, untimed, to
produce residuals), with the forward pinned at its own resolution so a
backward candidate never perturbs the forward measurement.

jax/ops imports are all lazy — this module sits below ops in the import
graph (ops imports tune.runtime) and must not close the cycle.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.tune import harness, space
from apex_tpu.tune.cache import TuneCache, cache_key

_DTYPES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
           "fp32": "float32", "float32": "float32",
           "f32": "float32", "fp16": "float16", "float16": "float16"}

# the offline default sweep matrix: the bench model shapes (docs/perf.md)
DEFAULT_SHAPES = {
    "flash_attention": [
        dict(b=8, h=16, sq=1024, sk=1024, d=64, dtype="bfloat16",
             causal=True),
        dict(b=32, h=12, sq=512, sk=512, d=64, dtype="bfloat16",
             causal=False),
    ],
    "lm_head_ce": [
        dict(n=8192, v=32768, h=1024, dtype="bfloat16"),
        dict(n=16384, v=30522, h=768, dtype="bfloat16"),
    ],
    # the serve decode shapes: GPT bench heads at chat-scale contexts,
    # bf16 and fp8-KV pools (the page size is the pool's allocation
    # granule — serve.cache resolves it from these entries)
    "decode_attention": [
        dict(b=16, kv=16, group=1, s=1024, d=64, dtype="bfloat16"),
        dict(b=16, kv=16, group=1, s=1024, d=64, dtype="bfloat16",
             fp8=True),
    ],
    # the r13 kernels (ISSUE 13): LN at the GPT/BERT bench geometries,
    # fused CE at the BERT logits shape (the GPT path goes through
    # lm_head_ce), and the optimizer sweep at a GPT-125M-sized flat
    # shard per rank (world=8) and the whole-model shard (world=1)
    "fused_layer_norm": [
        dict(n=8192, h=1024, dtype="bfloat16"),
        dict(n=16384, h=768, dtype="bfloat16"),
    ],
    "xentropy": [
        dict(n=16384, v=30522, dtype="bfloat16"),
        dict(n=16384, v=30522, dtype="bfloat16", smoothing=True),
    ],
    "multi_tensor_update": [
        dict(n=16 * 1024 * 1024, dtype="float32"),
        dict(n=128 * 1024 * 1024, dtype="float32", lamb=True),
    ],
    # the serve weight-streaming dequant-matmul at the GPT bench
    # geometry: qkv ([h, 3h]) and fc2 ([4h, h]) at decode batch sizes
    "fp8_matmul": [
        dict(m=8, k=768, n=2304, dtype="bfloat16"),
        dict(m=8, k=3072, n=768, dtype="bfloat16"),
    ],
}


def _np_dtype(dtype: str):
    import jax.numpy as jnp
    return jnp.dtype(_DTYPES.get(dtype, dtype))


def parse_shape_spec(kernel: str, spec: str) -> dict:
    """``"b=8,h=16,s=1024,d=64,dtype=bf16,causal=1"`` -> shape dict.
    ``s=`` sets both sq and sk for flash. Unknown keys raise."""
    flash = kernel.startswith("flash_attention")
    decode = kernel == "decode_attention"
    if flash:
        known = {"b", "h", "s", "sq", "sk", "d", "dtype", "causal", "bias",
                 "dropout", "segments"}
    elif decode:
        known = {"b", "kv", "group", "s", "d", "dtype", "fp8"}
    elif kernel == "fused_layer_norm":
        known = {"n", "h", "dtype"}
    elif kernel == "xentropy":
        known = {"n", "v", "dtype", "smoothing"}
    elif kernel == "multi_tensor_update":
        known = {"n", "dtype", "lamb"}
    elif kernel == "fp8_matmul":
        known = {"m", "k", "n", "dtype"}
    else:
        known = {"n", "v", "h", "dtype", "smoothing"}
    # the optimizer update is fp32 math by contract (zero/update.py);
    # every other kernel defaults to the bf16 fast path
    out: dict = {"dtype": "float32" if kernel == "multi_tensor_update"
                 else "bfloat16"}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad shape field {part!r} (want key=value)")
        k, val = part.split("=", 1)
        k = k.strip()
        if k not in known:
            raise ValueError(f"unknown shape field {k!r} for {kernel} "
                             f"(known: {sorted(known)})")
        if k == "dtype":
            raw = val.strip()
            dt = _DTYPES.get(raw, raw)
            try:
                _np_dtype(dt)
            except Exception:
                raise ValueError(f"unknown dtype {raw!r} (known aliases: "
                                 f"{sorted(_DTYPES)})")
            out[k] = dt
        elif k in ("causal", "bias", "dropout", "segments", "smoothing",
                   "fp8", "lamb"):
            out[k] = val.strip() not in ("0", "false", "False", "")
        elif k == "s" and flash:
            out["sq"] = out["sk"] = int(val)
        else:
            out[k] = int(val)
    if decode:
        out.setdefault("b", 1)
        out.setdefault("kv", 1)
        out.setdefault("group", 1)
        for req in ("s", "d"):
            if req not in out:
                raise ValueError(f"decode_attention shape spec needs {req}")
    elif flash:
        out.setdefault("b", 1)
        out.setdefault("h", 1)
        for req in ("sq", "sk", "d"):
            if req not in out:
                raise ValueError(f"flash shape spec needs {req} (or s)")
    elif kernel == "fused_layer_norm":
        for req in ("n", "h"):
            if req not in out:
                raise ValueError(f"fused_layer_norm shape spec needs {req}")
    elif kernel == "xentropy":
        for req in ("n", "v"):
            if req not in out:
                raise ValueError(f"xentropy shape spec needs {req}")
    elif kernel == "multi_tensor_update":
        if "n" not in out:
            raise ValueError("multi_tensor_update shape spec needs n")
    elif kernel == "fp8_matmul":
        out.setdefault("m", 8)
        for req in ("k", "n"):
            if req not in out:
                raise ValueError(f"fp8_matmul shape spec needs {req}")
    else:
        for req in ("n", "v", "h"):
            if req not in out:
                raise ValueError(f"lm_head_ce shape spec needs {req}")
    return out


def split_shape(kernel: str, spec: dict):
    """(shape, dtype, flags) triplet in the cache-key vocabulary."""
    spec = dict(spec)
    raw = spec.pop("dtype", "bfloat16")
    dtype = _DTYPES.get(raw, raw)
    try:
        _np_dtype(dtype)
    except Exception:
        raise ValueError(
            f"unknown dtype {raw!r} (known aliases: {sorted(_DTYPES)})")
    if kernel.startswith("flash_attention"):
        flags = {k: bool(spec.pop(k, False))
                 for k in ("causal", "bias", "dropout", "segments")}
    elif kernel == "decode_attention":
        flags = {"fp8": bool(spec.pop("fp8", False))}
    elif kernel in ("fused_layer_norm", "fp8_matmul"):
        flags = {}
    elif kernel == "multi_tensor_update":
        flags = {"lamb": bool(spec.pop("lamb", False))}
    else:
        flags = {"smoothing": bool(spec.pop("smoothing", False))}
    spec["itemsize"] = _np_dtype(dtype).itemsize
    return spec, dtype, flags


def _flash_operands(shape: dict, dtype: str, flags: dict):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    b, h = shape.get("b", 1), shape.get("h", 1)
    sq, sk, d = shape["sq"], shape["sk"], shape["d"]
    dt = _np_dtype(dtype)
    q = jnp.asarray(rng.randn(b, h, sq, d) * 0.1, dt)
    k = jnp.asarray(rng.randn(b, h, sk, d) * 0.1, dt)
    v = jnp.asarray(rng.randn(b, h, sk, d) * 0.1, dt)
    kw = dict(causal=bool(flags.get("causal")), autotune="off")
    if flags.get("bias"):
        kw["bias"] = jnp.asarray(rng.randn(1, 1, sq, sk) * 0.2, jnp.float32)
    if flags.get("dropout"):
        kw.update(dropout_rate=0.1, dropout_seed=17)
    if flags.get("segments"):
        import numpy as _np
        sid = _np.zeros((b, sq), _np.int32)
        sid[:, sq // 2:] = 1
        kw["segment_ids_q"] = jnp.asarray(sid)
        if sk != sq:
            sidk = _np.zeros((b, sk), _np.int32)
            sidk[:, sk // 2:] = 1
            kw["segment_ids_kv"] = jnp.asarray(sidk)
    return (q, k, v), kw


def build_flash_fwd(shape: dict, dtype: str, flags: dict, *,
                    interpret: Optional[bool] = None):
    """``build(config)`` for the harness: a jitted forward-only call at
    the candidate tiling (backward pinned too, so the traced program is
    complete and the warning path stays quiet)."""
    import jax
    (q, k, v), kw = _flash_operands(shape, dtype, flags)

    def build(config):
        from apex_tpu.ops.flash_attention import flash_attention
        fn = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, block_q=config["block_q"], block_k=config["block_k"],
            block_q_bwd=config["block_q"], block_k_bwd=config["block_k"],
            interpret=interpret, **kw))
        return lambda: jax.block_until_ready(fn(q, k, v))
    return build


def build_flash_bwd(shape: dict, dtype: str, flags: dict, *,
                    interpret: Optional[bool] = None):
    """``build(config)``: times ONLY the backward — ``jax.vjp`` runs
    the forward once per build (untimed, heuristic-default tiling) and
    the timed callable applies the jitted vjp closure."""
    import jax
    import jax.numpy as jnp
    (q, k, v), kw = _flash_operands(shape, dtype, flags)

    def build(config):
        from apex_tpu.ops.flash_attention import flash_attention

        def f(q, k, v):
            return flash_attention(
                q, k, v, block_q_bwd=config["block_q"],
                block_k_bwd=config["block_k"], interpret=interpret, **kw)

        out, vjp = jax.vjp(f, q, k, v)
        do = jnp.ones_like(out)
        vjp_j = jax.jit(vjp)
        return lambda: jax.block_until_ready(vjp_j(do))
    return build


def build_lm_head_ce(shape: dict, dtype: str, flags: dict, *,
                     interpret: Optional[bool] = None):
    """``build(config)``: jitted fwd+bwd of the fused loss at the
    candidate (block_t, block_v) — the two phases share the knobs, so
    the sweep times them together (what a train step pays)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    n, v_, h = shape["n"], shape["v"], shape["h"]
    dt = _np_dtype(dtype)
    x = jnp.asarray(rng.randn(n, h) * 0.05, dt)
    emb = jnp.asarray(rng.randn(v_, h) * 0.05, dt)
    tgt = jnp.asarray(rng.randint(0, v_, (n,)), jnp.int32)
    smoothing = 0.1 if flags.get("smoothing") else 0.0

    def build(config):
        from apex_tpu.ops.lm_head_ce import fused_lm_head_cross_entropy

        def loss(x, emb):
            return jnp.mean(fused_lm_head_cross_entropy(
                x, emb, tgt, label_smoothing=smoothing,
                block_t=config["block_t"], block_v=config["block_v"],
                interpret=interpret, autotune="off"))

        fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
        return lambda: jax.block_until_ready(fn(x, emb))
    return build


def build_decode_attention(shape: dict, dtype: str, flags: dict, *,
                           interpret: Optional[bool] = None):
    """``build(config)``: jitted paged decode step at the candidate
    page size. Unlike the flash builders the OPERANDS depend on the
    config — the page size shapes the pool — so each candidate builds
    its own synthetic pool (disjoint per-sequence pages, full-context
    sequence lengths: every page live, the steady-state decode load)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    b, kv = shape.get("b", 1), shape.get("kv", 1)
    g, s, d = shape.get("group", 1), shape["s"], shape["d"]
    dt = _np_dtype(dtype)
    fp8 = bool(flags.get("fp8"))
    q = jnp.asarray(rng.randn(b, kv, g, d) * 0.1, dt)

    def build(config):
        from apex_tpu.ops.flash_attention import paged_decode_attention
        bs = config["block_kv"]
        m = -(-s // bs)
        n_pages = b * m + 1                      # page 0 stays null
        kp = rng.randn(kv, n_pages, bs, d) * 0.1
        vp = rng.randn(kv, n_pages, bs, d) * 0.1
        scales = {}
        if fp8:
            from apex_tpu.amp import fp8 as f8
            kp = jnp.clip(jnp.asarray(kp, jnp.float32), -f8.E4M3_MAX,
                          f8.E4M3_MAX).astype(f8.E4M3)
            vp = jnp.clip(jnp.asarray(vp, jnp.float32), -f8.E4M3_MAX,
                          f8.E4M3_MAX).astype(f8.E4M3)
            scales = dict(k_scales=jnp.ones((kv, n_pages), jnp.float32),
                          v_scales=jnp.ones((kv, n_pages), jnp.float32))
        else:
            kp, vp = jnp.asarray(kp, dt), jnp.asarray(vp, dt)
        bt = jnp.asarray(1 + np.arange(b * m).reshape(b, m), jnp.int32)
        sl = jnp.full((b,), s, jnp.int32)
        fn = jax.jit(lambda q, kp, vp, bt, sl: paged_decode_attention(
            q, kp, vp, bt, sl, interpret=interpret, **scales))
        return lambda: jax.block_until_ready(fn(q, kp, vp, bt, sl))
    return build


def build_fused_layer_norm(shape: dict, dtype: str, flags: dict, *,
                           interpret: Optional[bool] = None):
    """``build(config)``: jitted fwd+bwd of the fused LN at the
    candidate ``block_r`` — the kernel pair shares the knob, so the
    sweep times them together (what a train step pays)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    n, h = shape["n"], shape["h"]
    dt = _np_dtype(dtype)
    x = jnp.asarray(rng.randn(n, h) * 0.5, dt)
    w = jnp.asarray(1.0 + rng.randn(h) * 0.02, jnp.float32)
    b = jnp.asarray(rng.randn(h) * 0.02, jnp.float32)

    def build(config):
        from apex_tpu.ops.layer_norm import fused_layer_norm_affine

        def loss(x, w, b):
            y = fused_layer_norm_affine(
                x, w, b, (h,), block_r=config["block_r"],
                interpret=interpret, out_dtype=dt)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
        return lambda: jax.block_until_ready(fn(x, w, b))
    return build


def build_xentropy(shape: dict, dtype: str, flags: dict, *,
                   interpret: Optional[bool] = None):
    """``build(config)``: jitted fwd+bwd of the fused softmax-CE at the
    candidate (block_t, block_v)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    n, v_ = shape["n"], shape["v"]
    dt = _np_dtype(dtype)
    logits = jnp.asarray(rng.randn(n, v_) * 0.1, dt)
    labels = jnp.asarray(rng.randint(0, v_, (n,)), jnp.int32)
    smoothing = 0.1 if flags.get("smoothing") else 0.0

    def build(config):
        from apex_tpu.ops.fused_ce import softmax_cross_entropy_with_smoothing

        def loss(logits):
            return jnp.mean(softmax_cross_entropy_with_smoothing(
                logits, labels, smoothing,
                block_t=config["block_t"], block_v=config["block_v"],
                interpret=interpret))

        fn = jax.jit(jax.value_and_grad(loss))
        return lambda: jax.block_until_ready(fn(logits))
    return build


def build_multi_tensor_update(shape: dict, dtype: str, flags: dict, *,
                              interpret: Optional[bool] = None):
    """``build(config)``: one jitted fused shard update (Adam or the
    LAMB term) over a synthetic flat fp32 shard at the candidate
    ``block_n`` chunk."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    n = shape["n"]
    p = jnp.asarray(rng.randn(n) * 0.05, jnp.float32)
    g = jnp.asarray(rng.randn(n) * 0.01, jnp.float32)
    m = jnp.asarray(rng.randn(n) * 0.001, jnp.float32)
    v = jnp.asarray(np.abs(rng.randn(n)) * 1e-4, jnp.float32)
    step = jnp.asarray(7, jnp.int32)
    kind = "lamb" if flags.get("lamb") else "adam"

    def build(config):
        from apex_tpu.zero.fused_update import fused_shard_update

        fn = jax.jit(lambda p, g, m, v: fused_shard_update(
            p, g, m, v, step, kind=kind, lr=1e-3, betas=(0.9, 0.999),
            eps=1e-8, weight_decay=0.01, adam_w_mode=True,
            bias_correction=True, block_n=config["block_n"],
            interpret=interpret))
        return lambda: jax.block_until_ready(fn(p, g, m, v))
    return build


def build_fp8_matmul(shape: dict, dtype: str, flags: dict, *,
                     interpret: Optional[bool] = None):
    """``build(config)``: one jitted fused dequant-matmul over a
    synthetic e4m3-quantized weight at the candidate
    ``(block_k, block_n)`` tiles (serve weight-streaming's decode
    read)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(0)
    m, k, n = shape.get("m", 8), shape["k"], shape["n"]
    dt = _np_dtype(dtype)
    x = jnp.asarray(rng.randn(m, k) * 0.1, dt)
    from apex_tpu.ops.fp8_matmul import quantize_weight
    q, scale = quantize_weight(jnp.asarray(rng.randn(k, n) * 0.05,
                                           jnp.float32))

    def build(config):
        from apex_tpu.ops.fp8_matmul import fp8_dequant_matmul

        fn = jax.jit(lambda x, q, scale: fp8_dequant_matmul(
            x, q, scale, block_k=config["block_k"],
            block_n=config["block_n"], interpret=interpret))
        return lambda: jax.block_until_ready(fn(x, q, scale))
    return build


_BUILDERS = {"flash_attention_fwd": build_flash_fwd,
             "flash_attention_bwd": build_flash_bwd,
             "lm_head_ce": build_lm_head_ce,
             "decode_attention": build_decode_attention,
             "fused_layer_norm": build_fused_layer_norm,
             "xentropy": build_xentropy,
             "multi_tensor_update": build_multi_tensor_update,
             "fp8_matmul": build_fp8_matmul}


def tune_one(kernel: str, shape: dict, dtype: str, flags: dict, *,
             interpret: Optional[bool] = None, median_of: int = 5,
             warmup: int = 1, config_timeout_s: Optional[float] = 120.0,
             timer=None) -> dict:
    """Sweep one (kernel, shape bucket): enumerate the legal config
    space, measure, return the harness result dict."""
    candidates = space.config_space(kernel, shape, flags)
    build = _BUILDERS[kernel](shape, dtype, flags, interpret=interpret)
    return harness.sweep(candidates, build, timer=timer,
                         median_of=median_of, warmup=warmup,
                         config_timeout_s=config_timeout_s, label=kernel)


def tune_and_store(kernel: str, spec: dict, cache: TuneCache, *,
                   interpret: Optional[bool] = None, median_of: int = 5,
                   warmup: int = 1, config_timeout_s: Optional[float] = 120.0,
                   timer=None) -> dict:
    """Sweep + persist: the offline CLI's unit of work. Returns
    ``{key, kernel, best, best_s, n_candidates, n_failed}``."""
    shape, dtype, flags = split_shape(kernel, spec)
    result = tune_one(kernel, shape, dtype, flags, interpret=interpret,
                      median_of=median_of, warmup=warmup,
                      config_timeout_s=config_timeout_s, timer=timer)
    key = cache_key(kernel, shape, dtype, flags)
    if result["best"] is not None:
        cache.put(key, result["best"],
                  ms=(result["best_s"] or 0.0) * 1e3,
                  swept=len(result["results"]))
    return {"key": key, "kernel": kernel, "best": result["best"],
            "best_s": result["best_s"],
            "n_candidates": len(result["results"]) + len(result["failed"]),
            "n_failed": len(result["failed"]),
            "results": result["results"], "failed": result["failed"]}
