"""apex_tpu.data — host-side input pipeline.

The reference's imagenet example gets its throughput from a C++/CUDA
loader stack (DALI or torchvision+prefetcher with pinned memory,
``examples/imagenet/main_amp.py``). On TPU the input pipeline is routinely
the MFU ceiling (SURVEY §7 risks), and the GIL makes pure-python
per-image work a bottleneck — so the transform/prefetch core here is C++
(``csrc/apex_tpu_native.cpp``), with a numpy fallback when no compiler
exists (apex's "Python-only build" doctrine).
"""

from apex_tpu.data.loader import (  # noqa: F401
    DataLoader,
    transform_batch,
    f32_to_bf16,
    flatten,
    unflatten,
    native_available,
)
