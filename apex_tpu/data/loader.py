"""Threaded prefetching data loader + host tensor utilities.

Native core in ``csrc/apex_tpu_native.cpp`` via ctypes; every entry point
has a numpy fallback so the package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from apex_tpu import _native
from apex_tpu.monitor import hooks as _mon

_BF16_VIEW = np.uint16


def native_available() -> bool:
    return _native.available()


# ---------------------------------------------------------------------------
# flatten / unflatten (apex_C parity: csrc/flatten_unflatten.cpp)
# ---------------------------------------------------------------------------

def flatten(arrays: Sequence[np.ndarray], n_threads: int = 4) -> np.ndarray:
    """Concatenate host arrays' bytes into one flat uint8 buffer."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = np.asarray([a.nbytes for a in arrays], np.int64)
    total = int(sizes.sum())
    out = np.empty(total, np.uint8)
    lib = _native.lib()
    if lib is None or not arrays:
        off = 0
        for a, s in zip(arrays, sizes):
            out[off:off + s] = a.view(np.uint8).reshape(-1)
            off += s
        return out
    ptrs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays])
    lib.atp_flatten(ptrs, sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    len(arrays), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    n_threads)
    return out


def unflatten(flat: np.ndarray, templates: Sequence[np.ndarray],
              n_threads: int = 4) -> list[np.ndarray]:
    """Split a flat uint8 buffer back into arrays shaped like ``templates``."""
    flat = np.ascontiguousarray(flat.view(np.uint8).reshape(-1))
    outs = [np.empty_like(np.ascontiguousarray(t)) for t in templates]
    sizes = np.asarray([o.nbytes for o in outs], np.int64)
    if flat.nbytes != int(sizes.sum()):
        raise ValueError(f"flat buffer has {flat.nbytes} bytes, templates "
                         f"need {int(sizes.sum())}")
    lib = _native.lib()
    if lib is None or not outs:
        off = 0
        for o, s in zip(outs, sizes):
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + s]
            off += s
        return outs
    ptrs = (ctypes.c_void_p * len(outs))(
        *[o.ctypes.data_as(ctypes.c_void_p) for o in outs])
    lib.atp_unflatten(flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                      sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                      len(outs), ptrs, n_threads)
    return outs


def f32_to_bf16(x: np.ndarray, n_threads: int = 4) -> np.ndarray:
    """Round-to-nearest-even fp32→bf16; returns a uint16 bit-pattern array
    (viewable as ml_dtypes.bfloat16). Halves host→device transfer bytes."""
    x = np.ascontiguousarray(x, np.float32)
    out = np.empty(x.shape, _BF16_VIEW)
    lib = _native.lib()
    if lib is None:
        import ml_dtypes
        return x.astype(ml_dtypes.bfloat16).view(np.uint16)
    lib.atp_f32_to_bf16(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        x.size, n_threads)
    return out


# ---------------------------------------------------------------------------
# batch transform
# ---------------------------------------------------------------------------

def transform_batch(images: np.ndarray, indices: np.ndarray, out_h: int,
                    out_w: int, mean: Sequence[float], std: Sequence[float],
                    *, out_bf16: bool = False, augment: bool = False,
                    seed: int = 0, n_threads: int = 4) -> np.ndarray:
    """Gather ``images[indices]``, crop to (out_h, out_w) (random if
    ``augment`` else center), random-hflip (augment), normalize
    ``(x/255 - mean)/std``. uint8 NHWC in, fp32/bf16 NHWC out."""
    images = np.ascontiguousarray(images)
    if images.dtype != np.uint8 or images.ndim != 4:
        raise ValueError("images must be uint8 [N, H, W, C]")
    n = len(indices)
    n_src, sh, sw, c = images.shape
    if c > 8:
        raise ValueError("at most 8 channels")
    if out_h > sh or out_w > sw:
        # the native path would compute a negative crop range and read out
        # of bounds; fail identically on both paths
        raise ValueError(
            f"crop ({out_h}, {out_w}) exceeds source dims ({sh}, {sw})")
    indices = np.ascontiguousarray(indices, np.int64)
    if n and (indices.min() < 0 or indices.max() >= n_src):
        raise ValueError(
            f"indices out of range [0, {n_src}): "
            f"[{indices.min()}, {indices.max()}]")
    mean32 = np.ascontiguousarray(mean, np.float32)
    std32 = np.ascontiguousarray(std, np.float32)
    out = np.empty((n, out_h, out_w, c), _BF16_VIEW if out_bf16 else np.float32)
    lib = _native.lib()
    if lib is None:
        return _transform_batch_py(images, indices, out_h, out_w, mean32,
                                   std32, out_bf16, augment, seed)
    lib.atp_transform_batch_args(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, sh, sw, c, out_h, out_w,
        mean32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(out_bf16), int(augment),
        out.ctypes.data_as(ctypes.c_void_p), seed, n_threads)
    return out


def _transform_batch_py(images, indices, out_h, out_w, mean, std, out_bf16,
                        augment, seed):
    n = len(indices)
    _, sh, sw, c = images.shape
    rng = np.random.RandomState(seed & 0x7fffffff)
    out32 = np.empty((n, out_h, out_w, c), np.float32)
    for i, idx in enumerate(indices):
        if augment:
            y0 = rng.randint(0, sh - out_h + 1)
            x0 = rng.randint(0, sw - out_w + 1)
            flip = bool(rng.randint(2))
        else:
            y0, x0, flip = (sh - out_h) // 2, (sw - out_w) // 2, False
        img = images[idx, y0:y0 + out_h, x0:x0 + out_w]
        if flip:
            img = img[:, ::-1]
        out32[i] = (img.astype(np.float32) / 255.0 - mean) / std
    if out_bf16:
        return f32_to_bf16(out32)
    return out32


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

class DataLoader:
    """Prefetching loader over an in-memory uint8 image array.

    ``for x, y in DataLoader(images, labels, batch_size=128, ...)`` — the
    C++ worker pool keeps ``prefetch`` transformed batches ready while the
    accelerator step runs (DALI/prefetcher analog of the reference's
    imagenet pipeline). Falls back to synchronous numpy transforms plus a
    python prefetch thread without the native lib.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, *, crop: Optional[tuple[int, int]] = None,
                 mean: Sequence[float] = (0.485, 0.456, 0.406),
                 std: Sequence[float] = (0.229, 0.224, 0.225),
                 out_bf16: bool = False, augment: bool = True,
                 shuffle: bool = True, drop_last: bool = True,
                 seed: int = 0, prefetch: int = 4, workers: int = 2,
                 inner_threads: int = 4,
                 shard_id: int = 0, num_shards: int = 1):
        if images.dtype != np.uint8 or images.ndim != 4:
            raise ValueError("images must be uint8 [N, H, W, C]")
        if len(images) != len(labels):
            raise ValueError("images/labels length mismatch")
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(labels)
        self.batch_size = batch_size
        n, sh, sw, c = self.images.shape
        self.crop = crop or (sh, sw)
        if self.crop[0] > sh or self.crop[1] > sw:
            raise ValueError(
                f"crop {self.crop} exceeds source dims ({sh}, {sw})")
        if drop_last and n // max(1, num_shards) < batch_size:
            raise ValueError(
                f"drop_last=True with {n} images / {num_shards} shard(s) < "
                f"batch_size={batch_size} yields zero batches")
        self.mean, self.std = tuple(mean[:c]), tuple(std[:c])
        self.out_bf16 = out_bf16
        self.augment = augment
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.prefetch = max(1, prefetch)
        self.workers = max(1, workers)
        self.inner_threads = max(1, inner_threads)
        # multi-host: every host holds (or mmaps) the dataset and iterates
        # a disjoint stripe — pass shard_id=jax.process_index(),
        # num_shards=jax.process_count(); the per-epoch shuffle is
        # seed-synchronized so stripes stay disjoint across hosts
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"shard_id {shard_id} not in [0, {num_shards})")
        self.shard_id = shard_id
        self.num_shards = num_shards
        self._epoch = 0

    def __len__(self) -> int:
        n = self._shard_len()
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _shard_len(self) -> int:
        # every shard is truncated to the same length so all hosts run the
        # same number of batches per epoch — unequal shards would deadlock
        # lockstep collectives (torch DistributedSampler equalizes too)
        return len(self.images) // self.num_shards

    def _epoch_indices(self) -> np.ndarray:
        idx = np.arange(len(self.images), dtype=np.int64)
        if self.shuffle:
            np.random.RandomState((self.seed + self._epoch) & 0x7fffffff).shuffle(idx)
        # strided split of the SAME shuffled order on every host: shards
        # are disjoint; the tail remainder (< num_shards items) is dropped
        # to keep every host's epoch the same length
        return idx[self.shard_id::self.num_shards][:self._shard_len()]

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        self._epoch += 1
        idx = self._epoch_indices()
        batches = [idx[i * self.batch_size:(i + 1) * self.batch_size]
                   for i in range(len(self))]
        if not self.drop_last and len(idx) % self.batch_size:
            pass  # len() already included the ragged tail
        if not batches:
            return
        lib = _native.lib()
        if lib is not None:
            yield from self._iter_native(lib, batches)
        else:
            yield from self._iter_python(batches)

    def _iter_native(self, lib, batches):
        n, sh, sw, c = self.images.shape
        oh, ow = self.crop
        mean32 = np.ascontiguousarray(self.mean, np.float32)
        std32 = np.ascontiguousarray(self.std, np.float32)
        # ragged tails get their own slot size via per-batch loaders being
        # overkill — instead pad capacity to max batch and slice on yield
        max_b = max(len(b) for b in batches)
        handle = lib.atp_loader_create(
            self.images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            sh, sw, c, oh, ow,
            mean32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(self.out_bf16), int(self.augment), max_b,
            self.prefetch, self.workers, self.inner_threads)
        if not handle:
            yield from self._iter_python(batches)
            return
        dtype = _BF16_VIEW if self.out_bf16 else np.float32
        itemsize = 2 if self.out_bf16 else 4
        slot_bytes = max_b * oh * ow * c * itemsize
        try:
            submitted = 0
            next_out = 0
            padded = []
            for b in batches:
                pb = b if len(b) == max_b else np.concatenate(
                    [b, np.zeros(max_b - len(b), np.int64)])
                padded.append((pb, len(b)))
            while next_out < len(padded):
                while (submitted < len(padded)
                       and submitted - next_out < self.prefetch):
                    pb, _ = padded[submitted]
                    lib.atp_loader_submit(
                        handle,
                        pb.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                        max_b,
                        (self.seed + self._epoch * 131071 + submitted) & (2**64 - 1))
                    submitted += 1
                buf = np.empty(slot_bytes, np.uint8)
                # host-input wait: how long the consumer blocked on the
                # worker pool (0 when prefetch kept up with the step)
                t_wait = time.perf_counter()
                got = lib.atp_loader_next(
                    handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
                if _mon.enabled():
                    _mon.timer_event("data/host_wait",
                                     time.perf_counter() - t_wait)
                    _mon.counter("data/batches")
                if got < 0:
                    raise RuntimeError("native loader shut down")
                real = padded[next_out][1]
                x = buf.view(dtype).reshape(max_b, oh, ow, c)[:real]
                y = self.labels[batches[next_out]]
                next_out += 1
                yield x, y
        finally:
            lib.atp_loader_destroy(handle)

    def _iter_python(self, batches):
        import queue as _q
        q: _q.Queue = _q.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def producer():
            for bi, b in enumerate(batches):
                if stop.is_set():
                    return
                x = transform_batch(
                    self.images, b, *self.crop, self.mean, self.std,
                    out_bf16=self.out_bf16, augment=self.augment,
                    seed=(self.seed + self._epoch * 131071 + bi),
                    n_threads=self.inner_threads)
                q.put((x, self.labels[b]))
            q.put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                t_wait = time.perf_counter()
                item = q.get()
                if _mon.enabled():
                    _mon.timer_event("data/host_wait",
                                     time.perf_counter() - t_wait)
                    # backlog after the take — supporting context for
                    # starvation triage (the health watchdog's
                    # loader_starvation detection keys on the
                    # data/host_wait timer vs step time, not this)
                    _mon.gauge("data/prefetch_depth", q.qsize())
                if item is None:
                    return
                _mon.counter("data/batches")
                yield item
        finally:
            stop.set()
