"""Weight-norm reparameterization as param-tree transforms.

Reference semantics (``apex/reparameterization/weight_norm.py``): a
weight w is stored as direction v and magnitude g with
``w = g * v / ||v||`` (norm over all dims except the output dim); the
hook recomputes w before each forward so the optimizer trains (v, g).

Functional design: params are rewritten so each selected kernel leaf
becomes ``{"_wn_v": v, "_wn_g": g}``; ``materialize_weights`` folds them
back to dense kernels (inside jit, fused to nothing); gradients w.r.t.
(v, g) follow by autodiff — exactly the hook's math without mutation.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _norm_except_last(v):
    axes = tuple(range(v.ndim - 1))
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes, keepdims=True))


def _default_filter(path, leaf) -> bool:
    return hasattr(leaf, "ndim") and leaf.ndim >= 2 and \
        path and path[-1].lower() in ("kernel", "weight", "embedding")


def apply_weight_norm(params, name_filter: Optional[Callable] = None, dim: int = -1):
    """Split selected kernels into (v, g). ``dim`` kept for API parity;
    the norm is over all non-output dims (torch dim=0 equivalent for our
    [in..., out] layout)."""
    name_filter = name_filter or _default_filter

    def walk(path, tree):
        if isinstance(tree, dict):
            return {k: walk(path + (k,), v) for k, v in tree.items()}
        if name_filter(path, tree):
            g = _norm_except_last(tree)
            v = tree
            return {"_wn_v": v, "_wn_g": g.astype(tree.dtype)}
        return tree

    return walk((), params)


def materialize_weights(params):
    """Rebuild dense kernels from (v, g) leaves."""
    def walk(tree):
        if isinstance(tree, dict):
            if set(tree.keys()) == {"_wn_v", "_wn_g"}:
                v, g = tree["_wn_v"], tree["_wn_g"]
                w = v.astype(jnp.float32) / jnp.maximum(_norm_except_last(v), 1e-12)
                return (w * g.astype(jnp.float32)).astype(v.dtype)
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(params)


def remove_weight_norm(params):
    """Collapse (v, g) back to plain kernels
    (``apex/reparameterization/__init__.py remove_weight_norm``)."""
    return materialize_weights(params)


def reparameterized_apply(apply_fn):
    """Wrap ``apply_fn(params, ...)`` to materialize weight-normed params
    first — the functional analog of the forward pre-hook."""
    def wrapped(params, *args, **kwargs):
        return apply_fn(materialize_weights(params), *args, **kwargs)
    return wrapped
