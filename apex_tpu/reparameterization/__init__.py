"""apex_tpu.reparameterization — weight normalization.

Reference: ``apex/reparameterization/__init__.py:4``
(``apply_weight_norm`` installing forward pre-hooks,
``reparameterization.py:4``, ``weight_norm.py`` — w = g · v/||v||).

TPU/functional form: hooks become an explicit param-tree transform:
``apply_weight_norm(params)`` splits selected kernels into (v, g);
``materialize_weights`` rebuilds w (called inside the model's apply via
``reparameterized_apply``); ``remove_weight_norm`` collapses back.
"""

from apex_tpu.reparameterization.weight_norm import (  # noqa: F401
    apply_weight_norm,
    remove_weight_norm,
    materialize_weights,
    reparameterized_apply,
)
