"""Paged KV cache: a preallocated page pool + per-sequence block tables.

The pool is allocated ONCE (``init_cache``) and never reshaped: every
cache mutation is a scatter into the fixed arrays, so the decode step
can donate the pool and update it in place. Layout (the
``ops.flash_attention.paged_decode_attention`` contract):

    k_pool / v_pool   [num_layers, kv_heads, num_pages, page_size, d]
    k_scale / v_scale [num_layers, kv_heads, num_pages]  f32 (fp8 mode)

Page 0 is the **null page**: the host allocator never hands it out, and
every masked write (inactive batch slots, prompt padding) is routed to
it — so a scatter never needs a branch, and nothing ever reads the null
page's contents (block-table entries past a sequence's length point at
it but are masked by ``seq_lens``).

fp8-KV mode stores e4m3 pages through the :mod:`apex_tpu.amp.fp8` codec
with ONE scale per (layer, head, page), fixed when the page's slot-0
token is written (``compute_scale`` of that token's amax with
``fp8_margin`` powers of two of headroom; later tokens in the page
quantize with the same scale and saturate-clip past it — the e4m3 clip
is the codec's correctness rule). The slot-0 rule is what makes
evict/re-admit bit-exact: a page's scale is a deterministic function of
its first token regardless of whether that token arrived via prefill or
decode, so a recomputed cache is bitwise the original.

Page size resolves **explicit > tuned cache > heuristic** through
``apex_tpu.tune`` (:func:`resolve_page_size` — the ``decode_attention``
sweep of ``python -m apex_tpu.ops tune``), exactly like the flash
fwd/bwd blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import fp8 as fp8_mod

#: heuristic default page size: big enough that a 1k-token context is
#: 8 pages (program-count bound, like the flash forward), small enough
#: that the per-sequence tail waste (page_size/2 tokens average) stays
#: a few percent at chat lengths
DEFAULT_PAGE_SIZE = 128


def resolve_page_size(*, kv_heads: int, head_dim: int, context_len: int,
                      group: int = 1, dtype=jnp.bfloat16, fp8: bool = False,
                      batch: int = 1, page_size: Optional[int] = None,
                      autotune: Optional[str] = None) -> int:
    """Pool page size: explicit > tuned cache > heuristic (the flash
    fwd/bwd resolution order, via the ``decode_attention`` sweep)."""
    if page_size is not None:
        return int(page_size)
    from apex_tpu.tune import runtime as tune_rt
    policy = tune_rt.resolve_policy(autotune)
    if policy != "off":
        dt = jnp.dtype(dtype)
        shape = {"b": batch, "kv": kv_heads, "group": group,
                 "s": context_len, "d": head_dim, "itemsize": dt.itemsize}
        cfg = tune_rt.resolve("decode_attention", shape, dt.name,
                              {"fp8": bool(fp8)}, policy=policy)
        if cfg is not None:
            return int(cfg["block_kv"])
    # clip to the context like flash blocks clip to the sequence, but
    # keep the 8-sublane alignment the Pallas kernel requires
    return min(DEFAULT_PAGE_SIZE, max(8, -(-context_len // 8) * 8))


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Static pool geometry (hashable — rides jit as a static arg)."""

    num_layers: int
    kv_heads: int
    head_dim: int
    num_pages: int                 # INCLUDING the null page 0
    page_size: int
    dtype: Any = jnp.bfloat16      # pool dtype (ignored when fp8)
    fp8: bool = False
    fp8_margin: float = 2.0        # 2**margin headroom over the slot-0 amax

    def __post_init__(self):
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved null page)")
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")

    @property
    def pool_dtype(self):
        return fp8_mod.E4M3 if self.fp8 else jnp.dtype(self.dtype)

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    def pages_for_tokens(self, n: int) -> int:
        return -(-int(n) // self.page_size)

    # -- capacity accounting (host-side ints: the bench/test assertions
    #    about fp8 capacity come from HERE, not from hand-waving) ------

    def bytes_per_page(self) -> int:
        """HBM bytes one pool page costs across k+v (+ fp8 scales)."""
        elems = self.kv_heads * self.page_size * self.head_dim
        per = 2 * elems * jnp.dtype(self.pool_dtype).itemsize
        if self.fp8:
            per += 2 * self.kv_heads * 4          # k_scale + v_scale rows
        return per * self.num_layers

    def pool_bytes(self) -> int:
        return self.bytes_per_page() * self.num_pages

    def pages_in_budget(self, budget_bytes: int) -> int:
        return int(budget_bytes) // self.bytes_per_page()

    def max_concurrent_seqs(self, budget_bytes: int, seq_len: int) -> int:
        """How many ``seq_len``-token sequences fit a pool of
        ``budget_bytes`` (minus the null page)."""
        usable = max(0, self.pages_in_budget(budget_bytes) - 1)
        return usable // self.pages_for_tokens(seq_len)

    def occupancy_bytes(self, pages_in_use: int) -> int:
        """HBM bytes held by ``pages_in_use`` allocated pages — the
        per-step ``serve/pool_bytes_in_use`` gauge the engine records
        (same byte accounting as :meth:`bytes_per_page`, so the
        telemetry and the capacity claims can never drift apart)."""
        return int(pages_in_use) * self.bytes_per_page()


class CacheState(NamedTuple):
    """The device pytree the jitted steps thread and donate."""

    k_pool: jax.Array
    v_pool: jax.Array
    k_scale: Optional[jax.Array]   # None outside fp8 mode
    v_scale: Optional[jax.Array]


def init_cache(cfg: CacheConfig) -> CacheState:
    shape = (cfg.num_layers, cfg.kv_heads, cfg.num_pages, cfg.page_size,
             cfg.head_dim)
    k = jnp.zeros(shape, cfg.pool_dtype)
    v = jnp.zeros(shape, cfg.pool_dtype)
    if not cfg.fp8:
        return CacheState(k, v, None, None)
    # scales init to 1.0: finite and positive everywhere, so the
    # kernel's dequant divides are safe even for never-written pages.
    # Two DISTINCT arrays — aliased leaves break the donated step
    # (donate-same-buffer-twice)
    sshape = (cfg.num_layers, cfg.kv_heads, cfg.num_pages)
    return CacheState(k, v, jnp.ones(sshape, jnp.float32),
                      jnp.ones(sshape, jnp.float32))


def _page_scales(cfg: CacheConfig, x) -> jax.Array:
    """compute_scale over the head dim: ``x`` [..., kv, d] ->
    [..., kv]."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    return fp8_mod.compute_scale(amax, fp8_mod.E4M3_MAX,
                                 margin=cfg.fp8_margin)


def write_token(cfg: CacheConfig, state: CacheState, layer: int,
                page_ids, slots, k_new, v_new) -> CacheState:
    """Scatter one decode token per batch slot into layer ``layer``.

    ``page_ids``/``slots``: int32 [b] (masked slots carry page 0);
    ``k_new``/``v_new``: [b, kv_heads, d]. Pure — runs inside the
    donated decode step.
    """
    # NB indexing below mixes the scalar ``layer`` with index arrays:
    # both are "advanced" indices separated by the heads slice, so the
    # broadcast dims land FIRST — gathers/scatters see [b, kv, ...]
    k_t, v_t = k_new, v_new                        # [b, kv, d]
    k_scale = state.k_scale
    v_scale = state.v_scale
    if cfg.fp8:
        first = (slots == 0)[:, None]              # [b, 1]
        cand_k = _page_scales(cfg, k_new)          # [b, kv]
        cand_v = _page_scales(cfg, v_new)
        cur_k = state.k_scale[layer, :, page_ids]  # [b, kv]
        cur_v = state.v_scale[layer, :, page_ids]
        sk = jnp.where(first, cand_k, cur_k)
        sv = jnp.where(first, cand_v, cur_v)
        k_scale = state.k_scale.at[layer, :, page_ids].set(sk)
        v_scale = state.v_scale.at[layer, :, page_ids].set(sv)
        k_t = fp8_mod.quantize(k_t, sk[..., None], fp8_mod.E4M3)
        v_t = fp8_mod.quantize(v_t, sv[..., None], fp8_mod.E4M3)
    else:
        k_t = k_t.astype(cfg.pool_dtype)
        v_t = v_t.astype(cfg.pool_dtype)
    k_pool = state.k_pool.at[layer, :, page_ids, slots].set(k_t)
    v_pool = state.v_pool.at[layer, :, page_ids, slots].set(v_t)
    return CacheState(k_pool, v_pool, k_scale, v_scale)


def write_prompt(cfg: CacheConfig, state: CacheState, layer: int,
                 block_table, length, k_seq, v_seq) -> CacheState:
    """Scatter a whole (padded) prompt's K/V for one sequence.

    ``block_table``: int32 [m] (the sequence's pages); ``length``:
    traced scalar (real prompt length — positions past it route to the
    null page); ``k_seq``/``v_seq``: [S, kv_heads, d] with S static and
    a multiple-free shape (S <= m * page_size).
    """
    S = k_seq.shape[0]
    pos = jnp.arange(S, dtype=jnp.int32)
    live = pos < length
    pages = jnp.where(live, block_table[pos // cfg.page_size], 0)
    slots = pos % cfg.page_size
    # advanced-indexing note as in write_token: [S, kv, ...] layouts
    k_t, v_t = k_seq, v_seq                        # [S, kv, d]
    k_scale = state.k_scale
    v_scale = state.v_scale
    if cfg.fp8:
        # slot-0 rule: one scale write per touched page, from the
        # page's first token (static stride — S and page_size are
        # static), identical to what the decode write would have set
        pos0 = jnp.arange(0, S, cfg.page_size, dtype=jnp.int32)
        pages0 = pages[pos0]                       # masked ones hit null
        sk0 = _page_scales(cfg, k_seq[pos0])       # [m_used, kv]
        sv0 = _page_scales(cfg, v_seq[pos0])
        k_scale = state.k_scale.at[layer, :, pages0].set(sk0)
        v_scale = state.v_scale.at[layer, :, pages0].set(sv0)
        # every position quantizes with ITS page's (new) scale
        sk = k_scale[layer, :, pages]              # [S, kv]
        sv = v_scale[layer, :, pages]
        k_t = fp8_mod.quantize(k_t, sk[..., None], fp8_mod.E4M3)
        v_t = fp8_mod.quantize(v_t, sv[..., None], fp8_mod.E4M3)
    else:
        k_t = k_t.astype(cfg.pool_dtype)
        v_t = v_t.astype(cfg.pool_dtype)
    k_pool = state.k_pool.at[layer, :, pages, slots].set(k_t)
    v_pool = state.v_pool.at[layer, :, pages, slots].set(v_t)
    return CacheState(k_pool, v_pool, k_scale, v_scale)
