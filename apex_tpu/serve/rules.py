"""Regex-driven serve layout rules: leaf path -> PartitionSpec.

Generalizes :mod:`apex_tpu.zero.rules` (the ``match_partition_rules``
shape, SNIPPETS.md [2]) from ZeRO's binary shard/replicate decisions to
real ``PartitionSpec`` construction: an ordered ``(regex, decision)``
table matched with ``re.search`` against the leaf's slash-joined tree
path, first match wins, no-match is an error. Decisions:

- ``"replicate"`` — full copy per rank (``P()``);
- ``"shard:<axis>"`` — put the tensor-parallel mesh axis at tensor
  dimension ``<axis>`` (``"shard:1"`` on a ``[in, out]`` kernel is the
  Megatron column shard);
- ``"heads"`` — shorthand for ``"shard:1"``, the KV-cache convention:
  every cache leaf (``[L, kv_heads, ...]`` pools and scales) shards its
  heads dimension over the tensor axis, so each rank's pool holds its
  local heads' pages and the paged-attention reads stay rank-local.

Two default tables ship: :data:`CACHE_RULES` for the paged KV-cache
state and :data:`GPT_PARAM_RULES` for the GPT parameter tree the serve
model reads (column layers split their output dim, row layers their
input dim, the embedding its vocab dim — matching what the TP layers'
sliced init produces, so a full tp=1 tree fed through ``shard_map``
``in_specs`` lands each rank exactly its training-time shard).
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.zero.rules import first_match, leaf_path_names

REPLICATE = "replicate"
HEADS = "heads"

#: KV-cache layout: pools are [L, kv_heads, num_pages, page_size, d],
#: per-page fp8 scales are [L, kv_heads, num_pages] — heads dim 1 for
#: all of them, sharded over the tensor axis.
CACHE_RULES: tuple = (
    (r"(k|v)_pool", HEADS),
    (r"(k|v)_scale", HEADS),
    (r".*", REPLICATE),
)

#: The GPT param tree under serve TP: same layout the training TP
#: layers shard to (qkv packs per-head [q|k|v] column groups, so the
#: contiguous column split IS the head split).
GPT_PARAM_RULES: tuple = (
    (r"attn/qkv/kernel", "shard:1"),
    (r"attn/qkv/bias", "shard:0"),
    (r"attn/proj/kernel", "shard:0"),
    (r"mlp/fc1/kernel", "shard:1"),
    (r"mlp/fc1/bias", "shard:0"),
    (r"mlp/fc2/kernel", "shard:0"),
    (r"wte/embedding", "shard:0"),
    (r".*", REPLICATE),
)


def _parse_decision(rx: str, decision: str) -> int | None:
    """None = replicate, int = tensor dim carrying the tp axis."""
    if decision == REPLICATE:
        return None
    if decision == HEADS:
        return 1
    m = re.fullmatch(r"shard:(\d+)", decision)
    if m is None:
        raise ValueError(
            f"serve rule ({rx!r}, {decision!r}): decision must be "
            f"{REPLICATE!r}, {HEADS!r} or 'shard:<dim>'")
    return int(m.group(1))


def match_serve_rules(
    rules: Sequence[tuple[str, str]],
    tree: Any,
    *,
    axis_name: str = ps.TENSOR_AXIS,
    world: int | None = None,
    validate: bool | str = True,
) -> Any:
    """Pytree of ``PartitionSpec`` matching ``tree``.

    ``world``: the tensor-parallel size the specs must divide
    (default: the installed mesh's tensor axis). ``world == 1`` is the
    structural override — everything replicates (``P()``) so the same
    code path serves the single-chip engine. A sharded leaf whose
    target dim does not divide by ``world`` is an error at rule time,
    not a shard_map crash later.

    ``validate``: run the apexlint APXR table checks
    (:mod:`apex_tpu.lint.rules_tables`) against THIS tree at
    config-build time, raising with the finding text on shadowed rules
    (APXR202) or bad / out-of-range / non-divisible decisions
    (APXR203). ``"strict"`` additionally rejects dead rules and
    uncovered leaves (APXR201); ``False`` opts out for exploratory
    tables.
    """
    rules = tuple(rules)
    parsed = [(rx, _parse_decision(rx, d)) for rx, d in rules]
    w = ps.get_tensor_model_parallel_world_size() if world is None \
        else int(world)
    if validate:
        from apex_tpu.lint.rules_tables import constructor_validate
        constructor_validate(rules, [tree],
                             table_name="match_serve_rules",
                             kind="serve", world=max(w, 1),
                             strict=validate == "strict")

    def decide(path, leaf):
        name = "/".join(leaf_path_names(path))
        if w <= 1 or leaf is None:
            return P()
        idx = first_match(rules, name)
        if idx is None:
            raise ValueError(
                f"no serve layout rule matched leaf {name!r} — add a "
                f"rule (('.*', 'replicate') is the safe catch-all)")
        rx, dim = parsed[idx]
        if dim is None:
            return P()
        shape = np.shape(leaf)
        if dim >= len(shape) or shape[dim] % w:
            raise ValueError(
                f"serve rule {rx!r} shards dim {dim} of "
                f"{name!r} (shape {shape}) over {axis_name}="
                f"{w}: not divisible")
        spec = [None] * len(shape)
        spec[dim] = axis_name
        return P(*spec)

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [decide(p, x) for p, x in flat])
