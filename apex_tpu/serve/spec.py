"""Speculative decoding: draft/verify machinery for the serve engine.

The scheme is the standard draft-then-verify loop (Leviathan et al.;
vLLM's ``spec_decode``), specialized to this engine's fixed-shape
greedy contract:

- a **draft model** — the SAME GPT stack truncated to its first
  ``num_layers`` blocks (:func:`derive_draft`; shared wte/wpe/ln_f, so
  no new weights exist) — proposes ``k`` tokens per scheduler round
  through its own paged cache;
- the **target model verifies all k+1 positions in ONE call of the
  existing decode program**: rows ``0..k`` of the fixed-capacity batch
  carry positions ``n-1 .. n-1+k`` of a single sequence (token row 0 is
  the last committed token, rows 1..k the draft tokens). This works
  because ``decode_forward`` writes EVERY row's K/V per layer before
  any row attends, and per-row ``seq_lens = position + 1`` provides the
  causal mask — so row ``i`` attends over the committed prefix plus the
  draft prefix written by rows ``< i`` in the same call. No verify
  program exists: the engine still compiles exactly three programs
  (prefill, decode-=-verify, draft-decode);
- **host-side greedy acceptance** (:func:`accept_greedy`): commit the
  longest draft prefix matching the verifier's own argmaxes plus the
  verifier's next token ("bonus"). Because no op in the forward mixes
  batch rows, each verify row is bitwise the plain-decode row at the
  same (token, position, cache) — so greedy speculative output is
  TOKEN-IDENTICAL to plain paged decode (asserted in
  ``tests/test_serve_spec.py``), and every round commits at least one
  token (``k = 0`` degenerates to plain decode exactly).

Everything here is pure host math / host tree surgery — no jax device
work, no new compiled shapes. The engine owns the cache bookkeeping
(``Sequence.draft_cached``, rejected-suffix overwrite; see
``docs/serve.md``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence as Seq, Tuple

from apex_tpu.models.gpt import GPTConfig


def accept_greedy(draft_tokens: Seq[int],
                  verify_argmax: Seq[int]) -> Tuple[List[int], int]:
    """Greedy accept/reject for one speculative round.

    ``draft_tokens``: the ``k`` proposed tokens ``d_1..d_k``.
    ``verify_argmax``: the ``k+1`` verifier argmaxes ``a_0..a_k``,
    where ``a_i`` is the target's greedy token given the committed
    prefix plus ``d_1..d_i``.

    Returns ``(committed, num_accepted)``: ``committed`` is
    ``d_1..d_m`` plus the bonus token ``a_m`` (``m+1`` tokens, always
    at least one), where ``m`` is the longest prefix with
    ``d_i == a_{i-1}``. By induction each committed token equals the
    plain greedy token at its index — ``a_0`` IS the greedy
    continuation, ``d_1 == a_0`` makes ``a_1`` the greedy token one
    past it, and so on. ``k = 0`` commits ``[a_0]``: plain decode.
    """
    k = len(draft_tokens)
    if len(verify_argmax) != k + 1:
        raise ValueError(f"need {k + 1} verifier argmaxes for {k} draft "
                         f"tokens, got {len(verify_argmax)}")
    m = 0
    while m < k and int(draft_tokens[m]) == int(verify_argmax[m]):
        m += 1
    committed = [int(t) for t in draft_tokens[:m]]
    committed.append(int(verify_argmax[m]))
    return committed, m


def derive_draft(cfg: GPTConfig, params, *,
                 num_layers: int) -> Tuple[GPTConfig, dict]:
    """Depth-truncated draft: the target's first ``num_layers`` blocks
    with the SHARED embedding / positional / final-norm weights.

    Zero new parameters and zero training: the truncated stack is a
    legitimate (if crude) draft — early blocks carry most of the
    next-token signal on small models, and the acceptance test is
    exact, so a bad draft costs only speed, never correctness. The
    returned tree references the original leaves (no copy).
    """
    if not (1 <= num_layers <= cfg.num_layers):
        raise ValueError(f"draft num_layers must be in [1, "
                         f"{cfg.num_layers}], got {num_layers}")
    draft_cfg = dataclasses.replace(cfg, num_layers=num_layers)
    draft_params = {"wte": params["wte"], "wpe": params["wpe"],
                    "ln_f": params["ln_f"]}
    for i in range(num_layers):
        draft_params[f"block_{i}"] = params[f"block_{i}"]
    return draft_cfg, draft_params
