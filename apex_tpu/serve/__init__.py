"""``apex_tpu.serve`` — paged KV-cache inference with continuous
batching on the tensor-parallel stack.

The first non-training workload in the codebase, composing four
existing subsystems on the decode hot path:

- the **paged KV cache** (:mod:`~apex_tpu.serve.cache`): a
  preallocated page pool + per-sequence block tables, mutated in place
  through the donated decode step; fp8-KV mode stores e4m3 pages with
  per-page scales through the :mod:`apex_tpu.amp.fp8` codec (~2x cache
  capacity = ~2x concurrent sequences per chip);
- the **decode attention kernel**
  (``ops.flash_attention.paged_decode_attention``): single query per
  sequence reading K/V through the block table, GQA-aware, page size
  resolved explicit > tuned cache > heuristic via :mod:`apex_tpu.tune`
  (the ``decode_attention`` sweep);
- the **continuous-batching scheduler**
  (:mod:`~apex_tpu.serve.scheduler`): admit/evict/preempt at step
  granularity with capacity accounted in pages; preemption recomputes
  (prefill + decode-replay) and is bit-exact;
- **TP layouts** (:mod:`~apex_tpu.serve.rules`): ``zero.rules``-style
  regex tables producing real PartitionSpecs for the cache (heads over
  the tensor axis) and the GPT param tree;
- ``monitor.profile`` scopes thread prefill/decode attribution through
  the existing analytic walk.

Quick start (see ``examples/serve_gpt.py`` / ``docs/serve.md``)::

    engine = serve.ServeEngine(cfg, params, num_pages=64,
                               max_seq_len=256, max_prompt_len=64)
    engine.add_request(prompt_ids, max_new_tokens=32)
    outputs = engine.run()
"""

from apex_tpu.serve.cache import (CacheConfig, CacheState, init_cache,
                                  resolve_page_size)
from apex_tpu.serve.engine import ServeEngine, naive_generate
from apex_tpu.serve.model import quantize_gpt_weights, weight_stream_bytes
from apex_tpu.serve.rules import (CACHE_RULES, GPT_PARAM_RULES,
                                  match_serve_rules)
from apex_tpu.serve.scheduler import (PageAllocator, Scheduler, Sequence,
                                      StepPlan)
from apex_tpu.serve.spec import accept_greedy, derive_draft

__all__ = [
    "CacheConfig", "CacheState", "init_cache", "resolve_page_size",
    "ServeEngine", "naive_generate", "CACHE_RULES", "GPT_PARAM_RULES",
    "match_serve_rules", "PageAllocator", "Scheduler", "Sequence",
    "StepPlan", "accept_greedy", "derive_draft", "quantize_gpt_weights",
    "weight_stream_bytes",
]
