"""Serve-side GPT forward passes over the paged KV cache.

Pure functions over the SAME parameter tree ``models.gpt.GPT`` trains
(``wte/wpe/block_i/{ln1, attn/{qkv,proj}, ln2, mlp/{fc1,fc2}}/ln_f``),
applied through the SAME tensor-parallel layer modules
(``Column/RowParallelLinear``, ``VocabParallelEmbedding``,
``FusedLayerNorm``) — so a checkpoint trained anywhere on the stack
serves unmodified, and under ``shard_map`` over the tensor axis the
serve path pays exactly the training collectives (row-parallel psum,
logits gather). The only new math is the cache interaction:

- :func:`prefill_forward` runs one (padded) prompt through full causal
  attention and scatters every position's K/V into the sequence's
  pages;
- :func:`decode_forward` runs ONE token per batch slot, scatters its
  K/V, and attends over the cache through the block table (the
  paged-attention path of ``ops.flash_attention``).

Both are jit-pure: the engine compiles them once per static shape with
the cache donated. ``monitor.profile`` scopes (``serve_prefill`` /
``serve_decode`` + the per-module tags inside the TP layers) thread the
per-request cost attribution through the existing analytic walk.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.monitor import profile as _prof
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.flash_attention import (
    flash_attention, mha_reference, paged_attention_reference,
    paged_decode_attention)
from apex_tpu.serve import cache as cache_mod
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    mappings as tp_mappings)

PAGED_IMPLS = ("reference", "kernel")
PREFILL_IMPLS = ("reference", "flash")


def _mods(cfg: GPTConfig):
    h = cfg.hidden_size
    return dict(
        wte=VocabParallelEmbedding(num_embeddings=cfg.vocab_size,
                                   embedding_dim=h),
        ln=FusedLayerNorm(normalized_shape=h, dtype=cfg.dtype),
        qkv=ColumnParallelLinear(input_size=h, output_size=3 * h,
                                 gather_output=False),
        proj=RowParallelLinear(input_size=h, output_size=h,
                               input_is_parallel=True),
        fc1=ColumnParallelLinear(input_size=h, output_size=cfg.ffn,
                                 gather_output=False),
        fc2=RowParallelLinear(input_size=cfg.ffn, output_size=h,
                              input_is_parallel=True),
    )


def _apply(mod, sub, x):
    return mod.apply({"params": sub}, x)


def _linear(mod, sub, x, *, row=False, autotune=None, interpret=None):
    """One block linear, dispatching on the param LEAVES: a sub-tree
    carrying a ``scale`` sibling (written by :func:`quantize_gpt_weights`)
    streams its kernel as e4m3 through the fused dequant-matmul
    (``ops.fp8_matmul``, resolution explicit > tuned cache > reference);
    otherwise the ordinary TP layer module applies. The fp8 path
    replays the layer's TP semantics by hand — column shards need no
    collective in a serve forward, row shards psum — so the SAME
    shard_map in_specs serve both modes (the e4m3 kernel keeps the bf16
    kernel's shape, and the scalar scale falls to the rules'
    replicate catch-all)."""
    if "scale" not in sub:
        return _apply(mod, sub, x)
    from apex_tpu.ops import fp8_matmul as fp8mm
    y = fp8mm.fp8_dequant_matmul(x, sub["kernel"], sub["scale"],
                                 out_dtype=x.dtype, autotune=autotune,
                                 interpret=interpret)
    if row and ps.get_tensor_model_parallel_world_size() > 1:
        y = tp_mappings.reduce_from_tensor_model_parallel_region(
            y, ps.TENSOR_AXIS)
    if "bias" in sub:
        y = y + sub["bias"].astype(y.dtype)
    return y


_FP8_WEIGHT_LINEARS = (("attn", "qkv"), ("attn", "proj"),
                       ("mlp", "fc1"), ("mlp", "fc2"))


def _as_dict(tree):
    """Shallow plain-dict view of a mapping (dict or FrozenDict)."""
    return {k: tree[k] for k in tree}


def quantize_gpt_weights(cfg: GPTConfig, params, *, margin: float = 0.0):
    """Per-tensor e4m3 quantization of every block linear kernel
    (qkv / proj / fc1 / fc2): each ``kernel`` leaf is replaced by its
    fp8 encoding plus a sibling scalar ``scale`` leaf (amax-derived,
    :func:`apex_tpu.ops.fp8_matmul.quantize_weight`). Embeddings,
    positionals, norms and biases stay in their training dtype — they
    are a rounding error of the streamed bytes. Runs ONCE at engine
    build; the returned tree serves through the same shard_map specs
    (shapes unchanged; scales replicate)."""
    from apex_tpu.ops import fp8_matmul as fp8mm
    out = _as_dict(params)
    for i in range(cfg.num_layers):
        blk = _as_dict(out[f"block_{i}"])
        for group, name in _FP8_WEIGHT_LINEARS:
            grp = _as_dict(blk[group])
            lin = _as_dict(grp[name])
            q, scale = fp8mm.quantize_weight(lin["kernel"], margin=margin)
            lin["kernel"] = q
            lin["scale"] = scale
            grp[name] = lin
            blk[group] = grp
        out[f"block_{i}"] = blk
    return out


def weight_stream_bytes(cfg: GPTConfig, params) -> int:
    """HBM bytes of the block linear weights one decode step streams
    (kernels + fp8 scales; biases/norms excluded on both sides so the
    fp8-vs-bf16 ratio measures exactly what quantization changed).
    Host-side ints — the ``monitor.memory`` serve weight accounting and
    the bench's streamed-bytes assertion both come from here."""
    import numpy as np
    total = 0
    for i in range(cfg.num_layers):
        blk = params[f"block_{i}"]
        for group, name in _FP8_WEIGHT_LINEARS:
            lin = blk[group][name]
            kern = lin["kernel"]
            total += kern.size * np.dtype(kern.dtype).itemsize
            if "scale" in lin:
                scale = lin["scale"]
                total += scale.size * np.dtype(scale.dtype).itemsize
    return int(total)


def _split_qkv(cfg: GPTConfig, qkv):
    """[..., 3h/tp] -> q, k, v [..., heads_per, d] (the GPT packing:
    per-head [q|k|v] groups, so the tp column shard is a head split)."""
    tp = ps.get_tensor_model_parallel_world_size()
    heads_per = cfg.num_heads // tp
    d = cfg.hidden_size // cfg.num_heads
    qkv = qkv.reshape(qkv.shape[:-1] + (heads_per, 3 * d))
    return jnp.split(qkv, 3, axis=-1)


def _logits(cfg: GPTConfig, mods, params, x):
    """Vocab-parallel LM head + full-vocab gather (serve samples on the
    host; decode needs the whole row for argmax/top-k)."""
    with _prof.scope("lm_head"):
        emb = params["wte"]
        wte = mods["wte"]
        logits = wte.apply({"params": emb}, x, method=wte.attend)
        if ps.get_tensor_model_parallel_world_size() > 1:
            logits = tp_mappings.gather_from_tensor_model_parallel_region(
                logits, ps.TENSOR_AXIS, -1)
        return logits.astype(jnp.float32)


def _mlp(cfg: GPTConfig, mods, blk, x, lin_kw):
    y = _linear(mods["fc1"], blk["mlp"]["fc1"], x, **lin_kw)
    y = jax.nn.gelu(y.astype(jnp.float32), approximate=True).astype(x.dtype)
    return _linear(mods["fc2"], blk["mlp"]["fc2"], y, row=True, **lin_kw)


def _block_forward(cfg: GPTConfig, mods, blk, x, attend, lin_kw=None):
    """One transformer block — the ONE copy of the serve-side block
    structure (shared by decode, prefill and the no-cache baseline).
    ``attend(q, k, v)`` owns the per-variant cache interaction and
    returns the context in ``x``'s leading shape + ``[..., local_h]``.
    ``lin_kw`` threads the fp8-weight resolution knobs
    (autotune/interpret) into the four block linears.
    """
    lin_kw = lin_kw or {}
    h1 = _apply(mods["ln"], blk["ln1"], x)
    q, k, v = _split_qkv(cfg, _linear(mods["qkv"], blk["attn"]["qkv"], h1,
                                      **lin_kw))
    ctx = attend(q, k, v)
    x = x + _linear(mods["proj"], blk["attn"]["proj"],
                    ctx.astype(cfg.dtype), row=True, **lin_kw)
    h2 = _apply(mods["ln"], blk["ln2"], x)
    return x + _mlp(cfg, mods, blk, h2, lin_kw)


def decode_forward(cfg: GPTConfig, ccfg: cache_mod.CacheConfig, params,
                   state: cache_mod.CacheState, block_tables, positions,
                   tokens, active, *, paged_impl: str = "reference",
                   interpret: Optional[bool] = None,
                   autotune: Optional[str] = None):
    """One decode step over a fixed-capacity batch.

    ``tokens``/``positions``/``active``: [B] (the token being fed, its
    position = index in the sequence, and whether the slot is live —
    inactive slots carry token 0, position 0 and write to the null
    page). ``block_tables``: [B, m] int32. Returns ``(logits [B, V]
    f32, new_state)`` — rows of inactive slots are garbage by contract.
    Every slot's row depends only on its own inputs (no cross-row
    reduction anywhere), which is what makes decode-replay after a
    preemption bit-exact regardless of batch company.
    """
    if paged_impl not in PAGED_IMPLS:
        raise ValueError(f"paged_impl must be one of {PAGED_IMPLS}, got "
                         f"{paged_impl!r}")
    mods = _mods(cfg)
    B = tokens.shape[0]
    lin_kw = dict(autotune=autotune, interpret=interpret)
    with _prof.scope("serve_decode"):
        x = _apply(mods["wte"], params["wte"], tokens)
        x = (x + jnp.take(params["wpe"], positions, axis=0)).astype(cfg.dtype)
        seq_lens = jnp.where(active, positions + 1, 0).astype(jnp.int32)
        page_ids = jnp.where(
            active,
            block_tables[jnp.arange(B), positions // ccfg.page_size],
            0).astype(jnp.int32)
        slots = jnp.where(active, positions % ccfg.page_size,
                          0).astype(jnp.int32)
        # state is threaded through the attend closure: python-level
        # mutation is safe here because the layer loop is sequential
        # trace-time code
        state_box = [state]
        for i in range(cfg.num_layers):
            def attend(q, k, v, *, _i=i):
                state_box[0] = cache_mod.write_token(
                    ccfg, state_box[0], _i, page_ids, slots, k, v)
                st = state_box[0]
                with _prof.scope("paged_attn"):
                    q4 = q[:, :, None, :]            # [B, hp, group=1, d]
                    scales = {}
                    if ccfg.fp8:
                        scales = dict(k_scales=st.k_scale[_i],
                                      v_scales=st.v_scale[_i])
                    if paged_impl == "kernel":
                        ctx = paged_decode_attention(
                            q4, st.k_pool[_i], st.v_pool[_i],
                            block_tables, seq_lens, interpret=interpret,
                            **scales)
                    else:
                        ctx = paged_attention_reference(
                            q4, st.k_pool[_i], st.v_pool[_i],
                            block_tables, seq_lens, **scales)
                return ctx[:, :, 0, :].reshape(B, -1)

            with _prof.scope(f"block_{i}"):
                x = _block_forward(cfg, mods, params[f"block_{i}"], x,
                                   attend, lin_kw)
        x = _apply(mods["ln"], params["ln_f"], x)
        return _logits(cfg, mods, params, x), state_box[0]


def prefill_forward(cfg: GPTConfig, ccfg: cache_mod.CacheConfig, params,
                    state: cache_mod.CacheState, block_table, length,
                    ids, *, attention_impl: str = "reference",
                    interpret: Optional[bool] = None,
                    autotune: Optional[str] = None):
    """Full-prompt pass for ONE sequence (padded to the engine's static
    prompt length). ``ids``: [S] int32 (padded with anything past
    ``length``); ``block_table``: [m] int32 — pages covering positions
    ``0..length-1`` (padded entries unused). Writes every live
    position's K/V and returns ``(logits [V] f32 for position
    length-1, new_state)``.
    """
    if attention_impl not in PREFILL_IMPLS:
        raise ValueError(f"attention_impl must be one of {PREFILL_IMPLS}, "
                         f"got {attention_impl!r}")
    mods = _mods(cfg)
    S = ids.shape[0]
    d = cfg.hidden_size // cfg.num_heads
    lin_kw = dict(autotune=autotune, interpret=interpret)
    with _prof.scope("serve_prefill"):
        x = _apply(mods["wte"], params["wte"], ids[None])
        x = (x + params["wpe"][None, :S]).astype(cfg.dtype)
        sid = jnp.where(jnp.arange(S) < length, 0, -1)[None].astype(jnp.int32)
        state_box = [state]
        for i in range(cfg.num_layers):
            def attend(q, k, v, *, _i=i):
                state_box[0] = cache_mod.write_prompt(
                    ccfg, state_box[0], _i, block_table, length, k[0],
                    v[0])
                ctx = _causal_attend(q, k, v, d, sid, attention_impl,
                                     interpret, "prefill_attn")
                return ctx.reshape(1, S, -1)

            with _prof.scope(f"block_{i}"):
                x = _block_forward(cfg, mods, params[f"block_{i}"], x,
                                   attend, lin_kw)
        x = _apply(mods["ln"], params["ln_f"], x)
        x_last = jnp.take(x[0], length - 1, axis=0)
        return _logits(cfg, mods, params, x_last), state_box[0]


def _causal_attend(q, k, v, d, sid, attention_impl, interpret, scope):
    """Full causal attention over padded [b, S] token batches with
    padding segment ids — the shared attention of prefill and the
    no-cache baseline. Returns [b, S, hp, d]-shaped context."""
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    with _prof.scope(scope):
        if attention_impl == "flash":
            ctx = flash_attention(qh, kh, vh, causal=True, scale=d ** -0.5,
                                  segment_ids_q=sid, interpret=interpret)
        else:
            ctx = mha_reference(qh, kh, vh, causal=True, scale=d ** -0.5,
                                segment_ids_q=sid)
    return ctx.transpose(0, 2, 1, 3)


def full_forward_logits(cfg: GPTConfig, params, ids, lengths, *,
                        attention_impl: str = "reference"):
    """The NO-cache baseline forward: full causal attention over the
    whole padded context, logits at each row's last live position.
    ``ids``: [B, S] int32, ``lengths``: [B] int32. One fixed-shape
    program regardless of how far generation has progressed — this is
    what "naive full-recompute decode" pays per token, and what the
    ``serve_decode`` bench section measures the paged cache against.
    """
    if attention_impl not in PREFILL_IMPLS:
        raise ValueError(f"attention_impl must be one of {PREFILL_IMPLS}, "
                         f"got {attention_impl!r}")
    mods = _mods(cfg)
    B, S = ids.shape
    d = cfg.hidden_size // cfg.num_heads
    x = _apply(mods["wte"], params["wte"], ids)
    x = (x + params["wpe"][None, :S]).astype(cfg.dtype)
    sid = jnp.where(jnp.arange(S)[None, :] < lengths[:, None], 0,
                    -1).astype(jnp.int32)
    for i in range(cfg.num_layers):
        def attend(q, k, v):
            return _causal_attend(q, k, v, d, sid, attention_impl, None,
                                  "full_attn").reshape(B, S, -1)

        x = _block_forward(cfg, mods, params[f"block_{i}"], x, attend)
    x = _apply(mods["ln"], params["ln_f"], x)
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None],
                                 axis=1)[:, 0]
    return _logits(cfg, mods, params, x_last)
