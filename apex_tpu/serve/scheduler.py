"""Continuous-batching scheduler: admit / evict / preempt at step
granularity, with capacity accounted in pool pages.

Pure host-side state machine — no jax imports, no device work — so the
policy is unit-testable without a model and the engine's jitted steps
stay pure. The policy is the vLLM recompute-preemption shape:

- **FCFS admission**: waiting sequences admit in arrival order, when a
  batch slot is free AND the allocator can cover the sequence's current
  tokens plus the next decode write. Head-of-line blocking is
  deliberate (no starvation).
- **On-demand growth**: a running sequence takes one page exactly when
  its next decode position crosses a page boundary.
- **Evict-on-exhaustion**: when growth cannot be served, the LATEST-
  arrived running sequence is preempted — its pages are freed and the
  sequence returns to the head of the waiting queue *keeping its
  generated tokens*. Re-admission recomputes the cache (prefill of the
  prompt + decode-replay of the generated tokens through the SAME
  compiled programs), which is why preempt/resume is bit-exact — see
  ``docs/serve.md``.

Page 0 of the pool is the null page and is never allocated (the
``cache`` module's masked-write convention).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"


@dataclasses.dataclass
class Sequence:
    """One request's full lifecycle state."""

    seq_id: int
    prompt: List[int]
    max_new_tokens: int
    arrival: int = 0
    state: str = WAITING
    tokens: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None         # engine batch slot while RUNNING
    num_cached: int = 0                # positions with K/V in the pool
    n_preemptions: int = 0

    def __post_init__(self):
        if not self.prompt:
            raise ValueError("empty prompt")
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def num_tokens(self) -> int:
        return len(self.tokens)

    @property
    def num_generated(self) -> int:
        return len(self.tokens) - len(self.prompt)

    @property
    def done(self) -> bool:
        return self.num_generated >= self.max_new_tokens


class PageAllocator:
    """Free-list over pages ``1..num_pages-1`` (0 is the null page)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))  # pop() -> low ids

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not (0 < p < self.num_pages):
                raise ValueError(f"freeing invalid page {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)


@dataclasses.dataclass
class StepPlan:
    """What the engine should run this step: prefills first (each is a
    full-prompt pass + any decode-replay of generated tokens), then one
    batched decode over every running sequence."""

    prefill: List[Sequence] = dataclasses.field(default_factory=list)
    decode: List[Sequence] = dataclasses.field(default_factory=list)
    preempted: List[Sequence] = dataclasses.field(default_factory=list)


class Scheduler:
    def __init__(self, *, num_pages: int, page_size: int, max_batch: int):
        self.allocator = PageAllocator(num_pages)
        self.page_size = page_size
        self.max_batch = max_batch
        self.waiting: List[Sequence] = []
        self.running: List[Sequence] = []
        self._arrival = 0

    # -- bookkeeping -------------------------------------------------

    def add(self, seq: Sequence) -> None:
        seq.arrival = self._arrival
        self._arrival += 1
        seq.state = WAITING
        self.waiting.append(seq)

    def finish(self, seq: Sequence) -> None:
        seq.state = FINISHED
        self.running.remove(seq)
        self.allocator.free(seq.pages)
        seq.pages = []
        seq.slot = None
        seq.num_cached = 0

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def _pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def _preempt(self, seq: Sequence) -> None:
        seq.state = WAITING
        seq.n_preemptions += 1
        self.running.remove(seq)
        self.allocator.free(seq.pages)
        seq.pages = []
        seq.slot = None
        seq.num_cached = 0
        # back of the ARRIVAL order, front of readmission among later
        # arrivals: waiting stays sorted by arrival
        self.waiting.append(seq)
        self.waiting.sort(key=lambda s: s.arrival)

    # -- the per-step policy -----------------------------------------

    def schedule(self) -> StepPlan:
        plan = StepPlan()

        # 1. growth: every running sequence must hold pages for its
        # next decode write (position num_tokens-1). Earliest arrivals
        # are served first; exhaustion preempts the LATEST-arrived
        # running sequence — possibly the grower itself, when it is the
        # latest.
        for seq in sorted(self.running, key=lambda s: s.arrival):
            if seq.state != RUNNING:
                continue                    # preempted earlier this pass
            grown = True
            while self._pages_needed(seq.num_tokens) > len(seq.pages):
                need = self._pages_needed(seq.num_tokens) - len(seq.pages)
                got = self.allocator.alloc(need)
                if got is not None:
                    seq.pages.extend(got)
                    break
                victim = max(self.running, key=lambda s: s.arrival)
                self._preempt(victim)
                plan.preempted.append(victim)
                if victim is seq:
                    grown = False
                    break
            if grown and seq.state == RUNNING:
                plan.decode.append(seq)

        # 2. FCFS admission into free slots/pages. A resumed sequence
        # needs pages for ALL its tokens (prompt + generated: the
        # recompute) plus the next write.
        while self.waiting and len(self.running) < self.max_batch:
            seq = self.waiting[0]
            need = self._pages_needed(seq.num_tokens + 1)
            if need > self.allocator.num_pages - 1:
                raise RuntimeError(
                    f"sequence {seq.seq_id} needs {need} pages; the pool "
                    f"has {self.allocator.num_pages - 1} usable — it can "
                    f"never be admitted (grow num_pages or page_size)")
            got = self.allocator.alloc(need)
            if got is None:
                break                       # head-of-line: no skip-ahead
            self.waiting.pop(0)
            seq.pages = got
            seq.state = RUNNING
            self.running.append(seq)
            plan.prefill.append(seq)
        return plan
