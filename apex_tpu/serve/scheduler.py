"""Continuous-batching scheduler: admit / evict / preempt at step
granularity, with capacity accounted in pool pages.

Pure host-side state machine — no jax imports, no device work — so the
policy is unit-testable without a model and the engine's jitted steps
stay pure. The policy is the vLLM recompute-preemption shape:

- **FCFS admission**: waiting sequences admit in arrival order, when a
  batch slot is free AND the allocator can cover the sequence's current
  tokens plus the next decode write. Head-of-line blocking is
  deliberate (no starvation).
- **On-demand growth**: a running sequence takes one page exactly when
  its next decode position crosses a page boundary.
- **Evict-on-exhaustion**: when growth cannot be served, the LATEST-
  arrived running sequence is preempted — its pages are freed and the
  sequence returns to the head of the waiting queue *keeping its
  generated tokens*. Re-admission recomputes the cache (prefill of the
  prompt + decode-replay of the generated tokens through the SAME
  compiled programs), which is why preempt/resume is bit-exact — see
  ``docs/serve.md``.

Page 0 of the pool is the null page and is never allocated (the
``cache`` module's masked-write convention).

Telemetry: every scheduling transition is traced through
:mod:`apex_tpu.monitor.spans` and the host hooks — a ``serve/queue_wait``
span opens when a sequence enters (or re-enters, after preemption) the
waiting queue and closes at admission, preemptions emit a
``serve/preempt`` annotation + counter, and the measured queue wait
feeds the ``serve/queue_wait_ms`` streaming histogram. All of it is
host-clock-only and detached-free (``apex_tpu.monitor`` is zero-dep —
this module still imports no jax, and with no recorder attached every
hook is one global read).
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

from apex_tpu.monitor import hooks as _mhooks
from apex_tpu.monitor import spans as _mspans

WAITING = "waiting"
RUNNING = "running"
FINISHED = "finished"


@dataclasses.dataclass
class Sequence:
    """One request's full lifecycle state."""

    seq_id: int
    prompt: List[int]
    max_new_tokens: int
    arrival: int = 0
    state: str = WAITING
    tokens: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None         # engine batch slot while RUNNING
    num_cached: int = 0                # positions with K/V in the pool
    draft_cached: int = 0              # positions in the DRAFT pool
    n_preemptions: int = 0
    # -- telemetry (host-only; None/0 when monitoring is detached) ----
    span: Optional[int] = None         # serve/request span id
    queue_span: Optional[int] = None   # open serve/queue_wait span id
    arrival_t: float = 0.0             # perf_counter at first add()
    queued_t: float = 0.0              # perf_counter at last (re)queue
    queue_wait_s: float = 0.0          # total time spent WAITING
    ttft_ms: Optional[float] = None    # arrival -> first generated token

    def __post_init__(self):
        if not self.prompt:
            raise ValueError("empty prompt")
        if not self.tokens:
            self.tokens = list(self.prompt)

    @property
    def num_tokens(self) -> int:
        return len(self.tokens)

    @property
    def num_generated(self) -> int:
        return len(self.tokens) - len(self.prompt)

    @property
    def done(self) -> bool:
        return self.num_generated >= self.max_new_tokens


class PageAllocator:
    """Free-list over pages ``1..num_pages-1`` (0 is the null page)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))  # pop() -> low ids

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not (0 < p < self.num_pages):
                raise ValueError(f"freeing invalid page {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(pages)


@dataclasses.dataclass
class StepPlan:
    """What the engine should run this step: prefills first (each is a
    full-prompt pass + any decode-replay of generated tokens), then one
    batched decode over every running sequence."""

    prefill: List[Sequence] = dataclasses.field(default_factory=list)
    decode: List[Sequence] = dataclasses.field(default_factory=list)
    preempted: List[Sequence] = dataclasses.field(default_factory=list)


class Scheduler:
    def __init__(self, *, num_pages: int, page_size: int, max_batch: int,
                 lookahead: int = 0):
        self.allocator = PageAllocator(num_pages)
        self.page_size = page_size
        self.max_batch = max_batch
        # speculative decoding writes up to ``lookahead`` positions past
        # the next decode position in one round (the verify window), so
        # growth/admission must cover them up front — a preemption
        # mid-window would otherwise strand a half-written round
        self.lookahead = int(lookahead)
        self.waiting: List[Sequence] = []
        self.running: List[Sequence] = []
        self._arrival = 0

    # -- bookkeeping -------------------------------------------------

    def add(self, seq: Sequence) -> None:
        seq.arrival = self._arrival
        self._arrival += 1
        seq.state = WAITING
        now = time.perf_counter()
        seq.arrival_t = seq.arrival_t or now
        seq.queued_t = now
        seq.queue_span = _mspans.start(
            "serve/queue_wait", parent=seq.span, seq_id=seq.seq_id)
        _mhooks.counter("serve/requests_queued")
        self.waiting.append(seq)

    def finish(self, seq: Sequence) -> None:
        seq.state = FINISHED
        self.running.remove(seq)
        self.allocator.free(seq.pages)
        seq.pages = []
        seq.slot = None
        seq.num_cached = 0
        seq.draft_cached = 0
        _mhooks.counter("serve/requests_finished")

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def _pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def _preempt(self, seq: Sequence) -> None:
        seq.state = WAITING
        seq.n_preemptions += 1
        freed = len(seq.pages)
        self.running.remove(seq)
        self.allocator.free(seq.pages)
        seq.pages = []
        seq.slot = None
        seq.num_cached = 0
        # the draft pool reuses the target's page ids, so eviction
        # invalidates the draft cache too — re-admission re-ingests
        seq.draft_cached = 0
        # evict/re-queue transition on the request trace: annotation on
        # the request span + a fresh queue-wait span (re-admission will
        # close it and add the second wait to the request's total)
        _mhooks.counter("serve/preemptions")
        _mspans.annotate("serve/preempt", span=seq.span,
                         seq_id=seq.seq_id,
                         n_preemptions=seq.n_preemptions,
                         freed_pages=freed,
                         tokens_kept=seq.num_tokens)
        seq.queued_t = time.perf_counter()
        seq.queue_span = _mspans.start(
            "serve/queue_wait", parent=seq.span, seq_id=seq.seq_id,
            resumed=True)
        # back of the ARRIVAL order, front of readmission among later
        # arrivals: waiting stays sorted by arrival
        self.waiting.append(seq)
        self.waiting.sort(key=lambda s: s.arrival)

    # -- the per-step policy -----------------------------------------

    def schedule(self) -> StepPlan:
        plan = StepPlan()

        # 1. growth: every running sequence must hold pages for its
        # next decode write (position num_tokens-1) plus the
        # speculative lookahead window. Earliest arrivals
        # are served first; exhaustion preempts the LATEST-arrived
        # running sequence — possibly the grower itself, when it is the
        # latest.
        for seq in sorted(self.running, key=lambda s: s.arrival):
            if seq.state != RUNNING:
                continue                    # preempted earlier this pass
            grown = True
            want = self._pages_needed(seq.num_tokens + self.lookahead)
            while want > len(seq.pages):
                need = want - len(seq.pages)
                got = self.allocator.alloc(need)
                if got is not None:
                    seq.pages.extend(got)
                    break
                victim = max(self.running, key=lambda s: s.arrival)
                self._preempt(victim)
                plan.preempted.append(victim)
                if victim is seq:
                    grown = False
                    break
            if grown and seq.state == RUNNING:
                plan.decode.append(seq)

        # 2. FCFS admission into free slots/pages. A resumed sequence
        # needs pages for ALL its tokens (prompt + generated: the
        # recompute) plus the next write.
        while self.waiting and len(self.running) < self.max_batch:
            seq = self.waiting[0]
            need = self._pages_needed(seq.num_tokens + 1 + self.lookahead)
            if need > self.allocator.num_pages - 1:
                raise RuntimeError(
                    f"sequence {seq.seq_id} needs {need} pages; the pool "
                    f"has {self.allocator.num_pages - 1} usable — it can "
                    f"never be admitted (grow num_pages or page_size)")
            got = self.allocator.alloc(need)
            if got is None:
                break                       # head-of-line: no skip-ahead
            self.waiting.pop(0)
            seq.pages = got
            seq.state = RUNNING
            # admission closes the open queue-wait span; the measured
            # wait (wall clock, span or not) feeds the streaming
            # histogram and the request's running total
            wait_s = time.perf_counter() - seq.queued_t \
                if seq.queued_t else 0.0
            seq.queue_wait_s += wait_s
            _mspans.end(seq.queue_span, seq_id=seq.seq_id)
            seq.queue_span = None
            _mhooks.observe("serve/queue_wait_ms", 1e3 * wait_s)
            _mhooks.counter("serve/admissions")
            self.running.append(seq)
            plan.prefill.append(seq)
        return plan
