"""The serve engine: jitted prefill/decode steps with a donated cache,
driven by the continuous-batching scheduler.

Shape discipline — the engine compiles at most THREE programs and
reuses them for the whole serving lifetime (replay after a preemption
goes through the same decode program; that reuse IS the bit-exactness
argument below):

- the **prefill step** runs one sequence at the static padded prompt
  length (``max_prompt_len``);
- the **decode step** runs the full fixed-capacity batch
  (``max_batch`` slots, inactive slots masked to the null page). With
  ``spec_k > 0`` the SAME compiled decode program doubles as the
  speculative **verifier**: rows ``0..k`` carry ``k+1`` consecutive
  positions of ONE sequence (the last committed token plus the draft
  tokens) — legal because every row's K/V writes land before any row
  attends and per-row ``seq_lens`` mask causality;
- the **draft-decode step** (``spec_k > 0`` only) is the decode
  program compiled for the depth-truncated draft model over its own
  page pool (:mod:`apex_tpu.serve.spec`).

Fixed shapes are not just a compile-cache nicety: because no operation
in the forward mixes batch rows, a slot's row is a function of that
slot's inputs alone, independent of batch company — so replaying a
preempted sequence's generated tokens through the SAME decode program
reproduces its cache and logits BIT-exactly (asserted in
``tests/test_serve.py``), and speculative greedy output is
token-identical to plain paged decode (``tests/test_serve_spec.py``).
The cache pytrees are donated through all steps: the pools update in
place, never 2x resident.

fp8 weight-streaming (``fp8_weights=True``): the block linear kernels
quantize ONCE at engine build to e4m3 with per-tensor scales
(:func:`apex_tpu.serve.model.quantize_gpt_weights`), cutting the
weight bytes every decode step streams ~2x vs bf16; the forward reads
them through the fused dequant-matmul (``ops.fp8_matmul``). Orthogonal
to and composable with speculative decoding.

Tensor parallelism: with a model-parallel mesh installed
(``parallel_state.initialize_model_parallel(tp)``), both steps wrap in
``shard_map`` with layouts from :mod:`apex_tpu.serve.rules` — the FULL
(tp=1-layout) param tree and cache are split by the in_specs, the TP
layers run their training collectives, and logits/next-token outputs
come back replicated. The host-side scheduler is unchanged.

Telemetry (``docs/serve.md`` / ``docs/observability.md``): with a
recorder attached, every request gets a span trace — queue-wait →
prefill (→ decode-replay on resume) → per-token decode — through
:mod:`apex_tpu.monitor.spans`, token latency / TTFT / queue wait feed
O(1)-memory streaming histograms, and each scheduler round records
pool-occupancy + queue-depth gauges inside a per-step record (so the
:class:`~apex_tpu.monitor.health.Watchdog`'s serve detectors observe
them online). All host-clock, zero jax in the hot path: the compiled
decode/prefill programs are byte-identical spans-on vs spans-off
(asserted in ``tests/test_serve_telemetry.py``), and detached mode
costs one global read per hook.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu._compat import shard_map
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.monitor import _state as _monitor_state
from apex_tpu.monitor import flight as _mflight
from apex_tpu.monitor import hooks as _mhooks
from apex_tpu.monitor import spans as _mspans
from apex_tpu.serve import cache as cache_mod
from apex_tpu.serve import model as model_mod
from apex_tpu.serve import rules as rules_mod
from apex_tpu.serve import spec as spec_mod
from apex_tpu.serve.scheduler import RUNNING, Scheduler, Sequence
from apex_tpu.transformer import parallel_state as ps


def _default_impls():
    on_tpu = jax.default_backend() == "tpu"
    return (("kernel" if on_tpu else "reference"),
            ("flash" if on_tpu else "reference"))


# auto-assigned replica identities ("replica0", "replica1", ...) for
# engines constructed without an explicit replica_id
_REPLICA_SEQ = 0
_REPLICA_SEQ_LOCK = threading.Lock()


class ServeEngine:
    """Paged-KV-cache GPT serving on one host (optionally TP-sharded).

    ``params`` is the full (tp=1 layout) ``models.gpt.GPT`` parameter
    tree (``variables["params"]``). Sampling is greedy argmax —
    deterministic by design, which the preempt/resume bit-exactness
    contract relies on.
    """

    def __init__(self, cfg: GPTConfig, params, *, num_pages: int,
                 max_seq_len: int, max_prompt_len: int,
                 page_size: Optional[int] = None, max_batch: int = 4,
                 fp8_kv: bool = False, fp8_margin: float = 2.0,
                 paged_impl: Optional[str] = None,
                 attention_impl: Optional[str] = None,
                 autotune: Optional[str] = None,
                 record_logits: bool = False,
                 interpret: Optional[bool] = None,
                 replica_id: Optional[str] = None,
                 spec_k: int = 0,
                 draft_num_layers: Optional[int] = None,
                 draft_cfg: Optional[GPTConfig] = None,
                 draft_params=None,
                 fp8_weights: bool = False,
                 fp8_weight_margin: float = 0.0):
        d_impl, p_impl = _default_impls()
        self.cfg = cfg
        self.fp8_weights = bool(fp8_weights)
        if fp8_weights:
            # one-time e4m3 encode of the block linear kernels: same
            # tree shape (+ scalar scale leaves), so the TP rules and
            # shard_map specs below apply unchanged
            params = model_mod.quantize_gpt_weights(
                cfg, params, margin=fp8_weight_margin)
        self.params = params
        # stable replica identity for fleet telemetry: labels every
        # exported sample (monitor.export) and keys this engine in a
        # monitor.fleet.ReplicaSet. Host-side only — never reaches a
        # compiled program.
        if replica_id is None:
            with _REPLICA_SEQ_LOCK:
                global _REPLICA_SEQ
                replica_id = f"replica{_REPLICA_SEQ}"
                _REPLICA_SEQ += 1
        self.replica_id = str(replica_id)
        self.export_port: Optional[int] = None
        self.paged_impl = paged_impl or d_impl
        self.attention_impl = attention_impl or p_impl
        self.interpret = interpret
        self.autotune = autotune
        self.tp = ps.get_tensor_model_parallel_world_size()
        if cfg.num_heads % self.tp:
            raise ValueError(f"num_heads {cfg.num_heads} not divisible "
                             f"by tp {self.tp}")
        head_dim = cfg.hidden_size // cfg.num_heads
        # the pool is allocated at GLOBAL head count — under tp the
        # shard_map in_specs split the heads dim, each rank holding its
        # local heads' pages; page-size resolution sees the PER-RANK
        # kernel geometry
        psize = cache_mod.resolve_page_size(
            kv_heads=cfg.num_heads // self.tp, head_dim=head_dim,
            context_len=max_seq_len, dtype=cfg.dtype, fp8=fp8_kv,
            batch=max_batch, page_size=page_size, autotune=autotune)
        if max_seq_len > cfg.max_seq_len:
            raise ValueError(f"max_seq_len {max_seq_len} exceeds the "
                             f"model's {cfg.max_seq_len}")
        if max_prompt_len > max_seq_len:
            raise ValueError("max_prompt_len exceeds max_seq_len")
        self.max_seq_len = max_seq_len
        self.max_prompt_len = max_prompt_len
        self.pages_per_seq = -(-max_seq_len // psize)
        self.ccfg = cache_mod.CacheConfig(
            num_layers=cfg.num_layers, kv_heads=cfg.num_heads,
            head_dim=head_dim, num_pages=num_pages, page_size=psize,
            dtype=cfg.dtype, fp8=fp8_kv, fp8_margin=fp8_margin)
        self.state = cache_mod.init_cache(self.ccfg)
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if self.spec_k:
            if self.spec_k + 1 > max_batch:
                raise ValueError(
                    f"spec_k={spec_k} needs max_batch >= {spec_k + 1} "
                    f"(the verify window rides the decode batch rows), "
                    f"got max_batch={max_batch}")
            if fp8_kv:
                # the fp8-KV slot-0 scale rule is sequential: a verify
                # window crossing a page boundary would scatter the old
                # and the fresh page scale to the SAME pool index in
                # one call (undefined order) and quantize later rows
                # with the stale scale — bit-parity with plain decode
                # would silently break
                raise ValueError("spec_k > 0 does not compose with "
                                 "fp8_kv (per-page slot-0 scales need "
                                 "sequential writes)")
            if draft_params is None:
                layers = draft_num_layers or max(1, cfg.num_layers // 2)
                self.draft_cfg, self.draft_params = spec_mod.derive_draft(
                    cfg, self.params, num_layers=layers)
            else:
                if draft_cfg is None:
                    raise ValueError("draft_params requires draft_cfg")
                self.draft_cfg = draft_cfg
                self.draft_params = (
                    model_mod.quantize_gpt_weights(
                        draft_cfg, draft_params, margin=fp8_weight_margin)
                    if fp8_weights else draft_params)
            if self.draft_cfg.num_heads % self.tp:
                raise ValueError(f"draft num_heads "
                                 f"{self.draft_cfg.num_heads} not "
                                 f"divisible by tp {self.tp}")
            # the draft pool mirrors the target pool's geometry
            # (num_pages, page_size) so the draft REUSES each
            # sequence's block table — zero new allocator state
            self.draft_ccfg = cache_mod.CacheConfig(
                num_layers=self.draft_cfg.num_layers,
                kv_heads=self.draft_cfg.num_heads,
                head_dim=(self.draft_cfg.hidden_size
                          // self.draft_cfg.num_heads),
                num_pages=num_pages, page_size=psize,
                dtype=self.draft_cfg.dtype)
            self.draft_state = cache_mod.init_cache(self.draft_ccfg)
        self.sched = Scheduler(num_pages=num_pages, page_size=psize,
                               max_batch=max_batch,
                               lookahead=self.spec_k)
        self.max_batch = max_batch
        self.slots: List[Optional[Sequence]] = [None] * max_batch
        self.record_logits = record_logits
        self.logits_log: Dict[int, Dict[int, np.ndarray]] = {}
        self.decode_step_times: List[float] = []
        self.tokens_generated = 0
        self._next_id = 0
        self.seqs: Dict[int, Sequence] = {}    # every request ever added
        self._build_steps()

    # -- jitted steps ------------------------------------------------

    def _build_steps(self):
        cfg, ccfg = self.cfg, self.ccfg

        def decode(params, state, bt, pos, tok, act):
            logits, state = model_mod.decode_forward(
                cfg, ccfg, params, state, bt, pos, tok, act,
                paged_impl=self.paged_impl, interpret=self.interpret,
                autotune=self.autotune)
            return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                state

        def prefill(params, state, bt, length, ids):
            logits, state = model_mod.prefill_forward(
                cfg, ccfg, params, state, bt, length, ids,
                attention_impl=self.attention_impl,
                interpret=self.interpret, autotune=self.autotune)
            return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                state

        draft = None
        if self.spec_k:
            dcfg, dccfg = self.draft_cfg, self.draft_ccfg

            def draft(params, state, bt, pos, tok, act):
                # greedy draft: only the argmaxes leave the program
                logits, state = model_mod.decode_forward(
                    dcfg, dccfg, params, state, bt, pos, tok, act,
                    paged_impl=self.paged_impl, interpret=self.interpret,
                    autotune=self.autotune)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), state

        if self.tp > 1:
            mesh = ps.get_mesh()
            from jax.sharding import PartitionSpec as P
            pspec = rules_mod.match_serve_rules(
                rules_mod.GPT_PARAM_RULES, self.params, world=self.tp)
            cspec = rules_mod.match_serve_rules(
                rules_mod.CACHE_RULES, self.state, world=self.tp)
            decode = shard_map(
                decode, mesh=mesh,
                in_specs=(pspec, cspec, P(), P(), P(), P()),
                out_specs=(P(), P(), cspec), check_vma=False)
            prefill = shard_map(
                prefill, mesh=mesh,
                in_specs=(pspec, cspec, P(), P(), P()),
                out_specs=(P(), P(), cspec), check_vma=False)
            if draft is not None:
                dpspec = rules_mod.match_serve_rules(
                    rules_mod.GPT_PARAM_RULES, self.draft_params,
                    world=self.tp)
                dcspec = rules_mod.match_serve_rules(
                    rules_mod.CACHE_RULES, self.draft_state,
                    world=self.tp)
                draft = shard_map(
                    draft, mesh=mesh,
                    in_specs=(dpspec, dcspec, P(), P(), P(), P()),
                    out_specs=(P(), dcspec), check_vma=False)
        # the cache pytree (arg 1) is donated: the pool mutates in
        # place across steps, never two copies resident (APX007's
        # convention for state threaded through a hot loop)
        self._decode = jax.jit(decode, donate_argnums=(1,))
        self._prefill = jax.jit(prefill, donate_argnums=(1,))
        self._draft_decode = (jax.jit(draft, donate_argnums=(1,))
                              if draft is not None else None)

    # -- request intake ----------------------------------------------

    def add_request(self, prompt: List[int], max_new_tokens: int) -> int:
        if len(prompt) > self.max_prompt_len:
            raise ValueError(f"prompt length {len(prompt)} exceeds "
                             f"max_prompt_len {self.max_prompt_len}")
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        seq = Sequence(seq_id=self._next_id, prompt=list(prompt),
                       max_new_tokens=max_new_tokens)
        self._next_id += 1
        self.seqs[seq.seq_id] = seq
        # the request ROOT span: opened before the scheduler sees the
        # sequence so the initial queue-wait span parents under it;
        # closed when the last token samples (or never, if the caller
        # abandons the engine — spans are host state, nothing leaks
        # into compiled programs)
        seq.span = _mspans.start("serve/request", seq_id=seq.seq_id,
                                 prompt_tokens=len(seq.prompt),
                                 max_new_tokens=max_new_tokens)
        self.sched.add(seq)
        return seq.seq_id

    # -- host-side step driving --------------------------------------

    def _bt_row(self, seq: Sequence) -> np.ndarray:
        row = np.zeros((self.pages_per_seq,), np.int32)
        row[:len(seq.pages)] = seq.pages
        return row

    def _record(self, seq: Sequence, pos: int, logits_row) -> None:
        if self.record_logits:
            self.logits_log.setdefault(seq.seq_id, {})[pos] = \
                np.asarray(logits_row)

    def _free_slot(self, seq: Sequence) -> None:
        for i, s in enumerate(self.slots):
            if s is seq:
                self.slots[i] = None

    def _sample(self, seq: Sequence, token: int) -> None:
        seq.tokens.append(int(token))
        self.tokens_generated += 1
        _mhooks.counter("serve/tokens_generated")
        if seq.num_generated == 1 and seq.ttft_ms is None \
                and seq.arrival_t:
            # time-to-first-token, measured ONCE per request (a resumed
            # sequence replays deterministically — its first token
            # already happened)
            seq.ttft_ms = 1e3 * (time.perf_counter() - seq.arrival_t)
            _mhooks.observe("serve/ttft_ms", seq.ttft_ms)
        if seq.done:
            self.sched.finish(seq)
            self._free_slot(seq)
            _mspans.end(seq.span, seq_id=seq.seq_id,
                        prompt_tokens=len(seq.prompt),
                        new_tokens=seq.num_generated,
                        preemptions=seq.n_preemptions,
                        ttft_ms=round(seq.ttft_ms, 3)
                        if seq.ttft_ms is not None else None,
                        queue_wait_ms=round(1e3 * seq.queue_wait_s, 3))
            seq.span = None

    def _replay_generated(self, seq: Sequence) -> None:
        """Recompute the cache for a resumed sequence's generated
        tokens through the decode program (single-slot-active batches):
        the same compiled rows as the original steps, hence bit-exact.
        The last token is NOT replayed — it is the next decode's
        input."""
        slot = self.slots.index(seq)
        for j in range(len(seq.prompt), seq.num_tokens - 1):
            tok = np.zeros((self.max_batch,), np.int32)
            pos = np.zeros((self.max_batch,), np.int32)
            act = np.zeros((self.max_batch,), bool)
            bts = np.zeros((self.max_batch, self.pages_per_seq), np.int32)
            tok[slot] = seq.tokens[j]
            pos[slot] = j
            act[slot] = True
            bts[slot] = self._bt_row(seq)
            logits, _, self.state = self._decode(
                self.params, self.state, jnp.asarray(bts),
                jnp.asarray(pos), jnp.asarray(tok), jnp.asarray(act))
            self._record(seq, j + 1, logits[slot])
            seq.num_cached = j + 1

    # -- speculative decoding ----------------------------------------

    def _blank_batch(self):
        return (np.zeros((self.max_batch,), np.int32),
                np.zeros((self.max_batch,), np.int32),
                np.zeros((self.max_batch,), bool),
                np.zeros((self.max_batch, self.pages_per_seq), np.int32))

    def _draft_propose(self, seq: Sequence, bt: np.ndarray,
                       k: int) -> List[int]:
        """Draft ``k`` tokens for one sequence. First ingests the
        not-yet-drafted committed positions ``draft_cached..n-1``
        through the draft-decode program — up to ``max_batch``
        CONSECUTIVE POSITIONS of this one sequence per call (legal for
        the same reason verify is: writes land before reads, per-row
        ``seq_lens`` mask causality) — which both rebuilds the draft
        cache over any rejected-round garbage and, via the last live
        row (the feed of ``tokens[n-1]``), yields the first proposal.
        Then ``k-1`` single-row calls extend speculatively."""
        n = seq.num_tokens
        d1 = None
        for lo in range(seq.draft_cached, n, self.max_batch):
            hi = min(lo + self.max_batch, n)
            tok, pos, act, bts = self._blank_batch()
            cnt = hi - lo
            tok[:cnt] = seq.tokens[lo:hi]
            pos[:cnt] = np.arange(lo, hi, dtype=np.int32)
            act[:cnt] = True
            bts[:cnt] = bt
            nxt, self.draft_state = self._draft_decode(
                self.draft_params, self.draft_state, jnp.asarray(bts),
                jnp.asarray(pos), jnp.asarray(tok), jnp.asarray(act))
            if hi == n:
                d1 = int(np.asarray(nxt)[cnt - 1])
        seq.draft_cached = n
        draft = [d1]
        for j in range(1, k):
            tok, pos, act, bts = self._blank_batch()
            tok[0] = draft[-1]
            pos[0] = n - 1 + j
            act[0] = True
            bts[0] = bt
            nxt, self.draft_state = self._draft_decode(
                self.draft_params, self.draft_state, jnp.asarray(bts),
                jnp.asarray(pos), jnp.asarray(tok), jnp.asarray(act))
            draft.append(int(np.asarray(nxt)[0]))
        return draft

    def _spec_round(self, seq: Sequence) -> None:
        """One speculative round for one sequence: draft ``k`` tokens,
        verify all ``k+1`` positions in ONE call of the compiled decode
        program (rows 0..k = positions ``n-1..n-1+k``; row 0 feeds the
        last committed token, rows 1..k the draft), then commit the
        longest accepted prefix + the verifier's bonus token
        (:func:`apex_tpu.serve.spec.accept_greedy`) — at least one
        token per round, token-identical to plain greedy decode.
        Rejected-suffix K/V in both pools is overwritten by the next
        round's window before any row can attend to it (rows only read
        positions <= their own)."""
        n = seq.num_tokens
        remaining = seq.max_new_tokens - seq.num_generated
        k = min(self.spec_k, remaining - 1)
        bt = self._bt_row(seq)
        draft: List[int] = []
        if k > 0:
            with _mspans.span("serve/draft", parent=seq.span,
                              seq_id=seq.seq_id, k=k):
                draft = self._draft_propose(seq, bt, k)
        tok, pos, act, bts = self._blank_batch()
        tok[0] = seq.tokens[-1]
        if k > 0:
            tok[1:k + 1] = draft
        pos[:k + 1] = (n - 1) + np.arange(k + 1, dtype=np.int32)
        act[:k + 1] = True
        bts[:k + 1] = bt
        t0 = time.perf_counter()
        with _mspans.span("serve/verify", parent=seq.span,
                          seq_id=seq.seq_id, rows=k + 1):
            logits, next_toks, self.state = self._decode(
                self.params, self.state, jnp.asarray(bts),
                jnp.asarray(pos), jnp.asarray(tok), jnp.asarray(act))
            next_np = np.asarray(next_toks)
        logits_np = np.asarray(logits) if self.record_logits else None
        dt = time.perf_counter() - t0
        self.decode_step_times.append(dt)
        committed, m = spec_mod.accept_greedy(
            draft, [int(t) for t in next_np[:k + 1]])
        # the target cache is now valid through position n-1+m (the
        # committed window rows); the draft cache through n-1+min(m,
        # k-1) — position n-1+j holds d_j's K/V, and d_k was never fed
        seq.num_cached = n + m
        if k > 0:
            seq.draft_cached = n + min(m, k - 1)
        _mhooks.counter("serve/spec_rounds")
        if k > 0:
            _mhooks.counter("serve/spec_draft_tokens", k)
            _mhooks.counter("serve/spec_accepted_tokens", m)
            _mhooks.observe("serve/spec_accept_rate", m / k)
        for i, t in enumerate(committed):
            if logits_np is not None:
                self._record(seq, n + i, logits_np[i])
            self._sample(seq, t)
        if _mhooks.enabled():
            per_tok = 1e3 * dt / len(committed)
            for _ in committed:
                _mhooks.observe("serve/token_latency_ms", per_tok)
            _mhooks.gauge("serve/batch_fill",
                          (k + 1) / self.max_batch)

    def _do_prefill(self, seq: Sequence) -> None:
        slot = self.slots.index(None)
        self.slots[slot] = seq
        seq.slot = slot
        resumed = seq.num_generated > 0
        S = self.max_prompt_len
        ids = np.zeros((S,), np.int32)
        ids[:len(seq.prompt)] = seq.prompt
        with _mspans.span("serve/prefill", parent=seq.span,
                          seq_id=seq.seq_id, resumed=resumed,
                          prompt_tokens=len(seq.prompt)):
            logits, next_tok, self.state = self._prefill(
                self.params, self.state, jnp.asarray(self._bt_row(seq)),
                jnp.int32(len(seq.prompt)), jnp.asarray(ids))
            seq.num_cached = len(seq.prompt)
        _mhooks.counter("serve/prefills")
        self._record(seq, len(seq.prompt), logits)
        if not resumed:
            self._sample(seq, next_tok)
        else:
            # resumed: the generated tokens already exist; rebuild the
            # cache deterministically instead of re-sampling
            with _mspans.span("serve/replay", parent=seq.span,
                              seq_id=seq.seq_id,
                              tokens=max(0, seq.num_generated - 1)):
                self._replay_generated(seq)

    def step(self) -> bool:
        """One scheduler round: prefills + one batched decode. Returns
        whether any work remains. With a recorder attached the round
        runs inside one per-step record (gauges/counters below land on
        it, so the Watchdog's serve detectors see them online)."""
        rec = _monitor_state.recorder
        if rec is not None and rec._open_step is None:
            with rec.step():
                return self._step_inner()
        return self._step_inner()

    def _step_inner(self) -> bool:
        plan = self.sched.schedule()
        for seq in plan.preempted:
            self._free_slot(seq)
        for seq in plan.prefill:
            self._do_prefill(seq)
        decodes = [s for s in plan.decode
                   if not s.done and s.state == RUNNING]
        if decodes and self.spec_k:
            # speculative mode: one draft+verify round per sequence
            # (the verify window owns the batch rows)
            for seq in decodes:
                if seq.done or seq.state != RUNNING:
                    continue
                self._spec_round(seq)
        elif decodes:
            tok = np.zeros((self.max_batch,), np.int32)
            pos = np.zeros((self.max_batch,), np.int32)
            act = np.zeros((self.max_batch,), bool)
            bts = np.zeros((self.max_batch, self.pages_per_seq), np.int32)
            for seq in decodes:
                slot = seq.slot
                tok[slot] = seq.tokens[-1]
                pos[slot] = seq.num_tokens - 1
                act[slot] = True
                bts[slot] = self._bt_row(seq)
            t0 = time.perf_counter()
            with _mspans.span("serve/decode_step",
                              n_active=len(decodes)):
                logits, next_toks, self.state = self._decode(
                    self.params, self.state, jnp.asarray(bts),
                    jnp.asarray(pos), jnp.asarray(tok), jnp.asarray(act))
                next_np = np.asarray(next_toks)
            logits_np = np.asarray(logits) if self.record_logits else None
            dt = time.perf_counter() - t0
            self.decode_step_times.append(dt)
            if _mhooks.enabled():
                # per-TOKEN latency: each active slot produced one
                # token this step — the streaming-percentile source of
                # the serve SLO numbers (p50/p95/p99)
                for _ in decodes:
                    _mhooks.observe("serve/token_latency_ms", 1e3 * dt)
                _mhooks.gauge("serve/batch_fill",
                              len(decodes) / self.max_batch)
            for seq in decodes:
                slot = seq.slot
                seq.num_cached = seq.num_tokens
                if logits_np is not None:
                    self._record(seq, seq.num_tokens, logits_np[slot])
                self._sample(seq, next_np[slot])
        self._record_step_gauges()
        return self.sched.has_work

    def _record_step_gauges(self) -> None:
        """Pool-occupancy + queue-state gauges, once per scheduler
        round (the Watchdog's serve-side inputs). One `enabled` read
        when detached."""
        if not _mhooks.enabled():
            return
        alloc = self.sched.allocator
        used = alloc.num_pages - 1 - alloc.free_pages
        _mhooks.gauge("serve/pages_in_use", used)
        _mhooks.gauge("serve/pages_free", alloc.free_pages)
        _mhooks.gauge("serve/pages_total", alloc.num_pages - 1)
        _mhooks.gauge("serve/pool_bytes_in_use",
                      self.ccfg.occupancy_bytes(used))
        _mhooks.gauge("serve/queue_depth", len(self.sched.waiting))
        if self.sched.waiting:
            oldest = min(s.queued_t for s in self.sched.waiting)
            _mhooks.gauge("serve/queue_wait_oldest_s",
                          max(0.0, time.perf_counter() - oldest))
        else:
            _mhooks.gauge("serve/queue_wait_oldest_s", 0.0)

    def preempt(self, seq_id: int) -> None:
        """Force-preempt a running sequence (tests/benchmarks; the
        organic path is the scheduler's evict-on-exhaustion)."""
        for seq in self.sched.running:
            if seq.seq_id == seq_id:
                self.sched._preempt(seq)
                self._free_slot(seq)
                return
        raise KeyError(f"sequence {seq_id} is not running")

    def run(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Drive until every request finished; returns seq_id ->
        generated tokens for EVERY request ever added (including ones
        that already finished during earlier manual ``step()`` calls)."""
        steps = 0
        t0 = time.perf_counter()
        tok0 = self.tokens_generated
        try:
            while self.sched.has_work:
                self.step()
                steps += 1
                if steps > max_steps:
                    raise RuntimeError("serve engine did not drain "
                                       f"in {max_steps} steps")
        except BaseException:
            # abort path: leave the black box (in-flight request spans
            # are still open — the flight dump names them). Inert
            # unless flight.install() armed dumps.
            _mflight.trigger("serve/abort")
            raise
        self._record_run_summary(t0, tok0)
        return {sid: s.tokens[len(s.prompt):]
                for sid, s in self.seqs.items()}

    def _record_run_summary(self, t0: float, tok0: int) -> None:
        """Goodput gauge + histogram-snapshot flush at drain time (one
        `enabled` read when detached)."""
        if not _mhooks.enabled():
            return
        dt = time.perf_counter() - t0
        toks = self.tokens_generated - tok0
        if dt > 0 and toks:
            # tokens/s/chip goodput: completed-token throughput per
            # participating chip (the serve twin of training MFU —
            # monitor.profile.mfu)
            _mhooks.gauge("serve/goodput_tokens_per_sec_chip",
                          toks / dt / max(1, self.tp))
        rec = _monitor_state.recorder
        if rec is not None:
            # cumulative SLO histograms ride the ring/stream, so a
            # crash after drain still leaves the percentiles on disk
            rec.emit_histograms()

    def serve(self, *, export_port: Optional[int] = None,
              export_addr: str = "127.0.0.1",
              max_steps: int = 100_000,
              export_recorder=None, on_export=None,
              export_hold: Optional[threading.Event] = None
              ) -> Dict[int, List[int]]:
        """:meth:`run` with a live metrics surface: when
        ``export_port`` is given, a :class:`~apex_tpu.monitor.export.
        MetricsExporter` serves ``GET /metrics`` (Prometheus text
        exposition of the attached recorder's counters/gauges/SLO
        histograms) for the duration of the drain — ``export_port=0``
        binds an ephemeral port (``self.export_port`` holds the bound
        port). Without ``export_port`` this IS ``run()`` — no thread,
        no ``http.server`` import.

        Fleet wiring (all host-side; compiled programs untouched):
        samples carry ``replica="<self.replica_id>"`` labels;
        ``export_recorder`` pins the exporter to a specific recorder
        (instead of resolving the attached one per scrape — what the
        multi-replica harness uses, one concrete recorder per engine
        thread); ``on_export(self)`` fires once the port is bound, the
        registration hook a :class:`~apex_tpu.monitor.fleet.ReplicaSet`
        hands in; ``export_hold`` keeps the endpoint scrapeable after
        the drain until the caller sets the event (bounded by a 60 s
        guard so a forgotten event cannot hang the engine)."""
        try:
            if export_port is None:
                return self.run(max_steps=max_steps)
            from apex_tpu.monitor import export as export_mod
            exporter = export_mod.MetricsExporter(recorder=export_recorder,
                                                  port=export_port,
                                                  addr=export_addr,
                                                  replica=self.replica_id)
            self.export_port = exporter.start()
            if on_export is not None:
                on_export(self)
            try:
                return self.run(max_steps=max_steps)
            finally:
                if export_hold is not None:
                    export_hold.wait(timeout=60.0)
                exporter.stop()
        finally:
            # engine shutdown: snapshot the final SLO/occupancy state
            # (no-op unless the flight recorder is armed)
            _mflight.trigger("serve/shutdown")


def naive_generate(cfg: GPTConfig, params, requests, *, max_seq_len: int,
                   attention_impl: Optional[str] = None):
    """The full-recompute baseline: same batched greedy decoding, NO
    KV cache — every token recomputes the whole prefix (one fixed-shape
    forward over the padded context per step). The bench's honesty
    anchor for the paged-cache speedup.

    ``requests``: list of ``(prompt, max_new_tokens)``. Returns
    ``(outputs: list[list[int]], step_times: list[float])``.
    """
    _, p_impl = _default_impls()
    impl = attention_impl or p_impl
    B = len(requests)
    S = max_seq_len

    @jax.jit
    def step(ids, lengths):
        logits = model_mod.full_forward_logits(cfg, params, ids, lengths,
                                               attention_impl=impl)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    ids = np.zeros((B, S), np.int32)
    lengths = np.zeros((B,), np.int32)
    todo = np.zeros((B,), np.int32)
    for i, (prompt, n_new) in enumerate(requests):
        ids[i, :len(prompt)] = prompt
        lengths[i] = len(prompt)
        todo[i] = n_new
    outputs: List[List[int]] = [[] for _ in range(B)]
    step_times: List[float] = []
    while (np.array([len(o) for o in outputs]) < todo).any():
        t0 = time.perf_counter()
        next_toks = np.asarray(step(jnp.asarray(ids), jnp.asarray(lengths)))
        step_times.append(time.perf_counter() - t0)
        for i in range(B):
            if len(outputs[i]) < todo[i]:
                outputs[i].append(int(next_toks[i]))
                ids[i, lengths[i]] = next_toks[i]
                lengths[i] += 1
    return outputs, step_times
