"""Multi-tensor apply: one fused update across a whole list of tensors.

Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py:3-30`` dispatches
to CUDA kernels (``csrc/multi_tensor_apply.cuh``) that chunk a list of
tensors into one kernel launch with a global ``noop_flag`` for inf/nan.

TPU design: there is no kernel-launch overhead to amortize under XLA — a
single ``jit`` region already fuses elementwise work — so the fusion axis
here is *array granularity*: ops take whole tensor lists, compute on either
the per-leaf or a packed flat-buffer representation, and return a device-
resident ``found_inf`` flag instead of mutating a noop buffer. Overflow
handling stays on device (no D2H sync; cf. apex's single sync point at
``apex/amp/scaler.py:197-200``).
"""

from apex_tpu.multi_tensor_apply.functional import (  # noqa: F401
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_applier,
    MultiTensorApply,
)
