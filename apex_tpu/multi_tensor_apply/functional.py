"""Functional multi-tensor ops (scale / axpby / l2norm).

Each mirrors an ``amp_C`` kernel (``csrc/amp_C_frontend.cpp:122-145``) but
is a pure function: outputs are returned, and the overflow flag is a
returned boolean scalar (True = overflow observed) rather than a mutated
GPU buffer. All are jit-safe and fuse into surrounding computation.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp


def _found_inf(tensors: Sequence[jax.Array]) -> jax.Array:
    if not tensors:
        return jnp.asarray(False)
    return ~jnp.stack([jnp.all(jnp.isfinite(t)) for t in tensors]).all()


def multi_tensor_scale(srcs: Sequence[jax.Array], scale, out_dtype=None):
    """``dst = src * scale`` across a tensor list.

    Reference: ``csrc/multi_tensor_scale_kernel.cu`` — used for grad
    unscaling (``apex/amp/scaler.py:114``) and fp32->fp16 master->model
    param copies (``apex/amp/_process_optimizer.py:14-25``).

    Returns ``(outs, found_inf)`` where ``found_inf`` reflects inf/nan in
    the *source* tensors (matching the kernel's check-before-write).
    """
    scale = jnp.asarray(scale, jnp.float32)
    outs = []
    for s in srcs:
        o = s.astype(jnp.float32) * scale
        outs.append(o.astype(out_dtype or s.dtype))
    return outs, _found_inf(srcs)


def multi_tensor_axpby(xs: Sequence[jax.Array], ys: Sequence[jax.Array], a, b, out_dtype=None):
    """``out = a*x + b*y`` across tensor lists.

    Reference: ``csrc/multi_tensor_axpby_kernel.cu`` — used for gradient
    accumulation across unscale calls (``apex/amp/scaler.py:152-195``).
    Returns ``(outs, found_inf)``; the flag checks both inputs.
    """
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    outs = []
    for x, y in zip(xs, ys):
        o = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        outs.append(o.astype(out_dtype or y.dtype))
    return outs, _found_inf(list(xs) + list(ys))


def multi_tensor_l2norm(tensors: Sequence[jax.Array], per_tensor: bool = False):
    """Global (and optionally per-tensor) L2 norm over a tensor list.

    Reference: ``csrc/multi_tensor_l2norm_kernel.cu`` — used by FusedLAMB's
    phase 1 (``apex/optimizers/fused_lamb.py:124-133``) and grad clipping.
    """
    if not tensors:
        z = jnp.zeros((), jnp.float32)
        return (z, jnp.zeros((0,), jnp.float32)) if per_tensor else (z, None)
    sq = jnp.stack([jnp.sum(jnp.square(t.astype(jnp.float32))) for t in tensors])
    norm = jnp.sqrt(jnp.sum(sq))
    if per_tensor:
        return norm, jnp.sqrt(sq)
    return norm, None


def multi_tensor_applier(op, tensor_lists, *args, **kwargs):
    """Apply ``op`` across tensor lists; parity shim for the apex call shape
    (``apex/multi_tensor_apply/multi_tensor_apply.py:24-30``) minus the
    mutable ``noop_flag`` argument, which is returned instead."""
    return op(*tensor_lists, *args, **kwargs) if isinstance(tensor_lists, (list, tuple)) and tensor_lists and isinstance(tensor_lists[0], (list, tuple)) else op(tensor_lists, *args, **kwargs)


class MultiTensorApply:
    """API-parity dispatcher. Always available on TPU (no extension build).

    Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py:3-30`` —
    ``available`` gated every fused path in apex; here it is always True.
    """

    available: bool = True
    warned: bool = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size  # kept for API parity; XLA chooses tiling

    def __call__(self, op, noop_flag_or_lists, *args, **kwargs):
        return multi_tensor_applier(op, noop_flag_or_lists, *args, **kwargs)
