"""MLP module: N fused dense+bias+activation layers.

Reference: ``apex/mlp/mlp.py:8-79`` — ``MLP(mlp_sizes, bias=True,
relu=True)`` runs every layer inside one fused autograd Function
(``MlpFunction``). Here ``apex_tpu.ops.mlp_forward`` is the single fused
region; activation choices mirror the kernel's none/relu/sigmoid
(``csrc/mlp.cpp``).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.mlp import mlp_forward


class MLP(nn.Module):
    mlp_sizes: Sequence[int]          # [in, hidden..., out]
    use_bias: bool = True
    activation: str = "relu"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        weights, biases = [], []
        for i in range(len(self.mlp_sizes) - 1):
            fan_in, fan_out = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            w = self.param(f"weight_{i}", nn.initializers.lecun_normal(),
                           (fan_out, fan_in), self.param_dtype)
            weights.append(w.astype(x.dtype))
            if self.use_bias:
                b = self.param(f"bias_{i}", nn.initializers.zeros,
                               (fan_out,), self.param_dtype)
            else:
                b = jnp.zeros((fan_out,), self.param_dtype)
            biases.append(b.astype(x.dtype))
        return mlp_forward(x, weights, biases, self.activation)

# O1 default-cast coverage: matmul-class (FP16_FUNCS row).
from apex_tpu.amp import lists as _amp_lists  # noqa: E402
_amp_lists.register_half_module(MLP)
