"""apex_tpu.mlp — fused multi-layer MLP module.

Reference: ``apex/mlp/mlp.py:8-79``.
"""

from apex_tpu.mlp.mlp import MLP  # noqa: F401
from apex_tpu.ops.mlp import mlp_forward  # noqa: F401
