"""Pull-based plaintext metrics endpoint (Prometheus text exposition).

Snapshots an attached :class:`~apex_tpu.monitor.recorder.Recorder`'s
counters, gauges, timers and log-scale histograms into the Prometheus
text exposition format (version 0.0.4) and serves it from a stdlib
``http.server`` thread — ``GET /metrics`` while a server is running,
or ``--once`` to stdout for CI:

    python -m apex_tpu.monitor export run.jsonl --once [--check]
    python -m apex_tpu.monitor export run.jsonl --port 9464

Live mode rides the serve engine: ``ServeEngine.serve(export_port=...)``
starts an exporter bound to whichever recorder is attached, so SLO
histograms (p50/p95/p99 token latency, TTFT), pool-occupancy gauges and
scheduler counters are scrapeable while requests are in flight.

Disabled mode is free by construction: this module is imported lazily
(``apex_tpu.monitor.__getattr__``) so a process that never exports
never pays the ``http.server`` import, and no thread exists until
:meth:`MetricsExporter.start`.

Mapping (names sanitized to ``[a-zA-Z0-9_:]``, ``apex_`` prefixed):

- counter  ``serve/preemptions``    -> ``apex_serve_preemptions_total``
- gauge    ``serve/queue_depth``    -> ``apex_serve_queue_depth``
- timer    ``data/host_wait``       -> ``apex_data_host_wait_seconds_total``
                                       + ``..._seconds_count`` (counters)
- histogram ``serve/ttft_ms``       -> ``apex_serve_ttft_ms_bucket{le=..}``
                                       + ``_sum`` + ``_count`` (classic
                                       cumulative histogram; bucket
                                       bounds are the LogHistogram's
                                       populated upper edges)

:func:`parse_prometheus` is the self-check twin: it parses an
exposition document back into ``{(name, labels): value}`` so the CLI's
``--check`` (and ``tests/test_export.py``'s golden round trip) can
assert scrape == aggregate.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Optional

from apex_tpu.monitor import _state

PREFIX = "apex_"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """A recorder event name as a legal Prometheus metric name."""
    out = _NAME_RE.sub("_", str(name))
    if out and out[0].isdigit():
        out = "_" + out
    return PREFIX + out


def _fmt_value(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def snapshot(recorder=None, events=None, header=None) -> dict:
    """One point-in-time metrics snapshot, from a live recorder
    (default: the attached one) or from an event list (the JSONL file
    modes). Shape: ``{counters, gauges, timers, histograms}`` where
    histograms hold :meth:`LogHistogram.snapshot` payloads.

    Recorder blind spots are exported too, so a saturated ring is
    itself observable: ``monitor/dropped_events`` (ring evictions →
    ``apex_monitor_dropped_events_total``) and ``monitor/open_spans``
    (started-but-unfinished spans → ``apex_monitor_open_spans``) —
    live from ``Recorder.dropped``/``spans.open_spans()``, file-backed
    from the dump ``header`` when the caller passes it."""
    if events is not None:
        if header:
            return _with_blind_spots(
                snapshot(events=events),
                header.get("dropped"), header.get("open_spans"))
        from apex_tpu.monitor.report import aggregate as _aggregate
        counters: dict = {}
        gauges: dict = {}
        timers: dict = {}
        hists: dict = {}
        agg = _aggregate(events)
        counters.update(agg.get("counters") or {})
        gauges.update(agg.get("gauges") or {})
        timers.update(agg.get("timers") or {})
        # aggregate() summarizes histograms; re-collect the raw
        # snapshots here so bucket counts survive into exposition
        for ev in events:
            if ev.get("kind") == "histogram":
                hists[ev.get("name")] = {
                    **{k: ev.get(k) for k in
                       ("lo", "hi", "buckets_per_decade", "sum", "min",
                        "max", "underflow", "overflow", "counts")},
                    "count": ev.get("value")}
        return {"counters": counters, "gauges": gauges, "timers": timers,
                "histograms": hists}
    rec = recorder if recorder is not None else _state.recorder
    if rec is None:
        return {"counters": {}, "gauges": {}, "timers": {},
                "histograms": {}}
    from apex_tpu.monitor.spans import open_spans
    agg_timers: dict = {}
    for ev in rec.records("timer"):
        t = agg_timers.setdefault(ev.get("name"), {"n": 0, "total_s": 0.0})
        t["n"] += 1
        t["total_s"] += float(ev.get("value") or 0.0)
    # the recorder shadows each timer with a "<name>/total_s" counter
    # (host bookkeeping, not an event) — the timer series already
    # exposes that value, and the file-backed path never sees the
    # shadow, so drop it for live == file consistency
    counters = {k: v for k, v in rec.counters().items()
                if not k.endswith("/total_s")}
    return _with_blind_spots(
        {"counters": counters, "gauges": rec.gauges(),
         "timers": agg_timers,
         "histograms": {k: h.snapshot()
                        for k, h in rec.histograms().items()}},
        rec.dropped, open_spans())


def _with_blind_spots(snap: dict, dropped, open_spans) -> dict:
    snap["counters"]["monitor/dropped_events"] = float(dropped or 0)
    snap["gauges"]["monitor/open_spans"] = float(open_spans or 0)
    return snap


def render_prometheus(snap: dict, replica: Optional[str] = None) -> str:
    """Prometheus text exposition (0.0.4) for a :func:`snapshot`.

    With ``replica`` set (fleet mode), every sample carries a stable
    ``replica="<id>"`` label so a fleet aggregator can key samples per
    replica even after concatenating scrapes, and two scrape-metadata
    samples lead the document: ``apex_replica_up 1`` (this endpoint
    rendered, so it is up — the poller writes the 0) and
    ``apex_scrape_timestamp_seconds`` (render wall time, for last-seen
    age). ``replica=None`` keeps the output byte-identical to the
    pre-fleet format — single-process scrapes are unchanged."""
    from apex_tpu.monitor.spans import LogHistogram

    rl = f',replica="{replica}"' if replica is not None else ""
    sole = f'{{replica="{replica}"}}' if replica is not None else ""
    lines: list[str] = []

    def emit(name: str, mtype: str, rows):
        lines.append(f"# TYPE {name} {mtype}")
        lines.extend(rows)

    if replica is not None:
        emit("apex_replica_up", "gauge", [f"apex_replica_up{sole} 1"])
        emit("apex_scrape_timestamp_seconds", "gauge",
             [f"apex_scrape_timestamp_seconds{sole} "
              f"{_fmt_value(time.time())}"])
    for k in sorted(snap.get("counters") or {}):
        n = sanitize(k) + "_total"
        emit(n, "counter", [f"{n}{sole} {_fmt_value(snap['counters'][k])}"])
    for k in sorted(snap.get("gauges") or {}):
        n = sanitize(k)
        emit(n, "gauge", [f"{n}{sole} {_fmt_value(snap['gauges'][k])}"])
    for k in sorted(snap.get("timers") or {}):
        t = snap["timers"][k]
        n = sanitize(k) + "_seconds"
        emit(n + "_total", "counter",
             [f"{n}_total{sole} {_fmt_value(t.get('total_s'))}"])
        emit(n + "_count", "counter",
             [f"{n}_count{sole} {_fmt_value(t.get('n'))}"])
    for k in sorted(snap.get("histograms") or {}):
        h = LogHistogram.from_snapshot(snap["histograms"][k])
        n = sanitize(k)
        rows = []
        cum = h.underflow
        for i in range(h.n_buckets):
            c = h._counts[i]
            if not c:
                continue
            cum += c
            le = h.bucket_bounds(i)[1]
            rows.append(f'{n}_bucket{{le="{_fmt_value(le)}"{rl}}} {cum}')
        rows.append(f'{n}_bucket{{le="+Inf"{rl}}} {h.count}')
        rows.append(f"{n}_sum{sole} {_fmt_value(h.sum)}")
        rows.append(f"{n}_count{sole} {h.count}")
        emit(n, "histogram", rows)
    return "\n".join(lines) + ("\n" if lines else "")


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$')


def parse_prometheus(text: str) -> dict:
    """Parse an exposition document into ``{(name, labels): value}``
    where ``labels`` is a sorted tuple of ``(key, value)`` pairs — the
    self-check half of the golden round trip."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labels, value = m.groups()
        lab = ()
        if labels:
            pairs = []
            for part in labels[1:-1].split(","):
                if not part.strip():
                    continue
                lk, lv = part.split("=", 1)
                pairs.append((lk.strip(), lv.strip().strip('"')))
            lab = tuple(sorted(pairs))
        out[(name, lab)] = (float("inf") if value == "+Inf"
                            else float("-inf") if value == "-Inf"
                            else float(value))
    return out


def parse_prometheus_types(text: str) -> dict:
    """``{metric_name: type}`` from the ``# TYPE`` comment lines of an
    exposition document. The fleet poller feeds this to
    ``fleet.classify_samples`` so a gauge whose *name* ends in
    ``_total`` (``serve/pages_total``) is never misread as a counter —
    the declared type wins over naming convention."""
    types: dict = {}
    for line in text.splitlines():
        parts = line.strip().split()
        if len(parts) == 4 and parts[0] == "#" and parts[1] == "TYPE":
            types[parts[2]] = parts[3]
    return types


def selfcheck_text(text: str, snap: dict,
                   replica: Optional[str] = None) -> None:
    """Assert ``text`` (an exposition render of ``snap``) parses and
    its counter/gauge/histogram-count samples equal the snapshot —
    the ``--check`` CLI mode and the CI export stage. Label-aware:
    pass ``replica`` to check a fleet-labeled render (every sample is
    then keyed by its ``replica=`` label, histogram buckets by
    ``le`` + ``replica`` together)."""
    parsed = parse_prometheus(text)
    lab = (("replica", str(replica)),) if replica is not None else ()
    for k, v in (snap.get("counters") or {}).items():
        got = parsed[(sanitize(k) + "_total", lab)]
        assert got == float(v), (k, got, v)
    for k, v in (snap.get("gauges") or {}).items():
        got = parsed[(sanitize(k), lab)]
        if v is None or (isinstance(v, float) and v != v):
            assert got != got, (k, got, v)
        else:
            assert got == float(v), (k, got, v)
    for k, h in (snap.get("histograms") or {}).items():
        n = sanitize(k)
        assert parsed[(n + "_count", lab)] == float(h.get("count") or 0), k
        inf = parsed[(n + "_bucket", tuple(sorted((("le", "+Inf"),) + lab)))]
        assert inf == float(h.get("count") or 0), k
    if replica is not None:
        assert parsed[("apex_replica_up", lab)] == 1.0
        assert parsed[("apex_scrape_timestamp_seconds", lab)] > 0


class MetricsExporter:
    """Serve ``GET /metrics`` from a daemon thread.

    ``recorder=None`` resolves the *attached* recorder at every scrape
    — attach/detach cycles are honored live, and a scrape while
    detached returns an empty (but valid) document. ``port=0`` binds an
    ephemeral port; the bound port is returned by :meth:`start` and
    kept on ``.port``. ``replica=<id>`` opts the render into fleet
    labeling (see :func:`render_prometheus`): a stable replica identity
    the serve engine provides so a ``FleetPoller`` can key samples; it
    defaults to off so single-process output is unchanged.
    """

    def __init__(self, recorder=None, port: int = 9464,
                 addr: str = "127.0.0.1", replica: Optional[str] = None):
        self.recorder = recorder
        self.addr = addr
        self.port = int(port)
        self.replica = replica
        self._srv = None
        self._thread = None

    def _render(self) -> str:
        rec = (self.recorder if self.recorder is not None
               else _state.recorder)
        return render_prometheus(snapshot(recorder=rec)
                                 if rec is not None else
                                 {"counters": {}, "gauges": {},
                                  "timers": {}, "histograms": {}},
                                 replica=self.replica)

    def start(self) -> int:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):                       # noqa: N802
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = exporter._render().encode()
                except Exception as e:              # noqa: BLE001
                    self.send_error(500, str(e)[:200])
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):              # scrapes are not news
                pass

        self._srv = ThreadingHTTPServer((self.addr, self.port), _Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="apex-tpu-metrics-exporter")
        self._thread.start()
        return self.port

    def stop(self):
        srv, self._srv = self._srv, None
        th, self._thread = self._thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if th is not None:
            th.join(timeout=5)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


def main(args) -> int:
    """``python -m apex_tpu.monitor export`` body (args pre-parsed by
    ``monitor.__main__``): render a recorder JSONL dump/stream once to
    stdout, optionally self-check the round trip, or serve it over
    HTTP (re-reading the file per scrape, so a live ``stream=`` file
    exports its current tail)."""
    from apex_tpu.monitor.report import load_jsonl

    def _snap():
        header, events = load_jsonl(args.path)
        return snapshot(events=events, header=header)

    if args.once:
        snap = _snap()
        text = render_prometheus(snap)
        if args.check:
            selfcheck_text(text, snap)
        print(text, end="")
        if args.check:
            import sys
            n = sum(len(snap[k]) for k in
                    ("counters", "gauges", "histograms"))
            print(f"export selfcheck ok: {n} metric(s) round-tripped",
                  file=sys.stderr)
        return 0

    exporter = MetricsExporter(port=args.port, addr=args.addr)
    exporter._render = lambda: render_prometheus(_snap())   # file-backed
    port = exporter.start()
    print(f"serving {args.path} at http://{args.addr}:{port}/metrics "
          f"(ctrl-c to stop)")
    try:
        exporter._thread.join()
    except KeyboardInterrupt:
        exporter.stop()
    return 0
