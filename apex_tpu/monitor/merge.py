"""Cross-host telemetry aggregation: rank-tagged shards + merged view.

One process can only see its own Recorder; a production run has one
Recorder per host. This module makes the mesh-wide picture:

- **Shards**: every process dumps its recorder to
  ``monitor-{process_index}.jsonl`` (:func:`dump_shard` — rank and
  world size land in the header ``meta``). Shards are ordinary
  ``Recorder.dump_jsonl`` files, so each one also renders standalone.
- **Offline merge**: ``python -m apex_tpu.monitor merge <shards...>``
  (or :func:`merge_shards`) combines shards into one cross-host view —
  collective bytes/counts summed across ranks per ``op@axis``, counters
  summed, timers kept as per-rank distributions with straggler
  percentiles (max/median of the per-rank means), and per-rank
  step-time skew (each rank's median step time over the global median,
  slowest rank named).
- **In-mesh merge**: :func:`allgather_summaries` produces the same
  merged view *inside* a multi-process run using host collectives
  (``process_allgather`` of each rank's JSON summary). Guarded to be
  free when monitoring is detached: no recorder → returns ``None``
  without importing jax.

The merged dict is what :func:`apex_tpu.monitor.report.
render_cross_host` renders and what ``health.Watchdog.check_cross_host``
scans for straggler ranks.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Iterable, Optional, Sequence

from apex_tpu.monitor import _state
from apex_tpu.monitor.report import aggregate, load_jsonl

SHARD_RE = re.compile(r"monitor-(\d+)\.jsonl$")
FLIGHT_RE = re.compile(r"flight-(\d+)\.jsonl$")


def shard_path(directory: str, process_index: int) -> str:
    """The rank-tagged shard file for one process."""
    return os.path.join(directory, f"monitor-{int(process_index)}.jsonl")


def dump_shard(recorder, directory: str, process_index: Optional[int] = None,
               process_count: Optional[int] = None) -> str:
    """Dump ``recorder`` as this process's shard under ``directory``.

    ``process_index``/``process_count`` default to the jax distributed
    runtime's values (the only jax touch in this module, and only when
    the caller does not supply them)."""
    if process_index is None or process_count is None:
        import jax
        if process_index is None:
            process_index = jax.process_index()
        if process_count is None:
            process_count = jax.process_count()
    recorder.meta["process_index"] = int(process_index)
    recorder.meta["process_count"] = int(process_count)
    os.makedirs(directory, exist_ok=True)
    path = shard_path(directory, process_index)
    recorder.dump_jsonl(path)
    return path


def find_shards(directory: str) -> list[str]:
    """All ``monitor-<rank>.jsonl`` files in ``directory``, rank order.
    Flight dumps (``flight-<rank>.jsonl``) fill in ranks that left no
    live shard — a killed run's black box merges like any other shard,
    but a rank with both contributes only the live shard (the flight
    dump is a bounded tail of the same recorder: counting both would
    double its collectives)."""
    tagged = {}
    for pattern, rx in (("flight-*.jsonl", FLIGHT_RE),
                        ("monitor-*.jsonl", SHARD_RE)):
        for p in glob.glob(os.path.join(directory, pattern)):
            m = rx.search(p)
            if m:
                tagged[int(m.group(1))] = p   # monitor- wins, second pass
    return [p for _, p in sorted(tagged.items())]


def rank_summary(header: dict, events: Iterable[dict],
                 rank: Optional[int] = None) -> dict:
    """One rank's aggregate, tagged with its process index (taken from
    the shard header meta when not given)."""
    if rank is None:
        rank = (header or {}).get("meta", {}).get("process_index", 0)
    return {"rank": int(rank), "aggregate": aggregate(events, header=header)}


def _dist(xs: Sequence[float]) -> dict:
    xs = sorted(xs)
    n = len(xs)
    med = xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])
    return {"n": n, "min": xs[0], "max": xs[-1], "median": med,
            "mean": sum(xs) / n}


def merge_summaries(summaries: Sequence[dict]) -> dict:
    """Combine per-rank summaries (:func:`rank_summary`) into the
    cross-host view (module docstring). Pure stdlib."""
    summaries = sorted(summaries, key=lambda s: s["rank"])
    ranks = [s["rank"] for s in summaries]
    out: dict = {"kind": "cross_host", "n_ranks": len(summaries),
                 "ranks": ranks}

    # collectives: bytes/counts summed across ranks, per-rank kept
    coll_sum: dict[str, dict] = {}
    coll_by_rank: dict[str, dict] = {}
    counters: dict[str, float] = {}
    for s in summaries:
        agg = s["aggregate"]
        coll_by_rank[str(s["rank"])] = agg.get("collectives", {})
        for k, v in agg.get("collectives", {}).items():
            slot = coll_sum.setdefault(k, {"count": 0, "bytes": 0})
            slot["count"] += int(v.get("count", 0))
            slot["bytes"] += int(v.get("bytes", 0))
        for k, v in agg.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    out["collectives"] = {k: coll_sum[k] for k in sorted(coll_sum)}
    out["collectives_by_rank"] = coll_by_rank
    out["counters"] = {k: counters[k] for k in sorted(counters)}

    # timers: per-rank distributions + straggler percentiles over the
    # per-rank means (a rank whose data/host_wait mean is 3x the median
    # is the input-starved straggler)
    timer_names = sorted({n for s in summaries
                          for n in s["aggregate"].get("timers", {})})
    timers: dict[str, dict] = {}
    for name in timer_names:
        by_rank = {}
        means = {}
        for s in summaries:
            t = s["aggregate"].get("timers", {}).get(name)
            if t:
                by_rank[str(s["rank"])] = t
                means[s["rank"]] = float(t.get("mean_s", 0.0))
        row: dict = {"by_rank": by_rank}
        if means:
            d = _dist(list(means.values()))
            slowest = max(means, key=means.get)
            row.update({
                "mean_s_median": round(d["median"], 6),
                "mean_s_max": round(d["max"], 6),
                "max_over_median": round(d["max"] / d["median"], 3)
                if d["median"] > 0 else None,
                "slowest_rank": slowest,
            })
        timers[name] = row
    out["timers"] = timers

    # steps: per-rank step-time distributions + skew per rank
    step_by_rank = {}
    medians = {}
    for s in summaries:
        st = s["aggregate"].get("steps")
        if st:
            step_by_rank[str(s["rank"])] = dict(st["step_time_s"],
                                                count=st["count"])
            medians[s["rank"]] = float(st["step_time_s"]["median"])
    if medians:
        global_med = _dist(list(medians.values()))["median"]
        skew = {str(r): round(m / global_med, 3) if global_med > 0 else None
                for r, m in medians.items()}
        slowest = max(medians, key=medians.get)
        out["steps"] = {
            "by_rank": step_by_rank,
            "skew": {
                "median_step_time_s": round(global_med, 6),
                "max_step_time_s": round(max(medians.values()), 6),
                "max_over_median": round(
                    max(medians.values()) / global_med, 3)
                if global_med > 0 else None,
                "per_rank_ratio": skew,
                "slowest_rank": slowest,
            },
        }

    # last-value gauges and health events stay rank-scoped (a loss-scale
    # gauge has no meaningful cross-rank sum)
    out["gauges_by_rank"] = {str(s["rank"]): s["aggregate"].get("gauges", {})
                             for s in summaries}
    health = []
    for s in summaries:
        for ev in s["aggregate"].get("health", []):
            health.append({**ev, "rank": s["rank"]})
    if health:
        out["health_events"] = health
    return out


def merge_shards(paths_or_dir) -> dict:
    """Load shard files (or every ``monitor-*.jsonl`` in a directory)
    and merge them into the cross-host view."""
    if isinstance(paths_or_dir, str):
        paths = find_shards(paths_or_dir) if os.path.isdir(paths_or_dir) \
            else [paths_or_dir]
    else:
        paths = list(paths_or_dir)
    if not paths:
        raise ValueError("no monitor shards found")
    summaries = []
    for i, p in enumerate(paths):
        header, events = load_jsonl(p)
        rank = (header.get("meta") or {}).get("process_index")
        if rank is None:
            m = SHARD_RE.search(str(p))
            rank = int(m.group(1)) if m else i
        summaries.append(rank_summary(header, events, rank=rank))
    return merge_summaries(summaries)


def allgather_summaries(recorder=None) -> Optional[dict]:
    """In-mesh merge: gather every process's local summary with host
    collectives and return the merged cross-host view on all ranks.

    Free when detached: with no recorder attached (and none passed)
    this returns ``None`` without importing jax or touching the mesh —
    safe to leave in production loops unconditionally. With one process
    it degenerates to a local merge. The gather is a *host* collective
    (``multihost_utils.process_allgather``), so it runs outside any
    compiled program and perturbs nothing that is being timed.
    """
    rec = recorder if recorder is not None else _state.recorder
    if rec is None:
        return None
    import jax
    import numpy as np
    rank = jax.process_index()
    local = rank_summary({"meta": rec.meta}, rec.records(), rank=rank)
    if jax.process_count() == 1:
        return merge_summaries([local])
    from jax.experimental import multihost_utils
    payload = np.frombuffer(json.dumps(local).encode("utf-8"), np.uint8)
    # ragged gather: lengths first, then zero-padded payloads
    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))).reshape(-1)
    padded = np.zeros(int(lens.max()), np.uint8)
    padded[:payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    summaries = [
        json.loads(bytes(gathered[i, :int(lens[i])]).decode("utf-8"))
        for i in range(gathered.shape[0])]
    return merge_summaries(summaries)
