"""Aggregate and render recorder dumps (the ``pyprof.prof`` CLI analog).

``python -m apex_tpu.monitor report run.jsonl`` renders the per-step
table and the aggregate summary this module computes; ``aggregate`` is
also what ``Recorder.aggregate()`` and the bench JSON embed. Pure
stdlib — reports render anywhere, including hosts with no jax.
"""

from __future__ import annotations

import json
import warnings
from typing import Iterable, Optional


def load_jsonl(path_or_file) -> tuple[dict, list[dict]]:
    """Read a ``Recorder.dump_jsonl`` file → (header, events).

    A truncated *trailing* line (a process killed mid-append to a
    streamed file) is dropped with a warning instead of raising — a
    crash must never produce a dump the merge/report CLIs choke on.
    Corruption anywhere else still raises: that is a damaged file, not
    an interrupted append."""
    if hasattr(path_or_file, "read"):
        lines = path_or_file.read().splitlines()
    else:
        with open(path_or_file) as f:
            lines = f.read().splitlines()
    header: dict = {}
    events: list[dict] = []
    nonempty = [ln.strip() for ln in lines if ln.strip()]
    for i, ln in enumerate(nonempty):
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError:
            if i == len(nonempty) - 1:
                warnings.warn(
                    f"dropping truncated trailing line ({len(ln)} bytes) "
                    f"from {getattr(path_or_file, 'name', path_or_file)}",
                    RuntimeWarning, stacklevel=2)
                break
            raise
        if obj.get("kind") == "header" and not header:
            header = obj
        else:
            events.append(obj)
    return header, events


def _dist(xs: list[float]) -> dict:
    xs = sorted(xs)
    n = len(xs)
    med = xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])
    return {"n": n, "min": xs[0], "max": xs[-1],
            "mean": sum(xs) / n, "median": med}


def aggregate(events: Iterable[dict], header: Optional[dict] = None) -> dict:
    """Aggregate a recorder event stream.

    Returns: ``steps`` (count + step-time distribution + first/last
    values of the per-step gauges), ``counters`` (final totals),
    ``gauges`` (last values), ``timers`` (count/total/mean per name),
    ``collectives`` (final per-``op@axis`` count/bytes table), any
    recorded pipeline ``schedules``, and ``health`` (the watchdog's
    typed ``health_event`` records, in order).
    """
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    timers: dict[str, dict] = {}
    collectives: dict[str, dict] = {}
    schedules: dict[str, dict] = {}
    utilization: dict[str, dict] = {}
    profile_rows: dict[str, dict] = {}
    histograms: dict[str, dict] = {}   # cumulative snapshots; last wins
    span_ends: list[dict] = []
    span_events: dict[str, int] = {}
    memory_rows: dict[str, dict] = {}    # per-program footprints
    memory_scopes: dict[str, dict] = {}  # per-scope analytic peaks
    gauge_series: dict[str, list] = {}   # trajectory-tracked gauges
    _TRACKED_GAUGES = ("serve/queue_depth", "serve/batch_fill",
                       "memory/hbm_bytes_in_use")
    steps: list[dict] = []
    health: list[dict] = []
    fleet_polls: list[dict] = []   # FleetPoller per-poll views, in order
    for ev in events:
        kind = ev.get("kind")
        name = ev.get("name", "")
        if kind == "counter":
            counters[name] = ev.get("total", counters.get(name, 0)
                                    + ev.get("value", 0))
        elif kind == "gauge":
            gauges[name] = ev.get("value")
            if name in _TRACKED_GAUGES:
                gauge_series.setdefault(name, []).append(
                    (ev.get("t"), ev.get("value")))
        elif kind == "histogram":
            # cumulative LogHistogram snapshot (spans.LogHistogram):
            # later emissions strictly contain earlier ones
            histograms[name] = {k: ev.get(k) for k in
                                ("lo", "hi", "buckets_per_decade", "sum",
                                 "min", "max", "underflow", "overflow",
                                 "counts")}
            histograms[name]["count"] = ev.get("value")
        elif kind == "span_end":
            span_ends.append(ev)
        elif kind in ("span_start", "span_event"):
            span_events[f"{kind}:{name}"] = \
                span_events.get(f"{kind}:{name}", 0) + 1
        elif kind == "timer":
            t = timers.setdefault(name, {"n": 0, "total_s": 0.0})
            t["n"] += 1
            t["total_s"] += float(ev.get("value") or 0.0)
        elif kind == "collective":
            slot = collectives.setdefault(name, {"count": 0, "bytes": 0})
            slot["count"] += int(ev.get("value") or 0)
            slot["bytes"] += int(ev.get("bytes") or 0)
        elif kind == "schedule":
            schedules[name] = {
                "total_ticks": ev.get("value"),
                "n_stages": ev.get("n_stages"),
                "n_microbatches": ev.get("n_microbatches"),
                "bubble_fraction": ev.get("bubble_fraction")}
        elif kind == "tick_mark":
            # measured slot occupancy: one mark per (tick, rank), one
            # boolean per executed unit slot (f/b/w) — see
            # hooks.traced_tick_marks
            rank = str(ev.get("rank", 0))
            row = utilization.setdefault(name, {}).setdefault(
                rank, {"ticks": 0, "slots_total": 0, "slots_valid": 0,
                       "by_slot": {}})
            row["ticks"] += 1
            for slot, valid in (ev.get("slots") or {}).items():
                row["slots_total"] += 1
                s = row["by_slot"].setdefault(slot, {"total": 0, "valid": 0})
                s["total"] += 1
                if valid:
                    s["valid"] += 1
                    row["slots_valid"] += 1
        elif kind == "profile":
            # per-scope analytic attribution rows (monitor.profile,
            # analytic_profile(record=True)); last emission wins
            row = {"flops": ev.get("value")}
            for k in ("hbm_bytes", "collective_bytes", "eqns",
                      "pallas_calls", "flops_scope_coverage"):
                if ev.get(k) is not None:
                    row[k] = ev[k]
            profile_rows[name] = row
        elif kind == "memory":
            # per-program footprint rows (monitor.memory,
            # memory_profile/compiled_memory_profile(record=True));
            # last emission wins
            row = {"total_bytes": ev.get("value")}
            for k in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes",
                      "analytic_peak_bytes", "peak_scope", "estimated",
                      "argument_bytes", "output_bytes"):
                if ev.get(k) is not None:
                    row[k] = ev[k]
            memory_rows[name] = row
        elif kind == "memory_scope":
            # per-scope analytic peak-live-bytes rows
            # (monitor.memory.analytic_high_water(record=True))
            memory_scopes[name] = {"peak_live_bytes": ev.get("value"),
                                   "eqns": ev.get("eqns")}
        elif kind == "step":
            steps.append(ev)
        elif kind == "health_event":
            health.append({k: ev.get(k) for k in
                           ("name", "value", "step", "severity",
                            "diagnosis", "gauge", "rank", "t")
                           if ev.get(k) is not None})
        elif kind == "fleet":
            # monitor.fleet.FleetPoller poll views; chronological
            fleet_polls.append(ev)
    out: dict = {}
    if header:
        out["run"] = {k: header.get(k) for k in ("name", "dropped", "meta")
                      if header.get(k) is not None}
    if steps:
        times = [float(s.get("step_time_s") or s.get("value") or 0.0)
                 for s in steps]
        gkeys = sorted({k for s in steps for k in (s.get("gauges") or {})})
        series = {}
        for k in gkeys:
            vals = [s["gauges"][k] for s in steps
                    if k in (s.get("gauges") or {})]
            if vals:
                series[k] = {"first": vals[0], "last": vals[-1],
                             "n": len(vals)}
        out["steps"] = {"count": len(steps), "step_time_s": _dist(times),
                        "gauges": series}
    for t in timers.values():
        t["total_s"] = round(t["total_s"], 6)
        t["mean_s"] = round(t["total_s"] / t["n"], 6) if t["n"] else 0.0
    out["counters"] = {k: counters[k] for k in sorted(counters)}
    out["gauges"] = {k: gauges[k] for k in sorted(gauges)}
    out["timers"] = {k: timers[k] for k in sorted(timers)}
    out["collectives"] = {k: collectives[k] for k in sorted(collectives)}
    if schedules:
        out["schedules"] = schedules
    if utilization:
        for sched, ranks in utilization.items():
            tot = val = 0
            for row in ranks.values():
                row["idle_fraction"] = round(
                    1.0 - row["slots_valid"] / row["slots_total"], 6) \
                    if row["slots_total"] else 0.0
                tot += row["slots_total"]
                val += row["slots_valid"]
            ranks["all"] = {
                "slots_total": tot, "slots_valid": val,
                "idle_fraction": round(1.0 - val / tot, 6) if tot else 0.0}
        out["pipeline_utilization"] = utilization
    measured = {k[len("profile/"):]: dict(v) for k, v in timers.items()
                if k.startswith("profile/")}
    if profile_rows or measured:
        prof: dict = {}
        if profile_rows:
            prof["analytic"] = {k: profile_rows[k]
                                for k in sorted(profile_rows)}
        if measured:
            prof["measured"] = measured
        out["profile"] = prof
    if histograms:
        from apex_tpu.monitor import spans as spans_mod
        out["histograms"] = {k: spans_mod.hist_summary(histograms[k])
                             for k in sorted(histograms)}
    if span_ends or span_events:
        per_name: dict[str, dict] = {}
        for e in span_ends:
            row = per_name.setdefault(e.get("name", ""),
                                      {"n": 0, "total_s": 0.0})
            row["n"] += 1
            row["total_s"] = round(row["total_s"]
                                   + float(e.get("value") or 0.0), 6)
        for row in per_name.values():
            row["mean_s"] = round(row["total_s"] / row["n"], 6) \
                if row["n"] else 0.0
        out["spans"] = {"by_name": {k: per_name[k]
                                    for k in sorted(per_name)}}
        if span_events:
            out["spans"]["events"] = {k: span_events[k]
                                      for k in sorted(span_events)}
    serve = _serve_block(span_ends, histograms, gauges, gauge_series,
                         counters)
    if serve:
        out["serve"] = serve
    mem = _memory_block(memory_rows, memory_scopes, gauges, gauge_series)
    if mem:
        out["memory"] = mem
    fl = _fleet_block(fleet_polls)
    if fl:
        out["fleet"] = fl
    if health:
        out["health"] = health
    return out


def _downsample(series: list, cap: int = 64) -> list:
    if len(series) <= cap:
        return [list(p) for p in series]
    stride = len(series) / cap
    picked = [series[int(i * stride)] for i in range(cap - 1)]
    picked.append(series[-1])
    return [list(p) for p in picked]


def _serve_block(span_ends, histograms, gauges, gauge_series, counters):
    """The request-level serve telemetry view: per-request table from
    ``serve/request`` span ends, SLO percentiles from the streaming
    histograms (``Recorder.observe``), pool-occupancy gauges, the
    queue-depth trajectory, and the scheduler counters."""
    requests = [e for e in span_ends if e.get("name") == "serve/request"]
    serve_hists = {k: v for k, v in histograms.items()
                   if k.startswith("serve/")}
    serve_gauges = {k: v for k, v in gauges.items()
                    if k.startswith("serve/")}
    serve_counters = {k: v for k, v in counters.items()
                      if k.startswith("serve/")}
    if not (requests or serve_hists or serve_gauges or serve_counters):
        return None
    from apex_tpu.monitor import spans as spans_mod
    out: dict = {}
    if requests:
        rows = []
        for e in requests:
            row = {"seq_id": e.get("seq_id"),
                   "e2e_ms": round(1e3 * float(e.get("value") or 0.0), 3)}
            for k in ("prompt_tokens", "new_tokens", "preemptions",
                      "ttft_ms", "queue_wait_ms", "error"):
                if e.get(k) is not None:
                    row[k] = e[k]
            rows.append(row)
        rows.sort(key=lambda r: (r["seq_id"] is None, r["seq_id"]))
        out["requests"] = rows
    slo = {}
    for key in ("token_latency_ms", "ttft_ms", "queue_wait_ms"):
        snap = serve_hists.get(f"serve/{key}")
        if snap:
            slo[key] = spans_mod.hist_summary(snap, percentiles=(50, 95, 99))
    if slo:
        out["slo"] = slo
    pool = {k[len("serve/"):]: serve_gauges[k] for k in
            ("serve/pages_in_use", "serve/pages_free", "serve/pages_total",
             "serve/pool_bytes_in_use") if k in serve_gauges}
    if pool:
        out["pool"] = pool
    depth = gauge_series.get("serve/queue_depth")
    if depth:
        vals = [v for _, v in depth]
        out["queue_depth"] = {"max": max(vals), "last": vals[-1],
                              "trajectory": _downsample(depth)}
    fill = gauge_series.get("serve/batch_fill")
    if fill:
        vals = [v for _, v in fill]
        out["batch_fill_mean"] = round(sum(vals) / len(vals), 4)
    if serve_counters:
        out["counters"] = serve_counters
    if "serve/goodput_tokens_per_sec_chip" in serve_gauges:
        out["goodput_tokens_per_sec_chip"] = \
            serve_gauges["serve/goodput_tokens_per_sec_chip"]
    return out


def _memory_block(memory_rows, memory_scopes, gauges, gauge_series):
    """The unified memory view: per-program compiled footprints
    (``memory`` events), per-scope analytic peaks (``memory_scope``
    events), the live gauges, and the downsampled HBM timeline from
    the sampler's ``memory/hbm_bytes_in_use`` step gauge."""
    mem_gauges = {k: v for k, v in gauges.items()
                  if k.startswith("memory/")}
    if not (memory_rows or memory_scopes or mem_gauges):
        return None
    out: dict = {}
    if memory_rows:
        out["programs"] = {k: memory_rows[k]
                           for k in sorted(memory_rows)}
    if memory_scopes:
        # ties (the top jaxpr's output equation sees the same live
        # bytes under no scope) resolve to the NAMED scope
        from apex_tpu.monitor.profile import UNSCOPED
        peak = max(memory_scopes.items(),
                   key=lambda kv: (kv[1].get("peak_live_bytes") or 0,
                                   kv[0] != UNSCOPED))
        out["analytic"] = {
            "peak_live_bytes": peak[1].get("peak_live_bytes"),
            "peak_scope": peak[0],
            "scopes": {k: memory_scopes[k]
                       for k in sorted(memory_scopes)}}
    if mem_gauges:
        out["gauges"] = mem_gauges
    series = gauge_series.get("memory/hbm_bytes_in_use")
    if series:
        vals = [v for _, v in series]
        out["timeline"] = {"samples": len(vals), "max": max(vals),
                           "last": vals[-1],
                           "trajectory": _downsample(series)}
    return out


def _fleet_block(fleet_polls: list[dict]):
    """The multi-replica view recorded by ``monitor.fleet.FleetPoller``:
    the LAST poll is the fleet state (replica table, summed counters,
    min/max/sum gauge views, merged-histogram percentiles); alerts and
    scale decisions accumulate across all polls so a burn that fired and
    cleared mid-run still shows."""
    if not fleet_polls:
        return None
    last = fleet_polls[-1]
    alerts: list[dict] = []
    decisions: list[dict] = []
    for ev in fleet_polls:
        alerts.extend(ev.get("alerts") or [])
        decisions.extend(ev.get("decisions") or [])
    return {"polls": len(fleet_polls),
            "n_replicas": last.get("n_replicas"),
            "n_up": last.get("value"),
            "replicas": last.get("replicas") or [],
            "counters": last.get("counters") or {},
            "gauges": last.get("gauges") or {},
            "hist_summary": last.get("hist_summary") or {},
            "alerts": alerts,
            "decisions": decisions}


def measured_idle_fraction(agg: dict, schedule: str):
    """Convenience: the measured all-rank idle-slot fraction of one
    pipeline schedule from an :func:`aggregate` result (``None`` when
    the schedule recorded no tick marks). ``schedule`` matches the
    tick-mark name, e.g. ``"pipeline/zb1"``."""
    ranks = (agg.get("pipeline_utilization") or {}).get(schedule)
    if not ranks:
        return None
    return ranks["all"]["idle_fraction"]


def _fmt(v) -> str:
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e5 or abs(v) < 1e-4:
            return f"{v:.3e}"
        return f"{v:.6g}"
    return str(v)


def render_steps(events: list[dict], max_rows: int = 50) -> str:
    """Markdown per-step table: step index, step time, and every gauge
    column observed (loss scale, grad norm, ...)."""
    steps = [e for e in events if e.get("kind") == "step"]
    if not steps:
        return "(no step records)"
    gkeys = sorted({k for s in steps for k in (s.get("gauges") or {})})
    hdr = ["step", "time_ms"] + gkeys + ["collectives"]
    lines = ["| " + " | ".join(hdr) + " |",
             "|" + "---|" * len(hdr)]
    for s in steps[:max_rows]:
        colls = s.get("collectives") or {}
        ncoll = sum(c.get("count", 0) for c in colls.values())
        row = [str(s.get("step")),
               f"{1e3 * float(s.get('step_time_s') or 0.0):.3f}"]
        row += [_fmt(s["gauges"][k]) if k in (s.get("gauges") or {}) else ""
                for k in gkeys]
        row.append(str(ncoll))
        lines.append("| " + " | ".join(row) + " |")
    if len(steps) > max_rows:
        lines.append(f"... ({len(steps) - max_rows} more steps)")
    return "\n".join(lines)


def render_serve(agg: dict, max_rows: int = 50) -> Optional[str]:
    """Render the ``serve`` block of an :func:`aggregate` result: SLO
    percentiles (span-derived), pool occupancy, queue trajectory, and
    the per-request span table. ``None`` when no serve telemetry was
    recorded. Used by ``render_report`` and ``examples/serve_gpt.py
    --monitor``."""
    sv = agg.get("serve")
    if not sv:
        return None
    parts = ["## serve (request-level telemetry)\n"]
    if sv.get("goodput_tokens_per_sec_chip") is not None:
        parts.append(f"goodput: "
                     f"{_fmt(sv['goodput_tokens_per_sec_chip'])} "
                     f"tokens/sec/chip")
    slo = sv.get("slo") or {}
    for key, label in (("token_latency_ms", "token latency"),
                       ("ttft_ms", "time to first token"),
                       ("queue_wait_ms", "queue wait")):
        row = slo.get(key)
        if row:
            parts.append(
                f"{label} ms: p50 {_fmt(row.get('p50'))}  "
                f"p95 {_fmt(row.get('p95'))}  p99 {_fmt(row.get('p99'))}  "
                f"(n={row.get('count')}, mean {_fmt(row.get('mean'))})")
    pool = sv.get("pool") or {}
    if pool:
        total = pool.get("pages_total")
        used = pool.get("pages_in_use")
        pct = f" ({100.0 * used / total:.1f}%)" \
            if total and used is not None else ""
        nbytes = pool.get("pool_bytes_in_use")
        tail = f", {_fmt(nbytes)} bytes" if nbytes is not None else ""
        parts.append(f"pool: {used}/{total} pages in use{pct}{tail}")
    qd = sv.get("queue_depth")
    line = []
    if qd:
        line.append(f"queue depth: max {_fmt(qd['max'])} "
                    f"last {_fmt(qd['last'])}")
    if sv.get("batch_fill_mean") is not None:
        line.append(f"batch fill mean {sv['batch_fill_mean']}")
    pre = (sv.get("counters") or {}).get("serve/preemptions")
    if pre is not None:
        line.append(f"preemptions {_fmt(pre)}")
    if line:
        parts.append("; ".join(line))
    reqs = sv.get("requests") or []
    if reqs:
        parts.append("")
        parts.append("| request | prompt | new tokens | queue ms | "
                     "ttft ms | e2e ms | preempts |\n"
                     "|---|---|---|---|---|---|---|")
        for r in reqs[:max_rows]:
            parts.append(
                f"| {r.get('seq_id')} | {r.get('prompt_tokens', '')} "
                f"| {r.get('new_tokens', '')} "
                f"| {_fmt(r.get('queue_wait_ms', ''))} "
                f"| {_fmt(r.get('ttft_ms', ''))} "
                f"| {_fmt(r.get('e2e_ms', ''))} "
                f"| {r.get('preemptions', 0)} |")
        if len(reqs) > max_rows:
            parts.append(f"... ({len(reqs) - max_rows} more requests)")
    return "\n".join(parts)


def _fmt_bytes(v) -> str:
    if v is None or v == "":
        return ""
    v = float(v)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}B"


def render_memory(agg: dict, max_rows: int = 30) -> Optional[str]:
    """Render the ``memory`` block of an :func:`aggregate` result:
    per-program footprint table, per-scope analytic peaks, the live
    gauges and the HBM timeline summary. ``None`` when no memory
    telemetry was recorded. Used by ``render_report`` and the
    ``python -m apex_tpu.monitor memory`` CLI."""
    mem = agg.get("memory")
    if not mem:
        return None
    parts = ["## memory\n"]
    progs = mem.get("programs") or {}
    if progs:
        parts.append("| program | total | argument | output | temp | "
                     "analytic peak | peak scope |\n"
                     "|---|---|---|---|---|---|---|")
        for name in sorted(progs):
            row = progs[name]
            parts.append(
                f"| {name} | {_fmt_bytes(row.get('total_bytes'))} "
                f"| {_fmt_bytes(row.get('argument_size_in_bytes', row.get('argument_bytes')))} "
                f"| {_fmt_bytes(row.get('output_size_in_bytes', row.get('output_bytes')))} "
                f"| {_fmt_bytes(row.get('temp_size_in_bytes'))} "
                f"| {_fmt_bytes(row.get('analytic_peak_bytes'))} "
                f"| {row.get('peak_scope', '')} |")
    analytic = mem.get("analytic") or {}
    scopes = analytic.get("scopes") or {}
    if scopes:
        parts.append(
            f"\nanalytic high water: "
            f"{_fmt_bytes(analytic.get('peak_live_bytes'))} at scope "
            f"`{analytic.get('peak_scope')}`\n")
        parts.append("| scope | peak live | eqns |\n|---|---|---|")
        order = sorted(scopes.items(),
                       key=lambda kv: -(kv[1].get("peak_live_bytes")
                                        or 0))
        for name, row in order[:max_rows]:
            parts.append(f"| {name} "
                         f"| {_fmt_bytes(row.get('peak_live_bytes'))} "
                         f"| {row.get('eqns', '')} |")
        if len(order) > max_rows:
            parts.append(f"... ({len(order) - max_rows} more scopes)")
    tl = mem.get("timeline")
    if tl:
        parts.append(f"\nhbm timeline: {tl['samples']} samples, "
                     f"max {_fmt_bytes(tl['max'])}, "
                     f"last {_fmt_bytes(tl['last'])}")
    g = mem.get("gauges") or {}
    line = []
    if "memory/hbm_bytes_in_use" in g:
        line.append(f"in use {_fmt_bytes(g['memory/hbm_bytes_in_use'])}")
    if "memory/hbm_limit_bytes" in g:
        line.append(f"limit {_fmt_bytes(g['memory/hbm_limit_bytes'])}")
    if "memory/hbm_utilization" in g:
        line.append(f"utilization "
                    f"{100.0 * g['memory/hbm_utilization']:.2f}%")
    if line:
        parts.append("hbm: " + ", ".join(line))
    return "\n".join(parts)


def render_fleet(agg: dict, max_rows: int = 30) -> Optional[str]:
    """Render the ``fleet`` block of an :func:`aggregate` result:
    per-replica up/age table from the last poll, fleet-summed counters,
    merged-histogram percentiles, and every ``slo_alert`` /
    ``scale_decision`` accumulated across polls. ``None`` when no fleet
    polls were recorded. Used by ``render_report`` and the
    ``python -m apex_tpu.monitor fleet`` CLI docs."""
    fl = agg.get("fleet")
    if not fl:
        return None
    parts = ["## fleet (multi-replica aggregation)\n"]
    parts.append(f"replicas up: {fl.get('n_up')}/{fl.get('n_replicas')} "
                 f"(over {fl.get('polls')} polls)")
    reps = fl.get("replicas") or []
    if reps:
        parts.append("\n| replica | endpoint | up | age s | error |\n"
                     "|---|---|---|---|---|")
        for r in reps[:max_rows]:
            age = r.get("age_s")
            parts.append(
                f"| {r.get('replica')} | {r.get('endpoint')} "
                f"| {r.get('up')} | {_fmt(age) if age is not None else ''} "
                f"| {r.get('error') or ''} |")
    ctr = fl.get("counters") or {}
    if ctr:
        keep = sorted(ctr)[:max_rows]
        parts.append("\n| counter (fleet sum) | total |\n|---|---|")
        for k in keep:
            parts.append(f"| {k} | {_fmt(ctr[k])} |")
        if len(ctr) > max_rows:
            parts.append(f"... ({len(ctr) - max_rows} more counters)")
    hs = fl.get("hist_summary") or {}
    for name in sorted(hs):
        row = hs[name]
        parts.append(f"{name} (merged): p50 {_fmt(row.get('p50'))}  "
                     f"p95 {_fmt(row.get('p95'))}  "
                     f"p99 {_fmt(row.get('p99'))}  "
                     f"(n={row.get('count')}, mean {_fmt(row.get('mean'))})")
    for a in (fl.get("alerts") or [])[:max_rows]:
        parts.append(f"- ALERT **{a.get('slo')}** [{a.get('severity')}] "
                     f"window={a.get('window')}: {a.get('diagnosis')}")
    for d in (fl.get("decisions") or [])[:max_rows]:
        parts.append(f"- DECISION **{d.get('decision')}** "
                     f"[{d.get('severity')}]: {d.get('rationale')}")
    return "\n".join(parts)


def render_report(events: list[dict], header: Optional[dict] = None,
                  max_rows: int = 50) -> str:
    """Full human-readable report: per-step table + aggregates."""
    agg = aggregate(events, header=header)
    parts = []
    run = agg.get("run", {})
    title = run.get("name") or "run"
    parts.append(f"# monitor report: {title}")
    if run.get("dropped"):
        parts.append(f"(ring buffer dropped {run['dropped']} events)")
    if agg.get("health"):
        parts.append("\n## health\n")
        for ev in agg["health"][:max_rows]:
            loc = f"step {ev['step']}" if ev.get("step") is not None else \
                (f"rank {ev['rank']}" if ev.get("rank") is not None else "-")
            parts.append(f"- **{ev.get('name')}** [{ev.get('severity')}] "
                         f"({loc}): {ev.get('diagnosis')}")
    serve = render_serve(agg, max_rows=max_rows)
    if serve:
        parts.append("\n" + serve)
    mem = render_memory(agg, max_rows=max_rows)
    if mem:
        parts.append("\n" + mem)
    fl = render_fleet(agg, max_rows=max_rows)
    if fl:
        parts.append("\n" + fl)
    parts.append("\n## per-step\n")
    parts.append(render_steps(events, max_rows=max_rows))
    if "steps" in agg:
        st = agg["steps"]["step_time_s"]
        parts.append(
            f"\nsteps: {agg['steps']['count']}  "
            f"step time ms: median {1e3 * st['median']:.3f}  "
            f"mean {1e3 * st['mean']:.3f}  "
            f"min {1e3 * st['min']:.3f}  max {1e3 * st['max']:.3f}")
    if agg.get("collectives"):
        parts.append("\n## collectives (per traced program)\n")
        parts.append("| collective | count | bytes |\n|---|---|---|")
        for k, v in agg["collectives"].items():
            parts.append(f"| {k} | {v['count']} | {v['bytes']} |")
    if agg.get("schedules"):
        parts.append("\n## pipeline schedules\n")
        parts.append("| schedule | stages | microbatches | ticks | "
                     "bubble |\n|---|---|---|---|---|")
        for k, v in agg["schedules"].items():
            parts.append(
                f"| {k} | {v.get('n_stages')} | {v.get('n_microbatches')} "
                f"| {v.get('total_ticks')} | {v.get('bubble_fraction')} |")
    if agg.get("pipeline_utilization"):
        parts.append("\n## pipeline utilization (measured slot "
                     "occupancy)\n")
        parts.append("| schedule | rank | ticks | slots | valid | "
                     "per-slot valid/total | idle |\n"
                     "|---|---|---|---|---|---|---|")
        for sched, ranks in agg["pipeline_utilization"].items():
            order = sorted((r for r in ranks if r != "all"), key=int)
            for rank in order + ["all"]:
                row = ranks[rank]
                per = " ".join(
                    f"{s}:{v['valid']}/{v['total']}"
                    for s, v in sorted(row.get("by_slot", {}).items()))
                parts.append(
                    f"| {sched} | {rank} | {row.get('ticks', '')} "
                    f"| {row['slots_total']} | {row['slots_valid']} "
                    f"| {per} | {row['idle_fraction']} |")
    if agg.get("profile"):
        prof = agg["profile"]
        parts.append("\n## profile (per-module cost attribution)\n")
        analytic = prof.get("analytic") or {}
        measured = prof.get("measured") or {}
        names = sorted(set(analytic) | set(measured),
                       key=lambda n: -(analytic.get(n, {}).get("flops")
                                       or 0))
        parts.append("| scope | flops | hbm bytes | coll bytes | "
                     "wall ms (measured) |\n|---|---|---|---|---|")
        for n in names[:max_rows]:
            a = analytic.get(n, {})
            m = measured.get(n)
            wall = f"{1e3 * m['mean_s']:.3f}" if m else ""
            parts.append(
                f"| {n} | {_fmt(a.get('flops', ''))} "
                f"| {_fmt(a.get('hbm_bytes', ''))} "
                f"| {_fmt(a.get('collective_bytes', ''))} | {wall} |")
    if agg.get("timers"):
        parts.append("\n## timers\n")
        parts.append("| timer | n | total s | mean s |\n|---|---|---|---|")
        for k, v in agg["timers"].items():
            parts.append(f"| {k} | {v['n']} | {_fmt(v['total_s'])} | "
                         f"{_fmt(v['mean_s'])} |")
    if agg.get("counters"):
        parts.append("\n## counters\n")
        parts.append("| counter | total |\n|---|---|")
        for k, v in agg["counters"].items():
            parts.append(f"| {k} | {_fmt(v)} |")
    return "\n".join(parts)


def render_cross_host(merged: dict, max_rows: int = 50) -> str:
    """Human-readable render of a ``merge.merge_summaries`` cross-host
    view: summed collective table, per-rank step-time skew, straggler
    percentiles for the host timers, and any health events."""
    parts = [f"# monitor cross-host report: {merged.get('n_ranks')} ranks "
             f"{merged.get('ranks')}"]
    if merged.get("health_events"):
        parts.append("\n## health\n")
        for ev in merged["health_events"][:max_rows]:
            parts.append(f"- **{ev.get('name')}** [{ev.get('severity')}] "
                         f"(rank {ev.get('rank')}): {ev.get('diagnosis')}")
    st = merged.get("steps")
    if st:
        sk = st["skew"]
        parts.append("\n## step-time skew per rank\n")
        parts.append("| rank | steps | median ms | x global median |\n"
                     "|---|---|---|---|")
        for rank in sorted(st["by_rank"], key=int):
            d = st["by_rank"][rank]
            ratio = (sk.get("per_rank_ratio") or {}).get(rank)
            parts.append(f"| {rank} | {d.get('count')} "
                         f"| {1e3 * d['median']:.3f} | {ratio} |")
        parts.append(f"\nslowest rank: {sk.get('slowest_rank')}  "
                     f"(max/median = {sk.get('max_over_median')})")
    if merged.get("collectives"):
        parts.append("\n## collectives (summed across ranks, "
                     "per traced program)\n")
        parts.append("| collective | count | bytes |\n|---|---|---|")
        for k, v in merged["collectives"].items():
            parts.append(f"| {k} | {v['count']} | {v['bytes']} |")
    if merged.get("timers"):
        parts.append("\n## timers (per-rank means, straggler "
                     "percentiles)\n")
        parts.append("| timer | median mean_s | max mean_s | max/median "
                     "| slowest rank |\n|---|---|---|---|---|")
        for k, v in merged["timers"].items():
            parts.append(
                f"| {k} | {_fmt(v.get('mean_s_median'))} "
                f"| {_fmt(v.get('mean_s_max'))} "
                f"| {v.get('max_over_median')} "
                f"| {v.get('slowest_rank')} |")
    return "\n".join(parts)


# ---------------------------------------------------------------------------
# selfcheck: the CI smoke for the whole pipeline
# ---------------------------------------------------------------------------

def selfcheck(n_steps: int = 3, verbose: bool = True) -> dict:
    """Record a synthetic ``n_steps``-step amp training run on CPU with
    a recorder attached, dump + reload the JSONL, and assert the report
    round-trips with the per-step fields the acceptance contract names
    (loss scale, grad norm, step time, collective table). Returns the
    aggregate. Raises AssertionError on any missing piece — wired into
    ``scripts/ci.sh``."""
    import io
    import jax.numpy as jnp
    from apex_tpu import monitor
    from apex_tpu.amp import scaler as scaler_mod
    from apex_tpu.optimizers import FusedSGD

    def loss_fn(params, x, y):
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean((h @ params["w2"] - y) ** 2)

    from apex_tpu import amp
    opt = FusedSGD(lr=0.05)
    params = {"w1": jnp.ones((4, 8), jnp.float32) * 0.1,
              "w2": jnp.ones((8, 2), jnp.float32) * 0.1}
    opt_state = opt.init(params)
    sstate = scaler_mod.init_state(2.0 ** 8)
    step = amp.make_train_step(loss_fn, opt, donate=False)
    x = jnp.ones((2, 4), jnp.float32)
    y = jnp.ones((2, 2), jnp.float32)

    rec = monitor.Recorder(name="selfcheck")
    monitor.trace.install_compile_logging()
    with monitor.attached(rec):
        for _ in range(n_steps):
            with rec.step():
                params, opt_state, sstate, loss = step(
                    params, opt_state, sstate, x, y)

    buf = io.StringIO()
    rec.dump_jsonl(buf)
    buf.seek(0)
    header, events = load_jsonl(buf)
    steps = [e for e in events if e["kind"] == "step"]
    assert len(steps) == n_steps, (len(steps), n_steps)
    for s in steps:
        assert "step_time_s" in s and s["step_time_s"] > 0, s
        assert "amp/loss_scale" in s["gauges"], s["gauges"]
        assert "optim/grad_norm" in s["gauges"], s["gauges"]
        assert "collectives" in s, s
    agg = aggregate(events, header=header)
    assert agg["steps"]["count"] == n_steps
    assert "amp/loss_scale" in agg["steps"]["gauges"]
    rendered = render_report(events, header=header)
    assert "monitor report" in rendered and "amp/loss_scale" in rendered
    # disabled-mode guarantee: a fresh trace with no recorder attached
    # carries no callback effects
    import jax
    jaxpr = str(jax.make_jaxpr(
        lambda p, o, s, x, y: scaler_mod.update(
            s, jnp.asarray(False), dynamic=True))(
                params, opt_state, sstate, x, y))
    assert "callback" not in jaxpr, "hooks active while detached"
    if verbose:
        print(rendered)
        print(f"\nmonitor selfcheck ok: {n_steps} steps, "
              f"{len(events)} events round-tripped")
    return agg
