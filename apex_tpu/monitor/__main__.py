"""CLI: render recorder dumps and smoke-test the telemetry pipeline.

    python -m apex_tpu.monitor report run.jsonl [--json] [--max-rows N]
    python -m apex_tpu.monitor merge SHARD... [--json] [-o OUT.json]
    python -m apex_tpu.monitor timeline DUMP... [-o trace.json]
                                       [--no-align] [--validate-only]
    python -m apex_tpu.monitor profile [--model gpt|mlp] [--measured]
    python -m apex_tpu.monitor memory [--model gpt|mlp|zero|serve]
                                      [--live] [--json]
    python -m apex_tpu.monitor regress RUNS... [--against BASELINE.json]
    python -m apex_tpu.monitor export run.jsonl [--once [--check]|--port N]
    python -m apex_tpu.monitor fleet ENDPOINT... [--watch|--once] [--json]
    python -m apex_tpu.monitor selfcheck [--steps N]

``report`` renders the per-step and aggregate tables from a
``Recorder.dump_jsonl`` file (the ``pyprof.prof`` analog — per-step
training telemetry instead of per-kernel nvprof records). ``merge``
combines rank-tagged shards (``monitor-<rank>.jsonl`` files, glob
patterns, or a directory holding them; flight dumps work too) from a
multi-process run into one cross-host view: collective bytes summed
across ranks, per-rank timer distributions with straggler percentiles,
per-rank step-time skew — and exits non-zero with a clear message when
zero shards match. ``timeline`` fuses the same shards and/or crash
``flight-<rank>.jsonl`` dumps (``apex_tpu.monitor.flight``) into one
Chrome-trace/Perfetto JSON — span trees, compile events, ``memory/
hbm_*`` counter tracks, health instants, one process track per rank,
cross-rank clock alignment on step boundaries, and a per-step
straggler overlay; open the output in https://ui.perfetto.dev or
chrome://tracing. ``profile``
builds a model train step (GPT by default; shape knobs below) and
prints the per-module cost attribution table — analytic FLOPs/bytes
per profile scope, optionally merged with measured eager wall times
(``--measured``) and an XProf per-op table (``--per-op``, subsuming
the old ``scripts/profile_gpt.py``). ``regress`` loads bench evidence
rounds (driver ``BENCH_r*.json`` wrappers, assembled bench JSON, or
``bench_stream.jsonl`` streams), degrades per round, and renders
noise-aware verdicts — exit status is non-zero only on a confirmed
regression. ``export`` renders a recorder JSONL dump/stream as
Prometheus text exposition — ``--once`` to stdout (``--check``
additionally parses the output back and asserts scrape == aggregate;
the ``scripts/ci.sh`` export stage), otherwise served over HTTP with
the file re-read per scrape. ``fleet`` polls N replica exports — live
``/metrics`` URLs and/or exposition files — and renders the per-replica
+ fleet table (counters summed, gauges min/max/sum, histograms merged
bucket-wise) with SLO burn-rate alerts and autoscale decisions;
``--once`` exits non-zero when an alert fires (the CI fleet stage).
``selfcheck`` records a synthetic 3-step amp run on CPU and asserts
the dump → report round trip (used by ``scripts/ci.sh``).

``profile`` also reports **MFU** (model FLOPs utilization): the
analytic step FLOPs divided by measured wall time and the
per-``device_kind`` peak-FLOPs table (``--peak-tflops`` overrides the
table; ``--no-mfu`` skips the timed execution).

``memory`` is the unified byte view (``monitor.memory``): for
``--model gpt|mlp`` it prints the compiled footprint
(``Compiled.memory_analysis``) and the analytic high-water walk's
per-scope peak table for the canonical train step (the ``profile``
recipe), plus the ``vmem_calibration`` tuner feedback rows;
``--live`` additionally runs the step under a :class:`MemorySampler`
and reports the HBM timeline. ``--model zero`` prints the ZeRO
dense/zero2/zero3 per-chip residency split measured through
``memory.resident_bytes`` (the PR 6 ratio, re-derived live);
``--model serve`` prints the KV-pool occupancy/capacity accounting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m apex_tpu.monitor")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("report", help="render a recorder JSONL dump")
    pr.add_argument("path", help="JSONL file from Recorder.dump_jsonl")
    pr.add_argument("--json", action="store_true",
                    help="print the aggregate as JSON instead of tables")
    pr.add_argument("--max-rows", type=int, default=50,
                    help="per-step table row cap")

    pm = sub.add_parser("merge",
                        help="merge rank-tagged shards into a "
                             "cross-host report")
    pm.add_argument("shards", nargs="+",
                    help="monitor-<rank>.jsonl files, glob patterns, "
                         "or one directory containing shards")
    pm.add_argument("--json", action="store_true",
                    help="print the merged view as JSON")
    pm.add_argument("-o", "--out", default=None,
                    help="also write the merged JSON here")

    pt = sub.add_parser("timeline",
                        help="fuse shards/flight dumps into one "
                             "Chrome-trace (Perfetto) JSON")
    pt.add_argument("dumps", nargs="+",
                    help="monitor-<rank>.jsonl / flight-<rank>.jsonl "
                         "files, glob patterns, or directories")
    pt.add_argument("-o", "--out", default="trace.json",
                    help="output trace path (default: trace.json)")
    pt.add_argument("--no-align", action="store_true",
                    help="skip cross-rank clock alignment")
    pt.add_argument("--straggler-ratio", type=float, default=None,
                    help="per-step slowest/median bar for straggler "
                         "instants (default 1.5)")
    pt.add_argument("--validate-only", action="store_true",
                    help="build + shape-check without writing the "
                         "trace (the CI gate mode)")

    pp = sub.add_parser("profile",
                        help="per-module cost attribution for a model "
                             "train step")
    pp.add_argument("--model", choices=("gpt", "mlp"), default="gpt")
    pp.add_argument("--batch", type=int, default=2)
    pp.add_argument("--seq", type=int, default=64)
    pp.add_argument("--hidden", type=int, default=64)
    pp.add_argument("--layers", type=int, default=2)
    pp.add_argument("--heads", type=int, default=2)
    pp.add_argument("--vocab", type=int, default=256)
    pp.add_argument("--dtype", choices=("float32", "bfloat16"),
                    default="float32")
    pp.add_argument("--attention", choices=("fused_softmax", "flash"),
                    default="fused_softmax",
                    help="fused_softmax keeps every matmul visible to "
                         "the analytic FLOP model; flash traces the "
                         "Pallas kernel (0 analytic FLOPs)")
    pp.add_argument("--fused-lm-head", action="store_true",
                    help="fuse the LM-head CE kernel (Pallas; 0 "
                         "analytic FLOPs for the head)")
    pp.add_argument("--measured", action="store_true",
                    help="also sample per-scope wall time eagerly "
                         "(jax.disable_jit)")
    pp.add_argument("--repeats", type=int, default=3,
                    help="eager repeats for --measured")
    pp.add_argument("--per-op", action="store_true",
                    help="also run an XProf trace and print the per-op "
                         "table (needs a device; the old "
                         "scripts/profile_gpt.py output)")
    pp.add_argument("--json", action="store_true")
    pp.add_argument("--max-rows", type=int, default=40)
    pp.add_argument("--mfu-repeats", type=int, default=3,
                    help="timed executions of the step for the MFU "
                         "wall-time denominator (median taken)")
    pp.add_argument("--peak-tflops", type=float, default=None,
                    help="peak TFLOP/s override for the MFU "
                         "denominator (default: the per-device_kind "
                         "table in monitor.profile)")
    pp.add_argument("--no-mfu", action="store_true",
                    help="skip the timed step execution + MFU line")

    pmem = sub.add_parser("memory",
                          help="unified memory view: compiled "
                               "footprint + analytic high water per "
                               "scope (+ZeRO/serve capacity reports)")
    pmem.add_argument("--model", choices=("gpt", "mlp", "zero", "serve"),
                      default="gpt")
    pmem.add_argument("--live", action="store_true",
                      help="also execute the step under a "
                           "MemorySampler and report the HBM timeline "
                           "(gpt/mlp models)")
    pmem.add_argument("--steps", type=int, default=3,
                      help="steps to execute under --live")
    pmem.add_argument("--interval", type=float, default=0.05,
                      help="sampler interval seconds for --live")
    pmem.add_argument("--no-calibration", action="store_true",
                      help="skip the tune/vmem calibration rows")
    pmem.add_argument("--json", action="store_true")
    pmem.add_argument("--max-rows", type=int, default=30)

    pg = sub.add_parser("regress",
                        help="bench-trajectory verdicts over evidence "
                             "rounds")
    pg.add_argument("runs", nargs="+",
                    help="evidence rounds in chronological order: "
                         "BENCH_r*.json driver wrappers, assembled "
                         "bench JSON, or bench_stream.jsonl streams")
    pg.add_argument("--against", default=None, metavar="BASELINE.json",
                    help="extra baseline round prepended to the history")
    pg.add_argument("--json", action="store_true")
    pg.add_argument("--nmad", type=float, default=3.0,
                    help="MAD multiplier for the noise band")
    pg.add_argument("--rel-tol", type=float, default=0.05,
                    help="relative floor of the noise band")
    pg.add_argument("--min-history", type=int, default=3,
                    help="comparable prior rounds required before a "
                         "regression verdict can gate")

    pe = sub.add_parser("export",
                        help="Prometheus text exposition from a "
                             "recorder JSONL dump/stream")
    pe.add_argument("path", help="Recorder.dump_jsonl file or "
                                 "bench/serve evidence stream")
    pe.add_argument("--once", action="store_true",
                    help="render one snapshot to stdout and exit")
    pe.add_argument("--check", action="store_true",
                    help="with --once: parse the emitted text back and "
                         "assert scrape == aggregate (CI self-check)")
    pe.add_argument("--port", type=int, default=9464)
    pe.add_argument("--addr", default="127.0.0.1")

    pf = sub.add_parser("fleet",
                        help="poll replica exports; fleet aggregate + "
                             "SLO burn-rate alerts + scale decisions")
    pf.add_argument("endpoints", nargs="+",
                    help="replica /metrics URLs and/or exposition "
                         "file paths")
    pf.add_argument("--once", action="store_true",
                    help="poll once and exit (non-zero when an SLO "
                         "alert fires) — the default mode")
    pf.add_argument("--watch", action="store_true",
                    help="poll repeatedly until interrupted")
    pf.add_argument("--json", action="store_true",
                    help="print each poll view as one JSON line")
    pf.add_argument("--interval", type=float, default=10.0,
                    help="--watch poll interval seconds")
    pf.add_argument("--timeout", type=float, default=2.0,
                    help="per-replica scrape timeout seconds")

    ps = sub.add_parser("selfcheck",
                        help="record a synthetic run; assert round-trip")
    ps.add_argument("--steps", type=int, default=3)
    ps.add_argument("--quiet", action="store_true")

    args = p.parse_args(argv)
    from apex_tpu.monitor import report as report_mod

    from apex_tpu.monitor.recorder import json_safe

    if args.cmd == "report":
        header, events = report_mod.load_jsonl(args.path)
        if args.json:
            print(json.dumps(
                json_safe(report_mod.aggregate(events, header=header)),
                indent=2))
        else:
            print(report_mod.render_report(events, header=header,
                                           max_rows=args.max_rows))
        return 0

    if args.cmd == "merge":
        from apex_tpu.monitor import merge as merge_mod
        from apex_tpu.monitor.timeline import _expand
        if len(args.shards) == 1 and os.path.isdir(args.shards[0]):
            shards = args.shards[0]   # directory; merge_shards resolves
            missing_msg = (f"no monitor shards found: no "
                           f"monitor-<rank>.jsonl or flight-<rank>."
                           f"jsonl in directory {args.shards[0]!r}")
        else:
            shards = _expand(args.shards)   # globs + files, deduped
            missing_msg = (f"no monitor shards found: nothing matched "
                           f"{' '.join(args.shards)!r}")
        try:
            merged = json_safe(merge_mod.merge_shards(shards))
        except ValueError as e:
            if "no monitor shards" in str(e):
                print(missing_msg, file=sys.stderr)
                return 2
            raise
        if args.out:
            with open(args.out, "w") as f:
                json.dump(merged, f, indent=2)
        if args.json:
            print(json.dumps(merged, indent=2))
        else:
            print(report_mod.render_cross_host(merged))
        return 0

    if args.cmd == "timeline":
        from apex_tpu.monitor import timeline as timeline_mod
        sources = timeline_mod.load_sources(args.dumps)
        if not sources:
            print(f"no recorder dumps found: nothing matched "
                  f"{' '.join(args.dumps)!r}", file=sys.stderr)
            return 2
        kw = {}
        if args.straggler_ratio is not None:
            kw["straggler_ratio"] = args.straggler_ratio
        trace = timeline_mod.build_timeline(
            sources, align=not args.no_align, **kw)
        problems = timeline_mod.validate_timeline(trace)
        if problems:
            for pr_ in problems[:20]:
                print(f"timeline shape error: {pr_}", file=sys.stderr)
            return 1
        n_ev = len(trace["traceEvents"])
        if args.validate_only:
            print(f"timeline ok: {n_ev} events across "
                  f"{len(sources)} rank(s) (not written)")
            return 0
        timeline_mod.write_timeline(trace, args.out)
        print(f"timeline: {n_ev} events across {len(sources)} rank(s) "
              f"-> {args.out} (open in https://ui.perfetto.dev or "
              f"chrome://tracing)")
        return 0

    if args.cmd == "regress":
        from apex_tpu.monitor import regress as regress_mod
        rounds = regress_mod.load_rounds(args.runs)
        against = (regress_mod.load_round(args.against)
                   if args.against else None)
        rep = regress_mod.compare(rounds, against=against, nmad=args.nmad,
                                  rel_tol=args.rel_tol,
                                  min_history=args.min_history)
        if args.json:
            print(json.dumps(json_safe(rep), indent=2))
        else:
            print(regress_mod.render_regress(rep))
        return rep["exit_code"]

    if args.cmd == "export":
        from apex_tpu.monitor import export as export_mod
        return export_mod.main(args)

    if args.cmd == "fleet":
        from apex_tpu.monitor import fleet as fleet_mod
        return fleet_mod.main(args)

    if args.cmd == "profile":
        return _run_profile(args)

    if args.cmd == "memory":
        return _run_memory(args)

    # selfcheck needs a backend; default to CPU unless the caller chose
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report_mod.selfcheck(n_steps=args.steps, verbose=not args.quiet)
    return 0


def _run_profile(args) -> int:
    from apex_tpu.monitor import profile as profile_mod
    from apex_tpu.monitor.recorder import json_safe

    # the ONE step recipe shared with the bench `profile` section
    step, step_args = profile_mod.demo_train_step(
        args.model, batch=args.batch, seq=args.seq, hidden=args.hidden,
        layers=args.layers, heads=args.heads, vocab=args.vocab,
        dtype=args.dtype, attention=args.attention,
        fused_lm_head=args.fused_lm_head)
    prof = profile_mod.analytic_profile(step, *step_args)
    measured = None
    if args.measured:
        measured = profile_mod.measured_profile(step, *step_args,
                                                repeats=args.repeats)
    mfu_row = None
    if not args.no_mfu:
        peak = (args.peak_tflops * 1e12
                if args.peak_tflops is not None else None)
        mfu_row = profile_mod.measured_mfu(
            step, step_args, flops=prof["total"]["flops"], peak=peak,
            repeats=args.mfu_repeats)
    if args.json:
        print(json.dumps(json_safe(
            {"analytic": prof, "measured": measured,
             "mfu": mfu_row}), indent=2))
    else:
        print(profile_mod.render_profile(prof, measured=measured,
                                         max_rows=args.max_rows))
        if mfu_row is not None:
            print(profile_mod.render_mfu(mfu_row))
    if args.per_op:
        # with --json, stdout must stay ONE parseable document: the
        # human-readable per-op table moves to stderr
        _profile_per_op(step, step_args,
                        out=sys.stderr if args.json else sys.stdout)
    return 0


def _run_memory(args) -> int:
    from apex_tpu import monitor
    from apex_tpu.monitor import memory as memory_mod
    from apex_tpu.monitor import profile as profile_mod
    from apex_tpu.monitor.recorder import json_safe

    out: dict = {"model": args.model}
    rendered: list = []
    if args.model == "zero":
        out["zero"] = memory_mod.zero_memory_report()
        pc = out["zero"]["per_chip_bytes"]
        rendered.append("# memory: ZeRO residency split (per-chip "
                        "resident param+opt bytes, measured)")
        rendered.append("| config | per-chip bytes | compiled temp |\n"
                        "|---|---|---|")
        for which in ("dense", "zero2", "zero3"):
            temp = (out["zero"]["compiled"].get(which) or {}).get(
                "temp_size_in_bytes", "")
            rendered.append(f"| {which} | {pc[which]} | {temp} |")
        rendered.append(
            f"\ndense/zero3 ratio: "
            f"{out['zero']['dense_over_zero3_ratio']} at world="
            f"{out['zero']['world_size']} (~world# within padding + "
            f"replicated-bias slack)")
    elif args.model == "serve":
        out["serve_pool"] = memory_mod.serve_pool_report()
        sp = out["serve_pool"]
        rendered.append("# memory: serve KV-pool accounting")
        rendered.append(
            f"pool {sp['pool_bytes']} B ({sp['usable_pages']} usable "
            f"pages x {sp['bytes_per_page']} B); occupancy "
            f"{sp['occupancy']} ({sp['pages_in_use']} pages, "
            f"{sp['bytes_in_use']} B in use)")
        rendered.append(
            f"capacity at the same pool budget: bf16 "
            f"{sp['bf16_seqs_at_budget']} vs fp8 "
            f"{sp['fp8_seqs_at_budget']} concurrent seqs "
            f"(ratio {sp['fp8_capacity_ratio']})")
    else:
        step, step_args = profile_mod.demo_train_step(args.model)
        prof = memory_mod.memory_profile(step, *step_args,
                                         label=f"{args.model}_step")
        out["profile"] = prof
        rendered.append(memory_mod.render_memory_profile(
            prof, max_rows=args.max_rows))
        if args.live:
            import jax
            rec = monitor.Recorder(name="memory-cli",
                                   traced_hooks=False)
            with monitor.attached(rec), \
                    memory_mod.MemorySampler(args.interval):
                for _ in range(max(1, args.steps)):
                    step_out = step(*step_args)
                jax.block_until_ready(step_out)
            agg = rec.aggregate()
            out["live"] = {"memory": agg.get("memory"),
                           "histograms": agg.get("histograms")}
            from apex_tpu.monitor import report as report_mod
            live_render = report_mod.render_memory(agg)
            if live_render:
                rendered.append("\n# live HBM timeline "
                                "(MemorySampler)\n")
                rendered.append(live_render)
    if not args.no_calibration and args.model in ("gpt", "mlp"):
        cal = memory_mod.vmem_calibration()
        out["vmem_calibration"] = cal
        rendered.append(f"\nvmem calibration: {cal['checked']} kernel "
                        f"config(s) checked, {cal['mispredicts']} "
                        f"envelope mispredict(s)")
        for row in cal["rows"]:
            rendered.append(
                f"- {row['kernel']} [{row['source']}] "
                f"{row['config']}: predicted "
                f"{row['predicted_vmem_bytes']} B vs compiled temp "
                f"{row['measured_temp_bytes']} B"
                f"{'  ** MISPREDICT **' if row['mispredict'] else ''}")
    if args.json:
        print(json.dumps(json_safe(out), indent=2))
    else:
        print("\n".join(rendered))
    return 0


def _profile_per_op(step, step_args, out=None):
    """XProf per-op table (the old ``scripts/profile_gpt.py`` body):
    trace one warm step, parse the op stats. Degrades with a notice
    when the platform yields no parseable trace."""
    import tempfile

    from apex_tpu import monitor

    out = out if out is not None else sys.stdout
    try:
        _block(step(*step_args))        # compile + warm
        d = tempfile.mkdtemp(prefix="apx_profile_")
        with monitor.trace.trace(d):
            _block(step(*step_args))
        rows = monitor.xprof.op_stats(d)
        tot = sum(r["total_self_time_us"] or 0 for r in rows)
        print(f"\ntotal device self time: {tot / 1e3:.2f} ms", file=out)
        print(f"{'self_us':>10} {'pct':>6} {'bound':>8}  operation",
              file=out)
        for r in rows[:45]:
            print(f"{r['total_self_time_us'] or 0:10.0f} "
                  f"{r['device_self_time_pct'] or 0:6.2f} "
                  f"{str(r['bound_by'] or ''):>8}  "
                  f"{r['operation'][:110]}", file=out)
    except Exception as e:                              # noqa: BLE001
        print(f"\n(per-op XProf table unavailable here: "
              f"{type(e).__name__}: {e})", file=sys.stderr)


def _block(out):
    import jax
    jax.block_until_ready(out)


if __name__ == "__main__":
    sys.exit(main())
