"""CLI: render recorder dumps and smoke-test the telemetry pipeline.

    python -m apex_tpu.monitor report run.jsonl [--json] [--max-rows N]
    python -m apex_tpu.monitor merge SHARD... [--json] [-o OUT.json]
    python -m apex_tpu.monitor selfcheck [--steps N]

``report`` renders the per-step and aggregate tables from a
``Recorder.dump_jsonl`` file (the ``pyprof.prof`` analog — per-step
training telemetry instead of per-kernel nvprof records). ``merge``
combines rank-tagged shards (``monitor-<rank>.jsonl``, or a directory
holding them) from a multi-process run into one cross-host view:
collective bytes summed across ranks, per-rank timer distributions
with straggler percentiles, per-rank step-time skew. ``selfcheck``
records a synthetic 3-step amp run on CPU and asserts the dump → report
round trip (used by ``scripts/ci.sh``).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m apex_tpu.monitor")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("report", help="render a recorder JSONL dump")
    pr.add_argument("path", help="JSONL file from Recorder.dump_jsonl")
    pr.add_argument("--json", action="store_true",
                    help="print the aggregate as JSON instead of tables")
    pr.add_argument("--max-rows", type=int, default=50,
                    help="per-step table row cap")

    pm = sub.add_parser("merge",
                        help="merge rank-tagged shards into a "
                             "cross-host report")
    pm.add_argument("shards", nargs="+",
                    help="monitor-<rank>.jsonl files, or one directory "
                         "containing them")
    pm.add_argument("--json", action="store_true",
                    help="print the merged view as JSON")
    pm.add_argument("-o", "--out", default=None,
                    help="also write the merged JSON here")

    ps = sub.add_parser("selfcheck",
                        help="record a synthetic run; assert round-trip")
    ps.add_argument("--steps", type=int, default=3)
    ps.add_argument("--quiet", action="store_true")

    args = p.parse_args(argv)
    from apex_tpu.monitor import report as report_mod

    from apex_tpu.monitor.recorder import json_safe

    if args.cmd == "report":
        header, events = report_mod.load_jsonl(args.path)
        if args.json:
            print(json.dumps(
                json_safe(report_mod.aggregate(events, header=header)),
                indent=2))
        else:
            print(report_mod.render_report(events, header=header,
                                           max_rows=args.max_rows))
        return 0

    if args.cmd == "merge":
        from apex_tpu.monitor import merge as merge_mod
        shards = args.shards
        if len(shards) == 1:
            shards = shards[0]   # may be a directory; merge_shards resolves
        merged = json_safe(merge_mod.merge_shards(shards))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(merged, f, indent=2)
        if args.json:
            print(json.dumps(merged, indent=2))
        else:
            print(report_mod.render_cross_host(merged))
        return 0

    # selfcheck needs a backend; default to CPU unless the caller chose
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report_mod.selfcheck(n_steps=args.steps, verbose=not args.quiet)
    return 0


if __name__ == "__main__":
    sys.exit(main())
