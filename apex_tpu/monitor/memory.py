"""The ONE memory surface: compiled footprints, analytic high water,
live HBM timeline, and the byte accounting every capacity claim reports
through.

Memory is the capacity axis behind the repo's headline claims (ZeRO-3's
~world# per-chip resident-byte shrink, fp8-KV's >=2x concurrent
sequences, the zero-bubble wgrad-stash envelopes, the tuner's VMEM
budget model) — this module is where all of those become observable
through one vocabulary, in the mold of ``profile.py``/``spans.py``:

- :func:`compiled_memory_profile` — XLA's own static accounting for a
  compiled program (``Compiled.memory_analysis()``: argument/output/
  temp/alias/generated-code bytes — the numbers the allocator will
  honor, known before the first run). Subsumes
  ``monitor.trace.memory_analysis`` (now a thin re-export shim, the
  pyprof precedent).
- :func:`analytic_high_water` — a deviceless liveness walk over the
  jaxpr (``make_jaxpr`` — nothing executes) charging **peak live
  bytes** to the innermost ``apx:`` profile scope, so "which module
  owns the peak" is answerable on a CPU CI host. Semantics are
  hand-computable (asserted by ``tests/test_memory.py``):

  * the top jaxpr's inputs and consts are resident for the whole
    program (the undonated-call convention — the caller owns the
    buffers until the call returns);
  * an intermediate is live from the equation that defines it through
    its last use; program outputs are live through the end;
  * at each equation the charge is ``resident + live intermediates +
    this equation's outputs``;
  * sub-jaxprs (pjit/scan/cond/while/custom-vjp — duck-typed, the
    ``profile.analytic_profile`` recursion pattern) add their internal
    intermediates ON TOP of the live set at the call site. Unlike
    FLOPs, a scan's peak does NOT multiply by trip count — iterations
    reuse the body's buffers, and the stacked outputs are already
    counted at full size on the outer equation (XLA allocates ``ys``
    up front). ``while`` flags the result ``estimated`` (dynamic trip
    counts; the per-iteration envelope is still the right bound).

- :class:`MemorySampler` — the live HBM timeline: a host thread
  polling ``device.memory_stats()`` on an interval into
  ``memory/hbm_bytes_in_use`` gauges and a streaming
  :class:`~apex_tpu.monitor.spans.LogHistogram`. Platforms whose
  backend returns ``None`` (CPU hosts) degrade to a nominal row — real
  ``jax.live_arrays()`` resident bytes against the :data:`HBM_BYTES`
  table limit (the ``profile.PEAK_FLOPS`` cpu-row convention: the
  whole pipeline is exercisable on CI, and platform-bound unit markers
  keep the nominal figure out of any cross-host verdict). The sampler
  installs the ``jax.monitoring`` compile listeners, so retrace storms
  land on the same recorder timeline as the byte samples.
- :func:`resident_bytes` — device-local resident buffer bytes of a
  pytree (or of every live array): the measurement behind the ZeRO
  residency ratios, shared by the bench and the CLI.
- :func:`zero_memory_report` / :func:`serve_pool_report` — the ZeRO
  dense/zero2/zero3 residency split and the serve KV-pool occupancy,
  derived THROUGH this layer (the bench ``memory`` section and
  ``python -m apex_tpu.monitor memory`` both call these — no
  bench-local byte accounting).
- :func:`vmem_calibration` — closes the tuner loop: compares
  ``tune.vmem.vmem_estimate`` envelope predictions against compiled
  temp bytes for resolved kernel configs, emitting
  ``tune/vmem_mispredict`` events when the envelope under-predicts.

Purity contract (the monitor rule): nothing here inserts operations or
forces a retrace. The analytic walk traces abstractly; the sampler is a
host thread reading ``memory_stats()``; gauges ride
``jax.debug``-free host paths. A step traced with a recorder attached
and a sampler running is byte-identical to one traced detached
(asserted by ``tests/test_memory.py``). Recorders resolve at fire
time: detaching stops the telemetry even while a sampler thread runs.

Health: :class:`~apex_tpu.monitor.health.Watchdog` watches the gauges
this module records — ``hbm_high_water`` (usage at a fraction of the
limit, hysteresis re-arm), ``memory_leak`` (positive slope over a
sliding window of step-record byte gauges) and ``recompile_storm``
(compile events landing in step after step) all fire BEFORE the OOM,
riding the ordinary step-record path.

Rendered by ``python -m apex_tpu.monitor memory`` and embedded in
``report.aggregate()["memory"]`` when rows are recorded
(``record=True``).
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

from apex_tpu.monitor import _state
from apex_tpu.monitor.profile import (UNSCOPED, _aval_bytes, _scope_of,
                                      _sub_jaxprs)

#: Per-chip HBM capacity by ``device_kind`` substring — the byte twin
#: of ``profile.PEAK_FLOPS``. Sources: published TPU specs (v2-v6e).
#: The ``cpu`` row is a NOMINAL table figure, not a hardware spec: it
#: exists so the HBM-utilization pipeline (sampler -> gauges ->
#: watchdog ``hbm_high_water``) is exercisable on CI hosts;
#: cross-host comparison is blocked by the bench's platform-bound unit
#: markers, so the arbitrariness never leaks into a verdict.
HBM_BYTES = {
    "tpu v2": 8 << 30,
    "tpu v3": 16 << 30,
    "tpu v4": 32 << 30,
    "tpu v5 lite": 16 << 30,
    "tpu v5e": 16 << 30,
    "tpu v5p": 95 << 30,
    "tpu v6 lite": 32 << 30,
    "tpu v6e": 32 << 30,
    "tpu7": 192 << 30,
    "cpu": 4 << 30,
}

#: The compiled-breakdown fields read off ``Compiled.memory_analysis()``
#: (one place, shared with the trace shim).
_MA_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")


def hbm_limit_for(device_kind: Optional[str] = None) -> Optional[int]:
    """Per-chip HBM bytes for a ``device_kind`` string (default: the
    first jax device's), by normalized longest-substring match against
    :data:`HBM_BYTES`. ``None`` for unknown kinds — callers must treat
    that as "utilization not computable", never substitute a guess."""
    if device_kind is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return None
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    kind = str(device_kind).strip().lower()
    best = None
    for key, val in HBM_BYTES.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, val)
    return best[1] if best else None


# ---------------------------------------------------------------------------
# resident bytes: the device-local measurement behind every residency claim
# ---------------------------------------------------------------------------

def _shard_bytes_by_device(leaves) -> dict:
    """One pass over ``leaves`` → ``{device: resident bytes}`` (the
    ONE shard-accumulation loop behind :func:`resident_bytes` and the
    snapshot's nominal rows)."""
    out: dict = {}
    for leaf in leaves:
        for sh in getattr(leaf, "addressable_shards", []):
            out[sh.device] = out.get(sh.device, 0) + sh.data.nbytes
    return out


def resident_bytes(tree=None, device=None) -> int:
    """Device-local resident buffer bytes.

    ``tree``: a pytree of jax arrays (default: every live array in the
    process, ``jax.live_arrays()``). ``device``: count only the shards
    resident on that device (default: the first local device —
    replicated trees count one full copy, sharded trees ``1/world``,
    exactly the per-chip residency the ZeRO ratios are about)."""
    import jax
    leaves = (jax.live_arrays() if tree is None
              else jax.tree_util.tree_leaves(tree))
    if device is None:
        try:
            device = jax.local_devices()[0]
        except Exception:
            return 0
    return _shard_bytes_by_device(leaves).get(device, 0)


# ---------------------------------------------------------------------------
# compiled-footprint attribution (Compiled.memory_analysis)
# ---------------------------------------------------------------------------

def compiled_memory_of(compiled, *, label: str = "program",
                       record: bool = False) -> dict:
    """Memory breakdown of an already-compiled executable. Returns the
    :data:`_MA_FIELDS` present plus ``total_bytes`` (argument + output
    + temp + generated code, minus aliased bytes — the allocator-
    footprint envelope); ``{}`` when the backend reports nothing."""
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    out = {}
    for k in _MA_FIELDS:
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    if out:
        total = (out.get("argument_size_in_bytes", 0)
                 + out.get("output_size_in_bytes", 0)
                 + out.get("temp_size_in_bytes", 0)
                 + out.get("generated_code_size_in_bytes", 0)
                 - out.get("alias_size_in_bytes", 0))
        out["total_bytes"] = max(total, 0)
    if record and out:
        rec = _state.recorder
        if rec is not None:
            rec.emit("memory", label, out["total_bytes"],
                     **{k: v for k, v in out.items() if k != "total_bytes"})
    return out


def compiled_memory_profile(fn: Callable, *args, label: str = "program",
                            record: bool = False, **kwargs) -> dict:
    """Compile ``fn(*args, **kwargs)`` and return XLA's static memory
    breakdown — the numbers the allocator will honor, known before the
    first run. ``record=True`` lands one typed ``memory`` event on the
    attached recorder (→ ``report.aggregate()["memory"]["programs"]``).
    """
    import jax
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    return compiled_memory_of(compiled, label=label, record=record)


# ---------------------------------------------------------------------------
# analytic high water: liveness walk, charged to the innermost scope
# ---------------------------------------------------------------------------

def _is_literal(v) -> bool:
    return hasattr(v, "val")          # jax.core.Literal; Vars have no .val


def _new_row() -> dict:
    return {"peak_live_bytes": 0, "eqns": 0}


def _live_walk(jaxpr, prefix: str, base: int, rows: dict, meta: dict,
               count_io: bool) -> int:
    """Linear-scan liveness over one jaxpr. ``base`` is the absolute
    live total outside this jaxpr (the call site's live set, operands
    and outputs included — recursive calls therefore count only their
    INTERNAL intermediates, ``count_io=False``). Returns the absolute
    peak observed inside."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)    # unwrap ClosedJaxpr
    n = len(jaxpr.eqns)
    last: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last[v] = i
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last[v] = n                 # program outputs live to the end
    arg_vars = set(jaxpr.invars) | set(jaxpr.constvars)
    resident = 0
    if count_io:
        resident = sum(_aval_bytes(v) for v in arg_vars)
    live: dict = {}
    peak = base + resident
    for i, eqn in enumerate(jaxpr.eqns):
        stack = str(getattr(eqn.source_info, "name_stack", ""))
        full = f"{prefix}/{stack}" if prefix else stack
        for v in eqn.outvars:
            if v not in arg_vars:
                live[v] = _aval_bytes(v)
        here = base + resident + sum(live.values())
        cur = here
        subs = _sub_jaxprs(eqn)
        if subs:
            if eqn.primitive.name == "while":
                meta["estimated"] = True
            for sub in subs:
                # every sibling stacks on the CALL SITE's live set, not
                # on the previous sibling's peak: cond branches (and
                # while's cond/body) are mutually exclusive, so the
                # equation's contribution is their max, never their sum
                inner = _live_walk(sub, full, here, rows, meta,
                                   count_io=False)
                cur = max(cur, inner)
        scope = _scope_of(full)
        row = rows.setdefault(scope, _new_row())
        row["eqns"] += 1
        if cur > row["peak_live_bytes"]:
            row["peak_live_bytes"] = cur
        if cur > meta["peak"]:
            meta["peak"] = cur
            meta["peak_scope"] = scope
        if cur > peak:
            peak = cur
        # free intermediates at their last use (outputs have last == n)
        for v in eqn.invars:
            if not _is_literal(v) and v not in arg_vars \
                    and last.get(v, -1) <= i:
                live.pop(v, None)
        for v in eqn.outvars:
            if v not in arg_vars and last.get(v, -1) <= i:
                live.pop(v, None)       # never read again (DropVar/dead)
    return peak


def attribute_high_water(closed_jaxpr) -> dict:
    """Analytic peak-live-bytes walk over a ``ClosedJaxpr`` (or
    anything with ``.jaxpr.eqns``/``.eqns``): per-scope peaks, the
    global peak and which ``apx:`` scope owns it."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    rows: dict = {}
    meta = {"estimated": False, "peak": 0, "peak_scope": UNSCOPED}
    peak = _live_walk(jaxpr, "", 0, rows, meta, count_io=True)
    args_bytes = sum(_aval_bytes(v) for v in
                     tuple(jaxpr.invars) + tuple(jaxpr.constvars))
    out_bytes = sum(_aval_bytes(v) for v in jaxpr.outvars
                    if not _is_literal(v))
    return {"peak_live_bytes": int(peak),
            "peak_scope": meta["peak_scope"],
            "scopes": rows,
            "argument_bytes": int(args_bytes),
            "output_bytes": int(out_bytes),
            "estimated": meta["estimated"]}


def _emit_scope_rows(rec, scopes: dict):
    """The ONE per-scope ``memory_scope`` emission (shared by
    :func:`analytic_high_water` and :func:`memory_profile`)."""
    for name, row in sorted(scopes.items()):
        rec.emit("memory_scope", name, row["peak_live_bytes"],
                 eqns=row["eqns"])


def analytic_high_water(fn: Callable, *args, record: bool = False,
                        label: str = "program", **kwargs) -> dict:
    """Trace ``fn(*args, **kwargs)`` abstractly (``jax.make_jaxpr`` —
    nothing executes, deviceless) and attribute its peak live bytes per
    profile scope. ``record=True`` emits one ``memory_scope`` event per
    scope plus the program's ``memory`` row with the analytic fields."""
    import functools
    import jax
    closed = jax.make_jaxpr(functools.partial(fn, **kwargs))(*args)
    hw = attribute_high_water(closed)
    if record:
        rec = _state.recorder
        if rec is not None:
            _emit_scope_rows(rec, hw["scopes"])
            rec.emit("memory", label, hw["peak_live_bytes"],
                     analytic_peak_bytes=hw["peak_live_bytes"],
                     peak_scope=hw["peak_scope"],
                     argument_bytes=hw["argument_bytes"],
                     output_bytes=hw["output_bytes"],
                     estimated=hw["estimated"])
    return hw


def memory_profile(fn: Callable, *args, label: str = "program",
                   record: bool = False, **kwargs) -> dict:
    """The combined per-program view: compiled breakdown + analytic
    high-water walk. ``record=True`` emits ONE ``memory`` event
    carrying both (plus the per-scope ``memory_scope`` rows), so the
    table rides JSONL dumps and ``report.aggregate()["memory"]``."""
    hw = analytic_high_water(fn, *args, **kwargs)
    compiled = compiled_memory_profile(fn, *args, **kwargs)
    if record:
        rec = _state.recorder
        if rec is not None:
            _emit_scope_rows(rec, hw["scopes"])
            rec.emit(
                "memory", label,
                compiled.get("total_bytes", hw["peak_live_bytes"]),
                analytic_peak_bytes=hw["peak_live_bytes"],
                peak_scope=hw["peak_scope"],
                estimated=hw["estimated"],
                **{k: v for k, v in compiled.items()
                   if k != "total_bytes"})
    return {"label": label, "compiled": compiled, "analytic": hw}


# ---------------------------------------------------------------------------
# live HBM timeline
# ---------------------------------------------------------------------------

def device_memory_snapshot(devices=None, recorder=None) -> list[dict]:
    """Per-device live memory stats. Platforms that report
    ``memory_stats()`` get the real row (``bytes_in_use``,
    ``peak_bytes_in_use``, ``bytes_limit`` when present); platforms
    that return ``None`` (CPU hosts) degrade to a NOMINAL row —
    ``jax.live_arrays()`` resident bytes against the :data:`HBM_BYTES`
    table limit, stamped ``"nominal": True`` (the ``PEAK_FLOPS``
    cpu-row convention). Recorded as ``memory/...`` gauges on the
    attached (or passed) recorder; the headline
    ``memory/hbm_bytes_in_use`` gauge is the max across devices."""
    import jax
    devices = devices if devices is not None else jax.local_devices()
    out = []
    rec = recorder if recorder is not None else _state.recorder
    worst = None
    live_by_dev = None       # one live-array pass shared by all rows
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        row = {"device": str(d), "platform": d.platform}
        if stats:
            row.update(stats)
            limit = stats.get("bytes_limit") or \
                hbm_limit_for(getattr(d, "device_kind", None))
        else:
            row["nominal"] = True
            limit = hbm_limit_for(getattr(d, "device_kind", None))
        if "bytes_in_use" not in row:
            # stats-less backend (or stats without the headline key):
            # the nominal bytes_in_use is the REAL live-array residency
            if live_by_dev is None:
                live_by_dev = _shard_bytes_by_device(jax.live_arrays())
            row["bytes_in_use"] = live_by_dev.get(d, 0)
        if limit:
            row["limit_bytes"] = int(limit)
            row["utilization"] = row["bytes_in_use"] / float(limit)
        out.append(row)
        if worst is None or row["bytes_in_use"] > worst["bytes_in_use"]:
            worst = row
        if rec is not None:
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                if k in row:
                    rec.gauge(f"memory/{d.id}/{k}", row[k])
    if rec is not None and worst is not None:
        rec.gauge("memory/hbm_bytes_in_use", worst["bytes_in_use"])
        if "limit_bytes" in worst:
            rec.gauge("memory/hbm_limit_bytes", worst["limit_bytes"])
            rec.gauge("memory/hbm_utilization",
                      round(worst["utilization"], 6))
    return out


class MemorySampler:
    """Host-side HBM timeline: polls :func:`device_memory_snapshot` on
    an interval thread, landing ``memory/hbm_bytes_in_use`` (+ limit/
    utilization and per-device) gauges and one streaming
    :class:`~apex_tpu.monitor.spans.LogHistogram` observation per
    sample on whichever recorder is attached AT SAMPLE TIME (detach
    stops the telemetry mid-flight; the thread itself is inert).

    Also installs the ``jax.monitoring`` compile listeners
    (:func:`~apex_tpu.monitor.trace.install_compile_logging`) so
    backend-compile events and the byte samples share one timeline —
    a retrace storm shows up as compile timers interleaved with the
    HBM gauges it inflates.

    Usage::

        with monitor.attached(rec), monitor.MemorySampler(0.2):
            train()
        rec.aggregate()["memory"]["timeline"]   # downsampled trajectory

    Purity: the sampler is a plain thread doing host reads — it
    inserts no ops and forces no retrace; traced programs are
    byte-identical with or without it (asserted by tests).
    """

    def __init__(self, interval_s: float = 0.5, *, devices=None,
                 recorder=None,
                 histogram: Optional[str] = "memory/hbm_mib_in_use"):
        self.interval_s = float(interval_s)
        self.devices = devices
        self.recorder = recorder          # None: resolve at sample time
        self.histogram = histogram
        self.samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> list[dict]:
        """One sample (also usable without the thread)."""
        rec = self.recorder if self.recorder is not None \
            else _state.recorder
        rows = device_memory_snapshot(self.devices, recorder=rec)
        if rec is not None and rows and self.histogram:
            worst = max(r.get("bytes_in_use", 0) for r in rows)
            # histogram in MiB (the unit is in the NAME: the gauge and
            # the histogram must be distinct Prometheus families — one
            # TYPE line per name — and the LogHistogram default range
            # suits MiB magnitudes, not raw bytes)
            rec.observe(self.histogram, worst / float(1 << 20))
        self.samples += 1
        return rows

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                pass                  # telemetry must never kill the run

    def start(self) -> "MemorySampler":
        if self._thread is not None:
            return self
        try:
            from apex_tpu.monitor import trace as _trace
            _trace.install_compile_logging()
        except Exception:
            pass
        try:
            self.sample_once()        # one sample lands immediately
        except Exception:
            pass                      # telemetry must never kill the run
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="apex-memory-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        try:
            self.sample_once()        # closing sample
        except Exception:
            pass

    def __enter__(self) -> "MemorySampler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# ---------------------------------------------------------------------------
# the capacity claims, derived through this layer
# ---------------------------------------------------------------------------

def zero_memory_report(world: Optional[int] = None, *, hidden: int = 128,
                       batch: int = 16, record: bool = False) -> dict:
    """The ZeRO residency split, measured through this layer: dense DDP
    vs ZeRO-2 (``DistributedFusedAdam``) vs ZeRO-3
    (``ZeroOptimizer(shard_params=True)``) at a matched tiny config on
    the host data mesh — per-chip resident param+optimizer bytes
    (:func:`resident_bytes` on device 0) and the compiled step
    footprint (:func:`compiled_memory_of`) per tier, plus the
    dense/ZeRO-3 shrink ratio (~``world``x within padding +
    replicated-bias slack, the PR 6 claim). Runs on host CPU devices by
    design: the residency split is backend-independent."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu._compat import shard_map
    from apex_tpu import zero
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import allreduce_gradients

    try:
        devs = jax.devices("cpu")
    except RuntimeError:
        devs = jax.devices()
    if world is None:
        world = max(w for w in (8, 4, 2, 1) if w <= len(devs))
    devs = devs[:world]
    mesh = Mesh(np.array(devs), ("data",))
    h, b = int(hidden), int(batch)
    rng = np.random.RandomState(7)
    params = {"w1": jnp.asarray(rng.randn(h, h) * 0.2, jnp.float32),
              "b1": jnp.asarray(rng.randn(h) * 0.1, jnp.float32),
              "w2": jnp.asarray(rng.randn(h, h) * 0.2, jnp.float32)}
    x = jnp.asarray(rng.randn(b * world, h), jnp.float32)
    y = jnp.asarray(rng.randn(b * world, h), jnp.float32)
    hyper = dict(lr=1e-2, weight_decay=0.01)

    def loss_fn(p, xs, ys):
        return jnp.mean(((jnp.tanh(xs @ p["w1"] + p["b1"])) @ p["w2"]
                         - ys) ** 2)

    decisions = jax.tree.map(
        lambda d: P("data") if (d and world > 1) else P(),
        zero.match_zero_rules(None, params))
    rep = jax.tree.map(lambda _: P(), params)
    zm3 = zero.ZeroShardedModel(None)

    def build(which):
        if which == "dense":
            opt = FusedAdam(params, master_weights=True, **hyper)

            def init(p):
                return p, opt.init(p)

            def step(p, st, xs, ys):
                g = jax.grad(loss_fn)(p, xs, ys)
                g = allreduce_gradients(g, "data")
                return opt.apply(st, p, g)

            return init, step, (rep, P())
        if which == "zero2":
            opt = DistributedFusedAdam(**hyper)

            def init(p):
                return p, opt.init(p)

            def step(p, st, xs, ys):
                g = jax.grad(loss_fn)(p, xs, ys)
                return opt.apply(st, p, g)

            sspec = zero.ShardedAdamState(
                P(), *((P("data") if world > 1 else P(),) * 3))
            return init, step, (rep, sspec)
        opt = zero.ZeroOptimizer(shard_params=True, **hyper)

        def init(p):
            shards = zm3.shard(p)
            return shards, opt.init(shards, zm3.spec)

        def step(s, st, xs, ys):
            g = jax.grad(lambda s: loss_fn(zm3.materialize(s), xs, ys))(s)
            return opt.apply(st, s, g, spec=zm3.spec)

        sspec = zero.Zero3State(P(), decisions, decisions, decisions)
        return init, step, (decisions, sspec)

    out: dict = {
        "world_size": world,
        "model_param_bytes": sum(int(v.size) * 4
                                 for v in jax.tree.leaves(params)),
        "per_chip_bytes": {}, "compiled": {},
    }
    for which in ("dense", "zero2", "zero3"):
        init, step, state_specs = build(which)
        jinit = jax.jit(shard_map(init, mesh=mesh, in_specs=(P(),),
                                  out_specs=state_specs, check_vma=False))
        p_or_s, st = jinit(params)
        out["per_chip_bytes"][which] = resident_bytes((p_or_s, st),
                                                      device=devs[0])
        compiled = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=(*state_specs, P("data"), P("data")),
            out_specs=state_specs,
            check_vma=False)).lower(p_or_s, st, x, y).compile()
        cm = compiled_memory_of(compiled, label=f"zero/{which}",
                                record=record)
        if cm:
            out["compiled"][which] = cm
    dense_b = out["per_chip_bytes"]["dense"]
    z3_b = out["per_chip_bytes"]["zero3"]
    out["dense_over_zero3_ratio"] = round(dense_b / max(z3_b, 1), 3)
    if record:
        rec = _state.recorder
        if rec is not None:
            for which, nbytes in out["per_chip_bytes"].items():
                rec.gauge(f"memory/zero/{which}_bytes_per_chip", nbytes)
            rec.gauge("memory/zero/dense_over_zero3_ratio",
                      out["dense_over_zero3_ratio"])
    return out


def serve_pool_report(*, num_layers: int = 12, kv_heads: int = 16,
                      head_dim: int = 64, num_pages: int = 256,
                      page_size: int = 128, seq_len: int = 1024,
                      pages_in_use: Optional[int] = None,
                      record: bool = False) -> dict:
    """Serve KV-pool occupancy through the cache's own byte accounting
    (``CacheConfig`` — the accounting PR 11's capacity claims come
    from): pool bytes, occupancy at ``pages_in_use`` (default: 3/4 of
    the usable pool), and the fp8-vs-bf16 concurrent-sequence capacity
    at the same pool budget."""
    import jax.numpy as jnp
    from apex_tpu.serve.cache import CacheConfig

    common = dict(num_layers=num_layers, kv_heads=kv_heads,
                  head_dim=head_dim, num_pages=num_pages,
                  page_size=page_size)
    bf16 = CacheConfig(dtype=jnp.bfloat16, **common)
    fp8 = CacheConfig(fp8=True, **common)
    usable = bf16.usable_pages
    if pages_in_use is None:
        pages_in_use = (3 * usable) // 4
    budget = bf16.pool_bytes()
    occupancy = pages_in_use / float(usable)
    out = {
        "pool_bytes": budget,
        "bytes_per_page": bf16.bytes_per_page(),
        "fp8_bytes_per_page": fp8.bytes_per_page(),
        "pages_in_use": int(pages_in_use),
        "usable_pages": usable,
        "occupancy": round(occupancy, 4),
        "bytes_in_use": bf16.occupancy_bytes(pages_in_use),
        "bf16_seqs_at_budget": bf16.max_concurrent_seqs(budget, seq_len),
        "fp8_seqs_at_budget": fp8.max_concurrent_seqs(budget, seq_len),
    }
    out["fp8_capacity_ratio"] = round(
        out["fp8_seqs_at_budget"] / max(out["bf16_seqs_at_budget"], 1), 3)
    if record:
        rec = _state.recorder
        if rec is not None:
            rec.gauge("memory/serve_pool_bytes", out["pool_bytes"])
            rec.gauge("memory/serve_pool_bytes_in_use",
                      out["bytes_in_use"])
            rec.gauge("memory/serve_pool_occupancy", out["occupancy"])
    return out


def serve_weight_report(cfg, params, *, record: bool = False) -> dict:
    """Serve weight-streaming accounting: the HBM bytes of block linear
    weights (kernels + any fp8 scales) ONE decode step streams, against
    the bf16 baseline of the same leaves — the byte accounting the
    bench's fp8-weight streamed-bytes assertion reads
    (``serve.model.weight_stream_bytes``; same rule the engine serves
    with, so telemetry and capacity claims cannot drift apart). A bf16
    tree reports ratio 1.0; an e4m3-quantized tree
    (``serve.quantize_gpt_weights``) ~0.5."""
    from apex_tpu.serve import model as serve_model

    streamed = serve_model.weight_stream_bytes(cfg, params)
    elems = 0
    for i in range(cfg.num_layers):
        blk = params[f"block_{i}"]
        for group, name in serve_model._FP8_WEIGHT_LINEARS:
            elems += int(blk[group][name]["kernel"].size)
    bf16 = 2 * elems
    out = {
        "weight_bytes_per_step": streamed,
        "bf16_weight_bytes_per_step": bf16,
        "weight_stream_ratio": round(streamed / max(bf16, 1), 4),
    }
    if record:
        rec = _state.recorder
        if rec is not None:
            rec.gauge("memory/serve_weight_bytes",
                      out["weight_bytes_per_step"])
            rec.gauge("memory/serve_weight_bytes_bf16",
                      out["bf16_weight_bytes_per_step"])
            rec.gauge("memory/serve_weight_ratio",
                      out["weight_stream_ratio"])
    return out


# ---------------------------------------------------------------------------
# tuner-loop calibration: envelope predictions vs compiled temp bytes
# ---------------------------------------------------------------------------

def _calibration_call(kernel: str, shape: dict, dtype: str, flags: dict,
                      config: dict, interpret):
    """(fn, args, vmem_kwargs) for one kernel at one block config —
    the compile target whose ``temp_size_in_bytes`` grounds the
    envelope. The three r13 kernels: cheap to compile at tiny shapes
    on any backend (interpret mode off-TPU)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(0)
    if kernel == "fused_layer_norm":
        from apex_tpu.ops.layer_norm import fused_layer_norm_affine
        n, h = shape["n"], shape["h"]
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.randn(n, h) * 0.5, dt)
        w = jnp.ones((h,), jnp.float32)
        b = jnp.zeros((h,), jnp.float32)

        def fn(x, w, b):
            return fused_layer_norm_affine(
                x, w, b, (h,), block_r=config["block_r"],
                interpret=interpret, out_dtype=dt)

        return fn, (x, w, b), dict(block_r=config["block_r"], h=h,
                                   itemsize=dt.itemsize)
    if kernel == "xentropy":
        from apex_tpu.ops.fused_ce import \
            softmax_cross_entropy_with_smoothing
        n, v = shape["n"], shape["v"]
        dt = jnp.dtype(dtype)
        logits = jnp.asarray(rng.randn(n, v) * 0.1, dt)
        labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)

        def fn(logits):
            return softmax_cross_entropy_with_smoothing(
                logits, labels, 0.0, block_t=config["block_t"],
                block_v=config["block_v"], interpret=interpret)

        return fn, (logits,), dict(block_t=config["block_t"],
                                   block_v=config["block_v"],
                                   itemsize=dt.itemsize)
    if kernel == "multi_tensor_update":
        from apex_tpu.zero.fused_update import fused_shard_update
        n = shape["n"]
        p = jnp.asarray(rng.randn(n) * 0.05, jnp.float32)
        g = jnp.asarray(rng.randn(n) * 0.01, jnp.float32)
        m = jnp.zeros((n,), jnp.float32)
        v = jnp.zeros((n,), jnp.float32)
        step = jnp.asarray(7, jnp.int32)

        def fn(p, g, m, v):
            return fused_shard_update(
                p, g, m, v, step, kind="adam", lr=1e-3,
                betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                adam_w_mode=True, bias_correction=True,
                block_n=config["block_n"], interpret=interpret)

        return fn, (p, g, m, v), dict(block_n=config["block_n"])
    raise ValueError(f"vmem_calibration supports "
                     f"fused_layer_norm/xentropy/multi_tensor_update, "
                     f"got {kernel!r}")


#: tiny default calibration shapes (compile in well under a second on a
#: CPU host in interpret mode — the CI-sized twin of
#: ``tune.kernels.DEFAULT_SHAPES``)
CALIBRATION_SHAPES = {
    "fused_layer_norm": dict(n=256, h=128, dtype="bfloat16"),
    "xentropy": dict(n=64, v=256, dtype="bfloat16"),
    "multi_tensor_update": dict(n=16384, dtype="float32"),
}


def vmem_calibration(kernels=None, *, shapes: Optional[dict] = None,
                     interpret: Optional[bool] = None,
                     record: bool = False) -> dict:
    """Close the tuner loop: for each kernel, resolve its block config
    (tuned cache entry when one exists — ``tune.runtime.resolve`` —
    else the first legal candidate of the pruned config space), compile
    the kernel call, and compare the ``tune.vmem.vmem_estimate``
    envelope prediction against the compiled ``temp_size_in_bytes``.

    A **mispredict** is the dangerous direction: measured temp bytes
    exceeding the envelope that the sweep pruner trusted as an upper
    bound. Each mispredict bumps the ``tune/vmem_mispredict`` counter
    and (``record=True``) lands one typed ``vmem_calibration`` event
    per kernel — the envelope model's first measured feedback.

    Off-TPU the kernels compile in interpret mode, where XLA's temp
    accounting covers the interpreted program rather than Mosaic's
    VMEM allocator — those rounds exercise the pipeline; the verdicts
    that matter come from hardware rounds (units are platform-stamped
    by the bench accordingly)."""
    from apex_tpu.tune import runtime, space, vmem
    from apex_tpu.tune.cache import cache_key

    kernels = tuple(kernels or CALIBRATION_SHAPES)
    rows = []
    mispredicts = 0
    rec = _state.recorder
    for kernel in kernels:
        shape = dict((shapes or {}).get(kernel)
                     or CALIBRATION_SHAPES[kernel])
        dtype = shape.pop("dtype")
        flags: dict = {}
        cfg = runtime.resolve(kernel, shape, dtype, flags,
                              policy="cache")
        source = "tuned" if cfg is not None else "heuristic"
        if cfg is None:
            cands = space.config_space(kernel, shape, flags)
            if not cands:
                continue
            cfg = cands[0]
        fn, args, vkw = _calibration_call(kernel, shape, dtype, flags,
                                          cfg, interpret)
        import jax
        compiled = jax.jit(fn).lower(*args).compile()
        cm = compiled_memory_of(compiled)
        predicted = vmem.vmem_estimate(kernel, **vkw)
        measured = cm.get("temp_size_in_bytes")
        row = {"kernel": kernel, "config": dict(cfg), "source": source,
               "key": cache_key(kernel, shape, dtype, flags),
               "predicted_vmem_bytes": int(predicted),
               "budget_bytes": vmem.budget_for(kernel),
               "measured_temp_bytes": measured}
        row["mispredict"] = bool(measured is not None
                                 and measured > predicted)
        if row["mispredict"]:
            mispredicts += 1
            if rec is not None:
                rec.counter("tune/vmem_mispredict")
        if record and rec is not None:
            rec.emit("vmem_calibration", kernel,
                     row["predicted_vmem_bytes"], **{
                         k: v for k, v in row.items()
                         if k not in ("kernel", "predicted_vmem_bytes")})
        rows.append(row)
    return {"rows": rows, "checked": len(rows),
            "mispredicts": mispredicts}


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render_memory_profile(prof: dict, max_rows: int = 30) -> str:
    """Human render of a :func:`memory_profile` result: the compiled
    breakdown line + the per-scope analytic peak table."""
    from apex_tpu.monitor.report import _fmt_bytes
    lines = [f"# memory profile: {prof.get('label', 'program')}"]
    cm = prof.get("compiled") or {}
    if cm:
        lines.append(
            f"compiled: total {_fmt_bytes(cm.get('total_bytes'))} "
            f"(argument {_fmt_bytes(cm.get('argument_size_in_bytes'))}, "
            f"output {_fmt_bytes(cm.get('output_size_in_bytes'))}, "
            f"temp {_fmt_bytes(cm.get('temp_size_in_bytes'))}, "
            f"generated "
            f"{_fmt_bytes(cm.get('generated_code_size_in_bytes'))})")
    hw = prof.get("analytic") or {}
    if hw:
        est = " (estimated: dynamic while-loop trip counts)" \
            if hw.get("estimated") else ""
        lines.append(
            f"analytic high water: {_fmt_bytes(hw['peak_live_bytes'])} "
            f"at scope `{hw['peak_scope']}`{est}  "
            f"(args {_fmt_bytes(hw['argument_bytes'])}, "
            f"outputs {_fmt_bytes(hw['output_bytes'])})")
        scopes = hw.get("scopes") or {}
        if scopes:
            lines.append("")
            lines.append("| scope | peak live | eqns |\n|---|---|---|")
            order = sorted(scopes.items(),
                           key=lambda kv: -kv[1]["peak_live_bytes"])
            for name, row in order[:max_rows]:
                lines.append(f"| {name} "
                             f"| {_fmt_bytes(row['peak_live_bytes'])} "
                             f"| {row['eqns']} |")
            if len(order) > max_rows:
                lines.append(f"... ({len(order) - max_rows} more scopes)")
    return "\n".join(lines)
