"""Module-level monitoring guard.

One attribute read decides whether any hook does work: ``recorder`` is
the attached :class:`~apex_tpu.monitor.recorder.Recorder` or ``None``.
Every instrumentation hook in the package begins with::

    rec = _state.recorder
    if rec is None:
        return

so disabled-mode cost is one global load + one compare, no jax import,
no allocation — a jitted step traced while detached is byte-identical
to the uninstrumented program.

``epoch`` increments on every attach/detach. Jitted wrappers that want
to pick up a newly-attached recorder (``amp.make_train_step``, the
stateful optimizer ``step``) thread it through as a static argument:
flipping the guard changes the static key, forcing exactly one retrace;
while the guard is stable the cached executable is reused.

This module imports nothing — it exists so ``hooks``/``recorder``/
``__init__`` can share the guard without an import cycle.
"""

from __future__ import annotations

recorder = None   # the attached Recorder, or None (monitoring disabled)
epoch = 0         # bumped on attach/detach; static jit key for hot paths
