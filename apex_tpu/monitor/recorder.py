"""Typed-event ring-buffer recorder.

The Recorder is the single sink for everything the instrumentation
hooks emit: host counters and gauges, timers, trace-time collective
accounting, device scalars arriving through ``jax.debug.callback``, and
per-step records assembled by the ``step()`` context manager. It is
deliberately zero-dependency — pure stdlib, no jax import — so it can
run in data-loader worker threads and in processes that never touch an
accelerator.

Event model (one dict per event, JSONL-serializable):

- ``counter``   {name, value=increment, total}   monotonic accumulators
- ``gauge``     {name, value}                    last-value-wins samples
- ``timer``     {name, value=seconds}            measured durations
- ``collective``{name="op@axis", value=count, bytes} trace-time accounting
- ``step``      {step, value=step_time_s, gauges, counters, collectives,
                 timers}                          one per training step
- ``histogram`` {name, value=count, counts, ...}  cumulative snapshot of
                 a :meth:`observe` log-scale histogram (O(1) memory; no
                 per-sample events)
- ``span_start``/``span_end``/``span_event``      request-level span
                 tracing (:mod:`apex_tpu.monitor.spans`)

Events live in a bounded ring (``capacity`` newest kept; ``dropped``
counts evictions), so a recorder attached for a million steps holds
memory constant. Aggregation (:meth:`aggregate`) and the CLI report
(``python -m apex_tpu.monitor report``) consume the JSONL dump.

Crash resilience: pass ``stream=<path or file>`` and every event is
ALSO appended to that file as one JSON line the moment it is emitted
(write + flush, so the line survives the process being killed). A run
that times out or crashes mid-step leaves a parseable JSONL holding
everything recorded up to the kill — this is what ``bench.py`` builds
its streaming evidence on, and what ``dump_shard`` rank-tagged shards
use on multi-host runs.

Observers: :meth:`add_observer` registers a host callback invoked with
every closed ``step`` record — the hook :class:`~apex_tpu.monitor.
health.Watchdog` uses to analyze the stream online without polling.
Observer exceptions are swallowed (telemetry must never kill training).

Threading: hooks may fire from loader worker threads and from runtime
callback threads; all mutation happens under one lock.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Iterable, Optional


def json_safe(obj):
    """Recursively replace non-finite floats with their string form
    ("NaN"/"Infinity"/"-Infinity"). Bare ``json.dumps`` emits literal
    ``NaN`` tokens — invalid strict JSON that jq/JSON.parse-style
    drivers reject — on exactly the runs the watchdog exists for (a
    NaN loss gauge). Strings keep the information and stay parseable;
    ``float("NaN")`` round-trips for consumers that want the value."""
    if isinstance(obj, float):
        if obj != obj:
            return "NaN"
        if obj in (float("inf"), float("-inf")):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def json_line(obj) -> str:
    """One strict-JSON line for an event dict (non-finite-safe)."""
    return json.dumps(json_safe(obj))


def _effects_barrier():
    """Drain pending jax debug callbacks so device scalars land in the
    step record that produced them. Guarded on jax being imported —
    never the importer of it."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            jax.effects_barrier()
        except Exception:
            pass


class Recorder:
    """Collects typed telemetry events into a bounded ring buffer.

    Typical lifecycle::

        rec = monitor.Recorder()
        with monitor.attached(rec):          # enables the package hooks
            for batch in loader:
                with rec.step():             # one per-step record
                    out = train_step(...)
        rec.dump_jsonl("run.jsonl")
        print(monitor.render_report(rec.records()))

    All emit methods are also callable directly (without any hook
    involvement) for user-level metrics.
    """

    def __init__(self, capacity: int = 65536, name: str = "run",
                 meta: Optional[dict] = None, traced_hooks: bool = True,
                 stream=None, stream_mode: str = "w"):
        self.name = name
        self.capacity = int(capacity)
        self.meta = dict(meta or {})
        # traced_hooks=False makes this a host-only observer: the traced
        # hook family (traced_scalar/traced_tick/collective/schedule and
        # the optimizer norm gauges) stays dormant, so compiled programs
        # are untouched while host timers and compile events still land.
        # bench.py uses this to time UNperturbed programs.
        self.traced_hooks = bool(traced_hooks)
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._emitted = 0              # lifetime count (ring may evict)
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, Any] = {}     # name -> LogHistogram
        self._collectives: dict[str, dict] = {}   # "op@axis" -> {count, bytes}
        self._lock = threading.RLock()
        self._step_idx = 0
        self._open_step: Optional[dict] = None
        self._observers: list[Callable] = []
        self._t0 = time.perf_counter()
        # incremental-flush stream: every event is appended + flushed as
        # it is emitted, so a killed process leaves a parseable JSONL of
        # everything recorded so far (module docstring)
        self._stream = None
        self._stream_owned = False
        if stream is not None:
            if hasattr(stream, "write"):
                self._stream = stream
            else:
                self._stream = open(stream, stream_mode)
                self._stream_owned = True
            self._stream_write({"kind": "header", "name": self.name,
                                "capacity": self.capacity, "dropped": 0,
                                "meta": self.meta})

    # -- internals ---------------------------------------------------------
    def _stream_write(self, ev: dict):
        f = self._stream
        if f is None:
            return
        try:
            f.write(json_line(ev) + "\n")
            f.flush()
        except Exception:
            pass   # telemetry must never kill the run

    def close(self):
        """Close an owned stream file (no-op otherwise)."""
        with self._lock:
            f, self._stream = self._stream, None
            owned, self._stream_owned = self._stream_owned, False
        if f is not None and owned:
            try:
                f.close()
            except Exception:
                pass

    def add_observer(self, fn: Callable) -> Callable:
        """Register ``fn(step_event, recorder)`` to run (on the host, in
        the stepping thread) every time a ``step`` record closes. Errors
        raised by observers are swallowed."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)
        return fn

    def remove_observer(self, fn: Callable):
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    def _emit(self, kind: str, name: str, value, **extra) -> dict:
        ev = {"kind": kind, "name": name, "value": value,
              "t": round(time.perf_counter() - self._t0, 6)}
        if extra:
            ev.update(extra)
        with self._lock:
            if self._open_step is not None:
                ev["step"] = self._open_step["step"]
            self._events.append(ev)
            self._emitted += 1
            self._stream_write(ev)
        return ev

    def emit(self, kind: str, name: str, value, **extra) -> dict:
        """Record a custom typed event (user-defined ``kind``). The
        event rides the ring, the JSONL dump, and — when streaming — is
        flushed to disk immediately (bench sections, health events)."""
        return self._emit(kind, name, value, **extra)

    @property
    def dropped(self) -> int:
        """Events evicted from the ring so far."""
        with self._lock:
            return self._emitted - len(self._events)

    # -- host-side primitives ----------------------------------------------
    def counter(self, name: str, inc: float = 1, **extra) -> float:
        with self._lock:
            total = self._counters.get(name, 0) + inc
            self._counters[name] = total
            step = self._open_step
            if step is not None:
                step["counters"][name] = step["counters"].get(name, 0) + inc
        self._emit("counter", name, inc, total=total, **extra)
        return total

    def gauge(self, name: str, value, **extra):
        value = float(value)
        with self._lock:
            self._gauges[name] = value
            step = self._open_step
            if step is not None:
                step["gauges"][name] = value
        self._emit("gauge", name, value, **extra)

    def observe(self, name: str, value, *, lo: float = None,
                hi: float = None, buckets_per_decade: int = None):
        """Record one sample into the named fixed-bucket log-scale
        histogram (:class:`~apex_tpu.monitor.spans.LogHistogram`).

        Deliberately NOT one event per sample: the histogram state is
        O(1) memory and the stream stays O(1) traffic under sustained
        serving — percentiles (p50/p95/p99) stay queryable for the
        whole run. Snapshots ride the ring/stream as ``histogram``
        events via :meth:`emit_histograms` (called by the serve engine
        at drain) and are appended automatically by
        :meth:`dump_jsonl`/:meth:`aggregate`. The bucket-range kwargs
        apply only on the FIRST observation of a name."""
        from apex_tpu.monitor.spans import LogHistogram
        value = float(value)
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                kw = {}
                if lo is not None:
                    kw["lo"] = lo
                if hi is not None:
                    kw["hi"] = hi
                if buckets_per_decade is not None:
                    kw["buckets_per_decade"] = buckets_per_decade
                h = self._histograms[name] = LogHistogram(**kw)
            h.record(value)

    def histograms(self) -> dict:
        """Live ``name -> LogHistogram`` map (the objects themselves;
        callers wanting a stable view should use their snapshots)."""
        with self._lock:
            return dict(self._histograms)

    def _histogram_events(self) -> list[dict]:
        """Fresh cumulative ``histogram`` snapshot events (not stored
        in the ring) — appended to dumps and aggregates so histograms
        survive the JSONL round trip."""
        with self._lock:
            snaps = {k: h.snapshot() for k, h in self._histograms.items()}
        return [{"kind": "histogram", "name": k, "value": snap["count"],
                 **{kk: vv for kk, vv in snap.items() if kk != "count"}}
                for k, snap in sorted(snaps.items())]

    def emit_histograms(self):
        """Flush one cumulative ``histogram`` snapshot event per
        observed histogram into the ring (and the stream, when
        streaming) — crash-resilient persistence for long runs; safe to
        call repeatedly (snapshots are cumulative, last one wins)."""
        for ev in self._histogram_events():
            self._emit(ev.pop("kind"), ev.pop("name"), ev.pop("value"),
                       **ev)

    def timer_event(self, name: str, seconds: float, **extra):
        with self._lock:
            step = self._open_step
            if step is not None:
                t = step["timers"].setdefault(name, {"n": 0, "total_s": 0.0})
                t["n"] += 1
                t["total_s"] = round(t["total_s"] + seconds, 6)
        with self._lock:
            self._counters[name + "/total_s"] = round(
                self._counters.get(name + "/total_s", 0.0) + seconds, 6)
        self._emit("timer", name, round(seconds, 6), **extra)

    @contextlib.contextmanager
    def timer(self, name: str, **extra):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timer_event(name, time.perf_counter() - t0, **extra)

    def collective(self, op: str, axis_name: str, nbytes: int = 0,
                   count: int = 1):
        """Trace-time collective accounting: called by the mapping/DDP
        hooks while a program is being traced, so totals are per traced
        program, not per executed step (XLA runs the same collectives
        every step; re-tracing re-counts)."""
        key = f"{op}@{axis_name}"
        with self._lock:
            slot = self._collectives.setdefault(
                key, {"count": 0, "bytes": 0})
            slot["count"] += int(count)
            slot["bytes"] += int(nbytes)
        self._emit("collective", key, int(count), bytes=int(nbytes))

    # -- device-side arrivals (jax.debug.callback target) -------------------
    def _device_scalar(self, name: str, value):
        """Target of the traced-scalar hooks; runs on the host when the
        device value is materialized. Behaves like a gauge."""
        try:
            self.gauge(name, float(value))
        except (TypeError, ValueError):
            pass

    def _device_tick(self, name: str, tick):
        """Target of per-tick schedule marks: records host-arrival time
        of pipeline tick ``tick`` (an ordering/progress signal; device
        step attribution belongs to XProf)."""
        try:
            self._emit("tick", name, int(tick))
        except (TypeError, ValueError):
            pass

    def _device_tick_marks(self, name: str, tick, rank, slots: dict):
        """Target of the measured slot-occupancy marks
        (``hooks.traced_tick_marks``): one event per (tick, rank) with
        the boolean validity of every unit slot the tick executed —
        the raw material of the per-rank pipeline utilization table
        (``report.aggregate()['pipeline_utilization']``)."""
        try:
            self._emit("tick_mark", name, int(tick), rank=int(rank),
                       slots={k: bool(v) for k, v in slots.items()})
        except (TypeError, ValueError):
            pass

    # -- per-step records ---------------------------------------------------
    @contextlib.contextmanager
    def step(self, **meta):
        """Open a per-step record; on exit, drains pending device
        callbacks and appends a ``step`` event carrying the step wall
        time plus every gauge/counter/timer observed during the step and
        the cumulative collective table."""
        with self._lock:
            idx = self._step_idx
            self._step_idx += 1
            self._open_step = {"step": idx, "gauges": {}, "counters": {},
                               "timers": {}}
        t0 = time.perf_counter()
        try:
            yield idx
        finally:
            _effects_barrier()
            dur = time.perf_counter() - t0
            with self._lock:
                rec = self._open_step
                self._open_step = None
                collectives = {k: dict(v)
                               for k, v in self._collectives.items()}
            ev = {"kind": "step", "name": "step", "step": rec["step"],
                  "value": round(dur, 6), "step_time_s": round(dur, 6),
                  "t": round(t0 - self._t0, 6),
                  "gauges": rec["gauges"], "counters": rec["counters"],
                  "timers": rec["timers"], "collectives": collectives}
            if meta:
                ev["meta"] = {k: v for k, v in meta.items()}
            with self._lock:
                self._events.append(ev)
                self._emitted += 1
                self._stream_write(ev)
                observers = list(self._observers)
            for obs in observers:
                try:
                    obs(ev, self)
                except Exception:
                    pass   # a watchdog bug must not kill the training loop

    # -- views ---------------------------------------------------------------
    def records(self, kind: Optional[str] = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def steps(self) -> list[dict]:
        return self.records("step")

    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def collectives(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._collectives.items()}

    # -- output --------------------------------------------------------------
    def dump_jsonl(self, path_or_file) -> int:
        """Write one JSON object per event (newest ``capacity`` events);
        first line is a header record. Returns the number of event lines
        written. Path writes are atomic (tmp + fsync + rename): a kill
        arriving mid-dump leaves the previous complete file or none,
        never a torn shard the merge CLI chokes on."""
        _effects_barrier()
        from apex_tpu.monitor.spans import open_spans
        header = {"kind": "header", "name": self.name,
                  "capacity": self.capacity, "dropped": self.dropped,
                  "open_spans": open_spans(), "meta": self.meta}
        evs = self.records() + self._histogram_events()

        def _write(f):
            f.write(json_line(header) + "\n")
            for e in evs:
                f.write(json_line(e) + "\n")

        if hasattr(path_or_file, "write"):
            _write(path_or_file)
            return len(evs)
        path = os.fspath(path_or_file)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "w") as f:
                _write(f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return len(evs)

    def aggregate(self) -> dict:
        """Aggregated summary (the JSON the CLI report renders)."""
        from apex_tpu.monitor.report import aggregate
        _effects_barrier()
        return aggregate(self.records() + self._histogram_events(),
                         header={"name": self.name, "dropped": self.dropped,
                                 "meta": self.meta})
