"""Per-module cost attribution (the ``apex.pyprof`` per-layer story).

``apex`` ships ``pyprof`` because "is it faster" is unanswerable without
per-layer attribution; the trace layer (``monitor.trace``) records *that*
time was spent, this module records *where*. Two mechanisms, one scope
vocabulary:

- :func:`scope` — tag a region of (possibly traced) code with a profile
  scope name. Inside a trace it pushes a ``jax.named_scope`` carrying the
  ``apx:`` prefix, so every equation traced under it is attributable; at
  the host level (and under ``measured_profile``'s eager mode) it also
  times the block through the existing recorder timer events
  (``profile/<path>``). Scopes nest: the innermost enclosing scope is
  charged. The package threads scopes through the TP layers, the amp and
  zero train steps, the pipeline ticks and the Pallas ops, so a stock
  train step is attributable out of the box.
- :func:`analytic_profile` — trace a function, walk the jaxpr (recursing
  through pjit/scan/cond/while/custom-vjp sub-jaxprs, multiplying scan
  trip counts) and charge each equation's FLOPs, HBM-proxy bytes and
  collective bytes to its innermost scope. The byte conventions match
  the trace-time collective table (``hooks.collective``: operand bytes),
  and Pallas kernel calls are counted per scope with their operand
  traffic (XLA's own ``cost_analysis`` counts custom calls as 0 FLOPs —
  same caveat as the bench MFU accounting).
- :func:`measured_profile` — sample per-scope WALL time: run the
  function eagerly (``jax.disable_jit``) with scope timing armed, so
  each scope's body executes op-by-op and its recorder timer measures
  real host time. A sampling mode for small shapes; device-accurate
  per-op numbers stay the job of XProf (``monitor.trace.trace`` +
  ``monitor.xprof``).

Purity contract (same as the rest of ``monitor``): ``scope`` inserts
**no operations** — ``jax.named_scope`` only annotates equation
metadata, so the jaxpr of a scoped program is byte-identical to the
unscoped one, recorder attached or not (asserted by
``tests/test_profile.py``). With no recorder attached and jax not
imported, ``scope`` is a stack push/pop and nothing else.

Rendered as a per-module table by ``python -m apex_tpu.monitor profile``
and embedded in ``report.aggregate()["profile"]`` when rows are
recorded into an attached recorder (``record=True``).
"""

from __future__ import annotations

import contextlib
import math
import re
import sys
import threading
from typing import Any, Callable, Optional

from apex_tpu.monitor import _state

# named-scope prefix marking OUR scopes: flax module scopes and user
# jax.named_scope calls share the same name stack, and the attributor
# must only credit regions the profile vocabulary claimed
SCOPE_PREFIX = "apx:"

# matches one profile-scope component anywhere in a name-stack string,
# including inside the jvp(...)/transpose(...) wrappers autodiff adds
# around forward and backward equations
_SCOPE_RE = re.compile(r"apx:([^/()]+)")

UNSCOPED = "(unscoped)"

_local = threading.local()


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_scope() -> str:
    """The host-side scope path at the call site ('' outside any)."""
    return "/".join(_stack())


@contextlib.contextmanager
def scope(name: str):
    """Tag a region for per-module cost attribution.

    ``name`` is one path component (no '/'; slashes are folded to '_').
    Nesting builds the path: ``scope("attn")`` inside ``scope("amp_grad")``
    attributes to ``amp_grad/attn``. Safe everywhere: inside jit traces
    it annotates metadata only (jaxpr-pure); at host level it times the
    block when a recorder is attached and measuring is armed
    (:func:`measured_profile`); with jax not even imported it degrades
    to a plain stack push.
    """
    name = str(name).replace("/", "_")
    st = _stack()
    st.append(name)
    try:
        jax = sys.modules.get("jax")
        cm = (jax.named_scope(SCOPE_PREFIX + name) if jax is not None
              else contextlib.nullcontext())
        rec = _state.recorder
        if rec is not None and getattr(_local, "measure", False):
            with cm, rec.timer("profile/" + "/".join(st)):
                yield
        else:
            with cm:
                yield
    finally:
        st.pop()


def scoped(name: str):
    """Decorator form of :func:`scope`."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with scope(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# analytic attribution: walk the jaxpr, charge the innermost scope
# ---------------------------------------------------------------------------

# primitives charged 1 FLOP per output element (the coarse unit-flop
# model: enough to rank matmuls vs elementwise chains, not a cycle
# count; transcendentals deliberately count 1 — their true cost is a
# VPU-implementation detail this model does not pretend to know)
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "max", "min", "neg", "abs",
    "exp", "log", "log1p", "expm1", "tanh", "logistic", "erf", "erf_inv",
    "erfc", "rsqrt", "sqrt", "sin", "cos", "tan", "sign", "floor", "ceil",
    "round", "integer_pow", "select_n", "clamp", "nextafter", "add_any",
    "and", "or", "xor", "not", "atan2", "square", "cbrt",
})

# reductions: charged 1 FLOP per INPUT element
_REDUCTIONS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "cumsum", "cummax", "cummin",
    "cumprod", "cumlogsumexp", "reduce_precision",
})

# collectives: operand bytes charged to collective_bytes — the SAME
# convention as the trace-time table (hooks.collective is called with
# the input operand by the mappings/DDP/zero comm layers)
_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "psum_scatter", "reduce_scatter", "pbroadcast",
})


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(math.prod(shape)) * dtype.itemsize
    except (TypeError, AttributeError):
        return 0


def _aval_elems(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    try:
        return int(math.prod(shape))
    except TypeError:
        return 0


def _dot_flops(eqn) -> int:
    """2*batch*M*N*K from the dot_general dimension numbers."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = math.prod(lhs[i] for i in lb) if lb else 1
    contract = math.prod(lhs[i] for i in lc) if lc else 1
    m = math.prod(d for i, d in enumerate(lhs) if i not in lc and i not in lb)
    n = math.prod(d for i, d in enumerate(rhs) if i not in rc and i not in rb)
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    """2 * out_elems * (kernel elems / out_features): the standard
    im2col count, feature-group-aware enough for the models here."""
    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params.get("dimension_numbers")
    out_features = rhs[dn.rhs_spec[0]] if dn is not None else rhs[-1]
    per_out = math.prod(rhs) // max(int(out_features), 1)
    return 2 * int(math.prod(out)) * per_out


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE:
        return sum(_aval_elems(o) for o in eqn.outvars)
    if name in _REDUCTIONS:
        return sum(_aval_elems(i) for i in eqn.invars)
    return 0


def _sub_jaxprs(eqn):
    """Every jaxpr nested in an equation's params (pjit/call/scan/cond/
    while/custom-vjp/remat — duck-typed so new primitives keep working)."""
    out = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if hasattr(x, "eqns"):                      # raw Jaxpr
                out.append(x)
            elif hasattr(x, "jaxpr") and hasattr(
                    getattr(x, "jaxpr"), "eqns"):       # ClosedJaxpr
                out.append(x.jaxpr)
    return out


def _scope_of(stack_str: str) -> str:
    parts = _SCOPE_RE.findall(stack_str)
    if not parts:
        return UNSCOPED
    # collapse consecutive repeats: a sub-jaxpr's inner name stacks
    # repeat the enclosing scope the walker already carries in the
    # prefix (and autodiff re-wraps the same scope in jvp/transpose
    # layers), so "amp_grad/amp_grad/fc1" is the fc1 backward, not a
    # nested amp_grad — fwd and bwd merge into one per-module row
    out = [parts[0]]
    for p in parts[1:]:
        if p != out[-1]:
            out.append(p)
    return "/".join(out)


def _new_row() -> dict:
    return {"flops": 0, "hbm_bytes": 0, "collective_bytes": 0,
            "eqns": 0, "pallas_calls": 0}


def _walk(jaxpr, prefix: str, mult: int, rows: dict, meta: dict):
    for eqn in jaxpr.eqns:
        stack = getattr(eqn.source_info, "name_stack", "")
        full = f"{prefix}/{stack}" if prefix else str(stack)
        name = eqn.primitive.name
        subs = _sub_jaxprs(eqn)
        if subs:
            sub_mult = mult
            if name == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1))
            elif name == "while":
                # trip count is dynamic: charge one iteration and flag
                # the result as a lower-bound estimate
                meta["estimated"] = True
            for sub in subs:
                _walk(sub, full, sub_mult, rows, meta)
            continue
        row = rows.setdefault(_scope_of(full), _new_row())
        row["eqns"] += 1
        row["flops"] += mult * _eqn_flops(eqn)
        nbytes = (sum(_aval_bytes(v) for v in eqn.invars)
                  + sum(_aval_bytes(v) for v in eqn.outvars))
        row["hbm_bytes"] += mult * nbytes
        if name in _COLLECTIVES:
            row["collective_bytes"] += mult * sum(
                _aval_bytes(v) for v in eqn.invars)
        if name == "pallas_call":
            row["pallas_calls"] += mult


def attribute_jaxpr(closed_jaxpr) -> dict:
    """Charge every equation of ``closed_jaxpr`` (a ``ClosedJaxpr`` or
    anything with ``.jaxpr.eqns``/``.eqns``) to its innermost enclosing
    profile scope. Returns the raw per-scope rows plus totals, the
    unscoped row, and the scoped-FLOPs coverage fraction."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    rows: dict[str, dict] = {}
    meta = {"estimated": False}
    _walk(jaxpr, "", 1, rows, meta)
    total = _new_row()
    for row in rows.values():
        for k in total:
            total[k] += row[k]
    unscoped = rows.get(UNSCOPED, _new_row())
    coverage = ((total["flops"] - unscoped["flops"]) / total["flops"]
                if total["flops"] else 1.0)
    return {"scopes": rows, "total": total, "unscoped": unscoped,
            "flops_scope_coverage": round(coverage, 6),
            "estimated": meta["estimated"]}


def analytic_profile(fn: Callable, *args, record: bool = False,
                     **kwargs) -> dict:
    """Trace ``fn(*args, **kwargs)`` and attribute its cost per scope.

    Traces with ``jax.make_jaxpr`` (abstract — nothing executes) and
    walks the result with :func:`attribute_jaxpr`. ``record=True`` also
    emits one typed ``profile`` event per scope into the attached
    recorder, so the table rides JSONL dumps and
    ``report.aggregate()["profile"]``.
    """
    import functools
    import jax
    closed = jax.make_jaxpr(functools.partial(fn, **kwargs))(*args)
    prof = attribute_jaxpr(closed)
    if record:
        rec = _state.recorder
        if rec is not None:
            for name, row in sorted(prof["scopes"].items()):
                rec.emit("profile", name, row["flops"],
                         hbm_bytes=row["hbm_bytes"],
                         collective_bytes=row["collective_bytes"],
                         eqns=row["eqns"], pallas_calls=row["pallas_calls"])
            rec.emit("profile", "(total)", prof["total"]["flops"],
                     hbm_bytes=prof["total"]["hbm_bytes"],
                     collective_bytes=prof["total"]["collective_bytes"],
                     eqns=prof["total"]["eqns"],
                     pallas_calls=prof["total"]["pallas_calls"],
                     flops_scope_coverage=prof["flops_scope_coverage"])
    return prof


@contextlib.contextmanager
def measuring():
    """Arm per-scope host timing for the block (used by
    :func:`measured_profile`; composable for custom loops)."""
    prev = getattr(_local, "measure", False)
    _local.measure = True
    try:
        yield
    finally:
        _local.measure = prev


def measured_profile(fn: Callable, *args, repeats: int = 3,
                     recorder=None, **kwargs) -> dict:
    """Sample per-scope WALL time by running ``fn`` eagerly.

    Runs ``fn(*args)`` ``repeats`` times under ``jax.disable_jit()``
    with scope timing armed: every :func:`scope` body executes op-by-op
    and its host timer measures real elapsed time, landing as
    ``profile/<path>`` timer events in ``recorder`` (default: the
    attached one, else a private recorder). Returns
    ``{"scopes": {path: {n, total_s, mean_s}}, "repeats": ...}``.

    This is a *sampling* mode for small shapes (eager dispatch overhead
    rides along); use XProf for device-accurate per-op attribution.
    """
    import jax
    from apex_tpu import monitor
    from apex_tpu.monitor.recorder import Recorder

    rec = recorder or _state.recorder or Recorder(name="measured_profile")
    with monitor.attached(rec), measuring(), jax.disable_jit():
        for _ in range(max(1, int(repeats))):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
    agg = rec.aggregate().get("timers", {})
    rows = {}
    for k, v in agg.items():
        if k.startswith("profile/"):
            rows[k[len("profile/"):]] = {
                "n": v["n"], "total_s": v["total_s"], "mean_s": v["mean_s"]}
    return {"scopes": rows, "repeats": int(repeats)}


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _fmt_count(v: float) -> str:
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def render_profile(prof: dict, measured: Optional[dict] = None,
                   max_rows: int = 40) -> str:
    """Markdown per-module table from :func:`analytic_profile` output
    (optionally merged with a :func:`measured_profile` result)."""
    total = prof["total"]
    tf = total["flops"] or 1
    mrows = (measured or {}).get("scopes", {})
    hdr = ["scope", "flops", "%flops", "hbm bytes", "coll bytes", "eqns"]
    if mrows:
        hdr.append("wall ms (measured)")
    lines = ["| " + " | ".join(hdr) + " |", "|" + "---|" * len(hdr)]
    order = sorted(prof["scopes"].items(),
                   key=lambda kv: (-kv[1]["flops"], kv[0]))
    for name, row in order[:max_rows]:
        cells = [name, _fmt_count(row["flops"]),
                 f"{100.0 * row['flops'] / tf:.1f}%",
                 _fmt_count(row["hbm_bytes"]),
                 _fmt_count(row["collective_bytes"]), str(row["eqns"])]
        if mrows:
            m = mrows.get(name)
            cells.append(f"{1e3 * m['mean_s']:.3f}" if m else "")
        lines.append("| " + " | ".join(cells) + " |")
    if len(order) > max_rows:
        lines.append(f"... ({len(order) - max_rows} more scopes)")
    lines.append("")
    est = " (lower bound: dynamic while-loop trip counts)" \
        if prof.get("estimated") else ""
    lines.append(
        f"total: {_fmt_count(total['flops'])} flops, "
        f"{_fmt_count(total['hbm_bytes'])} hbm bytes, "
        f"{_fmt_count(total['collective_bytes'])} collective bytes; "
        f"scoped-flops coverage "
        f"{100.0 * prof['flops_scope_coverage']:.1f}%{est}")
    return "\n".join(lines)


def demo_train_step(model: str = "gpt", *, batch: int = 2, seq: int = 64,
                    hidden: int = 64, layers: int = 2, heads: int = 2,
                    vocab: int = 256, dtype: str = "float32",
                    attention: str = "fused_softmax",
                    fused_lm_head: bool = False):
    """The canonical amp train step the profile CLI and the bench
    ``profile`` section attribute — ONE recipe, so both always measure
    the same program. Returns ``(step, args)`` with ``step(*args)``
    runnable and traceable. ``model`` is ``"gpt"`` (tiny Megatron-style
    GPT; ``fused_softmax``/unfused LM head by default so every matmul
    is visible to the analytic FLOP model) or ``"mlp"``. All heavy
    imports are deferred to the call."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from apex_tpu import amp
    from apex_tpu.amp import scaler as scaler_mod
    from apex_tpu.optimizers import FusedSGD

    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    if model == "gpt":
        from apex_tpu.models import GPT, GPTConfig
        from apex_tpu.transformer import parallel_state as ps
        ps.destroy_model_parallel()
        cfg = GPTConfig(vocab_size=vocab, max_seq_len=seq,
                        hidden_size=hidden, num_layers=layers,
                        num_heads=heads, dtype=jdtype,
                        attention_impl=attention,
                        fused_lm_head=fused_lm_head)
        gpt = GPT(cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, vocab, (batch, seq)), jnp.int32)
        labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1))
        params = gpt.init(jax.random.PRNGKey(0), ids)
        loss_fn = gpt.loss
        data = (ids, labels)
    elif model == "mlp":
        def loss_fn(p, x, y):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)

        params = {"w1": jnp.ones((hidden, 4 * hidden), jdtype) * 0.1,
                  "w2": jnp.ones((4 * hidden, hidden), jdtype) * 0.1}
        x = jnp.ones((batch, hidden), jdtype)
        data = (x, x)
    else:
        raise ValueError(f"model must be 'gpt' or 'mlp', got {model!r}")
    opt = FusedSGD(lr=0.01)
    opt_state = opt.init(params)
    sstate = scaler_mod.init_state(2.0 ** 8)
    step = amp.make_train_step(loss_fn, opt, donate=False)
    return step, (params, opt_state, sstate) + data


# ---------------------------------------------------------------------------
# MFU / goodput accounting
# ---------------------------------------------------------------------------

#: Dense peak FLOP/s per chip by ``device_kind`` substring (bf16/matmul
#: units — the MFU convention). Sources: published TPU specs (v2-v6e).
#: The ``cpu`` row is a NOMINAL table figure, not a hardware spec: it
#: exists so the whole MFU pipeline (analytic FLOPs ÷ wall ÷ peak) is
#: exercisable and same-host trajectories are self-consistent on CI
#: hosts; cross-host comparison is blocked by the bench's platform-
#: bound unit markers, so the arbitrariness never leaks into a verdict.
PEAK_FLOPS = {
    "tpu v2": 45e12,
    "tpu v3": 123e12,
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,
    "tpu v6e": 918e12,
    "tpu7": 2307e12,
    "cpu": 5e10,
}


def peak_flops_for(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak FLOP/s for a ``device_kind`` string (default: the first
    jax device's), by normalized longest-substring match against
    :data:`PEAK_FLOPS`. ``None`` for unknown kinds — callers must treat
    that as "MFU not computable", never substitute a guess."""
    if device_kind is None:
        jax = sys.modules.get("jax")
        if jax is None:
            return None
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    kind = str(device_kind).strip().lower()
    best = None
    for key, val in PEAK_FLOPS.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, val)
    return best[1] if best else None


def mfu(flops_per_step: float, step_time_s: float, *,
        peak: Optional[float] = None,
        device_kind: Optional[str] = None,
        n_devices: int = 1) -> Optional[dict]:
    """Model FLOPs utilization: ``flops_per_step / step_time_s`` over
    ``n_devices * peak``. ``peak`` (FLOP/s per device) wins over the
    ``device_kind`` table lookup. Returns ``None`` when the peak is
    unknown or the wall time is degenerate, else a dict with
    ``mfu_pct``, ``achieved_flops_per_sec``, ``peak_flops_per_sec``
    and the resolved ``device_kind``."""
    if step_time_s is None or step_time_s <= 0 or not flops_per_step:
        return None
    if peak is None:
        peak = peak_flops_for(device_kind)
    if peak is None or peak <= 0:
        return None
    achieved = float(flops_per_step) / float(step_time_s)
    total_peak = float(peak) * max(1, int(n_devices))
    return {"mfu_pct": round(100.0 * achieved / total_peak, 4),
            "achieved_flops_per_sec": achieved,
            "peak_flops_per_sec": total_peak,
            "device_kind": device_kind}


def measured_mfu(fn: Callable, args: tuple, *, flops: Optional[float] = None,
                 peak: Optional[float] = None, repeats: int = 3,
                 record: bool = False) -> Optional[dict]:
    """MFU of one executed step: times ``fn(*args)`` (median of
    ``repeats`` after one warmup/compile call, ``block_until_ready``
    both sides) and divides the analytic FLOPs walk (computed here when
    ``flops`` is not passed) by wall x peak. ``record=True`` lands
    ``profile/mfu_pct`` + ``profile/step_time_ms`` gauges on the
    attached recorder — the training-side twin of the serve engine's
    ``serve/goodput_tokens_per_sec_chip`` gauge."""
    import statistics
    import time as _time

    import jax

    if flops is None:
        flops = analytic_profile(fn, *args)["total"]["flops"]
    jax.block_until_ready(fn(*args))            # compile + warm
    times = []
    for _ in range(max(1, int(repeats))):
        t0 = _time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(_time.perf_counter() - t0)
    wall = statistics.median(times)
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = None
    row = mfu(flops, wall, peak=peak, device_kind=kind,
              n_devices=1)
    out = {"step_time_s": round(wall, 6), "flops": int(flops),
           "repeats": int(repeats), "device_kind": kind}
    if row is not None:
        out.update(row)
        out["device_kind"] = kind
    if record:
        rec = _state.recorder
        if rec is not None:
            rec.gauge("profile/step_time_ms", 1e3 * wall)
            if row is not None:
                rec.gauge("profile/mfu_pct", row["mfu_pct"])
                rec.gauge("profile/achieved_flops_per_sec",
                          row["achieved_flops_per_sec"])
    return out


def render_mfu(row: Optional[dict]) -> str:
    """One human line for a :func:`measured_mfu` result."""
    if not row:
        return "MFU: n/a (no timed execution)"
    base = (f"step {1e3 * row['step_time_s']:.3f} ms over "
            f"{row['repeats']} reps, "
            f"{_fmt_count(row['flops'])} analytic flops")
    if row.get("mfu_pct") is None:
        return (f"MFU: n/a — no peak-FLOPs entry for device_kind "
                f"{row.get('device_kind')!r} (pass --peak-tflops); "
                f"{base}")
    return (f"MFU: {row['mfu_pct']:.4g}% of "
            f"{row['peak_flops_per_sec'] / 1e12:.4g} TFLOP/s peak "
            f"({row.get('device_kind')}) — "
            f"{_fmt_count(row['achieved_flops_per_sec'])} flops/s "
            f"achieved; {base}")


def kernel_vmem_note(kernel: str, **kw) -> Optional[dict]:
    """VMEM envelope for a known Pallas kernel at a block config — the
    ``tune/vmem.py`` tile accounting, surfaced next to a profile row so
    an ops scope's on-chip working set sits beside its HBM traffic.
    Returns None for unknown kernels (never raises)."""
    try:
        from apex_tpu.tune import vmem
        return {"kernel": kernel,
                "vmem_bytes": vmem.vmem_estimate(kernel, **kw),
                "vmem_budget_bytes": vmem.budget_for(kernel)}
    except Exception:
        return None
