"""Request-level span tracing + fixed-bucket log-scale histograms.

The per-request evidence layer for ``apex_tpu.serve`` (and anything
else with a request-shaped lifecycle): typed ``span_start``/``span_end``
events with parent links, plus :class:`LogHistogram` — the O(1)-memory
streaming-percentile structure the serve SLO numbers (p50/p95/p99 token
latency, TTFT, queue wait) are computed from under sustained traffic.

Design rules (the monitor purity contract, serve-grade):

- **host-clock only, zero jax in the hot path**: a span is two
  ``time.perf_counter`` reads and two recorder events; nothing here
  imports jax, inserts ops, or touches traced code. A jitted program
  traced with spans active is byte-identical to one traced without
  (asserted by ``tests/test_serve_telemetry.py``).
- **detached = free**: every entry point's first action is one global
  read; with no recorder attached :func:`start` returns ``None`` and
  :func:`end`/:func:`annotate` on ``None`` return immediately — no id
  allocation, no event, no lock.
- **parent links, not thread context, carry request identity**: a
  request span outlives any one engine step (queue-wait → prefill →
  decode → preempt → re-admit can spread over thousands of steps), so
  callers hold span ids explicitly (``Sequence.span``) and pass
  ``parent=``. The :func:`span` context manager additionally keeps a
  thread-local stack for implicit nesting of block-shaped spans.

Event schema (one JSONL line each, riding the Recorder ring/stream):

- ``span_start`` {name, value=span_id, parent, **attrs}
- ``span_end``   {name, value=duration_s, span=span_id, parent, **attrs}
  (exception unwind adds ``error=<type name>``)
- ``span_event`` {name, value=span_id-or-None, **attrs} — point
  annotations (preempt/evict/re-admit transitions)

``report.aggregate()`` folds ``serve/request`` span ends into the
per-request table and ``histogram`` snapshot events into the SLO block;
``monitor.export`` renders the same histograms in Prometheus exposition
format.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Optional

from apex_tpu.monitor import _state

_lock = threading.Lock()
_next_id = 1
# open spans: span_id -> (name, parent, t0). Entries are removed on
# end(); a span whose recorder detached mid-flight is removed silently.
_open: dict = {}
_local = threading.local()


def _nesting_stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def start(name: str, parent: Optional[int] = None, **attrs) -> Optional[int]:
    """Open a span; returns its id, or ``None`` when monitoring is
    detached (making every later ``end(None)`` a free no-op)."""
    rec = _state.recorder
    if rec is None:
        return None
    global _next_id
    with _lock:
        sid = _next_id
        _next_id += 1
        _open[sid] = (name, parent, time.perf_counter())
    rec.emit("span_start", name, sid, parent=parent, **attrs)
    return sid


def end(span_id: Optional[int], **attrs) -> Optional[float]:
    """Close span ``span_id``; emits ``span_end`` with the measured
    duration and returns it (``None`` for a no-op close)."""
    if span_id is None:
        return None
    with _lock:
        entry = _open.pop(span_id, None)
    if entry is None:
        return None
    name, parent, t0 = entry
    dur = time.perf_counter() - t0
    rec = _state.recorder
    if rec is not None:
        rec.emit("span_end", name, round(dur, 6), span=span_id,
                 parent=parent, **attrs)
    return dur


def annotate(name: str, span: Optional[int] = None, **attrs):
    """Point annotation (a state transition, not a duration): one
    ``span_event`` record linked to ``span``."""
    rec = _state.recorder
    if rec is not None:
        rec.emit("span_event", name, span, **attrs)


@contextlib.contextmanager
def span(name: str, parent: Optional[int] = None, **attrs):
    """Block-shaped span. Nests implicitly: with no explicit
    ``parent``, the innermost open :func:`span` on this thread is the
    parent. An exception unwinds the span with ``error=<type name>``
    before re-raising."""
    st = _nesting_stack()
    if parent is None and st:
        parent = st[-1]
    sid = start(name, parent=parent, **attrs)
    if sid is not None:
        st.append(sid)
    try:
        yield sid
    except BaseException as e:
        end(sid, error=type(e).__name__)
        raise
    else:
        end(sid)
    finally:
        if sid is not None and st and st[-1] == sid:
            st.pop()


def open_spans() -> int:
    """Spans started but not yet ended (leak/debug accessor)."""
    with _lock:
        return len(_open)


# ---------------------------------------------------------------------------
# fixed-bucket log-scale histogram: O(1) memory streaming percentiles
# ---------------------------------------------------------------------------

class LogHistogram:
    """Streaming histogram over geometrically-spaced buckets.

    ``buckets_per_decade`` fixes the resolution: bucket ``i`` covers
    ``[lo * 10^(i/bpd), lo * 10^((i+1)/bpd))``, so a percentile
    estimate (the geometric midpoint of the bucket holding the
    nearest-rank sample) is within a factor ``10^(1/(2*bpd))`` of the
    exact sample — ~12% relative at the default ``bpd=10``, asserted
    by ``tests/test_spans.py``. Memory is the fixed bucket array no
    matter how many samples arrive: the serve engine can observe a
    token latency per generated token for days without growing.

    Values ``<= 0`` or below ``lo`` land in the underflow bin (reported
    at the observed min), values ``>= hi`` in the overflow bin
    (reported at the observed max); exact ``count``/``sum``/``min``/
    ``max`` are tracked alongside. Defaults suit millisecond latencies:
    1e-3 ms (1 us) .. 1e7 ms (~2.8 h).
    """

    def __init__(self, lo: float = 1e-3, hi: float = 1e7,
                 buckets_per_decade: int = 10):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
        self.lo = float(lo)
        self.hi = float(hi)
        self.bpd = int(buckets_per_decade)
        if self.bpd < 1:
            raise ValueError("buckets_per_decade must be >= 1")
        self.n_buckets = int(math.ceil(
            round(math.log10(self.hi / self.lo), 9) * self.bpd))
        self._counts = [0] * self.n_buckets
        self.underflow = 0
        self.overflow = 0
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def bucket_bounds(self, i: int) -> tuple:
        return (self.lo * 10.0 ** (i / self.bpd),
                self.lo * 10.0 ** ((i + 1) / self.bpd))

    def record(self, value) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v < self.lo:                       # incl. v <= 0
            self.underflow += 1
        elif v >= self.hi:
            self.overflow += 1
        else:
            i = int(math.log10(v / self.lo) * self.bpd)
            # float rounding at an exact bucket edge can land one off
            i = min(max(i, 0), self.n_buckets - 1)
            blo, bhi = self.bucket_bounds(i)
            if v < blo:
                i -= 1
            elif v >= bhi:
                i += 1
            self._counts[min(max(i, 0), self.n_buckets - 1)] += 1

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile estimate (geometric bucket midpoint,
        clipped to the exact observed [min, max])."""
        if self.count == 0:
            return None
        rank = max(1, int(math.ceil(p / 100.0 * self.count)))
        cum = self.underflow
        if rank <= cum:
            return self.min
        for i, c in enumerate(self._counts):
            cum += c
            if rank <= cum:
                blo, bhi = self.bucket_bounds(i)
                est = math.sqrt(blo * bhi)
                return min(max(est, self.min), self.max)
        return self.max                        # overflow bin

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    # -- (de)serialization: the `histogram` event payload -------------
    def snapshot(self) -> dict:
        """Cumulative JSONL-safe snapshot (sparse bucket counts)."""
        return {"lo": self.lo, "hi": self.hi,
                "buckets_per_decade": self.bpd,
                "count": self.count, "sum": round(self.sum, 6),
                "min": self.min, "max": self.max,
                "underflow": self.underflow, "overflow": self.overflow,
                "counts": {str(i): c for i, c in enumerate(self._counts)
                           if c}}

    @classmethod
    def from_snapshot(cls, snap: dict) -> "LogHistogram":
        h = cls(lo=float(snap["lo"]), hi=float(snap["hi"]),
                buckets_per_decade=int(snap["buckets_per_decade"]))
        h.count = int(snap.get("count", 0))
        h.sum = float(snap.get("sum", 0.0))
        h.min = snap.get("min")
        h.max = snap.get("max")
        h.underflow = int(snap.get("underflow", 0))
        h.overflow = int(snap.get("overflow", 0))
        for i, c in (snap.get("counts") or {}).items():
            h._counts[int(i)] = int(c)
        return h

    @classmethod
    def merge(cls, *snapshots: dict) -> "LogHistogram":
        """Bucket-wise sum of :meth:`snapshot` payloads sharing one
        bucket config — the fleet-aggregation primitive: a percentile
        of the merged histogram is the percentile of the POOLED sample
        population (within the same ``10^(1/(2*bpd))`` ~12% band as a
        single histogram), which averaging per-replica percentiles is
        not. Mismatched ``lo``/``hi``/``buckets_per_decade`` raise —
        merging incompatible bucket grids would silently misbucket."""
        if not snapshots:
            raise ValueError("merge needs at least one snapshot")
        cfg = (float(snapshots[0]["lo"]), float(snapshots[0]["hi"]),
               int(snapshots[0]["buckets_per_decade"]))
        h = cls(lo=cfg[0], hi=cfg[1], buckets_per_decade=cfg[2])
        for snap in snapshots:
            got = (float(snap["lo"]), float(snap["hi"]),
                   int(snap["buckets_per_decade"]))
            if got != cfg:
                raise ValueError(
                    f"histogram config mismatch: {got} != {cfg} "
                    "(lo, hi, buckets_per_decade must agree)")
            h.count += int(snap.get("count", 0))
            h.sum += float(snap.get("sum", 0.0))
            h.underflow += int(snap.get("underflow", 0))
            h.overflow += int(snap.get("overflow", 0))
            for i, c in (snap.get("counts") or {}).items():
                h._counts[int(i)] += int(c)
            mn, mx = snap.get("min"), snap.get("max")
            if mn is not None:
                h.min = mn if h.min is None else min(h.min, mn)
            if mx is not None:
                h.max = mx if h.max is None else max(h.max, mx)
        return h


def hist_summary(snap: dict, percentiles=(50, 95, 99)) -> dict:
    """Percentile summary of a :meth:`LogHistogram.snapshot` payload
    (the shape ``report.aggregate()`` embeds per histogram)."""
    h = LogHistogram.from_snapshot(snap)
    out = {"count": h.count, "mean": round(h.mean, 6) if h.count else None,
           "min": h.min, "max": h.max}
    for p in percentiles:
        v = h.percentile(p)
        out[f"p{p}"] = round(v, 6) if v is not None else None
    return out
