"""Per-op profiling tables from an XProf trace (the ``pyprof.parse`` +
``pyprof.prof`` pipeline as code; moved here from ``apex_tpu/pyprof/
parse.py``, which now re-exports this module).

Reference: ``apex/pyprof/parse/parse.py`` reads the nvprof SQLite DB and
``apex/pyprof/prof/prof.py`` maps each kernel to op semantics with
FLOPs/bytes — an automated trace → per-op table pipeline. The TPU
equivalent parses the ``framework_op_stats`` tool from an
``xplane.pb`` trace (captured with ``jax.profiler.trace`` /
``apex_tpu.monitor.trace.trace``) WITHOUT TensorBoard: each row carries
the op's self time, its share of device time, whether it is HBM- or
compute-bound, and the measured FLOP rate / memory bandwidth — richer
than the reference's name-based reconstruction because the profiler
measured the real kernels after XLA fusion.

Typical use::

    from apex_tpu import monitor
    with monitor.trace.trace("/tmp/tr"):
        step(...); jax.block_until_ready(out)
    for row in monitor.xprof.op_stats("/tmp/tr")[:10]:
        print(row["operation"], row["avg_self_time_us"], row["bound_by"])
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

# Stable snake_case view of the framework_op_stats columns we surface
# (input ids on the left as produced by xprof's gviz tables).
_COLUMNS = {
    "host_or_device": "host_or_device",
    "type": "op_type",
    "operation": "operation",
    "occurrences": "occurrences",
    "total_time": "total_time_us",
    "avg_time": "avg_time_us",
    "total_self_time": "total_self_time_us",
    "avg_self_time": "avg_self_time_us",
    "device_total_self_time_percent": "device_self_time_pct",
    "host_total_self_time_percent": "host_self_time_pct",
    "measured_flop_rate": "measured_flop_rate",
    "measured_memory_bw": "measured_memory_bw_gbps",
    "operational_intensity": "operational_intensity",
    "bound_by": "bound_by",
}


def _xplane_paths(logdir: str) -> List[str]:
    """xplane files of the NEWEST profile session under ``logdir``.

    ``jax.profiler.trace`` writes one timestamped session dir per
    capture; xprof's converter returns None when handed planes from
    different sessions, so re-used logdirs must resolve to one session
    (all files of that session are kept — multi-host captures have one
    per worker)."""
    paths = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(
            f"no *.xplane.pb under {logdir!r} — capture one with "
            f"apex_tpu.monitor.trace.trace(logdir)")
    by_session = {}
    for p in paths:
        by_session.setdefault(os.path.dirname(p), []).append(p)
    latest = max(by_session, key=os.path.getmtime)
    return sorted(by_session[latest])


def _gviz_tables(raw) -> List[List[dict]]:
    """Parse xprof's gviz JSON into per-table lists of dicts keyed by
    column id. ``framework_op_stats`` emits a combined (host+device)
    table and a device-only table over the SAME ops — they must not be
    concatenated (ops would double-count)."""
    if isinstance(raw, bytes):
        raw = raw.decode()
    tables = json.loads(raw)
    if isinstance(tables, dict):
        tables = [tables]
    out = []
    for table in tables:
        ids = [c.get("id") for c in table.get("cols", [])]
        rows = []
        for row in table.get("rows", []) or []:
            rows.append({i: (cell or {}).get("v")
                         for i, cell in zip(ids, row.get("c", []))})
        out.append(rows)
    return out


def op_stats_from_raw(raw, host: bool = False, include_idle: bool = False,
                      top: Optional[int] = None) -> List[dict]:
    """:func:`op_stats` on already-converted ``framework_op_stats``
    bytes/str (gviz JSON) — the parsing/ranking stage, separable for
    tests and for saved tool dumps."""
    tables = _gviz_tables(raw)
    want = "Host" if host else "Device"

    def placements(t):
        return {r.get("host_or_device") for r in t if r.get("type") != "IDLE"}

    # prefer a table dedicated to the wanted placement (xprof emits a
    # combined table AND a device-only table over the same ops); fall
    # back to filtering the combined one
    sel = None
    for t in tables:
        if t and placements(t) == {want}:
            sel = list(t)
            break

    def filter_all_tables(placement):
        # fall back across ALL tables (not just the first: converter
        # versions differ in emission order — advisor r3). Dedup is
        # CROSS-table only — the combined and device-only tables repeat
        # the same ops — while same-named rows within one table (e.g.
        # the same fusion in two compiled programs) are all kept.
        seen, rows = set(), []
        for t in tables:
            table_keys = set()
            for r in t:
                key = (r.get("operation"), r.get("host_or_device"))
                if r.get("host_or_device") == placement and key not in seen:
                    table_keys.add(key)
                    rows.append(r)
            seen |= table_keys
        return rows

    if sel is None:
        sel = filter_all_tables(want)
    if not sel and not host:
        sel = filter_all_tables("Host")
    if not include_idle:
        sel = [r for r in sel if r.get("type") != "IDLE"]
    out = []
    for r in sel:
        out.append({new: r.get(old) for old, new in _COLUMNS.items()})
    out.sort(key=lambda r: r.get("total_self_time_us") or 0.0, reverse=True)
    return out[:top] if top else out


def op_stats(logdir: str, host: bool = False,
             include_idle: bool = False,
             top: Optional[int] = None) -> List[dict]:
    """Per-op table from the trace in ``logdir``.

    Returns a list of dicts (keys: ``operation``, ``op_type``,
    ``occurrences``, ``total_self_time_us``, ``avg_self_time_us``,
    ``device_self_time_pct``, ``bound_by``, ``measured_flop_rate``,
    ``measured_memory_bw_gbps``, ``operational_intensity``, ...) sorted
    by total self time, descending. ``host=False`` selects device rows
    (falling back to host rows when the trace has no device activity —
    note CPU-only traces carry no framework ops at all, this tool is
    for TPU traces); ``top`` truncates.
    """
    from xprof.convert import raw_to_tool_data as rtd

    raw, _ = rtd.xspace_to_tool_data(_xplane_paths(logdir),
                                     "framework_op_stats", {})
    return op_stats_from_raw(raw, host=host, include_idle=include_idle,
                             top=top)


def top_ops(logdir: str, n: int = 5, host: bool = False) -> List[list]:
    """Compact ``[op name, self-time % of device total, bound_by]``
    triples for the n heaviest ops — what ``bench.py`` embeds per model.
    The share is computed from the self-time column (xprof's own
    percent column is unreliable across converter versions)."""
    rows = op_stats(logdir, host=host)
    total = sum(float(r.get("total_self_time_us") or 0.0) for r in rows)
    total = total or 1.0
    return [[r["operation"],
             round(100.0 * float(r.get("total_self_time_us") or 0.0)
                   / total, 2),
             r.get("bound_by") or ""] for r in rows[:n]]


def format_table(rows: List[dict], max_rows: int = 20) -> str:
    """Render rows as the markdown table used in docs/perf.md. The share
    column is computed from the rows' self-times (same policy as
    :func:`top_ops` — xprof's own percent column is unreliable)."""
    total = sum(float(r.get("total_self_time_us") or 0.0)
                for r in rows) or 1.0
    hdr = ("| op | type | n | self ms | self % | bound by | GF/s | GB/s |\n"
           "|---|---|---|---|---|---|---|---|")
    lines = [hdr]
    for r in rows[:max_rows]:
        self_us = float(r.get("total_self_time_us") or 0.0)
        lines.append(
            "| {op} | {ty} | {n} | {ms:.3f} | {pct:.1f} | {bb} | {fr:.1f} "
            "| {bw:.1f} |".format(
                op=str(r.get("operation"))[:48],
                ty=r.get("op_type") or "",
                n=int(r.get("occurrences") or 0),
                ms=self_us / 1000.0,
                pct=100.0 * self_us / total,
                bb=r.get("bound_by") or "",
                fr=float(r.get("measured_flop_rate") or 0.0) / 1e9,
                bw=float(r.get("measured_memory_bw_gbps") or 0.0)))
    return "\n".join(lines)
