"""Bench-trajectory regression detection over evidence rounds.

The repo accumulates one ``BENCH_r<NN>.json`` per round (a driver
wrapper: ``{"n", "cmd", "rc", "tail", "parsed"}``) plus streaming
``bench_stream.jsonl`` evidence. This module turns that pile into a
mechanical verdict:

- **Loader** (:func:`load_round`): ingests driver wrappers, assembled
  bench JSON, and raw evidence streams. Degrades *per round*, never
  crashes: a killed round (``rc != 0`` or ``parsed: null`` — the r05
  shape), a corrupt file, or a missing path becomes an explicit
  ``no-evidence`` row with the reason attached.
- **Versioned schema**: from schema 2 on, bench stamps ``schema`` and a
  per-metric ``units`` map on every section result. Older rounds get
  units from a documented legacy-inference table; in particular, a
  round with only the four contract keys (``metric/value/unit/
  vs_baseline`` — the r01 shape) predates the round-2 timing
  methodology (``block_until_ready`` did not block through the relay
  tunnel, so every r01 number is a *dispatch* rate), and ALL its
  metrics are stamped with a ``(r1 dispatch methodology)`` unit —
  overriding the file's own optimistic ``unit`` field. r01 vs r02+ is
  therefore ``incomparable`` (a unit change), not a fake 50x
  regression.
- **Noise-aware verdicts** (:func:`compare`): per metric, the prior
  comparable rounds form a median/MAD band; the candidate regresses
  only when it falls outside ``max(nmad * MAD, rel_tol * |median|)``
  in the metric's bad direction AND at least ``min_history`` prior
  comparable values exist (two points cannot define noise). Metrics
  with unknown direction never gate.

CLI::

    python -m apex_tpu.monitor regress BENCH_r0*.json \
        [--against BASELINE.json] [--json] [--nmad 3] [--rel-tol 0.05] \
        [--min-history 3]

Exit status is non-zero ONLY on a confirmed ``regression`` verdict —
``no-evidence``, ``incomparable`` and ``insufficient-history`` are
report rows, not failures. Wired into ``scripts/ci.sh`` as a gate over
the smoke-bench stream and the committed rounds.

Pure stdlib (no jax): verdicts render anywhere, including the driver
host.
"""

from __future__ import annotations

import json
import math
import os
from typing import Iterable, Optional

# the schema bench.py stamps from this PR on (see bench RESULT_SCHEMA)
CURRENT_SCHEMA = 2

NO_EVIDENCE = "no-evidence"

# keys that are bookkeeping, not metrics
_NON_METRIC_KEYS = frozenset({
    "schema", "n", "rc", "sections_completed", "timing",
})


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def _numeric_metrics(data: dict) -> dict:
    out = {}
    for k, v in data.items():
        if k in _NON_METRIC_KEYS or k.endswith(("_error", "_skipped")):
            continue
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[k] = float(v)
    return out


def suffix_unit(name: str) -> str:
    if name.endswith("_ms") or "_ms_" in name:
        return "ms"
    if name.endswith("_s"):
        return "s"
    if "tokens_per_sec" in name:
        return "tokens/sec"
    if "imgs_per_sec" in name:
        return "imgs/sec/chip"
    if "mfu" in name:
        return "mfu"
    if name.endswith("_pct"):
        return "%"
    if "speedup" in name or name == "vs_baseline":
        return "ratio"
    if "loss" in name:
        return "loss"
    # byte metrics (r15 on: the monitor.memory layer registers its
    # bench keys here so `monitor regress` gates them lower-better)
    if name.endswith(("_bytes", "_bytes_per_chip", "_bytes_per_page",
                      "_bytes_in_use")) or "_bytes_" in name:
        return "bytes"
    if "occupancy" in name:
        return "fraction (pool occupancy)"
    # fleet metrics (r18 on: monitor.fleet bench keys — replica/alert/
    # decision counts gate as counts, latency/goodput resolve above)
    if name.endswith(("_replicas", "_replicas_up", "_alerts",
                      "_decisions", "_polls")):
        return "count"
    return ""


def _legacy_units(metrics: dict, declared_unit: Optional[str],
                  raw_keys=None) -> tuple:
    """(schema, units) for a round that predates schema stamping.

    The inference table (documented, mechanical):

    - **schema 0** — only the four contract keys (the r01 shape: no
      ``o2_step_ms``, no per-model throughputs). Round 1 predates the
      round-2 timing methodology: the relay tunnel's
      ``block_until_ready`` did not block on device completion, so its
      numbers are dispatch rates. Every metric's unit gets the
      ``(r1 dispatch methodology)`` marker — the file's own ``unit``
      field is overridden because it is exactly the silent drift this
      loader exists to surface.
    - **schema 1** — anything else unstamped (r02-r05 era): the
      declared headline unit is honored and the rest come from the
      name-suffix table.
    """
    methodology_keys = {"o2_step_ms", "gpt_tokens_per_sec",
                        "bert_tokens_per_sec", "timing"}
    # detection runs over the RAW result keys, not the numeric metrics:
    # "timing" is a dict (a marker, not a metric) and would otherwise
    # never match, misclassifying a partial r02+ round as schema 0
    legacy_v0 = not (methodology_keys
                     & (set(metrics) if raw_keys is None
                        else set(raw_keys)))
    units = {k: suffix_unit(k) for k in metrics}
    units["value"] = declared_unit or units.get("value", "")
    if legacy_v0:
        units = {k: f"{u or 'unknown'} (r1 dispatch methodology)"
                 for k, u in units.items()}
        return 0, units
    return 1, units


def _round_from_data(data: dict, path: str, n=None) -> dict:
    metrics = _numeric_metrics(data)
    if not metrics:
        return _no_evidence(path, "no numeric metrics in evidence", n=n)
    if "schema" in data:
        schema = int(data["schema"])
        units = {k: str(v) for k, v in (data.get("units") or {}).items()}
        for k in metrics:
            units.setdefault(k, suffix_unit(k))
    else:
        schema, units = _legacy_units(metrics, data.get("unit"),
                                      raw_keys=set(data))
    rec = {"path": path, "round": n, "status": "ok", "schema": schema,
           "metrics": metrics, "units": units}
    if data.get("interrupted") or data.get("error"):
        rec["partial"] = str(data.get("interrupted") or data.get("error"))
    return rec


def _no_evidence(path: str, reason: str, n=None) -> dict:
    return {"path": path, "round": n, "status": NO_EVIDENCE,
            "reason": reason, "schema": None, "metrics": {}, "units": {}}


def _round_from_stream(lines: list, path: str) -> dict:
    data: dict = {}
    units: dict = {}
    schema = None
    sections = 0
    for obj in lines:
        if obj.get("kind") != "section":
            continue
        sections += 1
        data.update(obj.get("data") or {})
        units.update(obj.get("units") or {})
        if obj.get("schema") is not None:
            schema = obj["schema"]
    if not sections:
        return _no_evidence(path, "stream holds no section lines")
    if schema is not None:
        data["schema"] = schema
        data["units"] = units
    return _round_from_data(data, path)


def load_round(path: str) -> dict:
    """One evidence round from ``path`` — a driver ``BENCH_r*.json``
    wrapper, an assembled bench JSON, or a ``bench_stream.jsonl``
    evidence stream. Never raises: unreadable/corrupt/killed rounds
    come back as ``no-evidence`` rows carrying the reason."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return _no_evidence(path, f"unreadable: {e}")
    try:
        obj = json.loads(text)
    except ValueError:
        # not one JSON document: maybe a JSONL evidence stream
        lines = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                parsed = json.loads(ln)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                lines.append(parsed)
        if lines:
            return _round_from_stream(lines, path)
        return _no_evidence(path, "corrupt JSON (neither document nor "
                                  "JSONL stream)")
    if not isinstance(obj, dict):
        return _no_evidence(path, f"expected a JSON object, got "
                                  f"{type(obj).__name__}")
    if "rc" in obj and "parsed" in obj:
        # driver wrapper round
        n = obj.get("n")
        rc = obj.get("rc")
        parsed = obj.get("parsed")
        if rc not in (0, None):
            return _no_evidence(
                path, f"rc={rc}, parsed: "
                      f"{'null' if not parsed else 'partial'}", n=n)
        if not parsed:
            return _no_evidence(path, "rc=0 but parsed: null", n=n)
        return _round_from_data(parsed, path, n=n)
    if "kind" in obj:
        return _round_from_stream([obj], path)
    return _round_from_data(obj, path)


def load_rounds(paths: Iterable[str]) -> list:
    return [load_round(p) for p in paths]


# ---------------------------------------------------------------------------
# verdicts
# ---------------------------------------------------------------------------

def metric_direction(name: str, unit: str) -> Optional[str]:
    """"higher"/"lower" = which way is better; None = unknown (such a
    metric can be reported but never gates)."""
    base = unit.split(" (")[0]
    if base in ("ms", "s", "bytes") or name.endswith(("_ms", "_s")) \
            or "_ms_" in name or "idle" in name or "bubble" in name \
            or "bytes" in name or "loss" in name or base == "loss" \
            or "ttft" in name or "queue_wait" in name \
            or "occupancy" in name or "mispredict" in name \
            or "utilization" in name or "alert" in name:
        return "lower"
    if name.endswith(("_replicas_up",)):
        return "higher"
    if "/sec" in base or base in ("mfu", "ratio") or "per_sec" in name \
            or "speedup" in name or "mfu" in name or name == "vs_baseline" \
            or "goodput" in name or "capacity_ratio" in name:
        return "higher"
    return None


def _median(xs: list) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _label(rnd: dict) -> str:
    if rnd.get("round") is not None:
        return f"r{int(rnd['round']):02d}"
    return os.path.basename(str(rnd.get("path", "?")))


def compare(rounds: list, against: Optional[dict] = None,
            nmad: float = 3.0, rel_tol: float = 0.05,
            min_history: int = 3) -> dict:
    """Verdict report over ``rounds`` (chronological order; the last
    round WITH evidence is the candidate). ``against`` (an extra
    round record, e.g. a pinned baseline) is prepended to the history.

    Returns ``{"rounds", "candidate", "metrics", "regressions",
    "exit_code"}`` where each metric row carries ``verdict`` in
    {``ok``, ``regression``, ``improvement``, ``insufficient-history``,
    ``unknown-direction``} plus the band arithmetic, and rounds whose
    unit for that metric differs from the candidate's are listed under
    ``incomparable`` instead of entering the band."""
    summaries = []
    for r in rounds:
        row = {"round": _label(r), "status": r["status"],
               "schema": r.get("schema"), "path": r.get("path")}
        if r["status"] != "ok":
            row["reason"] = r.get("reason")
        elif r.get("partial"):
            row["partial"] = r["partial"]
        summaries.append(row)

    evidence = [r for r in rounds if r["status"] == "ok"]
    report: dict = {"rounds": summaries, "metrics": {}, "regressions": [],
                    "candidate": None, "exit_code": 0}
    if not evidence:
        report["note"] = "no round with evidence; nothing to compare"
        return report
    candidate = evidence[-1]
    history = ([] if against is None or against.get("status") != "ok"
               else [against]) + evidence[:-1]
    report["candidate"] = _label(candidate)

    for name in sorted(candidate["metrics"]):
        value = candidate["metrics"][name]
        unit = candidate["units"].get(name, "")
        prior, incomparable = [], []
        for r in history:
            if name not in r["metrics"]:
                continue
            r_unit = r["units"].get(name, "")
            if r_unit != unit:
                incomparable.append(
                    {"round": _label(r), "unit": r_unit})
            else:
                prior.append((_label(r), r["metrics"][name]))
        row: dict = {"unit": unit, "value": value,
                     "history": [{"round": lb, "value": v}
                                 for lb, v in prior]}
        if incomparable:
            row["incomparable"] = incomparable
        direction = metric_direction(name, unit)
        if direction is None:
            row["verdict"] = "unknown-direction"
        elif not prior or len(prior) < min_history:
            # `not prior` matters independently: min_history=0 must not
            # send an empty trajectory into the band arithmetic
            row["verdict"] = "insufficient-history"
            row["note"] = (f"{len(prior)} comparable prior round(s); "
                           f"need {min_history} for a noise band")
        else:
            vals = [v for _, v in prior]
            med = _median(vals)
            mad = _median([abs(v - med) for v in vals])
            band = max(nmad * mad, rel_tol * abs(med))
            delta = value - med
            row.update({"median": med, "mad": mad, "band": band,
                        "delta": delta, "direction": direction})
            worse = delta < -band if direction == "higher" else delta > band
            better = delta > band if direction == "higher" else delta < -band
            row["verdict"] = ("regression" if worse
                              else "improvement" if better else "ok")
            if worse:
                report["regressions"].append(name)
        report["metrics"][name] = row
    report["exit_code"] = 1 if report["regressions"] else 0
    return report


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e5 or abs(v) < 1e-3:
            return f"{v:.3e}"
        return f"{v:.4g}"
    return str(v)


def render_regress(report: dict, max_history: int = 8) -> str:
    """Human-readable verdict tables."""
    parts = ["# bench trajectory"]
    parts.append("\n## rounds\n")
    parts.append("| round | status | schema | detail |\n|---|---|---|---|")
    for row in report["rounds"]:
        detail = row.get("reason") or row.get("partial") or ""
        parts.append(f"| {row['round']} | {row['status']} "
                     f"| {row.get('schema') if row.get('schema') is not None else ''} "
                     f"| {detail} |")
    if report.get("note"):
        parts.append(f"\n{report['note']}")
        return "\n".join(parts)
    parts.append(f"\ncandidate round: **{report['candidate']}**")
    parts.append("\n## metrics\n")
    parts.append("| metric | unit | history | median | band | value | "
                 "verdict |\n|---|---|---|---|---|---|---|")
    order = sorted(
        report["metrics"].items(),
        key=lambda kv: ({"regression": 0, "improvement": 1, "ok": 2,
                         "insufficient-history": 3,
                         "unknown-direction": 4}.get(kv[1]["verdict"], 5),
                        kv[0]))
    for name, row in order:
        hist = " ".join(_fmt(h["value"])
                        for h in row["history"][-max_history:])
        verdict = row["verdict"]
        if row.get("incomparable"):
            inc = ",".join(i["round"] for i in row["incomparable"])
            verdict += f" (incomparable: {inc})"
        parts.append(
            f"| {name} | {row['unit']} | {hist} | {_fmt(row.get('median'))} "
            f"| {_fmt(row.get('band'))} | {_fmt(row['value'])} "
            f"| {verdict} |")
    if report["regressions"]:
        parts.append(f"\nREGRESSIONS: {', '.join(report['regressions'])}")
    else:
        parts.append("\nno confirmed regressions")
    return "\n".join(parts)
