"""Chrome-trace / Perfetto exporter: one timeline across every rank.

``python -m apex_tpu.monitor timeline <shards-or-flight-dumps...> -o
trace.json`` fuses rank-tagged recorder dumps (live ``monitor-N.jsonl``
shards and crash ``flight-N.jsonl`` dumps alike) into one Catapult
JSON that chrome://tracing and https://ui.perfetto.dev open directly:

- **one process track per rank** (``pid`` = process_index, named via
  ``process_name`` metadata), with fixed threads for steps, compile,
  health, and counters, plus one thread per span *tree* so concurrent
  requests render as parallel rows;
- **span trees** as duration events — closed spans are complete
  (``ph:"X"``) events nested by containment, spans still open at dump
  time (``span_start`` without ``span_end``, and the flight recorder's
  ``open_span`` stack) are unterminated ``ph:"B"`` events, which
  Perfetto renders as running-to-end-of-trace: the kill-time stack is
  visible at a glance;
- **compile events**: the ``jax/compile/trace|lower|backend`` timers
  (emitted at completion, so ``ts = t - duration``) as duration events,
  cache hits/misses as instants on the compile thread;
- **``memory/hbm_*`` sampler series** as counter tracks (``ph:"C"``);
- **health/watchdog events** as process-scoped instants (``ph:"i"``)
  — the nan/OOM-forecast/straggler marks sit on the same time axis as
  the spans that caused them.

Cross-rank clock alignment: every recorder stamps events with its own
``perf_counter`` origin, so rank clocks are mutually offset. Step
records carry their step index and start time; SPMD ranks execute the
same step numbers, so the per-rank offset to the reference rank is the
median of ``t_ref[step] - t_rank[step]`` over shared step indices —
robust to stragglers, exact enough to line up step boundaries. Ranks
sharing no step indices stay unaligned (offset 0, noted in metadata).

Straggler overlay (reusing :mod:`apex_tpu.monitor.merge`'s skew
machinery): per shared step, each rank's step time over the cross-rank
median rides a ``step/over_median`` counter track, and any step whose
slowest rank exceeds ``straggler_ratio`` x the median gets a named
instant on that rank; the run-level ``steps.skew`` block from
``merge_summaries`` (per-rank ratio, slowest rank) lands in the trace
metadata.

Pure stdlib, no jax import (APX001): timelines render anywhere,
including hosts with no accelerator — the triage path for a run that
no longer exists.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
from typing import Iterable, Optional

from apex_tpu.monitor.report import load_jsonl

__all__ = ["load_sources", "build_timeline", "validate_timeline",
           "write_timeline"]

RANK_RE = re.compile(r"(?:monitor|flight)-(\d+)\.jsonl$")

# fixed per-rank thread ids (span trees get TID_SPAN_BASE + k)
TID_STEPS = 1
TID_COMPILE = 2
TID_HEALTH = 3
TID_COUNTERS = 4
TID_SPAN_BASE = 10

COMPILE_TIMERS = ("jax/compile/trace", "jax/compile/lower",
                  "jax/compile/backend")
HBM_PREFIX = "memory/hbm_"
STRAGGLER_RATIO = 1.5

_GLOB_CHARS = ("*", "?", "[")


def _expand(specs: Iterable[str]) -> list[str]:
    """Paths from a mix of files, directories (all shards + flight
    dumps inside), and glob patterns; order-preserving, deduplicated."""
    paths: list[str] = []
    for spec in specs:
        spec = os.fspath(spec)
        if os.path.isdir(spec):
            paths.extend(sorted(
                _glob.glob(os.path.join(spec, "monitor-*.jsonl"))
                + _glob.glob(os.path.join(spec, "flight-*.jsonl"))))
        elif any(c in spec for c in _GLOB_CHARS):
            paths.extend(sorted(_glob.glob(spec)))
        else:
            paths.append(spec)
    seen: set = set()
    return [p for p in paths if not (p in seen or seen.add(p))]


def load_sources(specs: Iterable[str]) -> list[dict]:
    """Load dump files into per-rank groups ``{rank, paths, headers,
    events}``. Rank comes from the header ``meta.process_index``, else
    the ``monitor-N``/``flight-N`` filename, else enumeration order; a
    shard and a flight dump of the same rank fuse into one group."""
    loaded = []
    for path in _expand(specs):
        header, events = load_jsonl(path)
        rank = (header.get("meta") or {}).get("process_index")
        if rank is None:
            m = RANK_RE.search(os.path.basename(path))
            rank = int(m.group(1)) if m else None
        loaded.append({"path": path, "rank": rank,
                       "header": header, "events": events})
    used = {s["rank"] for s in loaded if s["rank"] is not None}
    nxt = 0
    for s in loaded:
        if s["rank"] is None:
            while nxt in used:
                nxt += 1
            s["rank"] = nxt
            used.add(nxt)
    groups: dict[int, dict] = {}
    for s in loaded:
        g = groups.setdefault(s["rank"], {"rank": int(s["rank"]),
                                          "paths": [], "headers": [],
                                          "events": []})
        g["paths"].append(s["path"])
        g["headers"].append(s["header"])
        g["events"].extend(s["events"])
    return [groups[r] for r in sorted(groups)]


def _num(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f and f not in (float("inf"), float("-inf")) else None


def _step_starts(events: list[dict]) -> dict[int, float]:
    out = {}
    for ev in events:
        if ev.get("kind") == "step":
            t = _num(ev.get("t"))
            if t is not None and ev.get("step") is not None:
                out[int(ev["step"])] = t
    return out


def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def clock_offsets(sources: list[dict]) -> dict[int, float]:
    """Per-rank seconds to ADD to local event times to land on the
    reference (lowest) rank's clock, from shared step-boundary events
    (module docstring)."""
    if not sources:
        return {}
    ref = _step_starts(sources[0]["events"])
    offsets = {sources[0]["rank"]: 0.0}
    for src in sources[1:]:
        mine = _step_starts(src["events"])
        common = sorted(set(ref) & set(mine))
        offsets[src["rank"]] = (
            _median([ref[k] - mine[k] for k in common]) if common else 0.0)
    return offsets


def _us(t_s: float) -> float:
    return round(t_s * 1e6, 3)


def _span_args(ev: dict) -> dict:
    skip = {"kind", "name", "value", "t", "span", "parent", "step"}
    return {k: v for k, v in ev.items() if k not in skip}


def _rank_span_events(events: list[dict], pid: int, off: float,
                      tid_of_root, out: list[dict]):
    """Span trees → X (closed) / unterminated B (open) duration events,
    one thread per root span so concurrent requests stack cleanly."""
    starts: dict = {}
    parent_of: dict = {}
    names: dict = {}
    closed = []           # (sid, t0, dur, name, args)
    opens: dict = {}      # sid -> (t0, name, args)
    for ev in events:
        kind = ev.get("kind")
        if kind == "span_start":
            sid = ev.get("value")
            if sid is not None:
                starts[sid] = ev
                parent_of[sid] = ev.get("parent")
                names[sid] = ev.get("name")
        elif kind == "span_end":
            sid = ev.get("span")
            dur = _num(ev.get("value")) or 0.0
            t_end = _num(ev.get("t")) or 0.0
            s = starts.pop(sid, None)
            t0 = _num(s.get("t")) if s is not None else None
            if t0 is None:
                t0 = t_end - dur
            args = _span_args(s) if s is not None else {}
            args.update(_span_args(ev))
            if sid is not None and sid not in parent_of:
                parent_of[sid] = ev.get("parent")
                names[sid] = ev.get("name")
            closed.append((sid, t0, dur, ev.get("name"), args))
            opens.pop(sid, None)
        elif kind == "open_span":
            sid = ev.get("value")
            t0 = _num(ev.get("t")) or 0.0
            if sid is not None:
                parent_of[sid] = ev.get("parent")
                names[sid] = ev.get("name")
                opens[sid] = (t0, ev.get("name"), _span_args(ev))
                starts.pop(sid, None)
    # span_start with neither end nor open_span record: open at dump time
    for sid, ev in starts.items():
        opens.setdefault(sid, (_num(ev.get("t")) or 0.0, ev.get("name"),
                               _span_args(ev)))

    def root_of(sid):
        cur, hops = sid, 0
        while hops < 1000:
            p = parent_of.get(cur)
            if p is None or p == cur or p not in parent_of:
                return cur
            cur, hops = p, hops + 1
        return cur

    for sid, t0, dur, name, args in closed:
        tid = tid_of_root(root_of(sid), names.get(root_of(sid)) or name)
        out.append({"ph": "X", "name": name, "pid": pid, "tid": tid,
                    "ts": _us(t0 + off), "dur": _us(max(dur, 0.0)),
                    "args": {**args, "span": sid,
                             "parent": parent_of.get(sid)}})
    for sid, (t0, name, args) in sorted(opens.items()):
        tid = tid_of_root(root_of(sid), names.get(root_of(sid)) or name)
        out.append({"ph": "B", "name": name, "pid": pid, "tid": tid,
                    "ts": _us(t0 + off),
                    "args": {**args, "span": sid, "open_at_dump": True,
                             "parent": parent_of.get(sid)}})


def build_timeline(sources: list[dict], align: bool = True,
                   straggler_ratio: float = STRAGGLER_RATIO) -> dict:
    """Fuse per-rank source groups (:func:`load_sources`) into one
    Chrome-trace dict (``{"traceEvents": [...], ...}``)."""
    offsets = clock_offsets(sources) if align else \
        {s["rank"]: 0.0 for s in sources}
    events: list[dict] = []
    step_durs: dict[int, dict[int, float]] = {}   # step -> rank -> dur
    step_ts: dict[int, dict[int, float]] = {}     # step -> rank -> ts (aligned)

    for src in sources:
        pid = src["rank"]
        off = offsets.get(pid, 0.0)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"rank {pid}"}})
        for tid, tname in ((TID_STEPS, "steps"), (TID_COMPILE, "compile"),
                           (TID_HEALTH, "health")):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        span_tids: dict = {}

        def tid_of_root(root_sid, root_name, _pid=pid,
                        _tids=span_tids):
            tid = _tids.get(root_sid)
            if tid is None:
                tid = TID_SPAN_BASE + len(_tids)
                _tids[root_sid] = tid
                events.append({"ph": "M", "name": "thread_name",
                               "pid": _pid, "tid": tid,
                               "args": {"name": f"span/{root_name}"}})
            return tid

        for ev in src["events"]:
            kind = ev.get("kind")
            t = _num(ev.get("t"))
            if kind == "step" and t is not None:
                dur = _num(ev.get("value")) or 0.0
                idx = ev.get("step")
                events.append({
                    "ph": "X", "name": f"step {idx}", "pid": pid,
                    "tid": TID_STEPS, "ts": _us(t + off),
                    "dur": _us(max(dur, 0.0)),
                    "args": {"step": idx, "step_time_s": dur}})
                if idx is not None:
                    step_durs.setdefault(int(idx), {})[pid] = dur
                    step_ts.setdefault(int(idx), {})[pid] = _us(t + off)
            elif kind == "timer" and ev.get("name") in COMPILE_TIMERS \
                    and t is not None:
                dur = _num(ev.get("value")) or 0.0
                events.append({
                    "ph": "X", "name": ev["name"], "pid": pid,
                    "tid": TID_COMPILE, "ts": _us(t - dur + off),
                    "dur": _us(max(dur, 0.0)),
                    "args": {k: v for k, v in ev.items()
                             if k not in ("kind", "name", "value", "t")}})
            elif kind == "counter" and t is not None and \
                    str(ev.get("name", "")).startswith("jax/compile/cache_"):
                events.append({
                    "ph": "i", "name": ev["name"], "pid": pid,
                    "tid": TID_COMPILE, "ts": _us(t + off), "s": "t",
                    "args": {"total": ev.get("total")}})
            elif kind == "gauge" and t is not None and \
                    str(ev.get("name", "")).startswith(HBM_PREFIX):
                v = _num(ev.get("value"))
                if v is not None:
                    events.append({
                        "ph": "C", "name": ev["name"], "pid": pid,
                        "tid": TID_COUNTERS, "ts": _us(t + off),
                        "args": {"value": v}})
            elif kind == "health_event" and t is not None:
                events.append({
                    "ph": "i", "name": f"health/{ev.get('name')}",
                    "pid": pid, "tid": TID_HEALTH, "ts": _us(t + off),
                    "s": "p",
                    "args": {"severity": ev.get("severity"),
                             "diagnosis": ev.get("diagnosis"),
                             "step": ev.get("step"),
                             "value": ev.get("value")}})
        _rank_span_events(src["events"], pid, off, tid_of_root, events)

    # straggler overlay: per shared step, each rank's time over the
    # cross-rank median; slowest rank named when past the bar
    for idx in sorted(step_durs):
        durs = step_durs[idx]
        if len(durs) < 2:
            continue
        med = _median(list(durs.values()))
        for pid, dur in durs.items():
            ratio = dur / med if med > 0 else 0.0
            events.append({"ph": "C", "name": "step/over_median",
                           "pid": pid, "tid": TID_COUNTERS,
                           "ts": step_ts[idx][pid],
                           "args": {"value": round(ratio, 3)}})
        slowest = max(durs, key=durs.get)
        ratio = durs[slowest] / med if med > 0 else 0.0
        if ratio >= straggler_ratio:
            events.append({
                "ph": "i", "pid": slowest, "tid": TID_STEPS,
                "ts": step_ts[idx][slowest], "s": "p",
                "name": f"straggler: rank {slowest} "
                        f"{ratio:.2f}x median (step {idx})",
                "args": {"step": idx, "ratio": round(ratio, 3),
                         "median_step_time_s": round(med, 6)}})

    # run-level skew block via the existing merge machinery
    skew = None
    try:
        from apex_tpu.monitor import merge as _merge
        summaries = [_merge.rank_summary(
            (s["headers"] or [{}])[0], s["events"], rank=s["rank"])
            for s in sources]
        if summaries:
            skew = _merge.merge_summaries(summaries).get(
                "steps", {}).get("skew")
    except Exception:
        skew = None

    # stable, per-track-monotonic order: metadata first, then by track/ts
    def sort_key(ev):
        # at equal ts, the longer duration (the enclosing parent) first
        return (0 if ev["ph"] == "M" else 1, ev["pid"],
                ev.get("tid", 0) or 0, ev.get("ts", 0.0) or 0.0,
                -(ev.get("dur", 0.0) or 0.0))

    events.sort(key=sort_key)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "apex_tpu_timeline": {
                "n_ranks": len(sources),
                "sources": {str(s["rank"]): s["paths"] for s in sources},
                "clock_offset_s": {str(r): round(o, 6)
                                   for r, o in offsets.items()},
                "aligned": bool(align),
                "straggler_ratio": straggler_ratio,
                "skew": skew,
            }
        },
    }


def validate_timeline(trace: dict) -> list[str]:
    """Shape-check a Chrome-trace dict; returns a list of problems
    (empty = valid). Checks the contract the CI gate enforces: every
    event has ``ph``/``pid`` (+ ``ts`` off the metadata phase),
    timestamps are monotonic per (pid, tid) track in list order,
    duration events carry non-negative ``dur``, and B/E begin/end
    events balance per track (unterminated B's — the open-span stack —
    are allowed; an E without a B is not)."""
    errs: list[str] = []
    evs = trace.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return ["traceEvents missing or empty"]
    last_ts: dict = {}
    stacks: dict = {}
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            errs.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if not ph:
            errs.append(f"event {i}: missing ph")
        if ev.get("pid") is None:
            errs.append(f"event {i}: missing pid")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            errs.append(f"event {i}: missing/non-numeric ts")
            continue
        key = (ev.get("pid"), ev.get("tid"))
        prev = last_ts.get(key)
        if prev is not None and ts < prev - 1e-6:
            errs.append(f"event {i}: ts {ts} < {prev} on track {key}")
        last_ts[key] = max(ts, prev) if prev is not None else ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errs.append(f"event {i}: X event needs dur >= 0")
        elif ph == "B":
            stacks.setdefault(key, []).append(ev.get("name"))
        elif ph == "E":
            st = stacks.get(key)
            if not st:
                errs.append(f"event {i}: E without matching B on "
                            f"track {key}")
            else:
                st.pop()
    return errs


def write_timeline(trace: dict, path: str) -> str:
    """Serialize a trace dict to ``path`` atomically."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(trace, f)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path
