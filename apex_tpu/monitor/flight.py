"""Crash-safe flight recorder: a black box that survives the kill.

Every telemetry surface so far (spans, step records, compile events,
HBM samples, health events) lives in the Recorder's in-process ring —
which dies with the process, exactly when a preempted, OOM-killed, or
watchdog-aborted run most needs it. This module arms dump triggers so
the *tail* of that ring lands on disk whenever the process is about to
stop being able to tell its own story:

- **signals** — SIGTERM/SIGINT (the preemption notice and the ^C),
  installed idempotently in the ``trace.install_compile_logging`` mold,
  chaining any previously-installed handler so the host's own shutdown
  logic still runs;
- **atexit** — normal-looking interpreter exits that never called
  ``monitor.detach`` (an uncaught exception unwinding ``main``);
- **fatal watchdog events** — ``health.Watchdog`` calls
  :func:`trigger` for the conditions in ``health.FLIGHT_DUMP_EVENTS``
  (``nan``, ``hbm_high_water``, ``memory_leak``): the dump captures the
  last seconds *before* the crash the event forecasts;
- **explicit** — :func:`snapshot` anywhere (serve-engine aborts,
  elastic reshard boundaries, a debugger prompt).

The dump is one rank-tagged ``flight-{process_index}.jsonl``: a
``header`` line carrying the trigger reason + blind-spot counters
(``dropped``, ``open_spans``), the newest ``tail_events`` ring events,
cumulative histogram snapshots, and one ``open_span`` record per
still-open span — the "what was rank 3 doing when it died" stack. The
write is atomic (tmp + fsync + rename), so a kill arriving *mid-dump*
leaves either the previous complete dump or none — never a torn file
(``merge``/``timeline`` additionally tolerate a truncated trailing
line, belt and braces). Repeated triggers overwrite: last dump wins.

APX001 discipline: pure stdlib, no jax at import. Every trigger's
first action is one global read — with monitoring detached, dumps are
no-ops and the installed handlers only chain.

Consume dumps with the same CLIs as live shards::

    python -m apex_tpu.monitor report   flight-0.jsonl
    python -m apex_tpu.monitor merge    'flight-*.jsonl' --json
    python -m apex_tpu.monitor timeline flight-*.jsonl -o trace.json
"""

from __future__ import annotations

import atexit
import os
import signal
import sys
import threading
import time
from typing import Optional

from apex_tpu.monitor import _state
from apex_tpu.monitor.recorder import json_line

__all__ = ["install", "uninstall", "installed", "snapshot", "trigger",
           "flight_path", "DEFAULT_TAIL_EVENTS"]

DEFAULT_TAIL_EVENTS = 4096

_lock = threading.Lock()
_installed = False
_prev_handlers: dict = {}          # signum -> previous handler
_config = {"directory": ".", "tail_events": DEFAULT_TAIL_EVENTS,
           "atexit_dump": False}


def flight_path(directory: str, process_index: int) -> str:
    """The rank-tagged flight-dump file for one process."""
    return os.path.join(directory, f"flight-{int(process_index)}.jsonl")


def _process_index(rec) -> int:
    """Best-effort rank: recorder meta (set by ``merge.dump_shard`` and
    bench), else an already-imported jax runtime, else 0. Never the
    importer of jax (APX001)."""
    idx = (rec.meta or {}).get("process_index")
    if idx is not None:
        try:
            return int(idx)
        except (TypeError, ValueError):
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


def _open_span_records(rec) -> list[dict]:
    """One ``open_span`` record per still-open span — the stack at dump
    time. ``t`` is recorder-relative start time (same clock as every
    other event), ``age_s`` how long it has been open."""
    from apex_tpu.monitor import spans
    now = time.perf_counter()
    with spans._lock:
        items = [(sid, name, parent, t0)
                 for sid, (name, parent, t0) in spans._open.items()]
    out = []
    for sid, name, parent, t0 in sorted(items):
        out.append({"kind": "open_span", "name": name, "value": sid,
                    "parent": parent, "t": round(t0 - rec._t0, 6),
                    "age_s": round(now - t0, 6)})
    return out


def snapshot(reason: str = "explicit", directory: Optional[str] = None,
             recorder=None, tail_events: Optional[int] = None
             ) -> Optional[str]:
    """Dump the ring tail to ``flight-{rank}.jsonl`` now; returns the
    path, or ``None`` when monitoring is detached (free no-op). Safe
    from signal handlers: the Recorder lock is reentrant and the write
    is atomic (tmp + fsync + rename)."""
    rec = recorder if recorder is not None else _state.recorder
    if rec is None:
        return None
    directory = directory if directory is not None else _config["directory"]
    tail = tail_events if tail_events is not None else _config["tail_events"]
    open_span_evs = _open_span_records(rec)
    events = rec.records()
    if tail and len(events) > tail:
        events = events[-tail:]
    header = {"kind": "header", "name": rec.name, "flight": True,
              "reason": str(reason),
              "t": round(time.perf_counter() - rec._t0, 6),
              "wall_time_unix": round(time.time(), 3),
              "capacity": rec.capacity, "tail_events": int(tail or 0),
              "dropped": rec.dropped, "open_spans": len(open_span_evs),
              "meta": dict(rec.meta)}
    header["meta"].setdefault("process_index", _process_index(rec))
    path = flight_path(directory, header["meta"]["process_index"])
    with _lock:
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(directory or ".", exist_ok=True)
            f = open(tmp, "w")
            try:
                f.write(json_line(header) + "\n")
                for ev in events:
                    f.write(json_line(ev) + "\n")
                for ev in rec._histogram_events():
                    f.write(json_line(ev) + "\n")
                for ev in open_span_evs:
                    f.write(json_line(ev) + "\n")
                f.flush()
                os.fsync(f.fileno())
            finally:
                f.close()
            os.replace(tmp, path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    return path


def _safe_snapshot(reason: str) -> Optional[str]:
    """Handler-path snapshot: a flight-recorder bug must never mask the
    signal that triggered it."""
    try:
        return snapshot(reason)
    except Exception:
        return None


def trigger(reason: str) -> Optional[str]:
    """Dump *if armed*: a no-op unless :func:`install` has run (and
    monitoring is attached). This is the hook the serve engine, elastic
    resharding, and the watchdog call unconditionally — inert wiring
    until someone opts the process into flight recording."""
    if not _installed:
        return None
    return _safe_snapshot(reason)


def _chain(signum, frame):
    """Invoke whatever handler was installed before ours, preserving
    the host's shutdown semantics (including default kill-by-signal)."""
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL:
        # re-deliver under the default disposition so the exit status
        # still says killed-by-signal (what process managers key on)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)
    # SIG_IGN / None: swallow, matching the prior disposition


def _on_signal(signum, frame):
    _safe_snapshot(f"signal:{signal.Signals(signum).name}")
    _chain(signum, frame)


def _on_atexit():
    if _installed and _config.get("atexit_dump"):
        _safe_snapshot("atexit")


def install(directory: Optional[str] = None,
            tail_events: Optional[int] = None,
            signals=(signal.SIGTERM, signal.SIGINT),
            atexit_dump: bool = True) -> bool:
    """Arm the flight recorder (idempotent; returns True on the first,
    arming call). Signal handlers are installed only from the main
    thread (``signal.signal`` raises elsewhere) and chain any prior
    handler; repeat calls just update ``directory``/``tail_events``.
    Nothing here touches jax or the recorder — arming a detached
    process is legal and free until something attaches."""
    global _installed
    if directory is not None:
        _config["directory"] = directory
    if tail_events is not None:
        _config["tail_events"] = int(tail_events)
    _config["atexit_dump"] = bool(atexit_dump)
    if _installed:
        return False
    if threading.current_thread() is threading.main_thread():
        for signum in signals:
            try:
                _prev_handlers[signum] = signal.getsignal(signum)
                signal.signal(signum, _on_signal)
            except (ValueError, OSError):
                pass
    atexit.register(_on_atexit)
    _installed = True
    return True


def installed() -> bool:
    return _installed


def uninstall():
    """Disarm and restore the chained handlers (test hygiene)."""
    global _installed
    if not _installed:
        return
    if threading.current_thread() is threading.main_thread():
        for signum, prev in list(_prev_handlers.items()):
            try:
                if signal.getsignal(signum) is _on_signal:
                    signal.signal(signum, prev if prev is not None
                                  else signal.SIG_DFL)
            except (ValueError, OSError):
                pass
    _prev_handlers.clear()
    try:
        atexit.unregister(_on_atexit)
    except Exception:
        pass
    _installed = False
