"""Declarative SLOs, multi-window burn-rate alerting, and autoscale
decisions over a fleet telemetry view.

This is the policy half of the fleet layer (:mod:`apex_tpu.monitor.
fleet` is the mechanism half: scraping + aggregation). It answers two
questions every poll:

1. **Is the fleet meeting its objectives?** A declarative
   :class:`SLO` table (objective + error budget) is evaluated with
   multi-window multi-burn-rate alerting: for each window pair the
   error-budget burn rate (observed error fraction / budgeted error
   fraction) must exceed the pair's threshold in BOTH the short and
   the long window before an alert fires — the short window makes the
   alert fast, the long window keeps a transient blip from paging.
   Defaults are the classic fast ``5m/1h @ 14.4x`` (page) and slow
   ``30m/6h @ 6x`` (ticket) pairs. Error fractions come from
   cumulative-histogram DELTAS between polls (the fraction of *new*
   samples over the objective), so a long-healthy fleet with one bad
   minute burns exactly that minute, and a single-poll ``--once``
   evaluation degrades to "the whole run is the window" — a violating
   fixture still fires, a compliant one stays silent.

2. **Should the fleet change size?** :class:`AutoscaleDecider` turns
   fleet-wide pressure signals — ``health/kv_pool_exhaustion`` /
   ``admission_starvation`` / ``eviction_storm`` counter deltas (the
   Watchdog's shadow counters, summed across replicas), per-replica
   pool-occupancy headroom, and queue-depth trends — into typed
   ``scale_decision`` events (``scale_out`` / ``scale_in`` /
   ``rebalance``), each carrying a quoted rationale naming the numbers
   that forced it. Decisions are advisory events (the input a router/
   autoscaler consumes); nothing here starts or stops replicas.

Both alert and decision ride the existing health-event schema
(``kind="health_event"``, names registered in
``health.HEALTH_EVENT_KINDS``), so ``report``/``merge``/``timeline``/
``flight`` consume them with zero new plumbing. Pure stdlib, no jax
at import (APX001).
"""

from __future__ import annotations

import collections
import math
from typing import Optional, Sequence

__all__ = ["SLO", "DEFAULT_SLOS", "DEFAULT_WINDOWS", "SLOEvaluator",
           "AutoscaleDecider"]


class SLO:
    """One objective over a fleet metric.

    ``kind="histogram"`` (default): ``metric`` names an exposition
    histogram base (e.g. ``apex_serve_ttft_ms``) and the objective is
    a latency bound — a new sample is an *error* when it lands above
    ``objective`` (judged conservatively at bucket granularity: a
    bucket is "good" only when its whole range is ≤ the objective).

    ``kind="gauge"``: ``metric`` names a gauge and the objective is a
    floor (``op=">="``, e.g. goodput/chip ≥ Y) or ceiling; the error
    fraction is the fraction of live replicas violating it.
    """

    def __init__(self, name: str, metric: str, *, objective: float,
                 kind: str = "histogram", op: str = "<=",
                 error_budget: float = 0.01, description: str = ""):
        if kind not in ("histogram", "gauge"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if op not in ("<=", ">="):
            raise ValueError(f"unknown SLO op {op!r}")
        if not (0.0 < error_budget <= 1.0):
            raise ValueError("error_budget must be in (0, 1]")
        self.name = name
        self.metric = metric
        self.objective = float(objective)
        self.kind = kind
        self.op = op
        self.error_budget = float(error_budget)
        self.description = description or \
            f"{metric} {op} {objective} ({kind})"

    def __repr__(self):
        return (f"SLO({self.name!r}, {self.metric!r}, "
                f"op={self.op!r}, objective={self.objective})")


# Latency objectives generous enough that warmed CPU-CI traffic never
# trips them (compile time is excluded by the bench warmup convention);
# a starved fixture (queue waits in the minutes) blows through all of
# them. Budgets are 1%: one violating sample in a hundred is budgeted,
# a fully-violating interval burns at 100x.
DEFAULT_SLOS = (
    SLO("ttft_p99", "apex_serve_ttft_ms", objective=10_000.0,
        description="time-to-first-token <= 10 s"),
    SLO("queue_wait_p99", "apex_serve_queue_wait_ms", objective=30_000.0,
        description="admission queue wait <= 30 s"),
    SLO("token_latency_p99", "apex_serve_token_latency_ms",
        objective=5_000.0, description="per-token latency <= 5 s"),
)

# (name, short_s, long_s, burn threshold, severity): both windows must
# burn above the threshold to fire. 14.4x on a 1% budget means ~2% of
# a 30-day budget gone in one hour — the SRE-workbook page pair; 6x is
# the slow ticket pair.
DEFAULT_WINDOWS = (
    {"name": "fast", "short_s": 300.0, "long_s": 3600.0,
     "burn": 14.4, "severity": "error"},
    {"name": "slow", "short_s": 1800.0, "long_s": 21600.0,
     "burn": 6.0, "severity": "warn"},
)


def _hist_good_count(snap: dict, objective: float) -> int:
    """Samples of a :meth:`LogHistogram.snapshot` payload known to be
    ≤ ``objective``: the underflow bin plus every bucket whose UPPER
    edge is ≤ the objective (a bucket straddling the objective counts
    as bad — conservative at the histogram's ~12% resolution)."""
    lo = float(snap["lo"])
    bpd = int(snap["buckets_per_decade"])
    good = int(snap.get("underflow", 0))
    for i, c in (snap.get("counts") or {}).items():
        upper = lo * 10.0 ** ((int(i) + 1) / bpd)
        if upper <= objective * (1.0 + 1e-9):
            good += int(c)
    return good


class SLOEvaluator:
    """Multi-window burn-rate evaluation of an :class:`SLO` table.

    Feed it one fleet view per poll (:meth:`observe`); it returns the
    alerts newly firing at that poll. Per (slo, window-pair) hysteresis:
    a firing pair stays latched until its short-window burn drops back
    under the threshold, so a sustained violation alerts once, not
    once per poll.
    """

    def __init__(self, slos: Optional[Sequence[SLO]] = None,
                 windows=None):
        self.slos = tuple(slos if slos is not None else DEFAULT_SLOS)
        self.windows = tuple(windows if windows is not None
                             else DEFAULT_WINDOWS)
        horizon = max((w["long_s"] for w in self.windows), default=0.0)
        self._horizon_s = float(horizon)
        # slo.name -> deque[(t, error_fraction)]
        self._samples: dict = collections.defaultdict(collections.deque)
        # slo.name -> (cum_count, cum_good) basis for histogram deltas
        self._basis: dict = {}
        self._latched: set = set()          # (slo.name, window name)

    # -- per-poll sampling -------------------------------------------------
    def _error_fraction(self, slo: SLO, fleet: dict) -> Optional[float]:
        if slo.kind == "histogram":
            snap = (fleet.get("histograms") or {}).get(slo.metric)
            if not snap:
                return None
            count = int(snap.get("count", 0))
            good = _hist_good_count(snap, slo.objective)
            if slo.op == ">=":            # floor on a latency is odd but legal
                good = count - good
            pc, pg = self._basis.get(slo.name, (0, 0))
            self._basis[slo.name] = (count, good)
            d_count, d_good = count - pc, good - pg
            if d_count <= 0:
                return None               # no new samples: no evidence
            return min(1.0, max(0.0, 1.0 - d_good / d_count))
        view = (fleet.get("gauges") or {}).get(slo.metric)
        if not view:
            return None
        vals = [v for v in (view.get("by_replica") or {}).values()
                if isinstance(v, (int, float)) and math.isfinite(v)]
        if not vals:
            return None
        if slo.op == ">=":
            bad = sum(1 for v in vals if v < slo.objective)
        else:
            bad = sum(1 for v in vals if v > slo.objective)
        return bad / len(vals)

    def _burn(self, name: str, t: float, window_s: float,
              budget: float) -> Optional[float]:
        xs = [f for (ts, f) in self._samples[name] if ts >= t - window_s]
        if not xs:
            return None
        return (sum(xs) / len(xs)) / budget

    def observe(self, fleet: dict, t: float) -> list:
        """Record this poll's error fractions and return newly-firing
        alert dicts (empty when every objective is inside budget)."""
        alerts = []
        for slo in self.slos:
            frac = self._error_fraction(slo, fleet)
            dq = self._samples[slo.name]
            if frac is not None:
                dq.append((t, frac))
            while dq and dq[0][0] < t - self._horizon_s:
                dq.popleft()
            for w in self.windows:
                key = (slo.name, w["name"])
                bs = self._burn(slo.name, t, w["short_s"], slo.error_budget)
                bl = self._burn(slo.name, t, w["long_s"], slo.error_budget)
                if bs is None or bl is None:
                    continue
                firing = bs >= w["burn"] and bl >= w["burn"]
                if not firing:
                    if bs < w["burn"]:
                        self._latched.discard(key)
                    continue
                if key in self._latched:
                    continue
                self._latched.add(key)
                alerts.append({
                    "slo": slo.name, "metric": slo.metric,
                    "window": w["name"], "severity": w["severity"],
                    "burn_short": round(bs, 2), "burn_long": round(bl, 2),
                    "threshold": w["burn"],
                    "error_budget": slo.error_budget,
                    "diagnosis": (
                        f"SLO '{slo.name}' ({slo.description}) burning "
                        f"error budget at {bs:.1f}x in the {w['name']} "
                        f"window pair ({int(w['short_s'])}s/"
                        f"{int(w['long_s'])}s, threshold {w['burn']}x, "
                        f"budget {slo.error_budget:g})"),
                })
        return alerts


class AutoscaleDecider:
    """Fleet pressure → typed scale decisions with quoted rationale.

    Inputs per poll (all read from the fleet view, nothing live):

    - **pressure counters**: deltas of the Watchdog shadow counters
      ``apex_health_{kv_pool_exhaustion,admission_starvation,
      eviction_storm}_total`` summed across replicas — new firings
      since the last poll mean the pool/admission path is saturating;
    - **headroom**: per-replica ``1 - pages_in_use/pages_total``;
    - **queue trend**: the fleet-summed ``apex_serve_queue_depth``
      history (rising queues with pressure = scale out NOW);
    - **fast-burn alerts** from the :class:`SLOEvaluator`.

    Rules (first match wins): new pressure or a fast-burn alert →
    ``scale_out``; a wide per-replica occupancy spread with a hot
    replica → ``rebalance``; ``scale_in_idle_polls`` consecutive
    fully-idle polls (empty queues, ample headroom, no pressure) →
    ``scale_in`` — so a single ``--once`` poll can demand scale-out
    but never scale-in. Repeat decisions are suppressed for
    ``cooldown_polls`` unless new pressure arrives.
    """

    PRESSURE_COUNTERS = ("apex_health_kv_pool_exhaustion_total",
                         "apex_health_admission_starvation_total",
                         "apex_health_eviction_storm_total")

    def __init__(self, *, min_headroom: float = 0.1,
                 scale_in_headroom: float = 0.8,
                 scale_in_idle_polls: int = 3,
                 imbalance: float = 0.5,
                 cooldown_polls: int = 5):
        self.min_headroom = float(min_headroom)
        self.scale_in_headroom = float(scale_in_headroom)
        self.scale_in_idle_polls = int(scale_in_idle_polls)
        self.imbalance = float(imbalance)
        self.cooldown_polls = int(cooldown_polls)
        self._prev_pressure: dict = {}
        self._queue_hist: collections.deque = collections.deque(maxlen=8)
        self._idle_streak = 0
        self._polls = 0
        self._last: Optional[tuple] = None    # (decision, poll index)

    # -- input extraction --------------------------------------------------
    def _pressure_delta(self, fleet: dict) -> dict:
        counters = fleet.get("counters") or {}
        delta = {}
        for k in self.PRESSURE_COUNTERS:
            cur = float(counters.get(k, 0.0))
            d = cur - self._prev_pressure.get(k, 0.0)
            self._prev_pressure[k] = cur
            if d > 0:
                delta[k] = d
        return delta

    @staticmethod
    def _occupancy(fleet: dict) -> dict:
        gauges = fleet.get("gauges") or {}
        used = (gauges.get("apex_serve_pages_in_use") or {}) \
            .get("by_replica") or {}
        total = (gauges.get("apex_serve_pages_total") or {}) \
            .get("by_replica") or {}
        occ = {}
        for rid, tot in total.items():
            if tot and rid in used:
                occ[rid] = used[rid] / tot
        return occ

    def _cooling(self, decision: str) -> bool:
        if self._last is None:
            return False
        last, at = self._last
        return last == decision and self._polls - at < self.cooldown_polls

    def decide(self, fleet: dict, alerts: Sequence[dict]) -> Optional[dict]:
        """One decision (or ``None``) for this poll's fleet view."""
        self._polls += 1
        pressure = self._pressure_delta(fleet)
        occ = self._occupancy(fleet)
        headroom = {rid: 1.0 - o for rid, o in occ.items()}
        min_head = min(headroom.values()) if headroom else None
        gauges = fleet.get("gauges") or {}
        qsum = (gauges.get("apex_serve_queue_depth") or {}).get("sum", 0.0)
        self._queue_hist.append(float(qsum or 0.0))
        q = list(self._queue_hist)
        rising = len(q) >= 3 and q[-1] > q[-2] > q[-3] and q[-1] > 0
        fast = [a for a in alerts if a.get("window") == "fast"]

        def _emit(decision, severity, rationale, **inputs):
            self._last = (decision, self._polls)
            return {"decision": decision, "severity": severity,
                    "rationale": rationale,
                    "inputs": {"pressure": pressure,
                               "min_headroom": min_head,
                               "queue_depth_sum": qsum, **inputs}}

        if pressure or fast:
            if not pressure and self._cooling("scale_out"):
                return None
            why = []
            for k, d in pressure.items():
                short = k[len("apex_health_"):-len("_total")]
                worst = self._worst_replica(fleet, k)
                why.append(f"{int(d)} new {short} firing(s)"
                           + (f" (worst: {worst})" if worst else ""))
            for a in fast:
                why.append(f"fast-burn SLO alert '{a['slo']}' at "
                           f"{a['burn_short']}x budget")
            if min_head is not None:
                why.append(f"min replica headroom {min_head:.0%}")
            if rising:
                why.append(f"queue depth rising (now {qsum:g})")
            self._idle_streak = 0
            return _emit(
                "scale_out", "warn",
                "scale out: " + "; ".join(why),
                alerts=[a["slo"] for a in fast])

        if len(occ) >= 2:
            hot = max(occ, key=occ.get)
            cold = min(occ, key=occ.get)
            spread = occ[hot] - occ[cold]
            if spread > self.imbalance and occ[hot] > 0.7 \
                    and not self._cooling("rebalance"):
                self._idle_streak = 0
                return _emit(
                    "rebalance", "warn",
                    f"rebalance: pool occupancy spread {spread:.0%} "
                    f"(hottest replica '{hot}' at {occ[hot]:.0%}, "
                    f"coldest '{cold}' at {occ[cold]:.0%})",
                    hot=hot, cold=cold, spread=round(spread, 3))

        idle = (not pressure and not alerts and (qsum or 0.0) == 0.0
                and (min_head is None or min_head >= self.scale_in_headroom))
        if idle:
            self._idle_streak += 1
            if self._idle_streak >= self.scale_in_idle_polls \
                    and not self._cooling("scale_in"):
                return _emit(
                    "scale_in", "info",
                    f"scale in: {self._idle_streak} consecutive idle "
                    f"polls (queues empty, min headroom "
                    f"{min_head:.0%})" if min_head is not None else
                    f"scale in: {self._idle_streak} consecutive idle "
                    "polls (queues empty)",
                    idle_polls=self._idle_streak)
        else:
            self._idle_streak = 0
        return None

    @staticmethod
    def _worst_replica(fleet: dict, counter: str) -> Optional[str]:
        """The replica contributing most to a pressure counter, when
        the fleet view kept per-replica counter detail."""
        by = (fleet.get("counters_by_replica") or {}).get(counter) or {}
        if not by:
            return None
        return max(by, key=by.get)
