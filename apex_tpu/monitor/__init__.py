"""apex_tpu.monitor — structured training telemetry for TPU training.

The observability subsystem the reference never had on TPU: a typed-event
:class:`Recorder` (counters, gauges, timers, per-step records in a ring
buffer, JSONL/JSON output, crash-resilient ``stream=`` incremental
flush), instrumentation hooks threaded through amp, optimizers, the
collective mappings, the pipeline schedules and the data loader, a
trace layer subsuming ``apex_tpu.pyprof`` (XProf annotations,
compile-event and jit-cache logging, device-memory snapshots), a
cross-host merge layer (``monitor.merge``: rank-tagged shards +
``python -m apex_tpu.monitor merge`` + in-mesh ``allgather_summaries``),
a training-health :class:`Watchdog` (``monitor.health``: NaN/overflow-
storm/divergence/plateau/starvation/straggler detection as typed
``health_event`` records), per-module cost attribution
(``monitor.profile``: :func:`scope` tags + the analytic jaxpr
attributor + measured wall-time sampling,
``python -m apex_tpu.monitor profile``), bench-trajectory regression
detection (``monitor.regress``: versioned round loader + noise-aware
verdicts, ``python -m apex_tpu.monitor regress``), request-level span
tracing + O(1)-memory log-scale latency histograms (``monitor.spans``:
the serve SLO evidence layer — per-request queue-wait/prefill/decode
traces with preempt/re-admit annotations, rendered as the ``serve``
block of the report), a pull-based Prometheus text-exposition endpoint
(``monitor.export``: lazily imported, ``python -m apex_tpu.monitor
export``), MFU/goodput accounting (``monitor.profile.mfu`` over the
analytic FLOPs walk + a per-device-kind peak table), the unified
memory surface (``monitor.memory``: compiled-footprint attribution,
the analytic high-water walk charged per ``apx:`` scope, the live
:class:`MemorySampler` HBM timeline, ZeRO/serve capacity reports and
the tuner's ``vmem_calibration`` feedback loop,
``python -m apex_tpu.monitor memory``), a crash-safe flight recorder
(``monitor.flight``: SIGTERM/SIGINT/atexit/fatal-watchdog triggers dump
the ring tail + open-span stack atomically to rank-tagged
``flight-<rank>.jsonl`` black boxes), a Chrome-trace/Perfetto exporter
(``monitor.timeline``: shards + flight dumps fused into one cross-rank
timeline with clock alignment and a straggler overlay,
``python -m apex_tpu.monitor timeline``), and a CLI report
(``python -m apex_tpu.monitor report run.jsonl``).

Quick start::

    from apex_tpu import monitor

    rec = monitor.Recorder()
    monitor.trace.install_compile_logging()      # optional: compile events
    with monitor.attached(rec):                  # enables package hooks
        for batch in loader:
            with rec.step():
                state = train_step(state, batch)
    rec.dump_jsonl("run.jsonl")                  # → monitor report CLI
    print(monitor.render_report(rec.records()))

Guarantees (details: docs/observability.md):

- **disabled = free**: with no recorder attached every hook is one
  global read + compare; traced programs are byte-identical to the
  uninstrumented ones (no inserted ops, no retrace).
- **attach = one retrace**: hot paths that thread the monitoring
  guard (``amp.make_train_step``, the stateful optimizer ``step``)
  switch between two cached programs — instrumented/uninstrumented —
  so a flip costs at most one trace and cycles never grow the cache.
- **zero deps**: importing this package (and recording host events)
  touches no jax; jax is imported lazily by the traced hooks and the
  trace layer (APX001-clean).
"""

from __future__ import annotations

import contextlib

from apex_tpu.monitor import _state
from apex_tpu.monitor import flight  # noqa: F401
from apex_tpu.monitor import health  # noqa: F401
from apex_tpu.monitor import hooks  # noqa: F401
from apex_tpu.monitor import memory  # noqa: F401
from apex_tpu.monitor import merge  # noqa: F401
from apex_tpu.monitor import profile  # noqa: F401
from apex_tpu.monitor import regress  # noqa: F401
from apex_tpu.monitor import spans  # noqa: F401
from apex_tpu.monitor import timeline  # noqa: F401
from apex_tpu.monitor import trace  # noqa: F401
from apex_tpu.monitor import xprof  # noqa: F401
from apex_tpu.monitor.health import Watchdog  # noqa: F401
from apex_tpu.monitor.memory import MemorySampler  # noqa: F401
from apex_tpu.monitor.profile import scope  # noqa: F401
from apex_tpu.monitor.recorder import Recorder  # noqa: F401
from apex_tpu.monitor.report import (  # noqa: F401
    aggregate, load_jsonl, render_cross_host, render_fleet, render_memory,
    render_report, render_serve, render_steps, selfcheck)
from apex_tpu.monitor.spans import LogHistogram  # noqa: F401
from apex_tpu.monitor.hooks import enabled, epoch  # noqa: F401


def __getattr__(name: str):
    # lazily-imported submodules: export pulls in http.server (and the
    # disabled-mode contract for the exporter is "no thread, no import
    # cost" — a process that never exports never pays for the module,
    # asserted by tests/test_export.py); fleet/slo sit on top of export
    # and inherit the same laziness so the guarantee survives
    if name in ("export", "fleet", "slo"):
        import importlib
        mod = importlib.import_module(f"apex_tpu.monitor.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu.monitor' has no attribute "
                         f"{name!r}")


def get_recorder() -> Recorder | None:
    """The attached recorder, or None when monitoring is disabled."""
    return _state.recorder


def attach(recorder: Recorder) -> Recorder:
    """Enable monitoring: route all package hooks to ``recorder``.

    Guard-threaded jitted steps pick up the instrumentation on their
    next call (at most one trace per guard flip); attach before first
    use of other jitted code if you want its trace-time events
    (collective accounting) captured. Device callbacks route to
    whichever recorder is attached when a program runs.
    """
    _state.recorder = recorder
    _state.epoch += 1
    return recorder


def detach() -> Recorder | None:
    """Disable monitoring; returns the previously attached recorder."""
    rec, _state.recorder = _state.recorder, None
    _state.epoch += 1
    return rec


@contextlib.contextmanager
def attached(recorder: Recorder):
    """``with monitor.attached(rec): ...`` — attach for the block."""
    prev = _state.recorder
    attach(recorder)
    try:
        yield recorder
    finally:
        if prev is None:
            detach()
        else:
            attach(prev)
