"""Instrumentation entry points called from inside apex_tpu subsystems.

Contract (the disabled-mode overhead guarantee, docs/observability.md):
every hook's first action is reading the module guard; with no recorder
attached it returns immediately — no jax import, no allocation, no
inserted ops. A jitted function traced while monitoring is disabled
therefore produces a jaxpr byte-identical to the uninstrumented
program (asserted by ``tests/test_monitor.py``).

Two families:

- **host hooks** (``counter``/``gauge``/``timer``): run in ordinary
  Python (data loader threads, eager wrappers). Never traced.
- **traced hooks** (``traced_scalar``/``traced_tick``): called from
  inside code under ``jit``/``shard_map``/``scan``; when enabled they
  insert a ``jax.debug.callback`` carrying the device value to the
  recorder. When disabled they insert nothing. NB: JAX's partial-eval
  drops debug callbacks from program regions that are *differentiated
  through* (e.g. a scan under ``value_and_grad``) — place traced hooks
  after the grad computation or in non-differentiated scans.
- **trace-time hooks** (``collective``/``pipeline_schedule``): run on
  the host *while a program is being traced* and record statically-known
  facts (collective op counts/bytes per axis, schedule geometry). Their
  totals are per traced program: a cached executable re-runs the same
  collectives every step without re-counting, so attach the recorder
  before tracing (the guard static arg in ``amp.make_train_step`` and
  ``FusedOptimizerBase.step`` forces that retrace automatically).
"""

from __future__ import annotations

import contextlib
import functools

from apex_tpu.monitor import _state

_NULL = contextlib.nullcontext()


def enabled() -> bool:
    """True iff a recorder is attached (host hooks are live)."""
    return _state.recorder is not None


def traced_enabled() -> bool:
    """True iff a recorder is attached AND it wants traced-hook
    instrumentation (``Recorder(traced_hooks=True)``, the default).
    Code that *inserts ops or callbacks into traced programs* must gate
    on this, not :func:`enabled` — a host-only observer recorder
    (``traced_hooks=False``, e.g. the bench's) must leave compiled
    programs byte-identical."""
    rec = _state.recorder
    return rec is not None and getattr(rec, "traced_hooks", True)


def epoch() -> int:
    """Monitoring epoch — bumped on every attach/detach (a change
    counter for caches that track recorder identity; the jitted hot
    paths key on :func:`traced_enabled` instead so their caches stay
    bounded at two programs)."""
    return _state.epoch


# -- host hooks --------------------------------------------------------------

def counter(name: str, inc: float = 1, **extra):
    rec = _state.recorder
    if rec is not None:
        rec.counter(name, inc, **extra)


def gauge(name: str, value, **extra):
    rec = _state.recorder
    if rec is not None:
        rec.gauge(name, value, **extra)


def observe(name: str, value, **kw):
    """Record one sample into the attached recorder's named log-scale
    histogram (``Recorder.observe`` — O(1) memory streaming
    percentiles; no per-sample event). The serve engine's token-latency
    / TTFT / queue-wait SLO numbers flow through here."""
    rec = _state.recorder
    if rec is not None:
        rec.observe(name, value, **kw)


def timer(name: str):
    """Context manager timing a host-side block; null when disabled."""
    rec = _state.recorder
    if rec is None:
        return _NULL
    return rec.timer(name)


def timer_event(name: str, seconds: float, **extra):
    rec = _state.recorder
    if rec is not None:
        rec.timer_event(name, seconds, **extra)


def tune_event(kernel: str, key: str, *, hit: bool, source: str,
               config=None):
    """One autotuner cache resolution (``apex_tpu.tune.runtime``):
    bumps the ``tune/cache_hit``/``tune/cache_miss`` counter, sets the
    ``tune/cache_hit`` gauge (1.0 on a hit — last-resolution-wins, the
    cheap thing a bench section asserts), and records a typed ``tune``
    event carrying the full cache key and the resolved config."""
    rec = _state.recorder
    if rec is None:
        return
    rec.counter("tune/cache_hit" if hit else "tune/cache_miss")
    rec.gauge("tune/cache_hit", 1.0 if hit else 0.0)
    rec.emit("tune", kernel, key, hit=bool(hit), source=source,
             config=config)


# -- traced hooks (insert a debug callback when enabled) ---------------------
#
# The callback targets resolve the recorder at FIRE time, not at trace
# time: a compiled program that carries instrumentation (because it was
# traced while a recorder was attached) stops emitting the moment the
# recorder is detached, and a later-attached recorder receives the
# events instead — no stale recorder is captured alive inside the
# executable. (Trace-time accounting — collectives, schedules — is by
# definition bound to the recorder attached when the trace ran.)

def _emit_scalar(name: str, value):
    # honor the receiver's traced_hooks opt-out at fire time too: a
    # host-only observer must not collect traced-hook telemetry baked
    # into programs compiled under an earlier instrumented recorder
    rec = _state.recorder
    if rec is not None and getattr(rec, "traced_hooks", True):
        rec._device_scalar(name, value)


def _emit_tick(name: str, tick):
    rec = _state.recorder
    if rec is not None and getattr(rec, "traced_hooks", True):
        rec._device_tick(name, tick)


def traced_scalar(name: str, value):
    """Record a device scalar as a gauge. Call from traced code with a
    jax scalar; inserts a ``jax.debug.callback`` only when enabled."""
    rec = _state.recorder
    if rec is None or not rec.traced_hooks:
        return
    import jax
    jax.debug.callback(
        functools.partial(_emit_scalar, name), value, ordered=False)


def traced_tick(name: str, tick):
    """Record a schedule tick mark (host-arrival timestamped)."""
    rec = _state.recorder
    if rec is None or not rec.traced_hooks:
        return
    import jax
    jax.debug.callback(
        functools.partial(_emit_tick, name), tick, ordered=False)


def _emit_tick_marks(name: str, keys, tick, rank, *vals):
    rec = _state.recorder
    if rec is not None and getattr(rec, "traced_hooks", True):
        rec._device_tick_marks(name, tick, rank, dict(zip(keys, vals)))


def traced_tick_marks(name: str, tick, rank, **slots):
    """Record one MEASURED slot-occupancy mark for a pipeline tick.

    ``slots`` are traced booleans, one per unit slot the tick body
    executes (``f`` = forward unit, ``b`` = backward-input/dgrad unit,
    ``w`` = backward-weight/wgrad unit); a False slot means the
    computation ran masked on padding — an idle slot. ``rank`` is the
    traced pipeline rank, so the aggregated table
    (``report.aggregate()["pipeline_utilization"]``) is per rank.
    Inserts one ``jax.debug.callback`` when enabled, nothing otherwise
    (the disabled-mode purity contract)."""
    rec = _state.recorder
    if rec is None or not rec.traced_hooks:
        return
    import jax
    keys = tuple(sorted(slots))
    jax.debug.callback(
        functools.partial(_emit_tick_marks, name, keys), tick, rank,
        *(slots[k] for k in keys), ordered=False)


# -- trace-time hooks --------------------------------------------------------

def tree_bytes(tree) -> int:
    """Static byte count of a pytree of arrays/tracers (shape/dtype are
    trace-time constants). Only call from an enabled path."""
    import jax
    import numpy as np
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
    return total


def collective(op: str, axis_name, operand=None, *, nbytes: int = None,
               count: int = 1):
    """Account one collective call on ``axis_name`` (trace time).

    ``operand`` (a pytree of arrays/tracers) gives the byte volume;
    pass ``nbytes`` directly when the operand is not at hand.
    ``axis_name`` may be a tuple of names (counted once per name).
    """
    rec = _state.recorder
    if rec is None or not rec.traced_hooks:
        return
    if nbytes is None:
        nbytes = tree_bytes(operand) if operand is not None else 0
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    for ax in names:
        rec.collective(op, str(ax), nbytes=nbytes, count=count)


def pipeline_schedule(schedule: str, n_stages: int, n_microbatches: int,
                      total_ticks: int, useful_ticks: int = None,
                      useful_slots: int = None, total_slots: int = None):
    """Record a pipeline schedule's geometry and its analytic
    bubble-fraction estimate: the fraction of scan ticks a rank spends
    on padding rather than a real microbatch unit,
    ``1 - useful_ticks / total_ticks`` (``useful_ticks`` defaults to
    ``n_microbatches`` — one unit per microbatch per stream). Schedules
    with heterogeneous ticks (zero-bubble: the wgrad stream leaves the
    tick grid) pass ``useful_slots``/``total_slots`` — executed
    unit-slot counts per rank — and the bubble fraction is
    ``1 - useful_slots / total_slots`` instead; for the homogeneous
    schedules the two definitions coincide. Measured per-tick arrivals
    come from ``traced_tick``/``traced_tick_marks`` separately."""
    rec = _state.recorder
    if rec is None or not rec.traced_hooks:
        return
    extra = {}
    if useful_slots is not None and total_slots is not None:
        bubble = 1.0 - (float(useful_slots) / float(total_slots)) \
            if total_slots else 0.0
        extra = {"useful_slots": int(useful_slots),
                 "total_slots": int(total_slots)}
    else:
        useful = n_microbatches if useful_ticks is None else useful_ticks
        bubble = 1.0 - (float(useful) / float(total_ticks)) \
            if total_ticks else 0.0
    rec.gauge(f"pipeline/{schedule}/bubble_fraction", round(bubble, 6))
    rec._emit("schedule", f"pipeline/{schedule}", total_ticks,
              n_stages=int(n_stages), n_microbatches=int(n_microbatches),
              bubble_fraction=round(bubble, 6), **extra)
