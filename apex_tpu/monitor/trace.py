"""Trace layer: annotations, XProf sessions, compile events, memory.

This subsumes ``apex_tpu.pyprof`` (which is now a thin re-export shim):

- :func:`annotate` / :func:`wrap` / :func:`init` — the NVTX-parity
  surface (``apex/pyprof/nvtx/nvmarker.py``): ``jax.named_scope`` tags
  the HLO (per-op in XProf), ``jax.profiler.TraceAnnotation`` tags the
  host timeline. When a recorder is attached, ``wrap`` also times the
  wrapped call as a host timer event.
- :func:`trace` — capture an XProf session (the nvprof-session analog);
  feed the logdir to :mod:`apex_tpu.monitor.xprof` or the CLI report.
- :func:`cost_analysis` / :func:`flop_report` — XLA's own FLOP/byte
  accounting for a compiled program (the ``pyprof.prof`` analog).
- :func:`install_compile_logging` — registers ``jax.monitoring``
  listeners once; afterwards every jaxpr trace, MLIR lowering and
  backend compile (plus compilation-cache hits/misses) is recorded into
  whichever recorder is attached at the time it happens. Idempotent,
  and a no-op while monitoring is disabled (the listener checks the
  guard per event).
- :func:`device_memory_snapshot` / :func:`memory_analysis` —
  DEPRECATED re-export shims over :mod:`apex_tpu.monitor.memory`, the
  one memory surface (compiled footprints, analytic high water, the
  live HBM sampler).

All jax imports are deferred to call time: importing this module (and
therefore ``apex_tpu.monitor``) does no jax work (APX001 discipline).
"""

from __future__ import annotations

import contextlib
import functools
import json

from apex_tpu.monitor import _state

# jax.monitoring event keys worth surfacing (jax/_src/dispatch.py and
# jax/_src/compilation_cache.py); durations are recorded as timer
# events under the mapped name, point events as counters.
_DURATION_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "jax/compile/trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "jax/compile/lower",
    "/jax/core/compile/backend_compile_duration": "jax/compile/backend",
}
_POINT_EVENTS = {
    "/jax/compilation_cache/cache_misses": "jax/compile/cache_miss",
    "/jax/compilation_cache/cache_hits": "jax/compile/cache_hit",
}

_compile_logging_installed = False


def init(enable: bool = True):
    """Parity shim for ``pyprof.nvtx.init()``: JAX needs no global
    patching — annotation is opt-in via :func:`annotate`/:func:`wrap`."""
    return enable


@contextlib.contextmanager
def annotate(name: str, **metadata):
    """Named range visible in the XProf host timeline and HLO op names.

    The named scope rides :func:`apex_tpu.monitor.profile.scope`, so an
    ``annotate``-tagged region also appears as a row in the per-module
    cost attribution table (``monitor.profile.analytic_profile``)."""
    import jax
    from apex_tpu.monitor import profile as _profile
    payload = name if not metadata else \
        f"{name}|{json.dumps(metadata, default=str)}"
    with jax.profiler.TraceAnnotation(payload):
        with _profile.scope(name):
            yield


def _describe_args(args, kwargs):
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return f"{x.dtype}{list(x.shape)}"
        return type(x).__name__
    return {
        "args": [one(a) for a in args],
        "kwargs": {k: one(v) for k, v in kwargs.items()},
    }


def wrap(fn, name: str | None = None):
    """Decorate ``fn`` with an annotation carrying the op name and arg
    shapes (the ``add_wrapper`` payload, ``nvmarker.py:206``); with a
    recorder attached the call is also timed as ``trace/<name>``."""
    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        rec = _state.recorder
        with annotate(label, **_describe_args(args, kwargs)):
            if rec is None:
                return fn(*args, **kwargs)
            with rec.timer(f"trace/{label}"):
                return fn(*args, **kwargs)

    return wrapper


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture an XProf trace of the block (the nvprof-session analog);
    parse with :mod:`apex_tpu.monitor.xprof` or view in TensorBoard."""
    import jax
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# XLA cost accounting (the pyprof.prof analog)
# ---------------------------------------------------------------------------

def cost_analysis(fn, *args, **kwargs) -> dict:
    """Compile ``fn`` and return XLA's cost analysis dict
    (``flops``, ``bytes accessed``, per-memory-space breakdowns)."""
    import jax
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def flop_report(fn, *args, step_time_s: float | None = None,
                peak_flops: float | None = None, **kwargs) -> dict:
    """FLOPs/bytes + arithmetic intensity (+ MFU when timings given) —
    the summary ``pyprof.prof`` prints per kernel, at whole-program
    granularity."""
    ca = cost_analysis(fn, *args, **kwargs)
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    rep = {
        "flops": flops,
        "bytes_accessed": byts,
        "arithmetic_intensity": flops / byts if byts else float("inf"),
    }
    if step_time_s:
        rep["achieved_flops_per_s"] = flops / step_time_s
        if peak_flops:
            rep["mfu"] = flops / step_time_s / peak_flops
    return rep


# ---------------------------------------------------------------------------
# compile-event and jit-cache logging
# ---------------------------------------------------------------------------

def install_compile_logging() -> bool:
    """Register ``jax.monitoring`` listeners feeding the attached
    recorder. Install once per process (idempotent); events arriving
    while no recorder is attached are discarded by the listener, so the
    disabled-mode guarantee holds. Returns True when the listeners are
    (now) installed."""
    global _compile_logging_installed
    if _compile_logging_installed:
        return True
    import jax.monitoring as jmon

    def on_duration(event: str, duration: float, **kw):
        rec = _state.recorder
        if rec is None:
            return
        name = _DURATION_EVENTS.get(event)
        if name is not None:
            rec.timer_event(name, float(duration))

    def on_event(event: str, **kw):
        rec = _state.recorder
        if rec is None:
            return
        name = _POINT_EVENTS.get(event)
        if name is not None:
            rec.counter(name)

    jmon.register_event_duration_secs_listener(on_duration)
    jmon.register_event_listener(on_event)
    _compile_logging_installed = True
    return True


def compile_seconds(recorder=None) -> float:
    """Total backend-compile seconds accumulated in ``recorder`` (or the
    attached one) since it was created — the compile-vs-steady split the
    bench embeds. Requires :func:`install_compile_logging`."""
    rec = recorder if recorder is not None else _state.recorder
    if rec is None:
        return 0.0
    return float(rec.counters().get("jax/compile/backend/total_s", 0.0))


# ---------------------------------------------------------------------------
# memory — moved to apex_tpu.monitor.memory (thin re-export shims)
# ---------------------------------------------------------------------------

def device_memory_snapshot(devices=None) -> list[dict]:
    """DEPRECATED location: use
    :func:`apex_tpu.monitor.memory.device_memory_snapshot` — the ONE
    memory surface (the pyprof/xentropy re-export precedent). This shim
    delegates; new callers get the extended rows (nominal degradation
    on stats-less backends, limit/utilization, the headline
    ``memory/hbm_bytes_in_use`` gauge)."""
    from apex_tpu.monitor import memory as _memory
    return _memory.device_memory_snapshot(devices)


def memory_analysis(fn, *args, **kwargs) -> dict:
    """DEPRECATED location: use
    :func:`apex_tpu.monitor.memory.compiled_memory_profile` — same
    compiled breakdown plus the ``total_bytes`` envelope and the
    ``record=`` path into ``report.aggregate()["memory"]``. This shim
    delegates (key set is a superset of the historical one)."""
    from apex_tpu.monitor import memory as _memory
    return _memory.compiled_memory_profile(fn, *args, **kwargs)
