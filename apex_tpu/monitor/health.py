"""Training-health watchdog over the Recorder event stream.

The telemetry PR 2 built records what happened; this layer says what is
*wrong*. A :class:`Watchdog` registers as a step observer on a
:class:`~apex_tpu.monitor.recorder.Recorder` and scans every closed
step record on the host for the conditions that actually kill
mixed-precision distributed runs:

- ``nan``                non-finite loss / grad-norm / any step gauge
- ``overflow_storm``     the dynamic loss scale halving (or the
                         overflow flag firing) >= N times in a window —
                         grads are persistently non-finite, the scaler
                         is treading water instead of recovering
- ``loss_divergence``    loss blowing past ``divergence_factor`` x its
                         best value after a grace period
- ``loss_plateau``       loss flat (relative change < rtol) over a full
                         window
- ``loader_starvation``  ``data/host_wait`` eating more than a fraction
                         of the step time for consecutive steps — the
                         chip is waiting on the input pipeline
- ``straggler``          (cross-host, via :meth:`Watchdog.
                         check_cross_host` on a ``merge`` view) a rank
                         whose median step time exceeds the global
                         median by ``straggler_ratio``

Serve-side conditions (the serve engine records its per-round gauges
and counters inside per-step records — ``ServeEngine.step`` — so the
same observer sees them with no serve-specific wiring):

- ``kv_pool_exhaustion``   the page allocator's free list at/below
                           ``kv_pool_min_free_fraction`` of the pool
                           (``serve/pages_free`` vs ``serve/
                           pages_total``) — admission and growth are
                           about to start evicting
- ``eviction_storm``       preemptions in >= ``eviction_trips`` of the
                           last ``eviction_window`` steps (the
                           ``serve/preemptions`` counter per step):
                           the pool is thrashing — every admission
                           evicts someone whose recompute evicts the
                           next
- ``admission_starvation`` the oldest waiting request's age
                           (``serve/queue_wait_oldest_s``), EMA-
                           smoothed, above ``admission_age_s`` — the
                           queue head cannot be admitted (pool or
                           batch slots too small for the traffic)

Memory conditions (the OOM-forecast layer — ``monitor.memory``'s
sampler/snapshot gauges ride ordinary step records, so the same
observer sees them with no memory-specific wiring):

- ``hbm_high_water``       ``memory/hbm_bytes_in_use`` at/above
                           ``hbm_high_water_fraction`` of
                           ``memory/hbm_limit_bytes`` — the allocator
                           is about to OOM on the next spike;
                           hysteresis re-arm below 90% of the bar
- ``memory_leak``          positive least-squares slope of the
                           ``memory/hbm_bytes_in_use`` step gauge over
                           a full ``leak_window``, with predicted
                           growth over the window at/above
                           ``leak_rel_threshold`` of the window mean
                           (a constant footprint NEVER fires — the
                           false-positive guard is tested)
- ``recompile_storm``      backend compiles / jit-cache misses landing
                           in >= ``recompile_trips`` of the last
                           ``recompile_window`` steps after a
                           ``recompile_grace`` warmup — a shape or
                           static-arg churn is retracing every step
                           (and each retrace's executable + buffers
                           inflate HBM: the classic slow-motion OOM)

Each detection emits one typed ``health_event`` record into the
recorder — ``{"kind": "health_event", "name": <condition>, "severity",
"diagnosis", ...}`` — which rides the JSONL dump, shows up in
``python -m apex_tpu.monitor report``, and (when the recorder streams)
is flushed to disk immediately. ``on_event`` lets the training loop
react, e.g. dump :meth:`Watchdog.diagnostics_bundle` and abort.

Everything here is host-side Python over already-recorded events: the
watchdog inserts no ops, forces no retrace, and costs nothing when
monitoring is detached (the disabled-mode purity guarantee of
docs/observability.md is untouched).
"""

from __future__ import annotations

import collections
import math
from typing import Callable, Optional

HEALTH_EVENT_KINDS = (
    "nan", "overflow_storm", "loss_divergence", "loss_plateau",
    "loader_starvation", "straggler",
    "kv_pool_exhaustion", "eviction_storm", "admission_starvation",
    "hbm_high_water", "memory_leak", "recompile_storm",
    # fleet-level conditions (apex_tpu.monitor.slo/fleet): SLO error
    # budget burning too fast, and autoscale decisions derived from
    # fleet-wide pressure signals
    "slo_alert", "scale_decision",
)

# Conditions fatal enough that the process may not get another chance
# to tell its story: each firing also triggers a flight-recorder dump
# (apex_tpu.monitor.flight — inert unless flight.install() armed it).
FLIGHT_DUMP_EVENTS = ("nan", "hbm_high_water", "memory_leak")


def _finite(v) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return True   # non-numeric gauges are not NaN signals


class Watchdog:
    """Online health analysis of a recorder's step stream.

    Usage::

        rec = monitor.Recorder()
        dog = monitor.Watchdog(rec, on_event=my_handler)
        with monitor.attached(rec):
            for batch in loader:
                with rec.step():
                    state = train_step(state, batch)
        # dog.events holds every health_event; they are also in
        # rec.records("health_event") and the rendered report.

    All thresholds are keyword-configurable. ``loss_gauges`` names the
    gauges tried (in order) as "the loss" for plateau/divergence
    tracking; NaN detection scans *every* gauge on the step record.
    """

    def __init__(self, recorder=None, *,
                 on_event: Optional[Callable] = None,
                 loss_gauges=("train/loss", "loss"),
                 overflow_window: int = 20, overflow_trips: int = 3,
                 divergence_factor: float = 3.0,
                 divergence_grace: int = 10,
                 divergence_patience: int = 3,
                 divergence_smoothing: float = 0.2,
                 plateau_window: int = 50, plateau_rtol: float = 1e-3,
                 starvation_fraction: float = 0.5,
                 starvation_window: int = 5,
                 straggler_ratio: float = 1.5,
                 kv_pool_min_free_fraction: float = 0.1,
                 eviction_window: int = 20, eviction_trips: int = 3,
                 admission_age_s: float = 30.0,
                 admission_smoothing: float = 0.3,
                 hbm_high_water_fraction: float = 0.9,
                 leak_window: int = 20,
                 leak_rel_threshold: float = 0.05,
                 recompile_window: int = 10, recompile_trips: int = 3,
                 recompile_grace: int = 3,
                 diagnostics_steps: int = 16,
                 scaler=None):
        self.on_event = on_event
        self.loss_gauges = tuple(loss_gauges)
        self.overflow_window = int(overflow_window)
        self.overflow_trips = int(overflow_trips)
        self.divergence_factor = float(divergence_factor)
        self.divergence_grace = int(divergence_grace)
        self.divergence_patience = int(divergence_patience)
        self.divergence_smoothing = float(divergence_smoothing)
        self.plateau_window = int(plateau_window)
        self.plateau_rtol = float(plateau_rtol)
        self.starvation_fraction = float(starvation_fraction)
        self.starvation_window = int(starvation_window)
        self.straggler_ratio = float(straggler_ratio)
        self.kv_pool_min_free_fraction = float(kv_pool_min_free_fraction)
        self.eviction_window = int(eviction_window)
        self.eviction_trips = int(eviction_trips)
        self.admission_age_s = float(admission_age_s)
        self.admission_smoothing = float(admission_smoothing)
        self.hbm_high_water_fraction = float(hbm_high_water_fraction)
        self.leak_window = int(leak_window)
        self.leak_rel_threshold = float(leak_rel_threshold)
        self.recompile_window = int(recompile_window)
        self.recompile_trips = int(recompile_trips)
        self.recompile_grace = int(recompile_grace)
        self.diagnostics_steps = int(diagnostics_steps)
        self.scaler = scaler            # optional LossScaler for bundles
        self.events: list[dict] = []
        self.recorder = None
        # detection state
        self._nan_seen: set = set()
        self._overflow_hist: collections.deque = collections.deque(
            maxlen=self.overflow_window)
        self._overflow_active = False
        self._prev_scale: Optional[float] = None
        self._loss_hist: collections.deque = collections.deque(
            maxlen=self.plateau_window)
        self._best_loss: Optional[float] = None
        self._loss_ema: Optional[float] = None   # divergence smoother
        self._best_ema: Optional[float] = None
        self._div_run = 0          # consecutive steps above the bar
        self._diverged = False
        self._plateaued = False
        self._starve_hist: collections.deque = collections.deque(
            maxlen=self.starvation_window)
        self._starving = False
        # serve-side detection state
        self._pool_low = False
        self._evict_hist: collections.deque = collections.deque(
            maxlen=self.eviction_window)
        self._evict_active = False
        self._queue_age_ema: Optional[float] = None
        self._admission_starved = False
        # memory detection state
        self._hbm_high = False
        self._leak_hist: collections.deque = collections.deque(
            maxlen=self.leak_window)
        self._leak_fired = False
        self._recompile_hist: collections.deque = collections.deque(
            maxlen=self.recompile_window)
        self._recompile_active = False
        self._n_steps = 0
        if recorder is not None:
            self.watch(recorder)

    # -- wiring -------------------------------------------------------------
    def watch(self, recorder):
        """Register on ``recorder``'s step stream; returns the recorder
        (so ``monitor.attached(dog.watch(rec))`` composes)."""
        recorder.add_observer(self._on_step)
        self.recorder = recorder
        return recorder

    def unwatch(self):
        if self.recorder is not None:
            self.recorder.remove_observer(self._on_step)
            self.recorder = None

    # -- event emission -----------------------------------------------------
    def _fire(self, rec, name: str, value, diagnosis: str,
              severity: str = "warn", **details) -> dict:
        ev = rec.emit("health_event", name, value, severity=severity,
                      diagnosis=diagnosis, **details)
        # shadow counter: health firings become scrapeable
        # (`apex_health_<name>_total` in the Prometheus exposition) —
        # the fleet autoscale decision engine sums these across replicas
        rec.counter(f"health/{name}")
        self.events.append(ev)
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:
                pass
        if name in FLIGHT_DUMP_EVENTS:
            # fatal forecast: dump the black box while the process can
            # still write (no-op unless flight.install() armed dumps)
            try:
                from apex_tpu.monitor import flight as _flight
                _flight.trigger(f"health:{name}")
            except Exception:
                pass
        return ev

    # -- per-step analysis --------------------------------------------------
    def _on_step(self, step_ev: dict, rec):
        self._n_steps += 1
        step = step_ev.get("step")
        gauges = step_ev.get("gauges") or {}

        # 1) non-finite values anywhere on the step record (once/gauge)
        for gname, v in gauges.items():
            if not _finite(v) and gname not in self._nan_seen:
                self._nan_seen.add(gname)
                self._fire(
                    rec, "nan", v if isinstance(v, (int, float)) else None,
                    f"non-finite value in gauge '{gname}' at step {step} "
                    f"({v!r}). A NaN/inf loss or grad norm usually means "
                    "optimizer divergence (lr too high / missing warmup) "
                    "or fp16 overflow with loss scaling disabled — check "
                    "the optim/grad_norm trend and the amp/loss_scale "
                    "history leading up to this step.",
                    severity="error", gauge=gname, step=step)

        # 2) overflow storm: scale halvings / overflow flags in a window
        scale = gauges.get("amp/loss_scale")
        overflow = gauges.get("amp/overflow")
        tripped = bool(overflow) and _finite(overflow) and \
            float(overflow) != 0.0
        if not tripped and scale is not None and _finite(scale) \
                and self._prev_scale is not None and _finite(self._prev_scale):
            tripped = float(scale) < float(self._prev_scale)
        if scale is not None:
            self._prev_scale = scale
        if scale is not None or overflow is not None:
            self._overflow_hist.append(1 if tripped else 0)
            trips = sum(self._overflow_hist)
            if trips >= self.overflow_trips and not self._overflow_active:
                self._overflow_active = True
                self._fire(
                    rec, "overflow_storm", trips,
                    f"loss scale tripped {trips}x in the last "
                    f"{len(self._overflow_hist)} steps (scale now "
                    f"{scale}): gradients are persistently non-finite "
                    "and the dynamic scaler is shrinking instead of "
                    "recovering. Typical causes: lr too high for the "
                    "half dtype, a non-finite input batch, or a "
                    "min_loss_scale floor set too high.",
                    severity="error", step=step, loss_scale=scale,
                    window=len(self._overflow_hist))
            elif trips == 0:
                self._overflow_active = False

        # 3) loss divergence / plateau
        loss = None
        loss_name = None
        for cand in self.loss_gauges:
            if cand in gauges:
                loss, loss_name = gauges[cand], cand
                break
        if loss is not None and _finite(loss):
            loss = float(loss)
            if self._best_loss is None or loss < self._best_loss:
                self._best_loss = loss
            # divergence runs on an EMA of the loss, not the raw value:
            # healthy early training with momentum oscillates (a 1.1 ->
            # 7.7 -> falling overshoot was measured on the simple
            # example), and a spike that decays must not page anyone.
            # Genuine divergence moves the EMA orders of magnitude in a
            # step or two and still fires immediately.
            a = self.divergence_smoothing
            self._loss_ema = loss if self._loss_ema is None else \
                (1.0 - a) * self._loss_ema + a * loss
            if self._best_ema is None or self._loss_ema < self._best_ema:
                self._best_ema = self._loss_ema
                self._div_run = 0
            elif (self._n_steps > self.divergence_grace
                  and self._best_ema > 0
                  and self._loss_ema
                  > self.divergence_factor * self._best_ema):
                self._div_run += 1
                if (self._div_run >= self.divergence_patience
                        and not self._diverged):
                    self._diverged = True
                    self._fire(
                        rec, "loss_divergence", loss,
                        f"'{loss_name}' at step {step}: smoothed loss "
                        f"{self._loss_ema:.4g} >= "
                        f"{self.divergence_factor}x its best "
                        f"{self._best_ema:.4g} for {self._div_run} "
                        "consecutive steps: the run is diverging. Lower "
                        "the learning rate, add warmup, or check the "
                        "grad-norm trend for an exploding layer.",
                        severity="error", step=step, gauge=loss_name,
                        best=self._best_ema)
            else:
                self._div_run = 0
            self._loss_hist.append(loss)
            if (len(self._loss_hist) == self.plateau_window
                    and not self._plateaued and not self._diverged):
                half = self.plateau_window // 2
                hist = list(self._loss_hist)
                a = sum(hist[:half]) / half
                b = sum(hist[half:]) / (len(hist) - half)
                denom = max(abs(a), 1e-12)
                if abs(a - b) / denom < self.plateau_rtol:
                    self._plateaued = True
                    self._fire(
                        rec, "loss_plateau", loss,
                        f"'{loss_name}' flat over the last "
                        f"{self.plateau_window} steps "
                        f"({a:.4g} -> {b:.4g}, relative change < "
                        f"{self.plateau_rtol:g}): training has stalled "
                        "— converged, lr decayed to zero, or the "
                        "optimizer is skipping every step (check "
                        "amp/skipped_steps).",
                        severity="info", step=step, gauge=loss_name)

        # 4) data-loader starvation: host wait as a fraction of step time
        step_s = float(step_ev.get("step_time_s") or 0.0)
        wait = (step_ev.get("timers") or {}).get("data/host_wait")
        if wait is not None and step_s > 0:
            frac = float(wait.get("total_s", 0.0)) / step_s
            self._starve_hist.append(frac)
            if (len(self._starve_hist) == self.starvation_window
                    and min(self._starve_hist) >= self.starvation_fraction):
                if not self._starving:
                    self._starving = True
                    self._fire(
                        rec, "loader_starvation", round(frac, 4),
                        f"data/host_wait took {100 * frac:.0f}% of the "
                        f"step for {self.starvation_window} consecutive "
                        "steps: the accelerator is starving on the "
                        "input pipeline. Raise loader workers/prefetch "
                        "or move transforms off the hot path.",
                        severity="warn", step=step,
                        window=self.starvation_window)
            elif self._starve_hist and self._starve_hist[-1] \
                    < self.starvation_fraction:
                self._starving = False

        self._serve_checks(rec, step, step_ev, gauges)
        self._memory_checks(rec, step, step_ev, gauges)

    # -- memory analysis (the OOM-forecast layer) ---------------------------
    def _memory_checks(self, rec, step, step_ev: dict, gauges: dict):
        """``monitor.memory``'s sampler/snapshot gauges ride ordinary
        step records; these three conditions fire BEFORE an OOM does.
        One early-out on a step with no memory signal."""
        in_use = gauges.get("memory/hbm_bytes_in_use")
        limit = gauges.get("memory/hbm_limit_bytes")
        counters = step_ev.get("counters") or {}
        timers = step_ev.get("timers") or {}
        compiled = bool(counters.get("jax/compile/cache_miss")
                        or "jax/compile/backend" in timers)

        # 1) recompile storm: compile events landing step after step
        # once warmup is over — beyond the wall-clock cost, every
        # retrace's executable and its buffers inflate HBM (the
        # slow-motion OOM the two gauges below then confirm). The
        # tracker runs on EVERY step: a quiet step must push a 0, or
        # sparse one-off compiles across a long run would read as
        # consecutive and fire a false storm.
        if self._n_steps > self.recompile_grace:
            self._recompile_hist.append(1 if compiled else 0)
            trips = sum(self._recompile_hist)
            if trips >= self.recompile_trips \
                    and not self._recompile_active:
                self._recompile_active = True
                self._fire(
                    rec, "recompile_storm", trips,
                    f"jit compiles landed in {trips} of the last "
                    f"{len(self._recompile_hist)} steps (step {step}, "
                    f"after a {self.recompile_grace}-step warmup "
                    "grace): a shape, dtype or static-arg is changing "
                    "every step and XLA is retracing instead of "
                    "reusing — pad to fixed shapes or hoist the "
                    "varying value out of the static args. Each "
                    "retrace also leaks executable + buffer HBM "
                    "(watch memory/hbm_bytes_in_use).",
                    severity="warn", step=step,
                    window=len(self._recompile_hist))
            elif trips == 0:
                self._recompile_active = False

        if in_use is None and limit is None:
            return

        # 2) hbm high water: usage at/above the fraction of the limit —
        # the next allocation spike (a retrace, a bigger batch, a
        # fragmentation miss) OOMs. Hysteresis re-arm at 90% of the bar.
        if in_use is not None and limit and _finite(in_use) \
                and _finite(limit):
            frac = float(in_use) / float(limit)
            if frac >= self.hbm_high_water_fraction:
                if not self._hbm_high:
                    self._hbm_high = True
                    self._fire(
                        rec, "hbm_high_water", round(frac, 4),
                        f"HBM at {100 * frac:.0f}% of the device limit "
                        f"at step {step} ({int(in_use)}/{int(limit)} "
                        f"bytes, bar "
                        f"{100 * self.hbm_high_water_fraction:.0f}%): "
                        "the next allocation spike OOMs. Shrink the "
                        "batch/activation footprint (remat, ZeRO "
                        "shard_params, fp8-KV) or move state off-chip "
                        "before the allocator does it for you with a "
                        "crash.",
                        severity="error", step=step,
                        bytes_in_use=int(in_use), limit_bytes=int(limit))
            elif frac < 0.9 * self.hbm_high_water_fraction:
                self._hbm_high = False        # hysteresis: re-arm

        # 3) memory leak: positive least-squares slope over a FULL
        # sliding window of the step byte gauge, with the predicted
        # growth over the window at least ``leak_rel_threshold`` of the
        # window mean — a flat footprint (slope ~0) and ordinary
        # sample noise never fire (the false-positive guard).
        if in_use is not None and _finite(in_use):
            self._leak_hist.append(float(in_use))
            if (len(self._leak_hist) == self.leak_window
                    and not self._leak_fired):
                ys = list(self._leak_hist)
                n = len(ys)
                xbar = (n - 1) / 2.0
                ybar = sum(ys) / n
                denom = sum((i - xbar) ** 2 for i in range(n))
                slope = sum((i - xbar) * (y - ybar)
                            for i, y in enumerate(ys)) / denom
                growth = slope * (n - 1)
                if slope > 0 and ybar > 0 \
                        and growth >= self.leak_rel_threshold * ybar:
                    self._leak_fired = True
                    self._fire(
                        rec, "memory_leak", round(slope, 2),
                        f"memory/hbm_bytes_in_use grew "
                        f"~{int(growth)} bytes over the last {n} steps "
                        f"({100 * growth / ybar:.1f}% of the mean "
                        f"footprint, slope {slope:.0f} B/step) at step "
                        f"{step}: something is accumulating per step — "
                        "a python-side list of device arrays, an "
                        "unbounded cache, or a new executable per step "
                        "(check recompile_storm). At this rate the "
                        "high-water bar is a matter of steps.",
                        severity="warn", step=step,
                        growth_bytes=int(growth), window=n)

    # -- serve-side analysis ------------------------------------------------
    def _serve_checks(self, rec, step, step_ev: dict, gauges: dict):
        """The serve engine's per-round gauges/counters ride ordinary
        step records (``ServeEngine.step``), so serve health reuses the
        training observer verbatim. One early-out on a non-serve step
        record."""
        free = gauges.get("serve/pages_free")
        total = gauges.get("serve/pages_total")
        if free is None and total is None \
                and "serve/preemptions" not in (step_ev.get("counters")
                                                or {}) \
                and "serve/queue_wait_oldest_s" not in gauges:
            return

        # 1) kv pool exhaustion: the free list at/below the threshold
        # fraction of the pool — the allocator is about to start
        # evicting on every growth/admission
        if free is not None and total and _finite(free) and _finite(total):
            frac = float(free) / float(total)
            if frac <= self.kv_pool_min_free_fraction:
                if not self._pool_low:
                    self._pool_low = True
                    self._fire(
                        rec, "kv_pool_exhaustion", round(frac, 4),
                        f"KV page pool nearly exhausted at step {step}: "
                        f"{int(free)}/{int(total)} pages free "
                        f"({100 * frac:.0f}% <= "
                        f"{100 * self.kv_pool_min_free_fraction:.0f}% "
                        "threshold). Growth and admission are about to "
                        "preempt running sequences — grow num_pages, "
                        "shrink page_size tail waste, or enable fp8-KV "
                        "(~2x pages at the same HBM).",
                        severity="warn", step=step,
                        pages_free=int(free), pages_total=int(total))
            elif frac > 2.0 * self.kv_pool_min_free_fraction:
                self._pool_low = False        # hysteresis: re-arm

        # 2) eviction storm: preemptions in too many of the last N
        # steps — the pool thrashes (each admission evicts a sequence
        # whose recompute re-evicts the next; throughput collapses to
        # re-prefill work)
        pre = (step_ev.get("counters") or {}).get("serve/preemptions", 0)
        if free is not None or pre:
            self._evict_hist.append(1 if pre else 0)
            trips = sum(self._evict_hist)
            if trips >= self.eviction_trips and not self._evict_active:
                self._evict_active = True
                self._fire(
                    rec, "eviction_storm", trips,
                    f"preemptions fired in {trips} of the last "
                    f"{len(self._evict_hist)} serve steps (step {step})"
                    ": the page pool is thrashing — evicted sequences "
                    "recompute their caches only to evict the next. "
                    "Tokens/sec is now dominated by re-prefill; grow "
                    "the pool or lower max_batch.",
                    severity="error", step=step,
                    window=len(self._evict_hist))
            elif trips == 0:
                self._evict_active = False

        # 3) admission starvation: the oldest waiting request's age,
        # EMA-smoothed so one slow admission round does not page anyone
        age = gauges.get("serve/queue_wait_oldest_s")
        if age is not None and _finite(age):
            a = self.admission_smoothing
            age = float(age)
            self._queue_age_ema = age if self._queue_age_ema is None \
                else (1.0 - a) * self._queue_age_ema + a * age
            if self._queue_age_ema >= self.admission_age_s:
                if not self._admission_starved:
                    self._admission_starved = True
                    self._fire(
                        rec, "admission_starvation",
                        round(self._queue_age_ema, 3),
                        f"oldest waiting request has been queued "
                        f"~{self._queue_age_ema:.1f}s (EMA) at step "
                        f"{step}, over the {self.admission_age_s:g}s "
                        "bar: FCFS admission cannot place the queue "
                        "head — the pool or the batch slots are too "
                        "small for the offered traffic.",
                        severity="warn", step=step,
                        age_ema_s=round(self._queue_age_ema, 3))
            elif self._queue_age_ema < 0.5 * self.admission_age_s:
                self._admission_starved = False

    # -- cross-host ---------------------------------------------------------
    def check_cross_host(self, merged: dict, recorder=None) -> list[dict]:
        """Scan a ``merge`` cross-host view for straggler ranks: any
        rank whose median step time exceeds ``straggler_ratio`` x the
        global median. Emits one ``straggler`` health_event per flagged
        rank into ``recorder`` (default: the watched recorder) and
        returns the events. Host-wait stragglers (per-timer
        ``max_over_median``) are reported on the same event."""
        rec = recorder if recorder is not None else self.recorder
        events = []
        skew = (merged.get("steps") or {}).get("skew") or {}
        ratios = skew.get("per_rank_ratio") or {}
        waits = (merged.get("timers") or {}).get("data/host_wait") or {}
        for rank, ratio in sorted(ratios.items()):
            if ratio is None or ratio < self.straggler_ratio:
                continue
            diag = (f"rank {rank} median step time is {ratio}x the "
                    f"global median ({skew.get('median_step_time_s')}s)"
                    ": straggler rank — slow host, contended NIC, or an "
                    "input-pipeline stall on that host.")
            wait_row = (waits.get("by_rank") or {}).get(str(rank))
            if wait_row is not None and waits.get("slowest_rank") is not None \
                    and str(waits["slowest_rank"]) == str(rank):
                diag += (" Its data/host_wait mean is also the fleet max "
                         f"({wait_row.get('mean_s')}s) — the input "
                         "pipeline is the likely cause.")
            details = {"rank": int(rank), "ratio": ratio, "severity": "warn",
                       "diagnosis": diag}
            if rec is not None:
                events.append(self._fire(
                    rec, "straggler", ratio, diag, severity="warn",
                    rank=int(rank)))
            else:
                ev = {"kind": "health_event", "name": "straggler",
                      "value": ratio, **details}
                self.events.append(ev)
                events.append(ev)
                if self.on_event is not None:
                    try:
                        self.on_event(ev)
                    except Exception:
                        pass
        return events

    # -- diagnostics --------------------------------------------------------
    def diagnostics_bundle(self, k: Optional[int] = None) -> dict:
        """Snapshot for post-mortems: the last-K step records, current
        gauges/counters, every health event so far, the scaler state
        summary (when a scaler was registered), and a per-device memory
        snapshot (best-effort; empty off-accelerator)."""
        k = self.diagnostics_steps if k is None else int(k)
        bundle: dict = {"health_events": list(self.events)}
        rec = self.recorder
        if rec is not None:
            bundle["last_steps"] = rec.steps()[-k:]
            bundle["gauges"] = rec.gauges()
            bundle["counters"] = rec.counters()
        if self.scaler is not None:
            try:
                bundle["scaler"] = self.scaler.state_summary()
            except Exception:
                pass
        try:
            from apex_tpu.monitor import memory as _memory
            bundle["device_memory"] = _memory.device_memory_snapshot()
        except Exception:
            bundle["device_memory"] = []
        return bundle
