"""Multi-replica telemetry: scrape N replica exports, aggregate with
honest semantics, alert on SLO burn, emit autoscale decision events.

Every earlier telemetry surface observes ONE process. Production chat
traffic is many serve replicas behind a router; this module is the
fleet-shaped counterpart of what ``merge`` does for training ranks:

- :class:`ReplicaSet` — the registry of replica endpoints: live
  ``MetricsExporter`` HTTP URLs (``ServeEngine.serve(export_port=...)``
  registers itself via the ``on_export`` hook) and/or file-backed
  exposition snapshots (``monitor export --once`` output).
- :class:`FleetPoller` — scrapes every endpoint through the existing
  ``parse_prometheus``, tolerating dead/slow replicas: a per-scrape
  timeout or refused connection marks the replica ``up=0`` with its
  last-seen age and the poll loop continues — a dying replica can
  NEVER kill fleet observability. Aggregation semantics are honest by
  construction:

  ===========  ========================================================
  counters     summed across live replicas (monotone totals add)
  gauges       kept per-replica + min/max/sum/mean views (a last-value
               gauge has no single honest scalar)
  histograms   ``LogHistogram.merge`` of the reconstructed per-replica
               bucket snapshots — fleet p50/p99 come from ONE merged
               histogram over the pooled population, never an average
               of per-replica percentiles (which is not a percentile
               of anything)
  ===========  ========================================================

  Each poll feeds the :mod:`~apex_tpu.monitor.slo` policy layer
  (multi-window burn-rate ``slo_alert``s + ``scale_decision`` events,
  both typed health events) and, with a recorder given, emits one
  ``kind="fleet"`` event per poll — the ``## fleet`` block of
  ``report.aggregate()``.

- :class:`ReplicaThreadRouter` + :class:`LocalFleet` — the CPU-testable
  multi-replica harness: K ``ServeEngine``s on threads, each
  ``serve(export_port=0)`` with its OWN concrete Recorder (the router
  is attached as the single global recorder and routes every write-path
  hook to the calling thread's recorder), registered into a
  ``ReplicaSet`` as their ports bind. Purity: all of this is host-side
  thread plumbing — compiled prefill/decode programs are byte-identical
  with a fleet poller scraping (asserted in ``tests/test_fleet.py``).

CLI::

    python -m apex_tpu.monitor fleet ENDPOINT [ENDPOINT...] \
        [--watch | --once] [--json] [--interval S] [--timeout S]

where ENDPOINT is an ``http(s)://...`` URL or an exposition file path;
``--once`` exits non-zero when any SLO alert fires (the CI gate).

No jax anywhere in this module (APX001) — imported lazily via
``apex_tpu.monitor.__getattr__`` like ``export``.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from typing import Dict, Optional

from apex_tpu.monitor import slo as slo_mod
from apex_tpu.monitor.export import parse_prometheus, parse_prometheus_types
from apex_tpu.monitor.recorder import Recorder, json_safe
from apex_tpu.monitor.spans import LogHistogram, hist_summary

__all__ = ["ReplicaSet", "FleetPoller", "ReplicaThreadRouter",
           "LocalFleet", "classify_samples",
           "histogram_snapshot_from_buckets", "main"]

# exposition defaults assumed when reconstructing histograms from
# bucket edges (Recorder.observe's LogHistogram defaults)
DEFAULT_HIST = {"lo": 1e-3, "hi": 1e7, "buckets_per_decade": 10}


# ---------------------------------------------------------------------------
# scrape classification: one exposition document -> per-replica views
# ---------------------------------------------------------------------------

def classify_samples(parsed: dict, default_replica: str = "",
                     types: Optional[dict] = None) -> dict:
    """Split ``parse_prometheus`` output into per-replica typed views
    ``{replica: {counters, gauges, histograms, scrape_time}}``.

    Label-aware: a ``replica=`` label keys the sample (one document may
    carry many replicas — e.g. concatenated scrapes); unlabeled samples
    fall back to ``default_replica`` (the registered endpoint id).
    ``types`` (``parse_prometheus_types`` output) takes precedence when
    it names a sample — a gauge declared ``# TYPE ... gauge`` stays a
    gauge even when its name ends in ``_total``. Without a declared
    type, classification follows the exporter's naming convention:
    ``*_bucket{le=...}`` + ``*_sum``/``*_count`` siblings are
    histograms, other ``*_total``/``*_count`` samples are counters,
    everything else is a gauge."""
    types = types or {}
    staged: Dict[str, dict] = {}
    views: Dict[str, dict] = {}

    def view(rid):
        return views.setdefault(rid, {
            "counters": {}, "gauges": {}, "histograms": {},
            "scrape_time": None})

    for (name, labels), value in parsed.items():
        lab = dict(labels)
        rid = lab.get("replica", default_replica)
        v = view(rid)
        if name == "apex_replica_up":
            continue                       # the poller decides up-ness
        if name == "apex_scrape_timestamp_seconds":
            v["scrape_time"] = value
            continue
        if name.endswith("_bucket") and "le" in lab:
            base = name[:-len("_bucket")]
            h = v["histograms"].setdefault(
                base, {"buckets": {}, "sum": 0.0, "count": 0})
            h["buckets"][_le(lab["le"])] = value
            continue
        staged.setdefault(rid, {})[name] = value
    for rid, samples in staged.items():
        v = view(rid)
        hists = v["histograms"]
        for name, value in samples.items():
            if name.endswith("_sum") and name[:-len("_sum")] in hists:
                hists[name[:-len("_sum")]]["sum"] = value
            elif name.endswith("_count") and name[:-len("_count")] in hists:
                hists[name[:-len("_count")]]["count"] = int(value)
            elif types.get(name) == "gauge":
                v["gauges"][name] = value
            elif types.get(name) == "counter" \
                    or name.endswith("_total") or name.endswith("_count"):
                v["counters"][name] = value
            else:
                v["gauges"][name] = value
    return views


def _le(raw: str) -> float:
    return float("inf") if raw == "+Inf" else float(raw)


def histogram_snapshot_from_buckets(hist: dict, *, lo: float = None,
                                    hi: float = None,
                                    buckets_per_decade: int = None) -> dict:
    """Invert the exporter's cumulative-bucket rendering back into a
    :meth:`LogHistogram.snapshot` payload (so fleet merging can use
    ``LogHistogram.merge``). Bucket index recovery relies on the
    exporter emitting each populated bucket's exact upper edge
    ``lo * 10^((i+1)/bpd)``.

    Documented slack vs the original histogram: the exposition folds
    the underflow bin into the first populated bucket's cumulative
    count (indistinguishable after rendering), and exact min/max are
    not exported — they are replaced by the populated bucket range. In
    range, percentiles are unaffected (same buckets, same midpoints)."""
    lo = float(lo if lo is not None else DEFAULT_HIST["lo"])
    hi = float(hi if hi is not None else DEFAULT_HIST["hi"])
    bpd = int(buckets_per_decade if buckets_per_decade is not None
              else DEFAULT_HIST["buckets_per_decade"])
    proto = LogHistogram(lo=lo, hi=hi, buckets_per_decade=bpd)
    count = int(hist.get("count") or 0)
    counts: Dict[str, int] = {}
    prev = 0.0
    last_finite_cum = 0.0
    for le in sorted(hist.get("buckets") or {}):
        cum = hist["buckets"][le]
        if math.isinf(le):
            continue
        i = int(round(math.log10(le / lo) * bpd)) - 1
        i = min(max(i, 0), proto.n_buckets - 1)
        c = int(round(cum - prev))
        if c > 0:
            counts[str(i)] = counts.get(str(i), 0) + c
        prev = cum
        last_finite_cum = cum
    overflow = max(0, count - int(round(last_finite_cum)))
    mn = mx = None
    if counts:
        idxs = sorted(int(i) for i in counts)
        mn = proto.bucket_bounds(idxs[0])[0]
        mx = proto.bucket_bounds(idxs[-1])[1]
    if overflow:
        mx = hi
    return {"lo": lo, "hi": hi, "buckets_per_decade": bpd,
            "count": count, "sum": float(hist.get("sum") or 0.0),
            "min": mn, "max": mx, "underflow": 0, "overflow": overflow,
            "counts": counts}


# ---------------------------------------------------------------------------
# replica registry + poller
# ---------------------------------------------------------------------------

class _Replica:
    __slots__ = ("rid", "endpoint", "kind", "up", "last_seen_t", "error")

    def __init__(self, rid: str, endpoint: str):
        self.rid = rid
        self.endpoint = endpoint
        self.kind = "url" if "://" in endpoint else "file"
        self.up = None                 # unknown until first poll
        self.last_seen_t = None        # monotonic, poller clock
        self.error = None


class ReplicaSet:
    """Registry of replica endpoints the :class:`FleetPoller` scrapes.

    ``add(rid, endpoint)`` takes an HTTP(S) ``/metrics`` URL or an
    exposition file path; :meth:`register_engine` is the live-serve
    hook — pass it as ``ServeEngine.serve(on_export=rs.register_engine)``
    and the engine registers itself the moment its port binds."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}

    def add(self, rid: str, endpoint: str) -> None:
        with self._lock:
            self._replicas[str(rid)] = _Replica(str(rid), str(endpoint))

    def register_engine(self, engine, addr: str = "127.0.0.1") -> None:
        if getattr(engine, "export_port", None) is None:
            raise ValueError("engine has no bound export port; register "
                             "from serve(on_export=...) or after start")
        self.add(engine.replica_id,
                 f"http://{addr}:{engine.export_port}/metrics")

    def remove(self, rid: str) -> None:
        with self._lock:
            self._replicas.pop(str(rid), None)

    def ids(self) -> list:
        with self._lock:
            return sorted(self._replicas)

    def items(self) -> list:
        with self._lock:
            return [self._replicas[k] for k in sorted(self._replicas)]

    def __len__(self):
        with self._lock:
            return len(self._replicas)


class FleetPoller:
    """Scrape a :class:`ReplicaSet`, aggregate, evaluate SLOs, decide.

    One :meth:`poll_once` call never raises on a replica's account: a
    scrape failure (timeout, refused connection, unreadable file,
    garbage payload) marks that replica ``up=0`` with its last-seen
    age and the loop continues. Aggregates cover LIVE replicas only —
    a dead replica's stale counters age out of the fleet view (its row
    stays in the replica table) rather than being frozen in as if
    still current."""

    def __init__(self, replica_set: ReplicaSet, *, recorder=None,
                 timeout_s: float = 2.0, slos=None, windows=None,
                 evaluator=None, decider=None, now=time.monotonic):
        self.replica_set = replica_set
        self.recorder = recorder
        self.timeout_s = float(timeout_s)
        self.evaluator = evaluator if evaluator is not None else \
            slo_mod.SLOEvaluator(slos=slos, windows=windows)
        self.decider = decider if decider is not None else \
            slo_mod.AutoscaleDecider()
        self.now = now
        self.polls = 0
        self.alerts: list = []         # accumulated across polls
        self.decisions: list = []
        self.last_view: Optional[dict] = None

    # -- scraping ----------------------------------------------------------
    def _scrape(self, rep: _Replica) -> str:
        if rep.kind == "file":
            with open(rep.endpoint) as f:
                return f.read()
        import urllib.request
        with urllib.request.urlopen(rep.endpoint,
                                    timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    def poll_once(self) -> dict:
        """Scrape every replica once and return the fleet view dict
        (also kept on ``self.last_view``); emits ``fleet`` +
        ``health_event`` records into the recorder when one is set."""
        t = self.now()
        self.polls += 1
        live_views: Dict[str, dict] = {}
        rows = []
        for rep in self.replica_set.items():
            try:
                text = self._scrape(rep)
                views = classify_samples(
                    parse_prometheus(text), default_replica=rep.rid,
                    types=parse_prometheus_types(text))
            except Exception as e:           # noqa: BLE001 — never fatal
                rep.up = False
                rep.error = f"{type(e).__name__}: {e}"
            else:
                rep.up = True
                rep.error = None
                rep.last_seen_t = t
                live_views.update(views)
            age = None if rep.last_seen_t is None \
                else round(t - rep.last_seen_t, 3)
            rows.append({"replica": rep.rid, "endpoint": rep.endpoint,
                         "up": 1 if rep.up else 0, "age_s": age,
                         "error": rep.error})
        fleet = self._aggregate(live_views)
        fleet.update({
            "t": round(t, 3), "poll": self.polls,
            "n_replicas": len(rows),
            "n_up": sum(r["up"] for r in rows),
            "replicas": rows,
        })
        alerts = self.evaluator.observe(fleet, t)
        decision = self.decider.decide(fleet, alerts)
        decisions = [decision] if decision else []
        fleet["alerts"] = alerts
        fleet["decisions"] = decisions
        self.alerts.extend(alerts)
        self.decisions.extend(decisions)
        self.last_view = fleet
        self._emit(fleet, alerts, decisions)
        return fleet

    # -- aggregation -------------------------------------------------------
    @staticmethod
    def _aggregate(views: Dict[str, dict]) -> dict:
        counters: Dict[str, float] = {}
        counters_by: Dict[str, dict] = {}
        gauges: Dict[str, dict] = {}
        hist_parts: Dict[str, list] = {}
        for rid in sorted(views):
            v = views[rid]
            for k, val in v["counters"].items():
                counters[k] = counters.get(k, 0.0) + val
                counters_by.setdefault(k, {})[rid] = val
            for k, val in v["gauges"].items():
                g = gauges.setdefault(
                    k, {"min": val, "max": val, "sum": 0.0,
                        "by_replica": {}})
                g["min"] = min(g["min"], val)
                g["max"] = max(g["max"], val)
                g["sum"] += val
                g["by_replica"][rid] = val
            for base, h in v["histograms"].items():
                hist_parts.setdefault(base, []).append(
                    histogram_snapshot_from_buckets(h))
        for g in gauges.values():
            g["mean"] = g["sum"] / len(g["by_replica"])
        merged: Dict[str, dict] = {}
        summaries: Dict[str, dict] = {}
        for base, parts in hist_parts.items():
            snap = LogHistogram.merge(*parts).snapshot()
            merged[base] = snap
            summaries[base] = hist_summary(snap)
        return {"counters": counters, "counters_by_replica": counters_by,
                "gauges": gauges, "histograms": merged,
                "hist_summary": summaries}

    # -- recorder emission -------------------------------------------------
    _DECISION_VALUE = {"scale_out": 1.0, "scale_in": -1.0,
                       "rebalance": 0.0}

    def _emit(self, fleet: dict, alerts, decisions) -> None:
        rec = self.recorder
        if rec is None:
            return
        for a in alerts:
            rec.emit("health_event", "slo_alert", a["burn_short"],
                     severity=a["severity"], diagnosis=a["diagnosis"],
                     slo=a["slo"], window=a["window"],
                     threshold=a["threshold"],
                     error_budget=a["error_budget"])
            rec.counter("health/slo_alert")
        for d in decisions:
            rec.emit("health_event", "scale_decision",
                     self._DECISION_VALUE.get(d["decision"]),
                     severity=d["severity"],
                     diagnosis=f"[{d['decision']}] {d['rationale']}",
                     decision=d["decision"], inputs=d["inputs"])
            rec.counter("health/scale_decision")
            rec.counter(f"fleet/decision_{d['decision']}")
        rec.emit("fleet", "fleet/poll", fleet["n_up"],
                 n_replicas=fleet["n_replicas"], poll=fleet["poll"],
                 replicas=fleet["replicas"], counters=fleet["counters"],
                 gauges={k: {kk: v[kk] for kk in
                             ("min", "max", "sum", "mean", "by_replica")}
                         for k, v in fleet["gauges"].items()},
                 histograms=fleet["histograms"],
                 hist_summary=fleet["hist_summary"],
                 alerts=alerts, decisions=decisions)

    def watch(self, interval_s: float = 10.0,
              iterations: Optional[int] = None, render=None):
        """Poll forever (or ``iterations`` times) at ``interval_s``,
        passing each view to ``render``. KeyboardInterrupt exits."""
        n = 0
        with contextlib.suppress(KeyboardInterrupt):
            while iterations is None or n < iterations:
                view = self.poll_once()
                if render is not None:
                    render(view)
                n += 1
                if iterations is not None and n >= iterations:
                    break
                time.sleep(interval_s)
        return self.last_view


# ---------------------------------------------------------------------------
# multi-replica harness: per-thread recorder routing + K engines
# ---------------------------------------------------------------------------

class ReplicaThreadRouter:
    """A write-path Recorder proxy that routes every hook to the
    CALLING THREAD's bound concrete Recorder.

    The monitor guard is one module global (``_state.recorder``); a
    multi-replica harness wants one recorder per engine thread without
    giving up that single-global purity contract. Attach the router as
    the one global recorder, then each engine thread calls
    :meth:`bind` once — every subsequent ``hooks.counter``/``gauge``/
    ``observe``/span/step write from that thread lands in its own
    recorder. Unbound threads' writes are dropped (a null recorder),
    never an error. ``traced_hooks`` is False: the router is a
    host-only observer by construction, so compiled programs stay
    byte-identical (the purity test scrapes a live fleet while
    re-tracing the engine programs)."""

    traced_hooks = False

    def __init__(self, name: str = "fleet-router"):
        self.name = name
        self.capacity = 0
        self.meta: dict = {}
        self._t0 = time.perf_counter()
        self._local = threading.local()
        self._lock = threading.Lock()
        self.recorders: Dict[str, Recorder] = {}

    def bind(self, rid: str, recorder: Recorder) -> Recorder:
        """Route this thread's telemetry to ``recorder`` (and remember
        it under ``rid`` for the harness/debugging)."""
        self._local.rec = recorder
        with self._lock:
            self.recorders[str(rid)] = recorder
        return recorder

    def unbind(self) -> None:
        self._local.rec = None

    def _rec(self) -> Optional[Recorder]:
        return getattr(self._local, "rec", None)

    # -- write path (the hook surface) ----------------------------------
    def counter(self, name, inc=1, **extra):
        rec = self._rec()
        return rec.counter(name, inc, **extra) if rec is not None else 0

    def gauge(self, name, value, **extra):
        rec = self._rec()
        if rec is not None:
            rec.gauge(name, value, **extra)

    def observe(self, name, value, **kw):
        rec = self._rec()
        if rec is not None:
            rec.observe(name, value, **kw)

    def timer_event(self, name, seconds, **extra):
        rec = self._rec()
        if rec is not None:
            rec.timer_event(name, seconds, **extra)

    def timer(self, name, **extra):
        rec = self._rec()
        return rec.timer(name, **extra) if rec is not None \
            else contextlib.nullcontext()

    def emit(self, kind, name, value, **extra):
        rec = self._rec()
        if rec is not None:
            return rec.emit(kind, name, value, **extra)
        return {"kind": kind, "name": name, "value": value}

    def step(self, **meta):
        rec = self._rec()
        return rec.step(**meta) if rec is not None \
            else contextlib.nullcontext(-1)

    @property
    def _open_step(self):
        rec = self._rec()
        return rec._open_step if rec is not None else None

    def emit_histograms(self):
        rec = self._rec()
        if rec is not None:
            rec.emit_histograms()

    # -- read path (flight dumps, reports on the bound thread) ----------
    @property
    def dropped(self):
        rec = self._rec()
        return rec.dropped if rec is not None else 0

    def records(self, kind=None):
        rec = self._rec()
        return rec.records(kind) if rec is not None else []

    def counters(self):
        rec = self._rec()
        return rec.counters() if rec is not None else {}

    def gauges(self):
        rec = self._rec()
        return rec.gauges() if rec is not None else {}

    def histograms(self):
        rec = self._rec()
        return rec.histograms() if rec is not None else {}

    def _histogram_events(self):
        rec = self._rec()
        return rec._histogram_events() if rec is not None else []

    def add_observer(self, fn):
        return fn                       # observers attach per-recorder

    def remove_observer(self, fn):
        pass


class LocalFleet:
    """CPU-testable multi-replica harness: K engines on threads.

    Each engine thread binds its own concrete Recorder into the shared
    :class:`ReplicaThreadRouter` (which the CALLER attaches globally:
    ``with monitor.attached(fleet.router): ...``), queues its requests,
    and runs ``serve(export_port=0)`` — registering into
    ``self.replica_set`` the moment its port binds, and holding its
    ``/metrics`` endpoint open after the drain until :meth:`release`
    (so a poller can take a final post-drain scrape: that is the
    counters-sum-exactly moment). Per-replica hold events let a test
    kill one replica early and watch the fleet degrade to ``up=0``.

    Usage::

        fleet = LocalFleet([eng_a, eng_b])
        with monitor.attached(fleet.router):
            fleet.start({eng_a.replica_id: reqs_a,
                         eng_b.replica_id: reqs_b})
            fleet.wait_ready()
            poller = FleetPoller(fleet.replica_set, recorder=my_rec)
            view = poller.poll_once()        # live scrape
            outputs = fleet.join()           # releases holds, joins
    """

    def __init__(self, engines, *, recorders=None,
                 watchdogs: Optional[dict] = None):
        self.engines = list(engines)
        self.router = ReplicaThreadRouter()
        self.replica_set = ReplicaSet()
        self.recorders: Dict[str, Recorder] = recorders or {
            e.replica_id: Recorder(traced_hooks=False, name=e.replica_id)
            for e in self.engines}
        # optional per-replica Watchdogs ({rid: kwargs}) observing each
        # concrete recorder's step stream — their firings become the
        # scrapeable apex_health_* counters the decision engine reads
        self.watchdogs: dict = {}
        if watchdogs:
            from apex_tpu.monitor.health import Watchdog
            for rid, kw in watchdogs.items():
                self.watchdogs[rid] = Watchdog(self.recorders[rid],
                                               **(kw or {}))
        self.holds = {e.replica_id: threading.Event()
                      for e in self.engines}
        self.ready = {e.replica_id: threading.Event()
                      for e in self.engines}
        self.outputs: Dict[str, dict] = {}
        self.errors: Dict[str, BaseException] = {}
        self._threads: list = []

    def start(self, requests: Dict[str, list]) -> None:
        """Spawn one serving thread per engine. ``requests`` maps
        replica_id -> list of ``(prompt, max_new_tokens)``."""
        for eng in self.engines:
            rid = eng.replica_id

            def body(eng=eng, rid=rid):
                self.router.bind(rid, self.recorders[rid])
                try:
                    for prompt, n_new in requests.get(rid, []):
                        eng.add_request(list(prompt), int(n_new))

                    def register(e, rid=rid):
                        self.replica_set.register_engine(e)
                        self.ready[rid].set()

                    self.outputs[rid] = eng.serve(
                        export_port=0,
                        export_recorder=self.recorders[rid],
                        on_export=register,
                        export_hold=self.holds[rid])
                except BaseException as e:    # noqa: BLE001 — surfaced in join
                    self.errors[rid] = e
                finally:
                    self.ready[rid].set()

            th = threading.Thread(target=body, daemon=True,
                                  name=f"fleet-{rid}")
            self._threads.append(th)
            th.start()

    def wait_ready(self, timeout: float = 60.0) -> None:
        """Block until every replica's export port is bound (or a
        thread died trying — re-raised here)."""
        for rid, ev in self.ready.items():
            if not ev.wait(timeout):
                raise TimeoutError(f"replica {rid} never bound its "
                                   "export port")
        self._reraise()

    def release(self, rid: Optional[str] = None) -> None:
        """Let one replica (or all) stop its exporter and return from
        ``serve()`` — killing its endpoint."""
        for r, ev in self.holds.items():
            if rid is None or r == rid:
                ev.set()

    def join(self, timeout: float = 120.0) -> Dict[str, dict]:
        """Release every hold, join the threads, re-raise any engine
        error, return ``{replica_id: serve() outputs}``."""
        self.release()
        for th in self._threads:
            th.join(timeout)
        self._reraise()
        return self.outputs

    def _reraise(self):
        for rid, e in self.errors.items():
            raise RuntimeError(f"replica {rid} failed") from e

    def drained(self) -> bool:
        """True once no engine has schedulable work left."""
        return all(not e.sched.has_work for e in self.engines)


# ---------------------------------------------------------------------------
# CLI: python -m apex_tpu.monitor fleet ...
# ---------------------------------------------------------------------------

def _endpoint_id(endpoint: str, index: int) -> str:
    if "://" in endpoint:
        rest = endpoint.split("://", 1)[1]
        return rest.split("/", 1)[0] or f"r{index}"
    base = os.path.basename(endpoint)
    return os.path.splitext(base)[0] or f"r{index}"


def render_fleet_table(view: dict) -> str:
    """Human-readable per-replica + fleet table for one poll view."""
    out = [f"fleet: {view['n_up']}/{view['n_replicas']} replicas up "
           f"(poll {view['poll']})"]
    out.append(f"{'replica':<16} {'up':>2} {'age_s':>8}  endpoint")
    for r in view["replicas"]:
        age = "-" if r["age_s"] is None else f"{r['age_s']:.1f}"
        line = f"{r['replica']:<16} {r['up']:>2} {age:>8}  {r['endpoint']}"
        if r.get("error"):
            line += f"  [{r['error']}]"
        out.append(line)
    if view.get("counters"):
        out.append("counters (fleet sum):")
        for k in sorted(view["counters"]):
            out.append(f"  {k} = {view['counters'][k]:g}")
    if view.get("hist_summary"):
        out.append("histograms (merged across replicas):")
        for k in sorted(view["hist_summary"]):
            s = view["hist_summary"][k]
            out.append(
                f"  {k}: count={s['count']} p50={s['p50']} "
                f"p95={s['p95']} p99={s['p99']}")
    for a in view.get("alerts") or []:
        out.append(f"ALERT [{a['severity']}] {a['diagnosis']}")
    for d in view.get("decisions") or []:
        out.append(f"DECISION [{d['decision']}] {d['rationale']}")
    return "\n".join(out)


def main(args) -> int:
    """``python -m apex_tpu.monitor fleet`` body (args pre-parsed by
    ``monitor.__main__``). ``--once`` exits 1 when any SLO alert
    fires — the CI gate; ``--watch`` polls until interrupted."""
    rs = ReplicaSet()
    for i, ep in enumerate(args.endpoints):
        rs.add(_endpoint_id(ep, i), ep)
    poller = FleetPoller(rs, timeout_s=args.timeout)

    def render(view):
        if args.json:
            print(json.dumps(json_safe(view)))
        else:
            print(render_fleet_table(view))

    if args.watch:
        poller.watch(interval_s=args.interval, render=render)
        return 0
    view = poller.poll_once()
    render(view)
    return 1 if view["alerts"] else 0
