"""FusedDense / FusedDenseGeluDense flax modules.

Reference: ``apex/fused_dense/fused_dense.py:56-85`` — ``FusedDense(in,
out)`` is a Linear whose bias is fused into the GEMM epilogue;
``FusedDenseGeluDense(in, intermediate, out)`` fuses
dense→GELU→dense. Both are amp half-functions (:50-52).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.dense import linear_bias, linear_gelu_linear


class FusedDense(nn.Module):
    in_features: int
    out_features: int
    use_bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # torch layout [out, in] for checkpoint/API parity with the reference
        weight = self.param(
            "weight", nn.initializers.lecun_normal(),
            (self.out_features, self.in_features), self.param_dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.out_features,), self.param_dtype)
        else:
            bias = jnp.zeros((self.out_features,), self.param_dtype)
        return linear_bias(x, weight.astype(x.dtype), bias.astype(x.dtype))


class FusedDenseGeluDense(nn.Module):
    in_features: int
    intermediate_features: int
    out_features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w1 = self.param("weight1", nn.initializers.lecun_normal(),
                        (self.intermediate_features, self.in_features), self.param_dtype)
        b1 = self.param("bias1", nn.initializers.zeros,
                        (self.intermediate_features,), self.param_dtype)
        w2 = self.param("weight2", nn.initializers.lecun_normal(),
                        (self.out_features, self.intermediate_features), self.param_dtype)
        b2 = self.param("bias2", nn.initializers.zeros,
                        (self.out_features,), self.param_dtype)
        return linear_gelu_linear(
            x, w1.astype(x.dtype), b1.astype(x.dtype),
            w2.astype(x.dtype), b2.astype(x.dtype))

# O1 default-cast coverage: matmul-class (FP16_FUNCS row); the modules
# compute in x.dtype, so the input cast carries the policy.
from apex_tpu.amp import lists as _amp_lists  # noqa: E402
_amp_lists.register_half_module(FusedDense)
_amp_lists.register_half_module(FusedDenseGeluDense)
