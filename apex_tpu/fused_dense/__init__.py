"""apex_tpu.fused_dense — FusedDense / FusedDenseGeluDense modules.

Reference: ``apex/fused_dense/fused_dense.py:6-85``.
"""

from apex_tpu.fused_dense.fused_dense import (  # noqa: F401
    FusedDense,
    FusedDenseGeluDense,
)
from apex_tpu.ops.dense import linear_bias, linear_gelu_linear  # noqa: F401
