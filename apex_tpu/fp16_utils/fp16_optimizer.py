"""FP16_Optimizer: manual master-weight mixed precision.

Reference: ``apex/fp16_utils/fp16_optimizer.py:13-270`` — wraps any
optimizer with fp32 master params, grad unscale, optional
``clip_master_grads``, and static/dynamic loss scaling, with
``state_dict``/``load_state_dict`` (:209-270).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler
from apex_tpu.multi_tensor_apply import multi_tensor_l2norm


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=False):
        self.optimizer = init_optimizer
        self.optimizer.master_weights = True
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self.first_closure_call_this_step = True
        self.verbose = verbose

    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale

    def scale_loss(self, loss):
        return loss * self.loss_scale

    def clip_master_grads(self, max_norm, grads, norm_type=2):
        """Clip unscaled fp32 grads by global norm
        (``fp16_optimizer.py:141-164``). Returns (clipped, total_norm)."""
        leaves = [g.reshape(-1) for g in jax.tree.leaves(grads)]
        norm, _ = multi_tensor_l2norm(leaves)
        clip = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        return jax.tree.map(lambda g: g * clip, grads), norm

    def step(self, grads=None, closure=None):
        """Unscale grads, check overflow, maybe skip, update scale."""
        if closure is not None:
            raise NotImplementedError("closures are not supported on TPU build")
        if self.optimizer.state is None:
            self.optimizer.initialize_state()
        inv = 1.0 / self.loss_scale
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
        self.overflow = self.loss_scaler.has_overflow(grads)
        if not self.overflow:
            self.optimizer.step(grads)
        self.loss_scaler.update_scale(self.overflow)
        return self.optimizer.params

    def zero_grad(self, set_grads_to_None=False):
        pass

    def state_dict(self) -> dict:
        return {
            "loss_scaler": self.loss_scaler.__dict__.copy(),
            "dynamic": isinstance(self.loss_scaler, DynamicLossScaler),
            "overflow": self.overflow,
            "optimizer_state_dict": self.optimizer.state_dict(),
        }

    def load_state_dict(self, sd: dict):
        self.loss_scaler.__dict__.update(sd["loss_scaler"])
        self.overflow = sd.get("overflow", False)
        self.optimizer.load_state_dict(sd["optimizer_state_dict"])
