"""apex_tpu.fp16_utils — legacy manual mixed-precision utilities.

Reference: ``apex/fp16_utils/__init__.py`` (FP16_Optimizer, loss scalers,
network conversion helpers). Superseded by ``apex_tpu.amp`` but kept for
API parity, like the reference keeps them.
"""

from apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    network_to_half,
    convert_network,
    prep_param_lists,
    master_params_to_model_params,
    model_grads_to_master_grads,
    to_python_float,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401
from apex_tpu.fp16_utils.loss_scaler import LossScaler, DynamicLossScaler  # noqa: F401
