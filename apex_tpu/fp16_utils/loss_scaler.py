"""Legacy loss-scaler classes.

Reference: ``apex/fp16_utils/loss_scaler.py:10-47`` — ``LossScaler``
(static) and ``DynamicLossScaler`` with ``has_overflow`` /
``update_scale`` / ``scale_gradient`` hooks used by FP16_Optimizer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.utils.tree import tree_all_finite


class LossScaler:
    """Static scale (``loss_scaler.py:10``)."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    @property
    def loss_scale(self):
        return self.cur_scale

    def has_overflow(self, params_or_grads) -> bool:
        return False

    def update_scale(self, overflow: bool):
        pass

    def scale_gradient(self, grads):
        return jax.tree.map(lambda g: g * self.cur_scale, grads)

    def backward(self, loss):
        raise NotImplementedError("compute grads of loss * loss_scale in JAX")


class DynamicLossScaler(LossScaler):
    """Dynamic scale (``loss_scaler.py:47``): halve on overflow, double
    every ``scale_window`` clean iterations."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2.0, scale_window=1000):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def has_overflow(self, grads) -> bool:
        return not bool(tree_all_finite(grads))

    def update_scale(self, overflow: bool):
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0 \
                and self.cur_iter > self.last_overflow_iter:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1
