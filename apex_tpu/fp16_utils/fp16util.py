"""Param-tree conversion helpers.

Reference: ``apex/fp16_utils/fp16util.py`` — ``network_to_half`` /
``convert_network`` (:44-77, half the model but keep batchnorm fp32),
``prep_param_lists`` (:78-128, model params + fp32 master copies),
``master_params_to_model_params`` / ``model_grads_to_master_grads``
(:130-162).

JAX params are pytrees, so these are pure tree casts; the batchnorm
exemption uses the same name predicate as amp O2.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import _is_norm_param
from apex_tpu.utils.tree import cast_floating


def network_to_half(params, half_dtype=jnp.bfloat16):
    """Cast all floating params to half (``fp16util.py:44-50``)."""
    return cast_floating(params, half_dtype)


def convert_network(params, dtype=jnp.bfloat16):
    """Cast params to ``dtype``, keeping norm params fp32
    (``fp16util.py:60-77``)."""
    return cast_floating(params, dtype, lambda names, x: not _is_norm_param(names))


@dataclass
class FlatMaster:
    """Flat fp32 master copy + the spec needed to unpack it back into the
    model-param tree (the reference's ``flat_master=True`` form, which
    keeps one contiguous fp32 tensor, ``fp16util.py:96-106``)."""

    flat: jax.Array
    spec: Any

    def to_tree(self):
        return self.spec.unpack(self.flat, dtype_from_spec=False)


def prep_param_lists(params, flat_master: bool = False):
    """Return (model_params, master_params) where master is an fp32 copy
    (``fp16util.py:78-128``); ``flat_master`` returns a :class:`FlatMaster`
    (one contiguous fp32 buffer) like the reference's flattened option."""
    master = cast_floating(params, jnp.float32)
    if flat_master:
        from apex_tpu.utils.flat import FlatBuffer
        spec = FlatBuffer.from_tree(master)
        return params, FlatMaster(spec.pack(master, dtype=jnp.float32), spec)
    return params, master


def master_params_to_model_params(model_params, master_params):
    """Downcast master values into the model param dtypes
    (``fp16util.py:130-144``)."""
    if isinstance(master_params, FlatMaster):
        master_params = master_params.to_tree()
    return jax.tree.map(
        lambda mp, ma: ma.astype(mp.dtype) if jnp.issubdtype(mp.dtype, jnp.floating) else ma,
        model_params, master_params)


def model_grads_to_master_grads(model_grads, flat_spec=None):
    """fp16 grads -> fp32 master grads (``fp16util.py:146-162``); pass the
    :class:`FlatMaster` spec to get grads in the flat form."""
    if flat_spec is not None:
        # pack() casts while copying into the flat buffer — no
        # intermediate fp32 tree
        spec = flat_spec.spec if isinstance(flat_spec, FlatMaster) else flat_spec
        return FlatMaster(spec.pack(model_grads, dtype=jnp.float32), spec)
    return cast_floating(model_grads, jnp.float32)


def to_python_float(t):
    return float(t) if hasattr(t, "item") or hasattr(t, "__float__") else t
