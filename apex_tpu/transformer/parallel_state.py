"""Model-parallel state: the DP×PP×TP (×CP×EP) grid as one jax Mesh.

Reference: ``apex/transformer/parallel_state.py:53-322`` —
``initialize_model_parallel(tp, pp, vpp)`` carves the NCCL world into
data/tensor/pipeline/embedding process groups and stores them in module
globals, with rank/world-size getters for each.

TPU-native translation: the grid IS a ``jax.sharding.Mesh`` with named
axes ``("data", "pipeline", "tensor")`` (+ optional ``context`` for
sequence/ring parallelism and ``expert`` for MoE). "Groups" are mesh axes;
"ranks" are ``lax.axis_index`` inside shard_map/jit (traced) and plain
coordinates outside. The embedding group (first+last PP stage,
``parallel_state.py:124-133``) becomes an ``axis_index_groups`` helper for
collectives restricted to those stages.

Axis order note: ("data", "pipeline", "tensor") puts tensor-parallel
neighbours innermost so TP collectives ride the fastest ICI links and DP
gradient reduction crosses the slower dimension — same locality policy as
the reference's "tp ranks contiguous" group construction
(``parallel_state.py:95-122``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh
from apex_tpu._compat import axis_size as _traced_axis_size

# Canonical axis names
DATA_AXIS = "data"
PIPELINE_AXIS = "pipeline"
TENSOR_AXIS = "tensor"
CONTEXT_AXIS = "context"   # sequence/ring-attention parallelism (new, §5 gap)
EXPERT_AXIS = "expert"     # MoE expert parallelism (new)

_MESH: Optional[Mesh] = None
_VIRTUAL_PIPELINE_WORLD_SIZE: Optional[int] = None
_VIRTUAL_PIPELINE_RANK: Optional[int] = None
_PIPELINE_SPLIT_RANK: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    context_parallel_size_: int = 1,
    expert_parallel_size_: int = 1,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build and install the global mesh.

    Mirrors ``initialize_model_parallel`` (``parallel_state.py:53-156``):
    world must factor as dp·pp·tp(·cp·ep); virtual-pipeline state is
    recorded for the interleaved schedule. Returns the Mesh (also kept as
    module state for the getters).
    """
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK, _PIPELINE_SPLIT_RANK
    devs = list(devices if devices is not None else jax.devices())
    world = len(devs)
    tp = tensor_model_parallel_size_
    pp = pipeline_model_parallel_size_
    cp = context_parallel_size_
    ep = expert_parallel_size_
    denom = tp * pp * cp * ep
    if world % denom != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tp ({tp}) x pp ({pp})"
            f" x cp ({cp}) x ep ({ep})")
    dp = world // denom

    if virtual_pipeline_model_parallel_size_ is not None:
        if pp < 2:
            # parallel_state.py:84-88: interleaved schedule needs pp >= 2
            raise RuntimeError(
                "pipeline-model-parallel size should be greater than 1 with "
                "interleaved schedule")
        _VIRTUAL_PIPELINE_WORLD_SIZE = virtual_pipeline_model_parallel_size_
        _VIRTUAL_PIPELINE_RANK = 0
    else:
        _VIRTUAL_PIPELINE_WORLD_SIZE = None
        _VIRTUAL_PIPELINE_RANK = None
    _PIPELINE_SPLIT_RANK = pipeline_model_parallel_split_rank_

    shape = [dp, pp, tp]
    names = [DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS]
    if cp > 1:
        shape.insert(1, cp)
        names.insert(1, CONTEXT_AXIS)
    if ep > 1:
        shape.insert(1, ep)
        names.insert(1, EXPERT_AXIS)
    arr = np.array(devs).reshape(shape)
    _MESH = Mesh(arr, tuple(names))
    return _MESH


def model_parallel_is_initialized() -> bool:
    """``parallel_state.py:159-166``."""
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _MESH


def destroy_model_parallel():
    """``parallel_state.py:(end) destroy_model_parallel``."""
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK, _PIPELINE_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PIPELINE_WORLD_SIZE = None
    _VIRTUAL_PIPELINE_RANK = None
    _PIPELINE_SPLIT_RANK = None


def _axis_size(name: str) -> int:
    if _MESH is None or name not in _MESH.axis_names:
        return 1
    return _MESH.shape[name]


# -- world sizes (host-side, static) ---------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    """``parallel_state.py:214-219``."""
    return _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(PIPELINE_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DATA_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_expert_parallel_world_size() -> int:
    return _axis_size(EXPERT_AXIS)


# -- ranks: traced inside shard_map, 0 outside ------------------------------

def _axis_rank(name: str):
    try:
        return jax.lax.axis_index(name)
    except NameError:
        return 0


def psum_if_bound(x, axis_name: str):
    """``lax.psum`` when ``axis_name`` is bound (inside ``shard_map``),
    identity otherwise — outside shard_map arrays carry *global* values, so
    the unreduced value is already the full reduction (tp=1 / GSPMD use)."""
    try:
        return jax.lax.psum(x, axis_name)
    except NameError:
        return x


def pmax_if_bound(x, axis_name: str):
    try:
        return jax.lax.pmax(x, axis_name)
    except NameError:
        return x


def sequence_parallel_active(flag: bool) -> bool:
    """Megatron-SP is in effect only when requested AND tp > 1."""
    return bool(flag) and get_tensor_model_parallel_world_size() > 1


def axis_size_if_bound(axis_name) -> int:
    """Size of ``axis_name`` inside shard_map, 1 when unbound/None.

    Reads the *traced axis env* (the compat ``axis_size``), not the
    static ``_MESH`` lookup ``_axis_size`` above: callers may be inside a
    shard_map over a mesh that was never installed as the global, and
    outside any shard_map the axis is unbound (NameError -> 1) even when
    a global mesh with that axis exists."""
    if axis_name is None:
        return 1
    try:
        return _traced_axis_size(axis_name)
    except NameError:
        return 1


def get_tensor_model_parallel_rank():
    """Inside shard_map: traced index on the tensor axis
    (``parallel_state.py:252-258`` analog). Outside: 0."""
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_AXIS)


# -- pipeline stage predicates (static, per-stage — used when building the
#    per-stage module list; parallel_state.py:260-322) ----------------------

def is_pipeline_first_stage(stage: int = 0, ignore_virtual: bool = False) -> bool:
    if not ignore_virtual and _VIRTUAL_PIPELINE_WORLD_SIZE is not None:
        if _VIRTUAL_PIPELINE_RANK != 0:
            return False
    return stage == 0


def is_pipeline_last_stage(stage: int, ignore_virtual: bool = False) -> bool:
    if not ignore_virtual and _VIRTUAL_PIPELINE_WORLD_SIZE is not None:
        if _VIRTUAL_PIPELINE_RANK != _VIRTUAL_PIPELINE_WORLD_SIZE - 1:
            return False
    return stage == get_pipeline_model_parallel_world_size() - 1


def get_virtual_pipeline_model_parallel_world_size():
    return _VIRTUAL_PIPELINE_WORLD_SIZE


def get_virtual_pipeline_model_parallel_rank():
    return _VIRTUAL_PIPELINE_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int):
    global _VIRTUAL_PIPELINE_RANK
    _VIRTUAL_PIPELINE_RANK = rank


def get_pipeline_model_parallel_split_rank():
    return _PIPELINE_SPLIT_RANK


def get_embedding_axis_index_groups():
    """Groups pairing first and last pipeline stage for tied-embedding grad
    reduction (``parallel_state.py:124-133`` embedding group). Returns
    ``axis_index_groups`` for a psum over the pipeline axis, or None when
    pp == 1."""
    pp = get_pipeline_model_parallel_world_size()
    if pp == 1:
        return None
    if pp == 2:
        return [[0, 1]]
    # only first+last participate; middle stages form singleton groups
    groups = [[0, pp - 1]] + [[i] for i in range(1, pp - 1)]
    return groups
