"""Microbatch calculators: constant + batch-size rampup.

Capability parity with
``apex/transformer/tensor_parallel/microbatches.py:20-160``
(``build_num_microbatches_calculator`` → ``ConstantNumMicroBatches`` /
``RampupBatchsizeNumMicroBatches``): given a global batch size, a
microbatch size, and the data-parallel width, decide how many
microbatches each rank's pipeline / grad-accumulation loop runs, with
optional linear global-batch-size rampup over the first N consumed
samples (the Megatron ``--rampup-batch-size`` recipe).

TPU note: these are HOST-side schedule objects, deliberately plain
Python, and ``n_microbatches`` is resolved to an int AT TRACE TIME. A
jitted step that closed over a calculator bakes in the count it had when
first traced — later ``update()`` calls cannot reach inside the cached
executable. The supported rampup pattern is Megatron's: the host loop
calls ``update(consumed_samples)`` after each step and passes the
current ``get()`` value (or the calculator, re-traced) into the step
builder, so each distinct microbatch count compiles once (a handful over
a whole run; XLA caches each). See :func:`resolve_num_microbatches`.
"""

from __future__ import annotations

from typing import Optional, Sequence


def resolve_num_microbatches(n) -> int:
    """Accept a raw int or a calculator wherever schedules take
    ``n_microbatches``.

    Resolution happens at trace time: inside ``jit`` the value is frozen
    into the compiled step. To act on a rampup, re-invoke the (jitted)
    step builder after ``calculator.update(...)`` changes ``get()`` —
    each new count is a new trace (see the module docstring).
    """
    if isinstance(n, NumMicroBatchesCalculator):
        return n.get()
    return int(n)


class NumMicroBatchesCalculator:
    """Base contract (reference ``microbatches.py:62-76``)."""

    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int,
               consistency_check: bool = False) -> None:
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Fixed global batch size (reference ``microbatches.py:79-91``)."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        per_step = micro_batch_size * data_parallel_size
        if global_batch_size % per_step:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible "
                f"by micro batch size ({micro_batch_size}) times data "
                f"parallel size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // per_step
        if self.num_micro_batches < 1:
            raise ValueError("need at least one microbatch")
        self.current_global_batch_size = global_batch_size

    def update(self, consumed_samples: int,
               consistency_check: bool = False) -> None:
        return None


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear global-batch-size rampup (reference ``microbatches.py:94-160``).

    The global batch size steps from ``start_batch_size`` to
    ``global_batch_size`` in ``batch_size_increment`` steps, spending
    ``rampup_samples / num_increments`` consumed samples at each size.
    Call :meth:`update` with the running consumed-sample count after each
    step (as Megatron's training loop does); :meth:`get` then reflects
    the current microbatch count.
    """

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 rampup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        if start_batch_size <= 0 or global_batch_size <= 0:
            raise ValueError("batch sizes must be positive")
        if batch_size_increment <= 0:
            raise ValueError("batch_size_increment must be positive")
        if rampup_samples < 0:
            raise ValueError("rampup_samples must be >= 0")
        diff = global_batch_size - start_batch_size
        if diff < 0:
            raise ValueError(
                f"start_batch_size ({start_batch_size}) exceeds "
                f"global_batch_size ({global_batch_size})")
        if diff % batch_size_increment:
            raise ValueError(
                f"global batch size interval ({diff}) must be divisible by "
                f"batch size increment ({batch_size_increment})")
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        if self.micro_batch_times_data_parallel_size <= 0:
            raise ValueError("micro_batch_size * data_parallel_size must be "
                             "positive")
        if start_batch_size < self.micro_batch_times_data_parallel_size:
            raise ValueError(
                f"start_batch_size ({start_batch_size}) yields zero "
                f"microbatches at micro_batch_size ({micro_batch_size}) x "
                f"data parallel size ({data_parallel_size})")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.global_batch_size = global_batch_size
        self.rampup_samples = rampup_samples
        num_increments = max(diff // batch_size_increment, 1)
        # rampup_samples == 0 means "no rampup": jump straight to the full
        # global batch size (guards the steps division in update)
        self.rampup_samples_per_increment = (
            rampup_samples / num_increments if rampup_samples > 0 else 0.0)
        self.update(0, False)

    def update(self, consumed_samples: int,
               consistency_check: bool = False) -> None:
        if consumed_samples >= self.rampup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = min(
                self.start_batch_size + steps * self.batch_size_increment,
                self.global_batch_size)
        if consistency_check and (
                self.current_global_batch_size
                % self.micro_batch_times_data_parallel_size):
            raise ValueError(
                f"current global batch size "
                f"({self.current_global_batch_size}) is not divisible by "
                f"micro-batch-size ({self.micro_batch_size}) times data "
                f"parallel size ({self.data_parallel_size})")
        self.num_micro_batches = (self.current_global_batch_size
                                  // self.micro_batch_times_data_parallel_size)


def build_num_microbatches_calculator(
        global_batch_size: int, micro_batch_size: int,
        data_parallel_size: int,
        rampup_batch_size: Optional[Sequence[int]] = None,
) -> NumMicroBatchesCalculator:
    """Factory mirroring reference ``microbatches.py:20-59`` with explicit
    arguments instead of the Megatron args namespace.

    ``rampup_batch_size``: None for constant, else the 3-tuple
    ``(start_batch_size, batch_size_increment, rampup_samples)``.
    """
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size must be (start_batch_size, "
            "batch_size_increment, rampup_samples); got "
            f"{rampup_batch_size!r}")
    start, incr, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start, incr, samples, global_batch_size, micro_batch_size,
        data_parallel_size)
