"""apex_tpu.transformer — Megatron-style model parallelism on a TPU mesh.

Reference package: ``apex/transformer`` (``apex/transformer/__init__.py``):
``parallel_state`` (process-group grid), ``tensor_parallel`` (TP layers +
collectives + RNG/checkpointing), ``pipeline_parallel`` (groups; schedule
added here as a first-class feature), ``functional`` (fused softmax).
"""

from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import functional  # noqa: F401
from apex_tpu.transformer.microbatches import (  # noqa: F401
    ConstantNumMicroBatches, NumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatches, build_num_microbatches_calculator)
from apex_tpu.transformer.moe import (  # noqa: F401
    ExpertParallelMLP, expert_parallel_mlp, top1_routing)
from apex_tpu.transformer.ring_attention import (  # noqa: F401
    ring_self_attention, ulysses_attention, zigzag_merge,
    zigzag_ring_self_attention, zigzag_split)

from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType  # noqa: F401
