"""Expert parallelism: switch-style MoE MLP over the ``expert`` mesh axis.

NEW capability (the reference declares no MoE anywhere; apex_tpu r1
declared the ``expert`` mesh axis in ``parallel_state.py`` without any
layer using it — VERDICT r1 next-round #10). TPU-native design per the
Mesh-TensorFlow/Switch formulation:

- top-1 (Switch) or top-2 (GShard, pair-renormalized gates) router with
  static **capacity** per expert (static shapes — XLA needs them;
  dropped tokens pass through with zero contribution, the standard
  switch residual contract);
- dispatch/combine as one-hot einsums (MXU-friendly, no gather/scatter);
- tokens move to their experts with ONE ``all_to_all`` over the
  ``expert`` axis and back with a second — the EP analog of the
  reference's NCCL alltoall-based sharded optimizers;
- each device holds only its ``E/ep`` local experts' weights;
- switch load-balancing auxiliary loss returned alongside the output.

Runs inside ``shard_map``; with ``axis_name=None`` (or the axis unbound)
it degrades to a single-device dense MoE, which is how the parity tests
pin the distributed path to the local one.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state as ps


def _place(one_hot, offset, capacity: int):
    """Slot each routed token in its expert's buffer (arrival order,
    starting at ``offset`` per expert); over-capacity tokens drop.
    one_hot: [t, E]; offset: scalar or [1, E]. Returns [t, E, C]."""
    pos = jnp.cumsum(one_hot, axis=0) * one_hot - 1.0        # [t, E], -1 if unrouted
    pos = pos + offset * one_hot
    keep = (pos >= 0) & (pos < capacity)
    pos_tok = jnp.sum(jnp.where(keep, pos, 0.0), axis=-1)    # [t]
    d = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                       dtype=jnp.float32)                    # [t, C]
    d = one_hot[:, :, None] * d[:, None, :]                  # [t, E, C]
    return d * keep.any(axis=-1)[:, None, None]


def top1_routing(logits, capacity: int, with_stats: bool = False):
    """Switch top-1 routing with per-expert capacity.

    logits: [t, E]. Returns (dispatch [t, E, C] one-hot, combine
    [t, E, C] gate-weighted, aux_loss scalar) — plus a routing-health
    dict ``{"wanted", "placed"}`` (desired vs capacity-slotted
    assignment counts) when ``with_stats``.
    """
    t, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                  # [t]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]
    one_hot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [t, E]
    dispatch = _place(one_hot, 0.0, capacity)
    combine = dispatch * gate[:, None, None]

    # switch aux loss: E * sum_e f_e * P_e (fraction routed x mean prob)
    f = jnp.mean(one_hot, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * p)
    if with_stats:
        stats = {"wanted": jnp.float32(t),
                 "placed": jnp.sum(dispatch, dtype=jnp.float32)}
        return dispatch, combine, aux, stats
    return dispatch, combine, aux


def top2_routing(logits, capacity: int, with_stats: bool = False):
    """GShard top-2 routing with per-expert capacity.

    logits: [t, E]. Each token is dispatched to its two highest-prob
    experts with gates renormalized over the pair (g1 + g2 = 1); the
    second choice queues BEHIND every first-choice assignment of that
    expert (the mesh-tf/GShard position rule), so first choices win
    capacity contention. Returns (dispatch [t, E, C], combine [t, E, C],
    aux_loss) with the same shapes/contract as :func:`top1_routing`.
    """
    t, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)                        # [t]
    oh1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
    probs2 = probs * (1.0 - oh1)
    idx2 = jnp.argmax(probs2, axis=-1)
    oh2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)
    p1 = jnp.take_along_axis(probs, idx1[:, None], axis=-1)[:, 0]
    # p2 reads the MASKED distribution: for a real second choice it
    # equals probs[idx2]; when the softmax saturated (p1 -> 1.0, probs2
    # all-zero) it is exactly 0 whatever expert argmax fell on —
    # including idx1 itself — so the guard below kills the ghost
    # dispatch instead of burning a capacity slot
    p2 = jnp.take_along_axis(probs2, idx2[:, None], axis=-1)[:, 0]
    oh2 = oh2 * (p2 > 0.0)[:, None]
    denom = jnp.maximum(p1 + p2, 1e-9)
    g1, g2 = p1 / denom, p2 / denom

    d1 = _place(oh1, 0.0, capacity)
    d2 = _place(oh2, jnp.sum(oh1, axis=0, keepdims=True),    # behind all top-1
                capacity)
    dispatch = d1 + d2
    combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]

    # aux loss on the FIRST choice only (GShard eq. for l_aux)
    f = jnp.mean(oh1, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * p)
    if with_stats:
        # wanted counts REAL assignments: every top-1 plus the live
        # (non-ghost, p2 > 0) second choices
        stats = {"wanted": jnp.float32(t) + jnp.sum(oh2, dtype=jnp.float32),
                 "placed": jnp.sum(dispatch, dtype=jnp.float32)}
        return dispatch, combine, aux, stats
    return dispatch, combine, aux


def expert_parallel_mlp(x, router_w, wi, wo, *,
                        axis_name: Optional[str] = ps.EXPERT_AXIS,
                        capacity_factor: float = 1.25,
                        activation: Callable = jax.nn.gelu,
                        num_selected_experts: int = 1,
                        return_stats: bool = False):
    """Switch (top-1) / GShard (top-2) MoE MLP layer.

    x: [t, h] local tokens; router_w: [h, E_global] (replicated);
    wi: [E_local, h, f]; wo: [E_local, f, h] (each device holds its local
    experts). Returns (y [t, h], aux_loss). Tokens over capacity produce
    zeros — add the residual outside, per the switch recipe.
    ``num_selected_experts``: 1 = switch top-1 routing, 2 = GShard top-2
    with pair-renormalized gates.
    """
    t, h = x.shape
    ep = ps.axis_size_if_bound(axis_name)
    e_local = wi.shape[0]
    E = e_local * ep
    if router_w.shape[-1] != E:
        raise ValueError(
            f"router has {router_w.shape[-1]} experts but wi provides "
            f"{e_local} x ep={ep} = {E}")
    if num_selected_experts not in (1, 2):
        raise ValueError(
            f"num_selected_experts must be 1 or 2, got {num_selected_experts}")
    # capacity scales with the assignments per token (GShard sizes top-2
    # buffers at 2*cf*t/E — without this, second choices are mostly
    # dropped at the default capacity_factor)
    capacity = max(1, int(capacity_factor * num_selected_experts * t / E))
    # router in fp32 (the switch recipe); expert compute stays in x.dtype
    # so bf16 training keeps MXU rate on the FLOPs-dominant einsums
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    routing = top1_routing if num_selected_experts == 1 else top2_routing
    dispatch, combine, aux, rstats = routing(logits, capacity,
                                             with_stats=True)
    # aux is computed from local tokens; average over the expert group so
    # every rank carries the same load-balancing scalar when x is sharded
    aux = ps.psum_if_bound(aux, axis_name) / ep
    # dispatch is 0/1 — safe in x.dtype; combine carries the fp32 router
    # gate and stays fp32 (the switch recipe keeps gating full precision)
    dispatch = dispatch.astype(x.dtype)

    # [t, E, C] x [t, h] -> [E, C, h] (tokens grouped by global expert)
    expert_in = jnp.einsum("tec,th->ech", dispatch, x)
    if ep > 1:
        # -> [ep(dst), E_local, C, h]; all_to_all ships slab i to rank i
        # and the result's new leading axis indexes the SOURCE rank
        expert_in = expert_in.reshape(ep, e_local, capacity, h)
        expert_in = jax.lax.all_to_all(expert_in, axis_name,
                                       split_axis=0, concat_axis=0,
                                       tiled=False)
        # [ep(src), e_local, C, h] -> [e_local, ep*C, h]
        expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
            e_local, ep * capacity, h)
    else:
        expert_in = expert_in.reshape(e_local, capacity, h)

    # local experts, batched on the expert dim (one big MXU einsum each);
    # fp32 accumulation via preferred_element_type, storage in x.dtype
    hmid = activation(jnp.einsum(
        "ekh,ehf->ekf", expert_in, wi.astype(expert_in.dtype),
        preferred_element_type=jnp.float32)).astype(expert_in.dtype)
    expert_out = jnp.einsum(
        "ekf,efh->ekh", hmid, wo.astype(hmid.dtype),
        preferred_element_type=jnp.float32).astype(hmid.dtype)

    if ep > 1:
        expert_out = expert_out.reshape(e_local, ep, capacity, h)
        expert_out = expert_out.transpose(1, 0, 2, 3)      # [ep(dst), e_local, C, h]
        expert_out = jax.lax.all_to_all(expert_out, axis_name,
                                        split_axis=0, concat_axis=0,
                                        tiled=False)
        # new leading axis = source (expert-holder) rank = global expert
        # group, matching the [E] = [ep, e_local] dispatch grouping
        expert_out = expert_out.reshape(E, capacity, h)
    else:
        expert_out = expert_out.reshape(E, capacity, h)

    y = jnp.einsum("tec,ech->th", combine, expert_out,
                   preferred_element_type=jnp.float32)
    if not return_stats:
        return y.astype(x.dtype), aux
    wanted = ps.psum_if_bound(rstats["wanted"], axis_name)
    placed = ps.psum_if_bound(rstats["placed"], axis_name)
    stats = {"drop_frac": 1.0 - placed / jnp.maximum(wanted, 1.0)}
    return y.astype(x.dtype), aux, stats


class ExpertParallelMLP:
    """Thin stateful wrapper bundling parameter construction.

    ``init(key, hidden, ffn, num_experts_global, ep)`` returns the local
    parameter tree {router, wi, wo} for one rank; ``apply(params, x)``
    calls :func:`expert_parallel_mlp`.
    """

    def __init__(self, axis_name: Optional[str] = ps.EXPERT_AXIS,
                 capacity_factor: float = 1.25,
                 activation: Callable = jax.nn.gelu,
                 num_selected_experts: int = 1):
        self.axis_name = axis_name
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.num_selected_experts = num_selected_experts

    @staticmethod
    def init(key, hidden: int, ffn: int, num_experts: int, ep: int = 1,
             dtype=jnp.float32):
        if num_experts % ep:
            raise ValueError(f"num_experts {num_experts} not divisible by "
                             f"ep {ep}")
        e_local = num_experts // ep
        k1, k2, k3 = jax.random.split(key, 3)
        s_in = (2.0 / hidden) ** 0.5
        s_out = (2.0 / ffn) ** 0.5
        return {
            "router": (jax.random.normal(k1, (hidden, num_experts)) * 0.02
                       ).astype(dtype),
            "wi": (jax.random.normal(k2, (e_local, hidden, ffn)) * s_in
                   ).astype(dtype),
            "wo": (jax.random.normal(k3, (e_local, ffn, hidden)) * s_out
                   ).astype(dtype),
        }

    def apply(self, params, x):
        return expert_parallel_mlp(
            x, params["router"], params["wi"], params["wo"],
            axis_name=self.axis_name, capacity_factor=self.capacity_factor,
            activation=self.activation,
            num_selected_experts=self.num_selected_experts)
