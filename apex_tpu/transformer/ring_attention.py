"""Ring attention: context-parallel exact attention for long sequences.

This is a first-class NEW capability (SURVEY §5 flags long-context as
absent from the reference — no ring attention, context parallel, or
Ulysses; its levers stop at 2048-token fused softmax and ≤512-token
FMHA). TPU design per the ring-attention pattern: the sequence is sharded
over the ``context`` mesh axis; each device holds local Q/K/V chunks,
K/V rotate around the ring via ``ppermute`` (ICI neighbor transfers),
and each device folds every visiting block into its local queries'
online-softmax state — exact attention over the full sequence with
O(seq/cp) memory per chip and compute overlapped with the ring transfer
by XLA's async collectives.

Causality is handled by global-position masking, and ring steps whose
(q-chunk, kv-chunk) pair is strictly in the future are *skipped* under
``lax.cond`` — a causal cp run does ~half the flops of the full ring
(VERDICT r1 weak #10).

The backward is a ``custom_vjp`` that runs a SECOND ring pass: dk/dv
accumulators travel around the ring with their kv chunks while each
device recomputes its blocks from the saved (q, k, v, out, lse) — the
autodiff tape holds only O(s_local) residuals, so backward memory does
not scale with cp (r1 kept every ppermuted K/V in the tape).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state as ps

_NEG_INF = -1e30


def _block_attn(q32, k32, v32, scale, mask):
    """One (q-block, kv-block) pair: returns (m, l, acc) partials."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                  # [b,h,q]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v32)
    return m, l, acc


def _fold(state, bm, bl, bacc):
    """Merge one block's (m, l, acc) into the online-softmax state,
    guarding exp(-inf - -inf) on never-touched rows."""
    m, l, acc = state
    m_new = jnp.maximum(m, bm)
    a_old = jnp.where(m > _NEG_INF / 2, jnp.exp(m - m_new), 0.0)
    a_blk = jnp.where(bm > _NEG_INF / 2, jnp.exp(bm - m_new), 0.0)
    return (m_new, a_old * l + a_blk * bl,
            a_old[..., None] * acc + a_blk[..., None] * bacc)


def _block_grads(qh, doh, lseh, deltah, kh, vh, scale, mask):
    """One (q-block, kv-block) pair of the flash backward:
    returns (dq, dk, dv) contributions. ``mask=None`` = full."""
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lseh[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, doh)
    dp = jnp.einsum("bhqd,bhkd->bhqk", doh, vh)
    ds = p * (dp - deltah[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kh)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qh)
    return dq, dk, dv


def _step_mask(rank, src, s_local, causal):
    """Block mask for (q chunk ``rank``, kv chunk ``src``); None = full."""
    if not causal:
        return None
    q_pos = rank * s_local + jnp.arange(s_local)
    k_pos = src * s_local + jnp.arange(s_local)
    return (k_pos[None, :] <= q_pos[:, None])[None, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_self_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                        causal: bool = False, scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis_name``.

    q, k, v: [b, h, s_local, d] — the local sequence chunk (global
    sequence = cp * s_local, chunks in rank order). Runs inside shard_map.
    Returns the local chunk of the attention output.
    """
    out, _ = _ring_fwd(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd(q, k, v, axis_name, causal, scale):
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale_v = d ** -0.5 if scale is None else scale
    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(t, carry):
        k_cur, v_cur, m, l, acc = carry
        src = jnp.mod(rank - t, cp)

        def compute(m=m, l=l, acc=acc, k_cur=k_cur, v_cur=v_cur, src=src):
            mask = _step_mask(rank, src, s_local, causal)
            bm, bl, bacc = _block_attn(
                q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
                scale_v, jnp.ones((1, 1, s_local, s_local), jnp.bool_)
                if mask is None else mask)
            return _fold((m, l, acc), bm, bl, bacc)

        if causal:
            # src > rank ⇒ every key is in the future: skip the matmuls
            m, l, acc = jax.lax.cond(
                src > rank, lambda *a: (m, l, acc), compute)
        else:
            m, l, acc = compute()
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc)

    init = (k, v,
            jnp.full((b, h, s_local), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, s_local), jnp.float32),
            jnp.zeros((b, h, s_local, d), jnp.float32))
    _, _, m, l, acc = jax.lax.fori_loop(0, cp, body, init)
    safe_l = jnp.where(l > 0, l, 1.0)
    out = (acc / safe_l[..., None]).astype(q.dtype)
    lse = m + jnp.log(safe_l)                               # [b,h,s_local]
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, res, do):
    q, k, v, out, lse = res
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale_v = d ** -0.5 if scale is None else scale
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # [b,h,s_local]

    def body(t, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = jnp.mod(rank - t, cp)

        def compute(k_cur=k_cur, v_cur=v_cur, dk_cur=dk_cur, dv_cur=dv_cur,
                    dq=dq, src=src):
            mask = _step_mask(rank, src, s_local, causal)
            bq, bk, bv = _block_grads(
                q32, do32, lse, delta, k_cur.astype(jnp.float32),
                v_cur.astype(jnp.float32), scale_v, mask)
            return dk_cur + bk, dv_cur + bv, dq + bq

        if causal:
            dk_cur, dv_cur, dq = jax.lax.cond(
                src > rank, lambda *a: (dk_cur, dv_cur, dq), compute)
        else:
            dk_cur, dv_cur, dq = compute()
        # dk/dv accumulators travel with their kv chunk; after cp steps
        # every chunk (and its grads) is back home
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq)

    zeros_kd = jnp.zeros((b, h, s_local, d), jnp.float32)
    init = (k, v, zeros_kd, zeros_kd, zeros_kd)
    _, _, dk, dv, dq = jax.lax.fori_loop(0, cp, body, init)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_self_attention.defvjp(_ring_fwd, _ring_bwd)


def ulysses_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                      causal: bool = False, scale: Optional[float] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern):
    re-shard [b, h, s/cp, d] → [b, h/cp, s, d] with one all_to_all, run
    full-sequence flash attention on the local heads, shard back.

    Complements ring attention: better when heads ≥ cp and the full
    sequence fits one chip's memory; the all_to_all rides ICI.
    """
    cp = jax.lax.axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % cp:
        raise ValueError(f"num heads {h} must be divisible by cp {cp}")

    def to_seq(t):   # [b, h, s/cp, d] -> [b, h/cp, s, d]
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def to_heads(t):  # inverse
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from apex_tpu.ops.flash_attention import flash_attention
    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    out = flash_attention(qs, ks, vs, causal=causal, scale=scale)
    return to_heads(out)


# ---------------------------------------------------------------------------
# Zigzag ring attention: load-balanced causal context parallelism
# ---------------------------------------------------------------------------

def zigzag_split(x, cp: int, axis: int = 2):
    """Reorder a global sequence into the zigzag layout: the sequence is
    cut into ``2*cp`` chunks and device r gets chunks ``(r, 2cp-1-r)``
    concatenated. Returns the reordered GLOBAL array (shard it over the
    context axis afterwards). Inverse: :func:`zigzag_merge`.

    Why: under plain rank-ordered causal ring attention every ring step
    has at least one device with live work, so the lockstep ring takes
    ``cp`` full steps regardless of masking. The zigzag pairing makes
    every device's causal workload equal (~2 of 4 half-pairs per step),
    halving causal wall-clock.
    """
    s = x.shape[axis]
    if s % (2 * cp):
        raise ValueError(f"seq len {s} not divisible by 2*cp={2 * cp}")
    chunks = jnp.split(x, 2 * cp, axis=axis)
    out = []
    for r in range(cp):
        out += [chunks[r], chunks[2 * cp - 1 - r]]
    return jnp.concatenate(out, axis=axis)


def zigzag_merge(x, cp: int, axis: int = 2):
    """Inverse of :func:`zigzag_split`."""
    s = x.shape[axis]
    if s % (2 * cp):
        raise ValueError(f"seq len {s} not divisible by 2*cp={2 * cp}")
    chunks = jnp.split(x, 2 * cp, axis=axis)
    out = [None] * (2 * cp)
    for r in range(cp):
        out[r] = chunks[2 * r]
        out[2 * cp - 1 - r] = chunks[2 * r + 1]
    return jnp.concatenate(out, axis=axis)


def _zz_halves(t):
    half = t.shape[2] // 2
    return t[:, :, :half], t[:, :, half:]


def _zz_causal_mask(half):
    """Within-chunk causal mask for the zigzag diagonal pairs."""
    i = jnp.arange(half)
    return (i[None, :] <= i[:, None])[None, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def zigzag_ring_self_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                               scale: Optional[float] = None):
    """CAUSAL exact attention over zigzag-ordered context shards.

    q, k, v: [b, h, s_local, d] where the local sequence is the
    concatenation of global chunks ``(r, 2cp-1-r)`` (see
    :func:`zigzag_split`). Every device does ~half the block work of the
    full ring each step — the causal load balance the plain ring cannot
    achieve. Returns the local output in the same zigzag layout.
    """
    out, _ = _zz_fwd(q, k, v, axis_name, scale)
    return out


def _zz_fwd(q, k, v, axis_name, scale):
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    half = s_local // 2
    scale_v = d ** -0.5 if scale is None else scale
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    q0, q1 = _zz_halves(q.astype(jnp.float32))
    causal_mask = _zz_causal_mask(half)

    def body(t, carry):
        k_cur, v_cur, st0, st1 = carry
        src = jnp.mod(rank - t, cp)
        k0, k1 = _zz_halves(k_cur.astype(jnp.float32))
        v0, v1 = _zz_halves(v_cur.astype(jnp.float32))
        full = jnp.ones((1, 1, half, half), jnp.bool_)

        # pair (q0, k0): chunk ids (rank, src) — live iff src <= rank;
        # causal-within when equal
        def q0k0(st0=st0, k0=k0, v0=v0, src=src):
            mask = jnp.where(src == rank, causal_mask, full)
            return _fold(st0, *_block_attn(q0, k0, v0, scale_v, mask))

        st0 = jax.lax.cond(src <= rank, q0k0, lambda: st0)
        # pair (q1, k0): q chunk 2cp-1-rank >= cp > src — always full
        st1 = _fold(st1, *_block_attn(q1, k0, v0, scale_v, full))
        # pair (q1, k1): chunk ids (2cp-1-rank, 2cp-1-src) — live iff
        # src >= rank; causal-within when equal
        def q1k1(st1=st1, k1=k1, v1=v1, src=src):
            mask = jnp.where(src == rank, causal_mask, full)
            return _fold(st1, *_block_attn(q1, k1, v1, scale_v, mask))

        st1 = jax.lax.cond(src >= rank, q1k1, lambda: st1)
        # pair (q0, k1): k chunk >= cp > q chunk — never live
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, st0, st1)

    def init_state():
        return (jnp.full((b, h, half), _NEG_INF, jnp.float32),
                jnp.zeros((b, h, half), jnp.float32),
                jnp.zeros((b, h, half, d), jnp.float32))

    _, _, (m0, l0, a0), (m1, l1, a1) = jax.lax.fori_loop(
        0, cp, body, (k, v, init_state(), init_state()))
    sl0 = jnp.where(l0 > 0, l0, 1.0)
    sl1 = jnp.where(l1 > 0, l1, 1.0)
    out = jnp.concatenate([a0 / sl0[..., None], a1 / sl1[..., None]],
                          axis=2).astype(q.dtype)
    lse = jnp.concatenate([m0 + jnp.log(sl0), m1 + jnp.log(sl1)], axis=2)
    return out, (q, k, v, out, lse)


def _zz_bwd(axis_name, scale, res, do):
    q, k, v, out, lse = res
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    half = s_local // 2
    scale_v = d ** -0.5 if scale is None else scale
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)
    q0, q1 = _zz_halves(q32)
    do0, do1 = _zz_halves(do32)
    lse0, lse1 = lse[:, :, :half], lse[:, :, half:]
    dl0, dl1 = delta[:, :, :half], delta[:, :, half:]
    causal_mask = _zz_causal_mask(half)
    full = jnp.ones((1, 1, half, half), jnp.bool_)

    def body(t, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = jnp.mod(rank - t, cp)
        k0, k1 = _zz_halves(k_cur.astype(jnp.float32))
        v0, v1 = _zz_halves(v_cur.astype(jnp.float32))
        dk0, dk1 = _zz_halves(dk_cur)
        dv0, dv1 = _zz_halves(dv_cur)
        dq0, dq1 = _zz_halves(dq)

        def p00(dq0=dq0, dk0=dk0, dv0=dv0, k0=k0, v0=v0, src=src):
            mask = jnp.where(src == rank, causal_mask, full)
            a, bk, bv = _block_grads(q0, do0, lse0, dl0, k0, v0, scale_v, mask)
            return dq0 + a, dk0 + bk, dv0 + bv

        dq0, dk0, dv0 = jax.lax.cond(src <= rank, p00,
                                     lambda: (dq0, dk0, dv0))
        a, bk, bv = _block_grads(q1, do1, lse1, dl1, k0, v0, scale_v, full)
        dq1, dk0, dv0 = dq1 + a, dk0 + bk, dv0 + bv

        def p11(dq1=dq1, dk1=dk1, dv1=dv1, k1=k1, v1=v1, src=src):
            mask = jnp.where(src == rank, causal_mask, full)
            a, bk, bv = _block_grads(q1, do1, lse1, dl1, k1, v1, scale_v, mask)
            return dq1 + a, dk1 + bk, dv1 + bv

        dq1, dk1, dv1 = jax.lax.cond(src >= rank, p11,
                                     lambda: (dq1, dk1, dv1))

        dq = jnp.concatenate([dq0, dq1], axis=2)
        dk_cur = jnp.concatenate([dk0, dk1], axis=2)
        dv_cur = jnp.concatenate([dv0, dv1], axis=2)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq)

    zeros = jnp.zeros((b, h, s_local, d), jnp.float32)
    _, _, dk, dv, dq = jax.lax.fori_loop(
        0, cp, body, (k, v, zeros, zeros, zeros))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


zigzag_ring_self_attention.defvjp(_zz_fwd, _zz_bwd)
