"""Ring attention: context-parallel exact attention for long sequences.

This is a first-class NEW capability (SURVEY §5 flags long-context as
absent from the reference — no ring attention, context parallel, or
Ulysses; its levers stop at 2048-token fused softmax and ≤512-token
FMHA). TPU design per the ring-attention pattern: the sequence is sharded
over the ``context`` mesh axis; each device holds local Q/K/V chunks,
K/V rotate around the ring via ``ppermute`` (ICI neighbor transfers),
and each device folds every visiting block into its local queries'
online-softmax state — exact attention over the full sequence with
O(seq/cp) memory per chip and compute overlapped with the ring transfer
by XLA's async collectives.

Causality is handled by global-position masking: block pairs strictly in
the future are skipped numerically (their contribution underflows via the
-inf max), so the math matches single-device causal attention exactly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state as ps

_NEG_INF = -1e30


def _block_attn(q32, k32, v32, scale, mask):
    """One (q-block, kv-block) pair: returns (m, l, acc) partials."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                  # [b,h,q]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v32)
    return m, l, acc


def ring_self_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                        causal: bool = False, scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis_name``.

    q, k, v: [b, h, s_local, d] — the local sequence chunk (global
    sequence = cp * s_local, chunks in rank order). Runs inside shard_map.
    Returns the local chunk of the attention output.
    """
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = d ** -0.5 if scale is None else scale
    q32 = q.astype(jnp.float32)
    q_pos = rank * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(t, carry):
        k_cur, v_cur, m, l, acc = carry
        src = jnp.mod(rank - t, cp)
        k_pos = src * s_local + jnp.arange(s_local)
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
        else:
            mask = jnp.ones((s_local, s_local), jnp.bool_)
        bm, bl, bacc = _block_attn(q32, k_cur.astype(jnp.float32),
                                   v_cur.astype(jnp.float32), scale,
                                   mask[None, None])
        m_new = jnp.maximum(m, bm)
        # guard: exp(-inf - -inf) on never-touched rows
        a_old = jnp.where(m > _NEG_INF / 2, jnp.exp(m - m_new), 0.0)
        a_blk = jnp.where(bm > _NEG_INF / 2, jnp.exp(bm - m_new), 0.0)
        l_new = a_old * l + a_blk * bl
        acc_new = a_old[..., None] * acc + a_blk[..., None] * bacc
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new)

    init = (k, v,
            jnp.full((b, h, s_local), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, s_local), jnp.float32),
            jnp.zeros((b, h, s_local, d), jnp.float32))
    _, _, m, l, acc = jax.lax.fori_loop(0, cp, body, init)
    safe_l = jnp.where(l > 0, l, 1.0)
    return (acc / safe_l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                      causal: bool = False, scale: Optional[float] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern):
    re-shard [b, h, s/cp, d] → [b, h/cp, s, d] with one all_to_all, run
    full-sequence flash attention on the local heads, shard back.

    Complements ring attention: better when heads ≥ cp and the full
    sequence fits one chip's memory; the all_to_all rides ICI.
    """
    cp = jax.lax.axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % cp:
        raise ValueError(f"num heads {h} must be divisible by cp {cp}")

    def to_seq(t):   # [b, h, s/cp, d] -> [b, h/cp, s, d]
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def to_heads(t):  # inverse
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from apex_tpu.ops.flash_attention import flash_attention
    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    out = flash_attention(qs, ks, vs, causal=causal, scale=scale,
                          block_q=min(128, qs.shape[2]), block_k=min(128, ks.shape[2]))
    return to_heads(out)
