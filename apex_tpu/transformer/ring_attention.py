"""Ring attention: context-parallel exact attention for long sequences.

This is a first-class NEW capability (SURVEY §5 flags long-context as
absent from the reference — no ring attention, context parallel, or
Ulysses; its levers stop at 2048-token fused softmax and ≤512-token
FMHA). TPU design per the ring-attention pattern: the sequence is sharded
over the ``context`` mesh axis; each device holds local Q/K/V chunks,
K/V rotate around the ring via ``ppermute`` (ICI neighbor transfers),
and each device folds every visiting block into its local queries'
partial-attention state — exact attention over the full sequence with
O(seq/cp) memory per chip and compute overlapped with the ring transfer
by XLA's async collectives.

Every (q-chunk, kv-chunk) block runs through the PALLAS flash-attention
kernel (``ops/flash_attention.py``), not XLA einsums: per ring step the
kernel returns the chunk's normalized output and per-row ``lse``, and
the partials merge with the standard two-way log-sum-exp fold — so no
``[s_local, s_local]`` fp32 score matrix is ever materialized and the
kernel's VMEM discipline, in-kernel dropout, and segment-id masking all
apply inside the ring (VERDICT r2 weak #3). The backward runs a second
ring pass calling the flash backward kernels per chunk with the GLOBAL
row statistics (the flash-attention-2 decomposition distributes over kv
chunks exactly), dk/dv accumulators traveling with their kv chunks; the
autodiff tape holds only O(s_local) residuals.

Dropout inside the ring: the kernel's counter-based RNG hashes LOCAL
block positions, so the step seed folds in (q-chunk owner rank, visiting
kv chunk, zigzag pair) — every global (q, k) pair gets an independent
counter stream, regenerated identically in the backward pass. Additive
``bias`` is NOT plumbed through the ring (a global [s, s] bias defeats
the point of context parallelism; use segment ids or causal masking).

The ring loop is a Python loop over the STATIC ring size: step 0 is the
self-chunk (static ``causal`` kernel), later steps are full blocks
skipped under ``lax.cond`` when strictly in the future — a causal cp run
does ~half the flops of the full ring, and each branch calls a kernel
with static flags.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import (
    _flash_bwd_impl, _flash_fwd_impl, _resolve_interpret)
from apex_tpu.transformer import parallel_state as ps
from apex_tpu._compat import axis_size as _axis_size

_NEG_INF = -1e30


def _step_seed(seed, q_rank, src, pair: int = 0):
    """Distinct dropout counter space per (q-chunk owner, kv chunk,
    zigzag pair): the flash kernel hashes LOCAL positions, so the seed
    must carry the global-chunk identity or masks would repeat across
    ring steps and devices. int32 wraparound is deliberate (hashing)."""
    if seed is None:
        return jnp.zeros((1,), jnp.int32)
    s = jnp.asarray(seed, jnp.int32).reshape(())
    return (s + jnp.asarray(q_rank, jnp.int32) * jnp.int32(1000003)
            + jnp.asarray(src, jnp.int32) * jnp.int32(7919)
            + jnp.int32(pair * 104729)).reshape((1,))


def _merge(out, lse, o_s, l_s):
    """Fold one chunk's normalized (out, lse) partial into the running
    state. Kernel lse for empty rows is ``-1e30`` (finite), so the
    unguarded logaddexp/exp form is NaN-free: empty partials get weight
    ~0 (or split evenly between all-empty partials, whose outputs are
    zero anyway)."""
    lse_new = jnp.logaddexp(lse, l_s)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_new = jnp.exp(l_s - lse_new)[..., None]
    return w_old * out + w_new * o_s.astype(jnp.float32), lse_new


def _ring_layout(axis_name):
    cp = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    return cp, rank, perm


def _permute(ts, axis_name, perm):
    return [None if t is None else jax.lax.ppermute(t, axis_name, perm)
            for t in ts]


# ---------------------------------------------------------------------------
# Plain (rank-ordered) ring
# ---------------------------------------------------------------------------

def _ring_fwd_impl(q, k, v, sid_q, sid_kv, seed, axis_name, causal, scale,
                   dropout_rate, block_q, block_k):
    cp, rank, perm = _ring_layout(axis_name)
    b, h, s_local, d = q.shape
    scale_v = d ** -0.5 if scale is None else scale
    interp = _resolve_interpret(None)
    bq = min(block_q or 1024, s_local)
    bk = min(block_k or 1024, s_local)

    out = jnp.zeros((b, h, s_local, d), jnp.float32)
    lse = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    k_cur, v_cur, sk_cur = k, v, sid_kv

    def chunk(k_c, v_c, sk_c, src, causal_c):
        return _flash_fwd_impl(
            q, k_c, v_c, sid_q, sk_c, None, _step_seed(seed, rank, src),
            scale_v, causal_c, dropout_rate, bq, bk, interp)

    for t in range(cp):
        src = jnp.mod(rank - t, cp)
        if t == 0:
            # the self chunk: static causal kernel when requested
            out, lse = _merge(out, lse, *chunk(k_cur, v_cur, sk_cur, src,
                                               causal))
        elif causal:
            def live(out=out, lse=lse, k_cur=k_cur, v_cur=v_cur,
                     sk_cur=sk_cur, src=src):
                return _merge(out, lse,
                              *chunk(k_cur, v_cur, sk_cur, src, False))

            # src > rank ⇒ every key is in the future: skip the kernel
            out, lse = jax.lax.cond(src < rank, live, lambda: (out, lse))
        else:
            out, lse = _merge(out, lse, *chunk(k_cur, v_cur, sk_cur, src,
                                               False))
        if t < cp - 1:
            k_cur, v_cur, sk_cur = _permute((k_cur, v_cur, sk_cur),
                                            axis_name, perm)
    return out.astype(q.dtype), lse


def _ring_bwd_impl(res, do, axis_name, causal, scale, dropout_rate,
                   block_q, block_k):
    q, k, v, out, lse, sid_q, sid_kv, seed = res
    cp, rank, perm = _ring_layout(axis_name)
    b, h, s_local, d = q.shape
    scale_v = d ** -0.5 if scale is None else scale
    interp = _resolve_interpret(None)
    bq = min(block_q or 1024, s_local)
    bk = min(block_k or 1024, s_local)

    def chunk_grads(k_c, v_c, sk_c, src, causal_c):
        # global lse/out in the residuals: the per-chunk backward then
        # computes globally-normalized p = exp(s - lse) and the exact
        # dq/dk/dv contributions of this kv chunk (FA-2 distributes)
        res_t = (q, k_c, v_c, out, lse, sid_q, sk_c, None,
                 _step_seed(seed, rank, src))
        return _flash_bwd_impl(
            res_t, do, scale=scale_v, causal=causal_c,
            dropout_rate=dropout_rate, block_q=bq, block_k=bk,
            interpret=interp)

    zeros = jnp.zeros((b, h, s_local, d), jnp.float32)
    dq, dk_cur, dv_cur = zeros, zeros, zeros
    k_cur, v_cur, sk_cur = k, v, sid_kv

    for t in range(cp):
        src = jnp.mod(rank - t, cp)
        if t == 0:
            g = chunk_grads(k_cur, v_cur, sk_cur, src, causal)
            dq = dq + g[0].astype(jnp.float32)
            dk_cur = dk_cur + g[1].astype(jnp.float32)
            dv_cur = dv_cur + g[2].astype(jnp.float32)
        elif causal:
            def live(dq=dq, dk_cur=dk_cur, dv_cur=dv_cur, k_cur=k_cur,
                     v_cur=v_cur, sk_cur=sk_cur, src=src):
                g = chunk_grads(k_cur, v_cur, sk_cur, src, False)
                return (dq + g[0].astype(jnp.float32),
                        dk_cur + g[1].astype(jnp.float32),
                        dv_cur + g[2].astype(jnp.float32))

            dq, dk_cur, dv_cur = jax.lax.cond(
                src < rank, live, lambda: (dq, dk_cur, dv_cur))
        else:
            g = chunk_grads(k_cur, v_cur, sk_cur, src, False)
            dq = dq + g[0].astype(jnp.float32)
            dk_cur = dk_cur + g[1].astype(jnp.float32)
            dv_cur = dv_cur + g[2].astype(jnp.float32)
        # dk/dv accumulators travel with their kv chunk; after cp
        # permutes every chunk (and its grads) is back home — the final
        # hop carries ONLY the accumulators (k/v/sids would arrive home
        # unused: 2-3 dead chunk transfers per layer, advisor r3)
        if t < cp - 1:
            k_cur, v_cur, sk_cur, dk_cur, dv_cur = _permute(
                (k_cur, v_cur, sk_cur, dk_cur, dv_cur), axis_name, perm)
        else:
            dk_cur, dv_cur = _permute((dk_cur, dv_cur), axis_name, perm)
    return (dq.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _ring_attention(q, k, v, sid_q, sid_kv, seed, axis_name, causal, scale,
                    dropout_rate, block_q, block_k):
    out, _ = _ring_fwd_vjp(q, k, v, sid_q, sid_kv, seed, axis_name, causal,
                           scale, dropout_rate, block_q, block_k)
    return out


def _ring_fwd_vjp(q, k, v, sid_q, sid_kv, seed, axis_name, causal, scale,
                  dropout_rate, block_q, block_k):
    out, lse = _ring_fwd_impl(q, k, v, sid_q, sid_kv, seed, axis_name,
                              causal, scale, dropout_rate, block_q, block_k)
    return out, (q, k, v, out, lse, sid_q, sid_kv, seed)


def _ring_bwd_vjp(axis_name, causal, scale, dropout_rate, block_q, block_k,
                  res, do):
    dq, dk, dv = _ring_bwd_impl(res, do, axis_name, causal, scale,
                                dropout_rate, block_q, block_k)
    return dq, dk, dv, None, None, None


_ring_attention.defvjp(_ring_fwd_vjp, _ring_bwd_vjp)


def ring_self_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                        causal: bool = False, scale: Optional[float] = None,
                        segment_ids_q=None, segment_ids_kv=None,
                        dropout_rate: float = 0.0, dropout_seed=None,
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None):
    """Exact attention with sequence sharded over ``axis_name``.

    q, k, v: [b, h, s_local, d] — the local sequence chunk (global
    sequence = cp * s_local, chunks in rank order). Runs inside
    shard_map; every block goes through the Pallas flash kernel. Returns
    the local chunk of the attention output.

    ``segment_ids_*``: [b, s_local] packed-varlen masking (ids travel
    around the ring with their kv chunks). ``dropout_rate``/
    ``dropout_seed``: in-kernel attention dropout; pass a fresh int32
    seed per step (masks are independent per ring step and device, and
    regenerated — never stored — in the backward).
    """
    if dropout_rate >= 1.0 or dropout_rate < 0.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if segment_ids_kv is None and segment_ids_q is not None:
        # default kv ids = q ids HERE, before the ring: the kv ids must
        # TRAVEL with their chunks (a per-kernel-call default would mask
        # every visiting chunk with the stationary local q ids)
        segment_ids_kv = segment_ids_q
    seed = (jnp.asarray(dropout_seed, jnp.int32).reshape(())
            if dropout_rate > 0.0 else jnp.zeros((), jnp.int32))
    return _ring_attention(q, k, v, segment_ids_q, segment_ids_kv, seed,
                           axis_name, causal, scale, float(dropout_rate),
                           block_q, block_k)


def ulysses_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                      causal: bool = False, scale: Optional[float] = None,
                      dropout_rate: float = 0.0, dropout_seed=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern):
    re-shard [b, h, s/cp, d] → [b, h/cp, s, d] with one all_to_all, run
    full-sequence flash attention on the local heads, shard back.

    Complements ring attention: better when heads ≥ cp and the full
    sequence fits one chip's memory; the all_to_all rides ICI. Dropout
    runs in-kernel on the full sequence; the cp rank is folded into the
    seed internally — the kernel hashes the LOCAL head index, so without
    the fold every rank's head shard would repeat the same masks.
    """
    cp = _axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % cp:
        raise ValueError(f"num heads {h} must be divisible by cp {cp}")

    def to_seq(t):   # [b, h, s/cp, d] -> [b, h/cp, s, d]
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def to_heads(t):  # inverse
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from apex_tpu.ops.flash_attention import flash_attention
    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    # the kernel hashes the LOCAL head index; fold the cp rank into the
    # seed so head shards don't repeat masks (same contract as tp in
    # models/gpt.py)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        dropout_seed = (jnp.asarray(dropout_seed, jnp.int32)
                        + jax.lax.axis_index(axis_name))
    out = flash_attention(qs, ks, vs, causal=causal, scale=scale,
                          dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)
    return to_heads(out)


# ---------------------------------------------------------------------------
# Zigzag ring attention: load-balanced causal context parallelism
# ---------------------------------------------------------------------------

def zigzag_split(x, cp: int, axis: int = 2):
    """Reorder a global sequence into the zigzag layout: the sequence is
    cut into ``2*cp`` chunks and device r gets chunks ``(r, 2cp-1-r)``
    concatenated. Returns the reordered GLOBAL array (shard it over the
    context axis afterwards). Inverse: :func:`zigzag_merge`.

    Why: under plain rank-ordered causal ring attention every ring step
    has at least one device with live work, so the lockstep ring takes
    ``cp`` full steps regardless of masking. The zigzag pairing makes
    every device's causal workload equal (~2 of 4 half-pairs per step),
    halving causal wall-clock.
    """
    s = x.shape[axis]
    if s % (2 * cp):
        raise ValueError(f"seq len {s} not divisible by 2*cp={2 * cp}")
    chunks = jnp.split(x, 2 * cp, axis=axis)
    out = []
    for r in range(cp):
        out += [chunks[r], chunks[2 * cp - 1 - r]]
    return jnp.concatenate(out, axis=axis)


def zigzag_merge(x, cp: int, axis: int = 2):
    """Inverse of :func:`zigzag_split`."""
    s = x.shape[axis]
    if s % (2 * cp):
        raise ValueError(f"seq len {s} not divisible by 2*cp={2 * cp}")
    chunks = jnp.split(x, 2 * cp, axis=axis)
    out = [None] * (2 * cp)
    for r in range(cp):
        out[r] = chunks[2 * r]
        out[2 * cp - 1 - r] = chunks[2 * r + 1]
    return jnp.concatenate(out, axis=axis)


def _zz_halves(t):
    if t is None:
        return None, None
    half = t.shape[2] // 2
    return t[:, :, :half], t[:, :, half:]


def _zz_sid_halves(t):
    if t is None:
        return None, None
    half = t.shape[1] // 2
    return t[:, :half], t[:, half:]


def _zz_fwd_impl(q, k, v, sid_q, sid_kv, seed, axis_name, scale,
                 dropout_rate, block_q, block_k):
    cp, rank, perm = _ring_layout(axis_name)
    b, h, s_local, d = q.shape
    half = s_local // 2
    scale_v = d ** -0.5 if scale is None else scale
    interp = _resolve_interpret(None)
    bq = min(block_q or 1024, half)
    bk = min(block_k or 1024, half)

    q0, q1 = _zz_halves(q)
    sq0, sq1 = _zz_sid_halves(sid_q)

    def chunk(q_h, sq_h, k_h, v_h, sk_h, src, pair, causal_c):
        return _flash_fwd_impl(
            q_h, k_h, v_h, sq_h, sk_h, None,
            _step_seed(seed, rank, src, pair), scale_v, causal_c,
            dropout_rate, bq, bk, interp)

    def init_state():
        return (jnp.zeros((b, h, half, d), jnp.float32),
                jnp.full((b, h, half), _NEG_INF, jnp.float32))

    st0, st1 = init_state(), init_state()
    k_cur, v_cur, skv_cur = k, v, sid_kv

    for t in range(cp):
        src = jnp.mod(rank - t, cp)
        k0, k1 = _zz_halves(k_cur)
        v0, v1 = _zz_halves(v_cur)
        sk0, sk1 = _zz_sid_halves(skv_cur)
        if t == 0:
            # src == rank: the two diagonal pairs are causal-within,
            # (q1, k0) is chunk (2cp-1-rank, rank) — always fully live
            st0 = _merge(*st0, *chunk(q0, sq0, k0, v0, sk0, src, 0, True))
            st1 = _merge(*st1, *chunk(q1, sq1, k0, v0, sk0, src, 1, False))
            st1 = _merge(*st1, *chunk(q1, sq1, k1, v1, sk1, src, 2, True))
        else:
            # pair (q0, k0): chunks (rank, src) — live iff src < rank
            def p00(st0=st0, k0=k0, v0=v0, sk0=sk0, src=src):
                return _merge(*st0, *chunk(q0, sq0, k0, v0, sk0, src, 0,
                                           False))

            st0 = jax.lax.cond(src < rank, p00, lambda: st0)
            # pair (q1, k0): q chunk 2cp-1-rank >= cp > src — always full
            st1 = _merge(*st1, *chunk(q1, sq1, k0, v0, sk0, src, 1, False))

            # pair (q1, k1): chunks (2cp-1-rank, 2cp-1-src) — live iff
            # src > rank  (pair (q0, k1) is never live: k chunk >= cp)
            def p11(st1=st1, k1=k1, v1=v1, sk1=sk1, src=src):
                return _merge(*st1, *chunk(q1, sq1, k1, v1, sk1, src, 2,
                                           False))

            st1 = jax.lax.cond(src > rank, p11, lambda: st1)
        if t < cp - 1:
            k_cur, v_cur, skv_cur = _permute((k_cur, v_cur, skv_cur),
                                             axis_name, perm)
    out = jnp.concatenate([st0[0], st1[0]], axis=2).astype(q.dtype)
    lse = jnp.concatenate([st0[1], st1[1]], axis=2)
    return out, lse


def _zz_bwd_impl(res, do, axis_name, scale, dropout_rate, block_q, block_k):
    q, k, v, out, lse, sid_q, sid_kv, seed = res
    cp, rank, perm = _ring_layout(axis_name)
    b, h, s_local, d = q.shape
    half = s_local // 2
    scale_v = d ** -0.5 if scale is None else scale
    interp = _resolve_interpret(None)
    bq = min(block_q or 1024, half)
    bk = min(block_k or 1024, half)

    q0, q1 = _zz_halves(q)
    do0, do1 = _zz_halves(do)
    out0, out1 = _zz_halves(out)
    lse0, lse1 = lse[:, :, :half], lse[:, :, half:]
    sq0, sq1 = _zz_sid_halves(sid_q)

    def pair_grads(q_h, do_h, out_h, lse_h, sq_h, k_h, v_h, sk_h, src,
                   pair, causal_c):
        res_t = (q_h, k_h, v_h, out_h, lse_h, sq_h, sk_h, None,
                 _step_seed(seed, rank, src, pair))
        return _flash_bwd_impl(
            res_t, do_h, scale=scale_v, causal=causal_c,
            dropout_rate=dropout_rate, block_q=bq, block_k=bk,
            interpret=interp)

    zeros_h = jnp.zeros((b, h, half, d), jnp.float32)
    dq0 = dq1 = zeros_h
    k_cur, v_cur, skv_cur = k, v, sid_kv
    dk_cur = jnp.zeros((b, h, s_local, d), jnp.float32)
    dv_cur = jnp.zeros((b, h, s_local, d), jnp.float32)

    for t in range(cp):
        src = jnp.mod(rank - t, cp)
        k0, k1 = _zz_halves(k_cur)
        v0, v1 = _zz_halves(v_cur)
        sk0, sk1 = _zz_sid_halves(skv_cur)
        dk0, dk1 = _zz_halves(dk_cur)
        dv0, dv1 = _zz_halves(dv_cur)

        if t == 0:
            g = pair_grads(q0, do0, out0, lse0, sq0, k0, v0, sk0, src, 0,
                           True)
            dq0, dk0, dv0 = (dq0 + g[0].astype(jnp.float32),
                             dk0 + g[1].astype(jnp.float32),
                             dv0 + g[2].astype(jnp.float32))
            g = pair_grads(q1, do1, out1, lse1, sq1, k0, v0, sk0, src, 1,
                           False)
            dq1, dk0, dv0 = (dq1 + g[0].astype(jnp.float32),
                             dk0 + g[1].astype(jnp.float32),
                             dv0 + g[2].astype(jnp.float32))
            g = pair_grads(q1, do1, out1, lse1, sq1, k1, v1, sk1, src, 2,
                           True)
            dq1, dk1, dv1 = (dq1 + g[0].astype(jnp.float32),
                             dk1 + g[1].astype(jnp.float32),
                             dv1 + g[2].astype(jnp.float32))
        else:
            def p00(dq0=dq0, dk0=dk0, dv0=dv0, k0=k0, v0=v0, sk0=sk0,
                    src=src):
                g = pair_grads(q0, do0, out0, lse0, sq0, k0, v0, sk0, src,
                               0, False)
                return (dq0 + g[0].astype(jnp.float32),
                        dk0 + g[1].astype(jnp.float32),
                        dv0 + g[2].astype(jnp.float32))

            dq0, dk0, dv0 = jax.lax.cond(src < rank, p00,
                                         lambda: (dq0, dk0, dv0))
            g = pair_grads(q1, do1, out1, lse1, sq1, k0, v0, sk0, src, 1,
                           False)
            dq1, dk0, dv0 = (dq1 + g[0].astype(jnp.float32),
                             dk0 + g[1].astype(jnp.float32),
                             dv0 + g[2].astype(jnp.float32))

            def p11(dq1=dq1, dk1=dk1, dv1=dv1, k1=k1, v1=v1, sk1=sk1,
                    src=src):
                g = pair_grads(q1, do1, out1, lse1, sq1, k1, v1, sk1, src,
                               2, False)
                return (dq1 + g[0].astype(jnp.float32),
                        dk1 + g[1].astype(jnp.float32),
                        dv1 + g[2].astype(jnp.float32))

            dq1, dk1, dv1 = jax.lax.cond(src > rank, p11,
                                         lambda: (dq1, dk1, dv1))

        dk_cur = jnp.concatenate([dk0, dk1], axis=2)
        dv_cur = jnp.concatenate([dv0, dv1], axis=2)
        # final hop: only the dk/dv accumulators still need to travel
        # home (k/v/sids would arrive unused — advisor r3)
        if t < cp - 1:
            k_cur, v_cur, skv_cur, dk_cur, dv_cur = _permute(
                (k_cur, v_cur, skv_cur, dk_cur, dv_cur), axis_name, perm)
        else:
            dk_cur, dv_cur = _permute((dk_cur, dv_cur), axis_name, perm)

    dq = jnp.concatenate([dq0, dq1], axis=2)
    return (dq.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _zz_attention(q, k, v, sid_q, sid_kv, seed, axis_name, scale,
                  dropout_rate, block_q, block_k):
    out, _ = _zz_fwd_vjp(q, k, v, sid_q, sid_kv, seed, axis_name, scale,
                         dropout_rate, block_q, block_k)
    return out


def _zz_fwd_vjp(q, k, v, sid_q, sid_kv, seed, axis_name, scale,
                dropout_rate, block_q, block_k):
    out, lse = _zz_fwd_impl(q, k, v, sid_q, sid_kv, seed, axis_name, scale,
                            dropout_rate, block_q, block_k)
    return out, (q, k, v, out, lse, sid_q, sid_kv, seed)


def _zz_bwd_vjp(axis_name, scale, dropout_rate, block_q, block_k, res, do):
    dq, dk, dv = _zz_bwd_impl(res, do, axis_name, scale, dropout_rate,
                              block_q, block_k)
    return dq, dk, dv, None, None, None


_zz_attention.defvjp(_zz_fwd_vjp, _zz_bwd_vjp)


def zigzag_ring_self_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                               scale: Optional[float] = None,
                               segment_ids_q=None, segment_ids_kv=None,
                               dropout_rate: float = 0.0, dropout_seed=None,
                               block_q: Optional[int] = None,
                               block_k: Optional[int] = None):
    """CAUSAL exact attention over zigzag-ordered context shards.

    q, k, v: [b, h, s_local, d] where the local sequence is the
    concatenation of global chunks ``(r, 2cp-1-r)`` (see
    :func:`zigzag_split`). Every device does ~half the block work of the
    full ring each step — the causal load balance the plain ring cannot
    achieve — and every half-pair runs through the Pallas flash kernel.
    Returns the local output in the same zigzag layout.

    ``segment_ids_*``: [b, s_local] in the SAME zigzag layout as q/k/v
    (apply :func:`zigzag_split` with ``axis=1``). Dropout as in
    :func:`ring_self_attention`.
    """
    if dropout_rate >= 1.0 or dropout_rate < 0.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if segment_ids_kv is None and segment_ids_q is not None:
        # see ring_self_attention: kv ids must travel with their chunks
        segment_ids_kv = segment_ids_q
    seed = (jnp.asarray(dropout_seed, jnp.int32).reshape(())
            if dropout_rate > 0.0 else jnp.zeros((), jnp.int32))
    return _zz_attention(q, k, v, segment_ids_q, segment_ids_kv, seed,
                         axis_name, scale, float(dropout_rate), block_q,
                         block_k)
