"""Ring attention: context-parallel exact attention for long sequences.

This is a first-class NEW capability (SURVEY §5 flags long-context as
absent from the reference — no ring attention, context parallel, or
Ulysses; its levers stop at 2048-token fused softmax and ≤512-token
FMHA). TPU design per the ring-attention pattern: the sequence is sharded
over the ``context`` mesh axis; each device holds local Q/K/V chunks,
K/V rotate around the ring via ``ppermute`` (ICI neighbor transfers),
and each device folds every visiting block into its local queries'
online-softmax state — exact attention over the full sequence with
O(seq/cp) memory per chip and compute overlapped with the ring transfer
by XLA's async collectives.

Causality is handled by global-position masking, and ring steps whose
(q-chunk, kv-chunk) pair is strictly in the future are *skipped* under
``lax.cond`` — a causal cp run does ~half the flops of the full ring
(VERDICT r1 weak #10).

The backward is a ``custom_vjp`` that runs a SECOND ring pass: dk/dv
accumulators travel around the ring with their kv chunks while each
device recomputes its blocks from the saved (q, k, v, out, lse) — the
autodiff tape holds only O(s_local) residuals, so backward memory does
not scale with cp (r1 kept every ppermuted K/V in the tape).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state as ps

_NEG_INF = -1e30


def _block_attn(q32, k32, v32, scale, mask):
    """One (q-block, kv-block) pair: returns (m, l, acc) partials."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                  # [b,h,q]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v32)
    return m, l, acc


def _step_mask(rank, src, s_local, causal):
    """Block mask for (q chunk ``rank``, kv chunk ``src``); None = full."""
    if not causal:
        return None
    q_pos = rank * s_local + jnp.arange(s_local)
    k_pos = src * s_local + jnp.arange(s_local)
    return (k_pos[None, :] <= q_pos[:, None])[None, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_self_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                        causal: bool = False, scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis_name``.

    q, k, v: [b, h, s_local, d] — the local sequence chunk (global
    sequence = cp * s_local, chunks in rank order). Runs inside shard_map.
    Returns the local chunk of the attention output.
    """
    out, _ = _ring_fwd(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd(q, k, v, axis_name, causal, scale):
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale_v = d ** -0.5 if scale is None else scale
    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def body(t, carry):
        k_cur, v_cur, m, l, acc = carry
        src = jnp.mod(rank - t, cp)

        def compute(m=m, l=l, acc=acc, k_cur=k_cur, v_cur=v_cur, src=src):
            mask = _step_mask(rank, src, s_local, causal)
            bm, bl, bacc = _block_attn(
                q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
                scale_v, jnp.ones((1, 1, s_local, s_local), jnp.bool_)
                if mask is None else mask)
            m_new = jnp.maximum(m, bm)
            # guard: exp(-inf - -inf) on never-touched rows
            a_old = jnp.where(m > _NEG_INF / 2, jnp.exp(m - m_new), 0.0)
            a_blk = jnp.where(bm > _NEG_INF / 2, jnp.exp(bm - m_new), 0.0)
            l_new = a_old * l + a_blk * bl
            acc_new = a_old[..., None] * acc + a_blk[..., None] * bacc
            return m_new, l_new, acc_new

        if causal:
            # src > rank ⇒ every key is in the future: skip the matmuls
            m, l, acc = jax.lax.cond(
                src > rank, lambda *a: (m, l, acc), compute)
        else:
            m, l, acc = compute()
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc)

    init = (k, v,
            jnp.full((b, h, s_local), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, s_local), jnp.float32),
            jnp.zeros((b, h, s_local, d), jnp.float32))
    _, _, m, l, acc = jax.lax.fori_loop(0, cp, body, init)
    safe_l = jnp.where(l > 0, l, 1.0)
    out = (acc / safe_l[..., None]).astype(q.dtype)
    lse = m + jnp.log(safe_l)                               # [b,h,s_local]
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, res, do):
    q, k, v, out, lse = res
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale_v = d ** -0.5 if scale is None else scale
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # [b,h,s_local]

    def body(t, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = jnp.mod(rank - t, cp)

        def compute(k_cur=k_cur, v_cur=v_cur, dk_cur=dk_cur, dv_cur=dv_cur,
                    dq=dq, src=src):
            mask = _step_mask(rank, src, s_local, causal)
            k32 = k_cur.astype(jnp.float32)
            v32 = v_cur.astype(jnp.float32)
            s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale_v
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp(s - lse[..., None])                   # exact softmax
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
            dv_new = dv_cur + jnp.einsum("bhqk,bhqd->bhkd", p, do32)
            dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v32)
            ds = p * (dp - delta[..., None]) * scale_v
            dq_new = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k32)
            dk_new = dk_cur + jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
            return dk_new, dv_new, dq_new

        if causal:
            dk_cur, dv_cur, dq = jax.lax.cond(
                src > rank, lambda *a: (dk_cur, dv_cur, dq), compute)
        else:
            dk_cur, dv_cur, dq = compute()
        # dk/dv accumulators travel with their kv chunk; after cp steps
        # every chunk (and its grads) is back home
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (k_nxt, v_nxt, dk_nxt, dv_nxt, dq)

    zeros_kd = jnp.zeros((b, h, s_local, d), jnp.float32)
    init = (k, v, zeros_kd, zeros_kd, zeros_kd)
    _, _, dk, dv, dq = jax.lax.fori_loop(0, cp, body, init)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_self_attention.defvjp(_ring_fwd, _ring_bwd)


def ulysses_attention(q, k, v, axis_name: str = ps.CONTEXT_AXIS,
                      causal: bool = False, scale: Optional[float] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern):
    re-shard [b, h, s/cp, d] → [b, h/cp, s, d] with one all_to_all, run
    full-sequence flash attention on the local heads, shard back.

    Complements ring attention: better when heads ≥ cp and the full
    sequence fits one chip's memory; the all_to_all rides ICI.
    """
    cp = jax.lax.axis_size(axis_name)
    b, h, s_local, d = q.shape
    if h % cp:
        raise ValueError(f"num heads {h} must be divisible by cp {cp}")

    def to_seq(t):   # [b, h, s/cp, d] -> [b, h/cp, s, d]
        return jax.lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def to_heads(t):  # inverse
        return jax.lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    from apex_tpu.ops.flash_attention import flash_attention
    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    out = flash_attention(qs, ks, vs, causal=causal, scale=scale)
    return to_heads(out)
