"""FusedScaleMaskSoftmax: policy wrapper over the fused softmax ops.

Reference: ``apex/transformer/functional/fused_softmax.py:21-174`` — a
module that routes attention scores to the causal
(``scaled_upper_triang_masked_softmax``) or padded-mask
(``scaled_masked_softmax``) CUDA kernel when eligible (fp16/bf16 input,
sk ≤ 2048, fusion enabled) and otherwise falls back to unfused
mask+softmax, with ``softmax_in_fp32`` and post-hoc scale handling.

TPU: the "kernel availability" gate disappears (the fused ops cover all
shapes); the class keeps the same decision surface so Megatron-style
configs port unchanged, and still honors ``scaled_masked_softmax_fusion=
False`` to force the naive path (useful for numerics debugging, like the
reference's fallback).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType


class FusedScaleMaskSoftmax:
    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = True,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active at the same time.")
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func or (lambda x, m: jnp.where(m, -10000.0, x))
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale

    def __call__(self, inputs, mask=None):
        """``inputs``: [b, np, sq, sk] attention scores."""
        scale = self.scale if self.scale is not None else 1.0
        if self.fusion:
            if self.attn_mask_type == AttnMaskType.causal:
                b, np_, sq, sk = inputs.shape
                out = scaled_upper_triang_masked_softmax(
                    inputs.reshape(-1, sq, sk), scale)
                return out.reshape(b, np_, sq, sk)
            return scaled_masked_softmax(inputs, mask, scale)
        # unfused fallback (fused_softmax.py:176-194)
        x = inputs
        if self.input_in_fp16 or self.input_in_bf16:
            if self.softmax_in_fp32:
                x = x.astype(jnp.float32)
        if scale != 1.0:
            x = x * scale
        if self.attn_mask_type == AttnMaskType.causal and mask is None:
            sq, sk = x.shape[-2], x.shape[-1]
            mask = jnp.arange(sk)[None, :] > jnp.arange(sq)[:, None] + (sk - sq)
        if mask is not None:
            x = self.mask_func(x, mask)
        probs = jax.nn.softmax(x, axis=-1)
        if (self.input_in_fp16 or self.input_in_bf16) and self.softmax_in_fp32:
            probs = probs.astype(jnp.float16 if self.input_in_fp16 else jnp.bfloat16)
        return probs

    @staticmethod
    def is_kernel_available(*_args, **_kw) -> bool:
        """Always True on TPU (no seqlen-2048 cap — the reference gates on
        kernel template limits, ``fused_softmax.py:154-174``)."""
        return True
