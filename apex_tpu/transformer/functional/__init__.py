"""apex_tpu.transformer.functional — fused softmax module layer.

Reference: ``apex/transformer/functional/__init__.py`` (FusedScaleMaskSoftmax).
"""

from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    FusedScaleMaskSoftmax,
)
