"""SPMD pipeline schedules.

The reference only ships the group topology (SURVEY §2.3: "no schedule
engine"); Megatron's schedules drive per-rank send/recv with 1F1B
bookkeeping. The TPU-native formulation: every stage runs the SAME scanned
program (SPMD), activations move with one ``ppermute`` per tick, microbatch
injection/collection are masked by stage index, and the backward schedule
falls out of ``jax.grad`` of the scan — XLA reverses the pipeline
automatically. Reverse-mode through the scan stashes one stage-input
residual per tick (GPipe's memory profile, linear in microbatch count);
``forward_backward_pipelining_1f1b`` below restores 1F1B's O(P·mb)
bound with explicit in-scan VJP (measured table: docs/perf.md).

``pipeline_apply(stage_fn, stage_params, x, n_microbatches)`` must run
inside ``shard_map`` over the ``pipeline`` mesh axis, with
``stage_params`` already per-stage (each rank holds its stage's weights)
and the stage activation shape uniform across stages (standard for
transformer blocks).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu._compat import axis_size as _axis_size
from apex_tpu.monitor import hooks as _mon
from apex_tpu.monitor import profile as _prof
from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.microbatches import resolve_num_microbatches
from apex_tpu.transformer.pipeline_parallel.backward_split import (
    dgrad_vjp, normalize_wgrad_stash, wgrad, with_remat_policy)
from apex_tpu.transformer.pipeline_parallel.p2p import (
    ring_shift, send_backward_recv_backward, send_forward_recv_forward)
from apex_tpu.utils.remat import resolve_remat_policy


def _scoped_tick(name: str, body: Callable) -> Callable:
    """Wrap a scan tick/flush body in a profile scope
    (``monitor.profile``): every equation the body traces is charged to
    ``name`` in the per-module attribution table. Metadata-only — the
    scan jaxpr is byte-identical with or without the tag."""
    def wrapped(carry, t):
        with _prof.scope(name):
            return body(carry, t)
    return wrapped


def _checkpointed(stage_fn: Callable, remat: bool, remat_policy):
    """``jax.checkpoint`` wrap for the differentiable schedules:
    ``remat=True`` recomputes in backward under the named/callable
    residual policy from ``apex_tpu.utils.remat`` (``None`` = full
    recompute, the historical behavior)."""
    if not remat:
        if remat_policy is not None:
            raise ValueError(
                "remat_policy is a jax.checkpoint residual policy and "
                "has no effect with remat=False; drop the policy or "
                "enable remat")
        return stage_fn
    policy = remat_policy if (remat_policy is None or callable(remat_policy)) \
        else resolve_remat_policy(remat_policy)
    return jax.checkpoint(stage_fn, policy=policy)


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   n_microbatches: int,
                   axis_name: str = ps.PIPELINE_AXIS,
                   remat: bool = True, remat_policy=None):
    """Run microbatched GPipe fill-drain over the pipeline axis.

    ``x``: [n_microbatches, mb, ...] input (consumed by stage 0).
    ``stage_fn(params, h) -> h`` is one stage; output shape == input shape.
    Returns [n_microbatches, mb, ...] final-stage outputs (valid on the
    last stage; replicate/psum externally if every stage needs them).
    ``n_microbatches`` may be an int or a ``NumMicroBatchesCalculator``.
    ``remat_policy``: residual policy name/callable for the ``remat``
    checkpoint (``apex_tpu.utils.remat``; e.g. ``"dots"`` saves matmul
    outputs instead of recomputing them in backward).
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    total_ticks = n_microbatches + n_stages - 1
    _mon.pipeline_schedule("fill_drain", n_stages, n_microbatches,
                           total_ticks)
    fn = _checkpointed(stage_fn, remat, remat_policy)

    h_shape = x.shape[1:]
    init_held = jnp.zeros(h_shape, x.dtype)
    init_out = jnp.zeros((n_microbatches,) + h_shape, x.dtype)

    # NB: no per-tick marks here — this scan is differentiated through
    # (fwd/bwd schedules take value_and_grad of it) and partial-eval
    # silently drops debug callbacks from differentiated regions, which
    # would make tick telemetry appear in inference and vanish in
    # training. The 1F1B schedules below build their backward manually
    # in a non-differentiated scan, so THEY carry the tick marks; this
    # schedule records its geometry/bubble estimate only.
    def tick(carry, t):
        held, outputs = carry
        inject_idx = jnp.clip(t, 0, n_microbatches - 1)
        inject = jax.lax.dynamic_index_in_dim(x, inject_idx, keepdims=False)
        use_inject = (rank == 0) & (t < n_microbatches)
        inp = jnp.where(use_inject, inject, held)
        out = fn(stage_params, inp)
        # collect on the last stage: tick t carries microbatch t-(n_stages-1)
        mb = t - (n_stages - 1)
        valid = (rank == n_stages - 1) & (mb >= 0)
        mb_c = jnp.clip(mb, 0, n_microbatches - 1)
        updated = jax.lax.dynamic_update_index_in_dim(outputs, out, mb_c, 0)
        outputs = jnp.where(valid, updated, outputs)
        # move activations one stage forward for the next tick
        held_next = send_forward_recv_forward(out, axis_name)
        return (held_next, outputs), None

    (_, outputs), _ = jax.lax.scan(_scoped_tick("pp_tick", tick),
                                   (init_held, init_out),
                                   jnp.arange(total_ticks))
    return outputs


def forward_backward_no_pipelining(loss_fn: Callable, params, batch,
                                   n_microbatches: int = 1):
    """Megatron's no-pipelining path: grad-accumulate over microbatches.

    ``loss_fn(params, microbatch) -> scalar``. Returns (mean loss, grads).
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)

    def scan_body(acc, mb):
        loss, g = jax.value_and_grad(loss_fn)(params, mb)
        return jax.tree.map(lambda a, b: a + b, acc, (loss, g)), None

    zero = (jnp.zeros(()), jax.tree.map(jnp.zeros_like, params))
    (loss_sum, grad_sum), _ = jax.lax.scan(scan_body, zero, batch)
    inv = 1.0 / n_microbatches
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, grad_sum)


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_head: Callable, stage_params, x,
        n_microbatches: int, axis_name: str = ps.PIPELINE_AXIS):
    """Fill-drain pipeline + loss, returning (loss, stage-param grads).

    ``loss_head(outputs) -> scalar`` applies on the last stage's collected
    outputs (masked to zero elsewhere, so a final ``psum`` of the loss and
    grads is exact). Runs inside shard_map over the pipeline axis.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)

    def full(params):
        outs = pipeline_apply(stage_fn, params, x, n_microbatches, axis_name)
        loss = loss_head(outs)
        return jnp.where(rank == n_stages - 1, loss, 0.0)

    loss, grads = jax.value_and_grad(full)(stage_params)
    return loss, grads



def _mb_slicer(inputs):
    """Per-microbatch slicer over [n_microbatches, ...]-leaved ``inputs``."""
    def slice_mb(m):
        return jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, m, keepdims=False),
            inputs)
    return slice_mb


def _probe_h(embed_fn, embed_params, slice_mb):
    probe = jax.eval_shape(lambda p: embed_fn(p, slice_mb(0)), embed_params)
    return probe.shape, probe.dtype


# debug-mode axis-usage probe (the embed_fn/loss_fn collective contract)
_AXIS_PROBE_ENV = "APEX_TPU_PIPELINE_AXIS_PROBE"


def _axis_probe_enabled(flag: Optional[bool]) -> bool:
    if flag is not None:
        return bool(flag)
    return os.environ.get(_AXIS_PROBE_ENV, "0") == "1"


def _probe_no_pipeline_collectives(tag: str, fn, args, axis_name: str):
    """Debug probe behind ``debug_axis_probe=True`` (or env
    ``APEX_TPU_PIPELINE_AXIS_PROBE=1``): abstractly trace ``fn`` (an
    eval_shape-cost trace — no compile, no execution) and fail fast if
    it carries collectives over the *pipeline* axis. The 1F1B tick
    cores run embed_fn/loss_fn under per-rank ``lax.cond`` branches, so
    a pipeline-axis collective inside them would be executed by only
    some pp ranks — a silent deadlock/corruption at runtime; this turns
    it into an immediate, named error at trace time. Group-local
    collectives (e.g. a VocabParallelEmbedding's tensor-axis psum) are
    fine and pass."""
    from apex_tpu.lint.jaxpr_checks import collective_axis_names
    jaxpr = jax.make_jaxpr(fn)(*args)
    used = collective_axis_names(jaxpr.jaxpr)
    if axis_name in used:
        raise ValueError(
            f"{tag} carries a collective over the pipeline axis "
            f"'{axis_name}' (axes seen: {sorted(used)}). The 1F1B "
            f"schedules run {tag} under lax.cond on a per-rank "
            "predicate, so only some pipeline ranks would execute the "
            "collective — a deadlock/corruption. Keep pipeline-axis "
            "reductions (loss/grad psum) OUTSIDE the schedule call; "
            "tensor-axis collectives inside embed/head are fine.")


def _head_seed(loss_fn, pred, head_params, out_b, in_b):
    """Loss + head grads + backward seed under ``lax.cond(pred)`` — ONLY
    the seeding rank pays for the head (its collectives are group-local
    over the tensor axis, so other pp rows skipping is sound). Shared by
    both 1F1B tick cores."""
    def head_branch(hp, h, inb):
        (loss, (dhp, dh)) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(hp, h, inb)
        return loss, dhp, dh.astype(h.dtype)

    def head_skip(hp, h, inb):
        return (jnp.zeros((), jnp.float32),
                jax.tree.map(jnp.zeros_like, hp),
                jnp.zeros_like(h))

    return jax.lax.cond(pred, head_branch, head_skip,
                        head_params, out_b, in_b)


def _embed_inject(embed_fn, pred, embed_params, in_mb, h_shape, h_dtype):
    """Injection embed under ``lax.cond(pred)``: only the rank that will
    actually consume the injection pays the embed compute (advisor r4 —
    previously every rank embedded every tick, nmb + 2(P-1) times per
    rank vs nmb total useful; measurable for large-vocab
    VocabParallelEmbedding). Sound for the same reason as the head/
    embed-pullback conds: embed collectives span the TENSOR axis within
    one pp row, and the predicate is uniform across that row, so pp
    rows that skip are not party to the collective."""
    def do(ep, mb):
        return embed_fn(ep, mb).astype(h_dtype)

    def skip(ep, mb):
        return jnp.zeros(h_shape, h_dtype)

    return jax.lax.cond(pred, do, skip, embed_params, in_mb)


def _embed_pullback(embed_fn, pred, embed_params, in_b, ct):
    """Embedding cotangent pullback under ``lax.cond(pred)`` (rank 0's
    input cotangent pulls back through ``embed_fn`` instead of falling
    off the pipeline edge). Shared by both 1F1B tick cores."""
    def embed_branch(ep, inb, c):
        _, pull = jax.vjp(lambda p: embed_fn(p, inb), ep)
        return pull(c)[0]

    def embed_skip(ep, inb, c):
        return jax.tree.map(jnp.zeros_like, ep)

    return jax.lax.cond(pred, embed_branch, embed_skip,
                        embed_params, in_b, ct)


def forward_backward_pipelining_1f1b(
        stage_fn: Callable, loss_mb: Callable, stage_params, x,
        n_microbatches: int, axis_name: str = ps.PIPELINE_AXIS,
        remat_policy=None):
    """1F1B pipeline: bounded activation memory, O(P·mb) not O(nmb·mb).

    The fill-drain schedule above differentiates *through* the scan, so
    reverse-mode stashes one stage-input residual per tick — peak
    activation memory grows linearly with ``n_microbatches`` (measured:
    `tests/test_transformer.py::test_pipeline_memory_discipline`). This
    schedule is the TPU-native restatement of Megatron 1F1B (the memory
    rationale behind ``apex/transformer/parallel_state.py:252-322``):
    forward and backward units run in the SAME scan, gradients accumulate
    in the carry, and the only cross-tick activation state is a circular
    stash of ``2P-1`` stage inputs per rank — constant in
    ``n_microbatches``.

    Tick ``i`` runs (SPMD, all ranks the same program):

    - forward unit ``m_f = i - rank`` (the fill-drain timeline): consume
      the held activation (or inject ``x[m_f]`` on rank 0), apply
      ``stage_fn``, stash the INPUT, ``ppermute`` the output forward.
    - backward unit ``m_b = i - 2(P-1) + rank`` (the time-reversed
      timeline, delayed so the last rank's backward of microbatch ``m``
      immediately follows its forward): pop the stashed input, replay
      ``stage_fn`` under ``jax.vjp`` (rematerialization — nothing but
      the input survives from the forward pass), seed the cotangent from
      ``loss_mb`` on the last rank or from the next stage's ``ppermute``
      otherwise, accumulate the parameter cotangent, send the input
      cotangent backward.

    The cotangent rank r emits at tick ``i`` is consumed by rank r-1 at
    tick ``i+1`` for the SAME microbatch (both sides compute
    ``m = i - 2(P-1) + r``), so one reverse ``ppermute`` per tick is the
    whole backward transport. Total ticks ``nmb + 2(P-1)`` vs fill-drain's
    ``2(nmb + P - 1)`` forward+backward ticks — same bubble fraction,
    same 2-forwards+1-backward compute per microbatch as remat fill-drain.

    ``loss_mb(out) -> scalar`` applies per microbatch on the last stage;
    the returned loss is the SUM over microbatches (divide inside
    ``loss_mb`` by ``n_microbatches`` for a mean). ``loss_mb`` runs
    under a last-rank-only ``lax.cond`` and therefore MUST NOT carry
    pipeline-axis collectives (tensor-axis ones are fine — see
    ``forward_backward_pipelining_1f1b_model`` for the full contract
    and the ``APEX_TPU_PIPELINE_AXIS_PROBE`` debug check). Returns
    ``(loss, grads)`` with the loss masked to the last rank — ``psum``
    both over the pipeline axis, exactly as with the fill-drain variant.

    This is the headless special case of
    ``forward_backward_pipelining_1f1b_model`` (identity injection from
    ``x``, no embed/head parameters) — one tick core serves both.
    """
    loss, grads = forward_backward_pipelining_1f1b_model(
        lambda _, x_mb: x_mb,                 # injection = x[m] directly
        stage_fn,
        lambda _, h, __: loss_mb(h),          # headless loss seed
        {"embed": {}, "stage": stage_params, "head": {}},
        x, n_microbatches, axis_name, remat_policy=remat_policy)
    return loss, grads["stage"]


def forward_backward_pipelining_1f1b_model(
        embed_fn: Callable, stage_fn: Callable, loss_fn: Callable,
        params, inputs, n_microbatches: int,
        axis_name: str = ps.PIPELINE_AXIS,
        debug_axis_probe: Optional[bool] = None,
        remat_policy=None):
    """1F1B for a FULL model: embed + stages + loss head, flat memory.

    **Contract — embed_fn/loss_fn must carry no pipeline-axis
    collectives.** Both run under ``lax.cond`` branches taken by a
    single pipeline rank (rank 0 for embed, the last rank for the loss
    head), so a collective over ``axis_name`` inside either would be
    entered by only part of the pipeline group: a deadlock on real
    meshes, silent corruption on others. Collectives over *other* axes
    (e.g. VocabParallelEmbedding's tensor-axis psum) are group-local to
    one pp row and are fine. Do pipeline-axis reductions (summing the
    returned loss/grads across ranks) OUTSIDE this call. Set
    ``debug_axis_probe=True`` (or env ``APEX_TPU_PIPELINE_AXIS_PROBE=1``)
    to verify the contract at trace time: an eval_shape-cost abstract
    trace of both functions raises a named error on violation.

    ``forward_backward_pipelining_1f1b`` above handles the stage stack
    only; a real model also needs gradients for the embedding (rank 0)
    and the loss head (last rank). This variant runs the same two-stream
    tick schedule with:

    - ``embed_fn(params['embed'], inputs_mb) -> h``: computes the
      injection for microbatch ``m``, under ``lax.cond`` so only rank 0
      pays for it (advisor r4 — see ``_embed_inject``; sound because
      embed collectives, e.g. VocabParallelEmbedding's tensor-axis
      psum, are group-local to one pp row and the predicate is uniform
      across that row; embed_fn must not carry pipeline-axis
      collectives, which nothing in the repo does).
    - ``loss_fn(params['head'], h_out, inputs_mb) -> scalar``: the loss
      head for one microbatch, run under ``lax.cond`` so ONLY the last
      pipeline rank pays for it (at tp>1 its collectives span the
      tensor axis within that pp row — group-local, so the other rows
      skipping the branch is sound). Its gradient seeds the backward.
    - embedding backward: rank 0's input cotangent, instead of being
      dropped off the pipeline edge, pulls back through ``embed_fn``
      (recomputed — ids index directly into ``inputs``, nothing extra
      is stashed).

    ``params``: dict with keys ``embed`` / ``stage`` / ``head``.
    ``inputs``: pytree with [n_microbatches, ...] leaves (e.g.
    ``(ids, labels)``) — sliced per unit for embed and loss.

    Returns ``(loss_sum, grads)`` where ``grads`` has the same dict
    structure; the loss and the embed/head grads live on their owning
    ranks (zero elsewhere) — ``psum`` them over the pipeline axis, as
    with ``PipelinedGPT.loss_and_grads``. ``loss_sum`` is the SUM of
    per-microbatch losses (divide inside ``loss_fn`` for a mean).
    Memory: the same 2P-1-slot activation stash as the plain 1F1B
    schedule — peak activations constant in ``n_microbatches``.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    stage_fn = with_remat_policy(stage_fn, remat_policy)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    is_last = rank == n_stages - 1
    is_first = rank == 0
    delay = 2 * (n_stages - 1)
    total_ticks = n_microbatches + delay
    _mon.pipeline_schedule("1f1b", n_stages, n_microbatches, total_ticks)
    stash_slots = max(1, 2 * n_stages - 1)

    slice_mb = _mb_slicer(inputs)

    h_shape, h_dtype = _probe_h(embed_fn, params["embed"], slice_mb)

    if _axis_probe_enabled(debug_axis_probe):
        _probe_no_pipeline_collectives(
            "embed_fn", embed_fn, (params["embed"], slice_mb(0)),
            axis_name)
        _probe_no_pipeline_collectives(
            "loss_fn", loss_fn,
            (params["head"], jnp.zeros(h_shape, h_dtype), slice_mb(0)),
            axis_name)

    init = (
        jnp.zeros(h_shape, h_dtype),                      # held_f
        jnp.zeros(h_shape, h_dtype),                      # held_b
        jnp.zeros((stash_slots,) + h_shape, h_dtype),     # input stash
        jax.tree.map(jnp.zeros_like, params),             # grad accumulator
        jnp.zeros((), jnp.float32),                       # loss sum
    )

    def tick(carry, i):
        held_f, held_b, stash, grads, loss_sum = carry
        _mon.traced_tick("pipeline/1f1b/tick", i)

        # -- forward unit ------------------------------------------------
        m_f = i - rank
        valid_f = (m_f >= 0) & (m_f < n_microbatches)
        m_fc = jnp.clip(m_f, 0, n_microbatches - 1)
        use_inject = valid_f & is_first
        inject = _embed_inject(embed_fn, use_inject, params["embed"],
                               slice_mb(m_fc), h_shape, h_dtype)
        inp = jnp.where(use_inject, inject, held_f)
        out = stage_fn(params["stage"], inp)
        slot = m_fc % stash_slots
        cur = jax.lax.dynamic_index_in_dim(stash, slot, keepdims=False)
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(valid_f, inp, cur), slot, 0)
        held_f = send_forward_recv_forward(out, axis_name)

        # -- backward unit ----------------------------------------------
        m_b = i - delay + rank
        valid_b = (m_b >= 0) & (m_b < n_microbatches)
        m_bc = jnp.clip(m_b, 0, n_microbatches - 1)
        in_b = slice_mb(m_bc)
        inp_b = jax.lax.dynamic_index_in_dim(
            stash, m_bc % stash_slots, keepdims=False)
        out_b, pull_stage = jax.vjp(stage_fn, params["stage"], inp_b)

        loss_val, dhead, seed = _head_seed(
            loss_fn, is_last & valid_b, params["head"], out_b, in_b)

        g_out = jnp.where(is_last, seed, held_b)
        dstage, dinp = pull_stage(g_out)

        dembed = _embed_pullback(
            embed_fn, is_first & valid_b, params["embed"], in_b,
            dinp.astype(h_dtype))

        grads = {
            "embed": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b & is_first, d, 0),
                grads["embed"], dembed),
            "stage": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0),
                grads["stage"], dstage),
            "head": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0),
                grads["head"], dhead),
        }
        loss_sum = loss_sum + loss_val    # zero off the last rank
        held_b = send_backward_recv_backward(dinp, axis_name)
        # measured slot occupancy: the combined-VJP tick executes one
        # forward and one full backward (dgrad AND wgrad) per tick, so
        # the b/w slots share valid_b — the baseline the zero-bubble
        # schedule's table is compared against
        _mon.traced_tick_marks("pipeline/1f1b", i, rank,
                               f=valid_f, b=valid_b, w=valid_b)

        return (held_f, held_b, stash, grads, loss_sum), None

    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        _scoped_tick("pp_tick", tick), init, jnp.arange(total_ticks))
    return loss_sum, grads


def forward_backward_pipelining_1f1b_interleaved_model(
        embed_fn: Callable, stage_fn: Callable, loss_fn: Callable,
        params, inputs, n_microbatches: int, n_chunks: int,
        axis_name: str = ps.PIPELINE_AXIS,
        debug_axis_probe: Optional[bool] = None,
        remat_policy=None):
    """Interleaved (vpp) 1F1B: Megatron's production schedule — virtual
    chunks AND flat activation memory — as one SPMD scan.

    This closes the gap the staged-grads interleaved path
    (``microbatch_group_size``) leaves open: that path bounds memory by
    paying one extra (P-1)-tick bubble per group, while this schedule
    keeps the single warmup/cooldown bubble and a stash that is constant
    in ``n_microbatches``. It is the schedule the reference's vpp rank
    state exists to serve (``apex/transformer/parallel_state.py:252-322``
    tracks virtual ranks precisely so Megatron's interleaved 1F1B can
    place chunk ``c`` of rank ``r`` at global stage ``g = c*P + r``).

    Timeline (D = V*P global stages; B(m) = (m//P)*V*P):

    - forward of (microbatch m, global stage g) at tick
      ``t_f = B(m) + (m%P) + g`` — the same enumeration as
      ``pipeline_apply_interleaved`` (unit ``u = t - rank``);
    - backward of (m, g) at tick ``t_b = B(m) + (m%P) + 2(D-1) - g`` —
      the exact time-reversal, so on the last global stage the backward
      runs in the same tick as the forward (1F1B's defining property)
      and each cotangent is consumed exactly one tick after it is
      produced by the next-lower global stage.

    Per-rank backward inversion: with ``w = t - 2(D-1) + rank``,
    ``l = w mod P``, ``z = (w - l)/P`` (= qV - c), ``q = ceil(z/V)``:
    chunk ``c_b = q*V - z`` decreasing within each group (chunk V-1
    first), microbatch ``m_b = q*P + l``. Both transports are one
    wrapped ring ``ppermute`` per tick: forward rank P-1 -> 0 feeds the
    next chunk; backward rank 0 -> P-1 feeds the previous chunk (the
    wrapped value landing on the last global stage is overridden by the
    loss-head seed, and rank 0's chunk-0 cotangent pulls back through
    ``embed_fn`` instead of riding the wrap).

    Stash: ``[V, 2P+1]`` slots per rank (slot ``m mod (2P+1)`` of chunk
    ``c``) — at the worst stage (g=0) at most 2P chunk-c forwards fit in
    the ``2(D-1)``-tick forward->backward span, so 2P+1 slots can never
    collide; peak activation memory is O(V·P·mb), CONSTANT in
    ``n_microbatches`` (asserted by
    ``test_pipeline_interleaved_1f1b_memory_flat``).

    Same contracts as ``forward_backward_pipelining_1f1b_model`` —
    including **embed_fn/loss_fn must carry no pipeline-axis
    collectives** (they run under single-rank ``lax.cond`` branches;
    tensor-axis collectives are fine; ``debug_axis_probe=True`` or env
    ``APEX_TPU_PIPELINE_AXIS_PROBE=1`` trace-checks this): ``params`` =
    {embed, stage, head} with ``stage`` leaves stacked [n_chunks, ...];
    returns ``(loss_sum, grads)`` with embed/head grads on their owning
    ranks — psum over the pipeline axis. Requires
    ``n_microbatches % P == 0`` (the Megatron interleaving constraint).
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    stage_fn = with_remat_policy(stage_fn, remat_policy)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    V = n_chunks
    P = n_stages
    D = V * P
    lead = {leaf.shape[0]
            for leaf in jax.tree_util.tree_leaves(params["stage"])}
    if lead != {V}:
        raise ValueError(
            f"params['stage'] leaves must be stacked [n_chunks={V}, ...]; "
            f"got leading dims {sorted(lead)}")
    if n_microbatches % n_stages != 0:
        raise ValueError(
            f"interleaved 1F1B needs n_microbatches ({n_microbatches}) "
            f"divisible by pipeline size ({n_stages})")
    is_last = rank == n_stages - 1
    is_first = rank == 0
    # last backward: microbatch nmb-1 at global stage 0
    total_ticks = ((n_microbatches - 1) // P) * D + (n_microbatches - 1) % P \
        + 2 * (D - 1) + 1
    _mon.pipeline_schedule("interleaved_1f1b", n_stages, n_microbatches,
                           total_ticks, useful_ticks=V * n_microbatches)
    stash_slots = 2 * P + 1

    slice_mb = _mb_slicer(inputs)

    def chunk_of(tree, c):
        return jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            tree)

    h_shape, h_dtype = _probe_h(embed_fn, params["embed"], slice_mb)

    if _axis_probe_enabled(debug_axis_probe):
        _probe_no_pipeline_collectives(
            "embed_fn", embed_fn, (params["embed"], slice_mb(0)),
            axis_name)
        _probe_no_pipeline_collectives(
            "loss_fn", loss_fn,
            (params["head"], jnp.zeros(h_shape, h_dtype), slice_mb(0)),
            axis_name)

    init = (
        jnp.zeros(h_shape, h_dtype),                          # held_f
        jnp.zeros(h_shape, h_dtype),                          # held_b
        jnp.zeros((V, stash_slots) + h_shape, h_dtype),       # input stash
        jax.tree.map(jnp.zeros_like, params),                 # grad acc
        jnp.zeros((), jnp.float32),                           # loss sum
    )

    def tick(carry, i):
        held_f, held_b, stash, grads, loss_sum = carry
        _mon.traced_tick("pipeline/interleaved_1f1b/tick", i)

        # -- forward unit (same enumeration as the fill-drain schedule) --
        u = i - rank
        valid_f = (u >= 0) & (u < V * n_microbatches)
        uc = jnp.clip(u, 0, V * n_microbatches - 1)
        grp, rem = uc // D, uc % D
        c_f = rem // P
        m_f = grp * P + rem % P
        pf = chunk_of(params["stage"], c_f)
        use_inject = valid_f & (c_f == 0) & is_first
        inject = _embed_inject(embed_fn, use_inject, params["embed"],
                               slice_mb(m_f), h_shape, h_dtype)
        inp = jnp.where(use_inject, inject, held_f)
        out = stage_fn(pf, inp)
        slot = m_f % stash_slots
        cur = jax.lax.dynamic_index_in_dim(
            jax.lax.dynamic_index_in_dim(stash, c_f, 0, keepdims=False),
            slot, 0, keepdims=False)
        new_slot = jnp.where(valid_f, inp, cur)
        stash = jax.lax.dynamic_update_slice(
            stash, new_slot[None, None], (c_f, slot) + (0,) * len(h_shape))
        held_f = ring_shift(out, axis_name, wrap=True)

        # -- backward unit (time-reversed enumeration) -------------------
        w = i - 2 * (D - 1) + rank
        l = w % P                                    # nonneg (floor mod)
        z = (w - l) // P                             # = q*V - c_b
        q = (z + V - 1) // V                         # ceil(z / V)
        c_b = q * V - z
        m_b = q * P + l
        valid_b = (q >= 0) & (m_b < n_microbatches)
        m_bc = jnp.clip(m_b, 0, n_microbatches - 1)
        c_bc = jnp.clip(c_b, 0, V - 1)
        in_b = slice_mb(m_bc)
        inp_b = jax.lax.dynamic_index_in_dim(
            jax.lax.dynamic_index_in_dim(stash, c_bc, 0, keepdims=False),
            m_bc % stash_slots, 0, keepdims=False)
        pb = chunk_of(params["stage"], c_bc)
        out_b, pull_stage = jax.vjp(stage_fn, pb, inp_b)

        seed_here = is_last & valid_b & (c_bc == V - 1)
        loss_val, dhead, seed = _head_seed(
            loss_fn, seed_here, params["head"], out_b, in_b)

        g_out = jnp.where(seed_here, seed, held_b)
        dchunk, dinp = pull_stage(g_out)

        dembed = _embed_pullback(
            embed_fn, is_first & valid_b & (c_bc == 0), params["embed"],
            in_b, dinp.astype(h_dtype))

        def scatter_chunk(acc, d):
            cur_c = jax.lax.dynamic_index_in_dim(acc, c_bc, 0,
                                                 keepdims=False)
            upd = cur_c + jnp.where(valid_b, d, 0)
            return jax.lax.dynamic_update_index_in_dim(acc, upd, c_bc, 0)

        grads = {
            "embed": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b & is_first, d, 0),
                grads["embed"], dembed),
            "stage": jax.tree.map(scatter_chunk, grads["stage"], dchunk),
            "head": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0),
                grads["head"], dhead),
        }
        loss_sum = loss_sum + loss_val        # zero off the seeding rank
        held_b = ring_shift(dinp, axis_name, reverse=True, wrap=True)
        _mon.traced_tick_marks("pipeline/interleaved_1f1b", i, rank,
                               f=valid_f, b=valid_b, w=valid_b)

        return (held_f, held_b, stash, grads, loss_sum), None

    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        _scoped_tick("pp_tick", tick), init, jnp.arange(total_ticks))
    return loss_sum, grads


def forward_backward_pipelining_1f1b_interleaved(
        stage_fn: Callable, loss_mb: Callable, chunk_params, x,
        n_microbatches: int, n_chunks: Optional[int] = None,
        axis_name: str = ps.PIPELINE_AXIS, remat_policy=None):
    """Headless interleaved 1F1B (stage stack only) — the vpp analog of
    ``forward_backward_pipelining_1f1b``. ``chunk_params`` leaves stacked
    [n_chunks, ...]; ``loss_mb(out) -> scalar`` per microbatch on the
    last rank's LAST chunk, run under a single-rank ``lax.cond`` — so it
    MUST NOT carry pipeline-axis collectives (the
    ``forward_backward_pipelining_1f1b_model`` contract; verify with
    ``APEX_TPU_PIPELINE_AXIS_PROBE=1``). Returns (loss_sum, chunk
    grads)."""
    if n_chunks is None:
        leaf = jax.tree_util.tree_leaves(chunk_params)[0]
        n_chunks = leaf.shape[0]
    loss, grads = forward_backward_pipelining_1f1b_interleaved_model(
        lambda _, x_mb: x_mb,
        stage_fn,
        lambda _, h, __: loss_mb(h),
        {"embed": {}, "stage": chunk_params, "head": {}},
        x, n_microbatches, n_chunks, axis_name,
        remat_policy=remat_policy)
    return loss, grads["stage"]


def forward_backward_pipelining_zb(
        stage_fn: Callable, loss_mb: Callable, stage_params, x,
        n_microbatches: int, axis_name: str = ps.PIPELINE_AXIS,
        wgrad_stash: Optional[int] = None, remat_policy=None):
    """Zero-bubble (ZB-H1-style) 1F1B: split backward, deferred wgrad.

    Headless special case of
    :func:`forward_backward_pipelining_zb_model` (identity injection
    from ``x``, no embed/head parameters), exactly as
    ``forward_backward_pipelining_1f1b`` is to its ``_model`` form.
    Same contract as 1F1B (``loss_mb`` per microbatch on the last rank,
    loss = SUM over microbatches, psum loss/grads externally); see the
    model variant for the wgrad-deferral semantics and the
    ``wgrad_stash`` knob. Gradients are bitwise the same computation as
    1F1B reordered — parity is pinned in ``tests/test_zero_bubble.py``.
    """
    loss, grads = forward_backward_pipelining_zb_model(
        lambda _, x_mb: x_mb,
        stage_fn,
        lambda _, h, __: loss_mb(h),
        {"embed": {}, "stage": stage_params, "head": {}},
        x, n_microbatches, axis_name,
        wgrad_stash=wgrad_stash, remat_policy=remat_policy)
    return loss, grads["stage"]


def forward_backward_pipelining_zb_model(
        embed_fn: Callable, stage_fn: Callable, loss_fn: Callable,
        params, inputs, n_microbatches: int,
        axis_name: str = ps.PIPELINE_AXIS,
        debug_axis_probe: Optional[bool] = None,
        wgrad_stash: Optional[int] = None, remat_policy=None):
    """Zero-bubble 1F1B for a FULL model: split backward (ZB-H1).

    Zero Bubble Pipeline Parallelism (Qi et al., 2023) factors each
    backward unit into **dgrad** (cotangent w.r.t. the stage input — on
    the pipeline's critical path, feeds the previous stage) and
    **wgrad** (cotangent w.r.t. the stage params — no inter-stage
    consumer, schedulable anywhere after its ``(activation, cotangent)``
    pair exists). This schedule keeps the 1F1B tick grid and ring
    dependency EXACTLY (dgrad runs at the 1F1B "B" tick; the reverse
    ``ppermute`` carries the same cotangents on the same ticks) but
    pulls the wgrad stream out of the tick-synchronous scan:

    - per tick: forward unit (identical to 1F1B) + dgrad-only backward
      (``backward_split.dgrad_vjp`` — the wgrad matmuls are not traced
      into the tick body at all), pushing ``(stage input, output
      cotangent)`` into the deferred-wgrad stash;
    - after the scan: a dense flush scan computes the deferred wgrads —
      every flush step is a real unit of work, no masking.

    Why this beats 1F1B here: the masked SPMD tick executes its full
    slot set on every tick, valid or not, so 1F1B's combined-VJP tick
    burns a full wgrad on each of the ``2(P-1)`` ring warmup/cooldown
    ticks. Splitting removes the wgrad slot from those bubble ticks:
    per-rank executed unit-slots drop from ``3·(nmb + 2(P-1))`` to
    ``2·(nmb + 2(P-1)) + nmb``, an idle-slot fraction of
    ``4(P-1)/(3·nmb + 4(P-1))`` vs 1F1B's
    ``2(P-1)/(nmb + 2(P-1))`` — strictly lower for P > 1 (measured per
    rank by the ``traced_tick_marks`` table, not just this formula;
    ``bench.py``'s ``pp_zero_bubble`` section records both).

    ``wgrad_stash`` (the memory knob, ``backward_split.
    normalize_wgrad_stash``): ``None`` = full deferral (stash holds all
    ``nmb`` pairs — peak stash memory ``2·nmb`` microbatch activations
    on top of the 1F1B input stash); ``0`` = eager flush (wgrad at its
    dgrad tick: exact 1F1B compute placement and memory, no stash, no
    flush scan); ``1 <= K < nmb`` = bounded (K pairs; the tick body
    flushes the oldest entry in-scan once full — masked in bubble
    ticks, so bounded mode trades the compute win back for memory).

    ``remat_policy`` wraps ``stage_fn`` in ``jax.checkpoint`` under the
    named policy (``apex_tpu.utils.remat``) so the per-unit pullbacks —
    including the deferred wgrad flush — save policy residuals instead
    of recomputing everything from the stashed input; the stash itself
    never double-saves what the policy would recompute (it holds only
    the ``(input, cotangent)`` pair either way).

    Everything else — the embed/loss contract (**no pipeline-axis
    collectives**, single-rank ``lax.cond`` branches,
    ``debug_axis_probe``/``APEX_TPU_PIPELINE_AXIS_PROBE=1``), the
    ``params`` dict {embed, stage, head}, the masked loss/grads return
    (psum over the pipeline axis outside) — is the
    ``forward_backward_pipelining_1f1b_model`` contract verbatim.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    stage_fn = with_remat_policy(stage_fn, remat_policy)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    is_last = rank == n_stages - 1
    is_first = rank == 0
    delay = 2 * (n_stages - 1)
    total_ticks = n_microbatches + delay
    K = normalize_wgrad_stash(wgrad_stash, n_microbatches)
    eager = K == 0
    in_tick_wgrad = 0 < K < n_microbatches
    # analytic bubble in executed unit-slots (docstring): every tick
    # carries f + b slots, a w slot only in eager/bounded mode, and the
    # flush contributes K fully-valid w slots
    w_tick_slots = total_ticks if (eager or in_tick_wgrad) else 0
    _mon.pipeline_schedule(
        "zb1", n_stages, n_microbatches, total_ticks,
        useful_slots=3 * n_microbatches,
        total_slots=2 * total_ticks + w_tick_slots + K)
    stash_slots = max(1, 2 * n_stages - 1)

    slice_mb = _mb_slicer(inputs)

    h_shape, h_dtype = _probe_h(embed_fn, params["embed"], slice_mb)

    if _axis_probe_enabled(debug_axis_probe):
        _probe_no_pipeline_collectives(
            "embed_fn", embed_fn, (params["embed"], slice_mb(0)),
            axis_name)
        _probe_no_pipeline_collectives(
            "loss_fn", loss_fn,
            (params["head"], jnp.zeros(h_shape, h_dtype), slice_mb(0)),
            axis_name)

    init = (
        jnp.zeros(h_shape, h_dtype),                      # held_f
        jnp.zeros(h_shape, h_dtype),                      # held_b
        jnp.zeros((stash_slots,) + h_shape, h_dtype),     # input stash
        # deferred-wgrad stash: K (activation, cotangent) pairs
        (jnp.zeros((K,) + h_shape, h_dtype),
         jnp.zeros((K,) + h_shape, h_dtype)) if K else None,
        jax.tree.map(jnp.zeros_like, params),             # grad accumulator
        jnp.zeros((), jnp.float32),                       # loss sum
    )

    def tick(carry, i):
        held_f, held_b, stash, wstash, grads, loss_sum = carry
        _mon.traced_tick("pipeline/zb1/tick", i)

        # -- forward unit (identical to 1F1B) ---------------------------
        m_f = i - rank
        valid_f = (m_f >= 0) & (m_f < n_microbatches)
        m_fc = jnp.clip(m_f, 0, n_microbatches - 1)
        use_inject = valid_f & is_first
        inject = _embed_inject(embed_fn, use_inject, params["embed"],
                               slice_mb(m_fc), h_shape, h_dtype)
        inp = jnp.where(use_inject, inject, held_f)
        out = stage_fn(params["stage"], inp)
        slot = m_fc % stash_slots
        cur = jax.lax.dynamic_index_in_dim(stash, slot, keepdims=False)
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(valid_f, inp, cur), slot, 0)
        held_f = send_forward_recv_forward(out, axis_name)

        # -- backward unit: dgrad ONLY on the critical path --------------
        m_b = i - delay + rank
        valid_b = (m_b >= 0) & (m_b < n_microbatches)
        m_bc = jnp.clip(m_b, 0, n_microbatches - 1)
        in_b = slice_mb(m_bc)
        inp_b = jax.lax.dynamic_index_in_dim(
            stash, m_bc % stash_slots, keepdims=False)
        out_b, pull_x = dgrad_vjp(stage_fn, params["stage"], inp_b)

        loss_val, dhead, seed = _head_seed(
            loss_fn, is_last & valid_b, params["head"], out_b, in_b)

        g_out = jnp.where(is_last, seed, held_b)
        dinp = pull_x(g_out)[0]

        dembed = _embed_pullback(
            embed_fn, is_first & valid_b, params["embed"], in_b,
            dinp.astype(h_dtype))

        # -- wgrad placement (the knob) ----------------------------------
        dstage = None
        w_valid = None
        if eager:
            # exact 1F1B placement: wgrad at its dgrad tick
            dstage, w_valid = wgrad(
                stage_fn, params["stage"], inp_b, g_out), valid_b
        if wstash is not None:
            # the incoming pair and the entry it would evict share slot
            # m_bc % K ((m_b - K) % K == m_b % K): ONE read serves both
            # the bounded-mode flush and the masked push fallback, and
            # it must happen before the update overwrites the slot
            w_slot = m_bc % K
            old_in = jax.lax.dynamic_index_in_dim(
                wstash[0], w_slot, keepdims=False)
            old_ct = jax.lax.dynamic_index_in_dim(
                wstash[1], w_slot, keepdims=False)
            if in_tick_wgrad:
                dstage = wgrad(stage_fn, params["stage"], old_in, old_ct)
                w_valid = valid_b & (m_b >= K)
            wstash = (
                jax.lax.dynamic_update_index_in_dim(
                    wstash[0], jnp.where(valid_b, inp_b, old_in),
                    w_slot, 0),
                jax.lax.dynamic_update_index_in_dim(
                    wstash[1], jnp.where(valid_b, g_out, old_ct),
                    w_slot, 0))

        grads = {
            "embed": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b & is_first, d, 0),
                grads["embed"], dembed),
            "stage": grads["stage"] if dstage is None else jax.tree.map(
                lambda a, d: a + jnp.where(w_valid, d, 0),
                grads["stage"], dstage),
            "head": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0),
                grads["head"], dhead),
        }
        loss_sum = loss_sum + loss_val    # zero off the last rank
        held_b = send_backward_recv_backward(dinp, axis_name)
        marks = {"f": valid_f, "b": valid_b}
        if w_valid is not None:
            marks["w"] = w_valid
        _mon.traced_tick_marks("pipeline/zb1", i, rank, **marks)

        return (held_f, held_b, stash, wstash, grads, loss_sum), None

    (_, _, _, wstash, grads, loss_sum), _ = jax.lax.scan(
        _scoped_tick("pp_tick", tick), init, jnp.arange(total_ticks))

    if K:
        # -- deferred-wgrad flush: the bubble ticks' wgrad work, run
        # densely — every step is a valid unit (microbatches
        # nmb-K .. nmb-1; every rank owns exactly nmb backward units,
        # so every stashed pair is real)
        def flush(stage_grads, f_idx):
            m = n_microbatches - K + f_idx
            w_slot = m % K
            w_in = jax.lax.dynamic_index_in_dim(
                wstash[0], w_slot, keepdims=False)
            w_ct = jax.lax.dynamic_index_in_dim(
                wstash[1], w_slot, keepdims=False)
            d = wgrad(stage_fn, params["stage"], w_in, w_ct)
            _mon.traced_tick_marks("pipeline/zb1", total_ticks + f_idx,
                                   rank, w=True)
            return jax.tree.map(jnp.add, stage_grads, d), None

        stage_grads, _ = jax.lax.scan(
            _scoped_tick("pp_wgrad_flush", flush), grads["stage"],
            jnp.arange(K))
        grads = dict(grads, stage=stage_grads)
    return loss_sum, grads


def forward_backward_pipelining_zb_interleaved(
        stage_fn: Callable, loss_mb: Callable, chunk_params, x,
        n_microbatches: int, n_chunks: Optional[int] = None,
        axis_name: str = ps.PIPELINE_AXIS,
        wgrad_stash: Optional[int] = None, remat_policy=None):
    """Headless interleaved zero-bubble (stage stack only) — the vpp
    analog of ``forward_backward_pipelining_zb``, same relationship as
    the 1F1B pair. ``chunk_params`` leaves stacked [n_chunks, ...];
    ``wgrad_stash`` supports only full deferral (``None``) and eager
    (``0``) on the interleaved variant."""
    if n_chunks is None:
        leaf = jax.tree_util.tree_leaves(chunk_params)[0]
        n_chunks = leaf.shape[0]
    loss, grads = forward_backward_pipelining_zb_interleaved_model(
        lambda _, x_mb: x_mb,
        stage_fn,
        lambda _, h, __: loss_mb(h),
        {"embed": {}, "stage": chunk_params, "head": {}},
        x, n_microbatches, n_chunks, axis_name,
        wgrad_stash=wgrad_stash, remat_policy=remat_policy)
    return loss, grads["stage"]


def forward_backward_pipelining_zb_interleaved_model(
        embed_fn: Callable, stage_fn: Callable, loss_fn: Callable,
        params, inputs, n_microbatches: int, n_chunks: int,
        axis_name: str = ps.PIPELINE_AXIS,
        debug_axis_probe: Optional[bool] = None,
        wgrad_stash: Optional[int] = None, remat_policy=None):
    """Interleaved (vpp) zero-bubble: the split-backward treatment of
    ``forward_backward_pipelining_1f1b_interleaved_model``.

    The tick grid, both ring transports, the backward enumeration
    (exact time-reversal, chunks descending within each group), the
    embed/head conds, and every contract — including **no pipeline-axis
    collectives in embed_fn/loss_fn** — are the interleaved 1F1B's
    unchanged; only the backward unit is dgrad-only
    (``backward_split.dgrad_vjp``) with the wgrad deferred. The stash
    holds one ``(activation, cotangent)`` pair per executed (chunk,
    microbatch) unit — ``[V, nmb]`` slots — and the post-scan flush
    runs all ``V·nmb`` wgrads densely, selecting chunk params per
    entry and scattering into the ``[V, ...]`` grad leaves exactly as
    the tick body does.

    ``wgrad_stash``: only ``None`` (full deferral) and ``0`` (eager =
    exact interleaved-1F1B placement) — the bounded middle exists only
    on the non-interleaved schedule (a bounded FIFO over the
    chunk-major backward order buys little once V > 1 and complicates
    the slot arithmetic; raise rather than silently reinterpret).
    Executed unit-slots per rank: ``2·T + V·nmb`` (T = total ticks) vs
    the interleaved 1F1B's ``3·T`` — the same strict idle-fraction
    reduction as the plain schedule.
    """
    if wgrad_stash not in (None, 0):
        raise ValueError(
            "the interleaved zero-bubble schedule supports only full "
            "deferral (wgrad_stash=None) or eager flush (0); got "
            f"{wgrad_stash!r}")
    n_microbatches = resolve_num_microbatches(n_microbatches)
    stage_fn = with_remat_policy(stage_fn, remat_policy)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    V = n_chunks
    P = n_stages
    D = V * P
    eager = wgrad_stash == 0
    lead = {leaf.shape[0]
            for leaf in jax.tree_util.tree_leaves(params["stage"])}
    if lead != {V}:
        raise ValueError(
            f"params['stage'] leaves must be stacked [n_chunks={V}, ...]; "
            f"got leading dims {sorted(lead)}")
    if n_microbatches % n_stages != 0:
        raise ValueError(
            f"interleaved zero-bubble needs n_microbatches "
            f"({n_microbatches}) divisible by pipeline size ({n_stages})")
    is_last = rank == n_stages - 1
    is_first = rank == 0
    total_ticks = ((n_microbatches - 1) // P) * D + (n_microbatches - 1) % P \
        + 2 * (D - 1) + 1
    n_units = V * n_microbatches
    _mon.pipeline_schedule(
        "interleaved_zb1", n_stages, n_microbatches, total_ticks,
        useful_slots=3 * n_units,
        total_slots=(3 if eager else 2) * total_ticks
        + (0 if eager else n_units))
    stash_slots = 2 * P + 1

    slice_mb = _mb_slicer(inputs)

    def chunk_of(tree, c):
        return jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            tree)

    h_shape, h_dtype = _probe_h(embed_fn, params["embed"], slice_mb)

    if _axis_probe_enabled(debug_axis_probe):
        _probe_no_pipeline_collectives(
            "embed_fn", embed_fn, (params["embed"], slice_mb(0)),
            axis_name)
        _probe_no_pipeline_collectives(
            "loss_fn", loss_fn,
            (params["head"], jnp.zeros(h_shape, h_dtype), slice_mb(0)),
            axis_name)

    init = (
        jnp.zeros(h_shape, h_dtype),                          # held_f
        jnp.zeros(h_shape, h_dtype),                          # held_b
        jnp.zeros((V, stash_slots) + h_shape, h_dtype),       # input stash
        # deferred-wgrad stash: one pair per (chunk, microbatch) unit
        None if eager else (
            jnp.zeros((V, n_microbatches) + h_shape, h_dtype),
            jnp.zeros((V, n_microbatches) + h_shape, h_dtype)),
        jax.tree.map(jnp.zeros_like, params),                 # grad acc
        jnp.zeros((), jnp.float32),                           # loss sum
    )

    def scatter_chunk(c, pred, acc, d):
        cur_c = jax.lax.dynamic_index_in_dim(acc, c, 0, keepdims=False)
        upd = cur_c + jnp.where(pred, d, 0)
        return jax.lax.dynamic_update_index_in_dim(acc, upd, c, 0)

    def tick(carry, i):
        held_f, held_b, stash, wstash, grads, loss_sum = carry
        _mon.traced_tick("pipeline/interleaved_zb1/tick", i)

        # -- forward unit (interleaved enumeration, unchanged) -----------
        u = i - rank
        valid_f = (u >= 0) & (u < n_units)
        uc = jnp.clip(u, 0, n_units - 1)
        grp, rem = uc // D, uc % D
        c_f = rem // P
        m_f = grp * P + rem % P
        pf = chunk_of(params["stage"], c_f)
        use_inject = valid_f & (c_f == 0) & is_first
        inject = _embed_inject(embed_fn, use_inject, params["embed"],
                               slice_mb(m_f), h_shape, h_dtype)
        inp = jnp.where(use_inject, inject, held_f)
        out = stage_fn(pf, inp)
        slot = m_f % stash_slots
        cur = jax.lax.dynamic_index_in_dim(
            jax.lax.dynamic_index_in_dim(stash, c_f, 0, keepdims=False),
            slot, 0, keepdims=False)
        new_slot = jnp.where(valid_f, inp, cur)
        stash = jax.lax.dynamic_update_slice(
            stash, new_slot[None, None], (c_f, slot) + (0,) * len(h_shape))
        held_f = ring_shift(out, axis_name, wrap=True)

        # -- backward unit: dgrad only (time-reversed enumeration) -------
        w = i - 2 * (D - 1) + rank
        l = w % P
        z = (w - l) // P
        q = (z + V - 1) // V
        c_b = q * V - z
        m_b = q * P + l
        valid_b = (q >= 0) & (m_b < n_microbatches)
        m_bc = jnp.clip(m_b, 0, n_microbatches - 1)
        c_bc = jnp.clip(c_b, 0, V - 1)
        in_b = slice_mb(m_bc)
        inp_b = jax.lax.dynamic_index_in_dim(
            jax.lax.dynamic_index_in_dim(stash, c_bc, 0, keepdims=False),
            m_bc % stash_slots, 0, keepdims=False)
        pb = chunk_of(params["stage"], c_bc)
        out_b, pull_x = dgrad_vjp(stage_fn, pb, inp_b)

        seed_here = is_last & valid_b & (c_bc == V - 1)
        loss_val, dhead, seed = _head_seed(
            loss_fn, seed_here, params["head"], out_b, in_b)

        g_out = jnp.where(seed_here, seed, held_b)
        dinp = pull_x(g_out)[0]

        dembed = _embed_pullback(
            embed_fn, is_first & valid_b & (c_bc == 0), params["embed"],
            in_b, dinp.astype(h_dtype))

        if eager:
            dchunk = wgrad(stage_fn, pb, inp_b, g_out)
            stage_grads = jax.tree.map(
                lambda a, d: scatter_chunk(c_bc, valid_b, a, d),
                grads["stage"], dchunk)
        else:
            stage_grads = grads["stage"]
            cur_in = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(
                    wstash[0], c_bc, 0, keepdims=False),
                m_bc, 0, keepdims=False)
            cur_ct = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(
                    wstash[1], c_bc, 0, keepdims=False),
                m_bc, 0, keepdims=False)
            idx = (c_bc, m_bc) + (0,) * len(h_shape)
            wstash = (
                jax.lax.dynamic_update_slice(
                    wstash[0], jnp.where(valid_b, inp_b, cur_in)[None, None],
                    idx),
                jax.lax.dynamic_update_slice(
                    wstash[1], jnp.where(valid_b, g_out, cur_ct)[None, None],
                    idx))

        grads = {
            "embed": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b & is_first, d, 0),
                grads["embed"], dembed),
            "stage": stage_grads,
            "head": jax.tree.map(
                lambda a, d: a + jnp.where(valid_b, d, 0),
                grads["head"], dhead),
        }
        loss_sum = loss_sum + loss_val
        held_b = ring_shift(dinp, axis_name, reverse=True, wrap=True)
        marks = {"f": valid_f, "b": valid_b}
        if eager:
            marks["w"] = valid_b
        _mon.traced_tick_marks("pipeline/interleaved_zb1", i, rank,
                               **marks)

        return (held_f, held_b, stash, wstash, grads, loss_sum), None

    (_, _, _, wstash, grads, loss_sum), _ = jax.lax.scan(
        _scoped_tick("pp_tick", tick), init, jnp.arange(total_ticks))

    if not eager:
        # dense flush over every (chunk, microbatch) unit — all valid
        def flush(stage_grads, f_idx):
            c = f_idx // n_microbatches
            m = f_idx % n_microbatches
            w_in = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(
                    wstash[0], c, 0, keepdims=False), m, 0, keepdims=False)
            w_ct = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(
                    wstash[1], c, 0, keepdims=False), m, 0, keepdims=False)
            d = wgrad(stage_fn, chunk_of(params["stage"], c), w_in, w_ct)
            _mon.traced_tick_marks("pipeline/interleaved_zb1",
                                   total_ticks + f_idx, rank, w=True)
            return jax.tree.map(
                lambda a, dd: scatter_chunk(c, True, a, dd),
                stage_grads, d), None

        stage_grads, _ = jax.lax.scan(
            _scoped_tick("pp_wgrad_flush", flush), grads["stage"],
            jnp.arange(n_units))
        grads = dict(grads, stage=stage_grads)
    return loss_sum, grads


def staged_group_scan(grad_of_group: Callable, params, xs,
                      n_microbatches: int, group_size: int, n_stages: int):
    """Shared staged-grads accumulator (the memory lever of
    ``microbatch_group_size`` — see docs/perf.md).

    Splits every leaf of ``xs`` ([n_microbatches, ...]) into
    ``n_microbatches // group_size`` groups and runs
    ``grad_of_group(xs_group) -> (grads, loss)`` over them in an outer
    NON-differentiated ``lax.scan``, accumulating both in the carry —
    peak activation residuals are O(group_size·mb) instead of
    O(n_microbatches·mb). Returns ``(loss_sum, grads_sum, n_groups)``
    with RAW SUMS over groups; the caller owns the normalization (the
    schedule-level API documents the sum, the model-level API divides
    by ``n_groups``).

    On the loss-scale asymmetry between the two public APIs (advisor
    r4): a SUM-over-microbatches ``loss_head`` is the one class for
    which grouping is exact (group sums add to the ungrouped total) —
    so the schedule-level API returns the raw sum and stays exact for
    that class, while ``PipelinedGPT.loss_and_grads`` divides by
    ``n_groups`` because ITS loss is a per-group mean. Normalizing
    inside the schedule would silently break the sum class instead;
    the asymmetry is deliberate and both docstrings state their rule.
    """
    if group_size % n_stages != 0 or n_microbatches % group_size != 0:
        raise ValueError(
            f"microbatch_group_size ({group_size}) must be a multiple of "
            f"the pipeline size ({n_stages}) dividing n_microbatches "
            f"({n_microbatches})")
    n_groups = n_microbatches // group_size
    xg = jax.tree.map(
        lambda a: a.reshape((n_groups, group_size) + a.shape[1:]), xs)

    def group(carry, xs_g):
        loss_sum, gacc = carry
        g, l = grad_of_group(xs_g)
        return (loss_sum + l, jax.tree.map(jnp.add, gacc, g)), None

    zero = (jnp.zeros(()), jax.tree.map(jnp.zeros_like, params))
    (loss, grads), _ = jax.lax.scan(group, zero, xg)
    return loss, grads, n_groups


def pipeline_apply_interleaved(stage_fn: Callable, chunk_params, x,
                               n_microbatches: int, n_chunks: int,
                               axis_name: str = ps.PIPELINE_AXIS,
                               remat: bool = True,
                               with_aux: bool = False,
                               remat_policy=None):
    """Interleaved (virtual-pipeline) schedule over the pipeline axis.

    Each rank holds ``n_chunks`` (= vpp) model chunks stacked on the
    leading axis of every leaf of ``chunk_params``; chunk ``c`` of rank
    ``r`` is *global* stage ``c*P + r`` (the Megatron interleaved
    assignment whose rank state the reference tracks,
    ``apex/transformer/parallel_state.py:252-322``).

    Schedule: unit (microbatch m, chunk c) runs on rank r at tick
    ``t = (m//P)*V*P + c*P + (m%P) + r``. Every activation is consumed
    exactly one tick after it is produced, so one held slot and one
    ring ``ppermute`` per tick suffice (same transport as the
    non-interleaved schedule) while each rank time-multiplexes its V
    chunks. Total ticks = ``V*nmb + P - 1`` — the (P-1)-tick bubble of
    GPipe's ``V*(nmb + P - 1)`` shrinks by the factor V that interleaving
    exists to deliver.

    Requires ``n_microbatches % P == 0`` (the Megatron constraint).
    ``x``: [n_microbatches, mb, ...]; returns [n_microbatches, mb, ...]
    final-stage outputs (valid on the last rank).

    ``with_aux``: ``stage_fn`` returns ``(h, aux_scalar)`` and the call
    returns ``(outputs, aux_sum)`` — aux (e.g. the MoE load-balancing
    loss) accumulated over exactly the REAL (mask-valid) units this rank
    executed; bubble ticks contribute nothing. Summing each rank's
    ``aux_sum`` over the pipeline axis gives the total over all stages
    and microbatches.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    V = n_chunks
    lead = {leaf.shape[0] for leaf in jax.tree_util.tree_leaves(chunk_params)}
    if lead != {V}:
        raise ValueError(
            f"chunk_params leaves must be stacked [n_chunks={V}, ...]; got "
            f"leading dims {sorted(lead)}")
    if n_microbatches % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({n_microbatches}) "
            f"divisible by pipeline size ({n_stages})")
    total_ticks = V * n_microbatches + n_stages - 1
    _mon.pipeline_schedule("interleaved", n_stages, n_microbatches,
                           total_ticks, useful_ticks=V * n_microbatches)
    fn = _checkpointed(stage_fn, remat, remat_policy)

    h_shape = x.shape[1:]
    init_held = jnp.zeros(h_shape, x.dtype)
    init_out = jnp.zeros((n_microbatches,) + h_shape, x.dtype)

    def tick(carry, t):
        held, outputs, aux_sum = carry
        u = t - rank                      # unit index in this rank's order
        valid = (u >= 0) & (u < V * n_microbatches)
        uc = jnp.clip(u, 0, V * n_microbatches - 1)
        group, rem = uc // (V * n_stages), uc % (V * n_stages)
        c = rem // n_stages               # chunk to apply this tick
        m = group * n_stages + rem % n_stages  # microbatch of this unit

        params_c = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            chunk_params)

        inject = jax.lax.dynamic_index_in_dim(x, m, keepdims=False)
        use_inject = valid & (c == 0) & (rank == 0)
        inp = jnp.where(use_inject, inject, held)
        if with_aux:
            out, aux = fn(params_c, inp)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        else:
            out = fn(params_c, inp)
        # collect completed microbatches on the last rank's last chunk
        done = valid & (c == V - 1) & (rank == n_stages - 1)
        updated = jax.lax.dynamic_update_index_in_dim(outputs, out, m, 0)
        outputs = jnp.where(done, updated, outputs)
        # cyclic: the last rank's chunk-c output wraps to rank 0, which
        # consumes it next tick as chunk c+1's input
        held_next = ring_shift(out, axis_name, wrap=True)
        return (held_next, outputs, aux_sum), None

    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (init_held, init_out, jnp.zeros((), jnp.float32)),
        jnp.arange(total_ticks))
    return (outputs, aux_sum) if with_aux else outputs


def forward_backward_pipelining_with_interleaving(
        stage_fn: Callable, loss_head: Callable, chunk_params, x,
        n_microbatches: int, n_chunks: Optional[int] = None,
        axis_name: str = ps.PIPELINE_AXIS,
        microbatch_group_size: Optional[int] = None):
    """Interleaved pipeline + loss, returning (loss, chunk-param grads).

    ``microbatch_group_size`` (staged grads): differentiating through the
    full schedule stashes one stage-input residual per tick, so peak
    activation memory grows with ``n_microbatches``. Setting a group size
    ``G`` (a multiple of the pipeline size that divides
    ``n_microbatches``) runs the schedule on G microbatches at a time in
    an outer non-differentiated scan, accumulating gradients in the
    carry — peak activation memory becomes O(G·mb) at the cost of one
    extra (P-1)-tick bubble per group. The returned loss is the SUM of
    per-group ``loss_head`` values: a ``loss_head`` that means over its
    microbatch axis needs an external ``/ (n_microbatches // G)``.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    if n_chunks is None:
        n_chunks = ps.get_virtual_pipeline_model_parallel_world_size() or 1
        if n_chunks == 1:
            leaf = jax.tree_util.tree_leaves(chunk_params)[0]
            n_chunks = leaf.shape[0]

    def full(params, xs, nmb):
        outs = pipeline_apply_interleaved(stage_fn, params, xs,
                                          nmb, n_chunks, axis_name)
        loss = loss_head(outs)
        return jnp.where(rank == n_stages - 1, loss, 0.0)

    if microbatch_group_size is None:
        return jax.value_and_grad(full)(chunk_params, x, n_microbatches)

    G = microbatch_group_size

    def grad_of_group(xs):
        loss, g = jax.value_and_grad(full)(chunk_params, xs, G)
        return g, loss

    loss, grads, _ = staged_group_scan(
        grad_of_group, chunk_params, x, n_microbatches, G, n_stages)
    return loss, grads


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size: int = 1):
    """Dispatch mirroring Megatron's ``get_forward_backward_func``
    (vpp state: ``apex/transformer/parallel_state.py:252-322``)."""
    if pipeline_model_parallel_size > 1:
        if (virtual_pipeline_model_parallel_size is not None
                and virtual_pipeline_model_parallel_size > 1):
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
