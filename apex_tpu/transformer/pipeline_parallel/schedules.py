"""SPMD pipeline schedules.

The reference only ships the group topology (SURVEY §2.3: "no schedule
engine"); Megatron's schedules drive per-rank send/recv with 1F1B
bookkeeping. The TPU-native formulation: every stage runs the SAME scanned
program (SPMD), activations move with one ``ppermute`` per tick, microbatch
injection/collection are masked by stage index, and the backward schedule
falls out of ``jax.grad`` of the scan — XLA reverses the pipeline
automatically, with ``jax.checkpoint`` on the stage function standing in
for 1F1B's memory discipline.

``pipeline_apply(stage_fn, stage_params, x, n_microbatches)`` must run
inside ``shard_map`` over the ``pipeline`` mesh axis, with
``stage_params`` already per-stage (each rank holds its stage's weights)
and the stage activation shape uniform across stages (standard for
transformer blocks).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state as ps
from apex_tpu.transformer.microbatches import resolve_num_microbatches
from apex_tpu.transformer.pipeline_parallel.p2p import (
    ring_shift, send_forward_recv_forward)


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   n_microbatches: int,
                   axis_name: str = ps.PIPELINE_AXIS,
                   remat: bool = True):
    """Run microbatched GPipe fill-drain over the pipeline axis.

    ``x``: [n_microbatches, mb, ...] input (consumed by stage 0).
    ``stage_fn(params, h) -> h`` is one stage; output shape == input shape.
    Returns [n_microbatches, mb, ...] final-stage outputs (valid on the
    last stage; replicate/psum externally if every stage needs them).
    ``n_microbatches`` may be an int or a ``NumMicroBatchesCalculator``.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    total_ticks = n_microbatches + n_stages - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    h_shape = x.shape[1:]
    init_held = jnp.zeros(h_shape, x.dtype)
    init_out = jnp.zeros((n_microbatches,) + h_shape, x.dtype)

    def tick(carry, t):
        held, outputs = carry
        inject_idx = jnp.clip(t, 0, n_microbatches - 1)
        inject = jax.lax.dynamic_index_in_dim(x, inject_idx, keepdims=False)
        use_inject = (rank == 0) & (t < n_microbatches)
        inp = jnp.where(use_inject, inject, held)
        out = fn(stage_params, inp)
        # collect on the last stage: tick t carries microbatch t-(n_stages-1)
        mb = t - (n_stages - 1)
        valid = (rank == n_stages - 1) & (mb >= 0)
        mb_c = jnp.clip(mb, 0, n_microbatches - 1)
        updated = jax.lax.dynamic_update_index_in_dim(outputs, out, mb_c, 0)
        outputs = jnp.where(valid, updated, outputs)
        # move activations one stage forward for the next tick
        held_next = send_forward_recv_forward(out, axis_name)
        return (held_next, outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (init_held, init_out),
                                   jnp.arange(total_ticks))
    return outputs


def forward_backward_no_pipelining(loss_fn: Callable, params, batch,
                                   n_microbatches: int = 1):
    """Megatron's no-pipelining path: grad-accumulate over microbatches.

    ``loss_fn(params, microbatch) -> scalar``. Returns (mean loss, grads).
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)

    def scan_body(acc, mb):
        loss, g = jax.value_and_grad(loss_fn)(params, mb)
        return jax.tree.map(lambda a, b: a + b, acc, (loss, g)), None

    zero = (jnp.zeros(()), jax.tree.map(jnp.zeros_like, params))
    (loss_sum, grad_sum), _ = jax.lax.scan(scan_body, zero, batch)
    inv = 1.0 / n_microbatches
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, grad_sum)


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_head: Callable, stage_params, x,
        n_microbatches: int, axis_name: str = ps.PIPELINE_AXIS):
    """Fill-drain pipeline + loss, returning (loss, stage-param grads).

    ``loss_head(outputs) -> scalar`` applies on the last stage's collected
    outputs (masked to zero elsewhere, so a final ``psum`` of the loss and
    grads is exact). Runs inside shard_map over the pipeline axis.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)

    def full(params):
        outs = pipeline_apply(stage_fn, params, x, n_microbatches, axis_name)
        loss = loss_head(outs)
        return jnp.where(rank == n_stages - 1, loss, 0.0)

    loss, grads = jax.value_and_grad(full)(stage_params)
    return loss, grads


def pipeline_apply_interleaved(stage_fn: Callable, chunk_params, x,
                               n_microbatches: int, n_chunks: int,
                               axis_name: str = ps.PIPELINE_AXIS,
                               remat: bool = True,
                               with_aux: bool = False):
    """Interleaved (virtual-pipeline) schedule over the pipeline axis.

    Each rank holds ``n_chunks`` (= vpp) model chunks stacked on the
    leading axis of every leaf of ``chunk_params``; chunk ``c`` of rank
    ``r`` is *global* stage ``c*P + r`` (the Megatron interleaved
    assignment whose rank state the reference tracks,
    ``apex/transformer/parallel_state.py:252-322``).

    Schedule: unit (microbatch m, chunk c) runs on rank r at tick
    ``t = (m//P)*V*P + c*P + (m%P) + r``. Every activation is consumed
    exactly one tick after it is produced, so one held slot and one
    ring ``ppermute`` per tick suffice (same transport as the
    non-interleaved schedule) while each rank time-multiplexes its V
    chunks. Total ticks = ``V*nmb + P - 1`` — the (P-1)-tick bubble of
    GPipe's ``V*(nmb + P - 1)`` shrinks by the factor V that interleaving
    exists to deliver.

    Requires ``n_microbatches % P == 0`` (the Megatron constraint).
    ``x``: [n_microbatches, mb, ...]; returns [n_microbatches, mb, ...]
    final-stage outputs (valid on the last rank).

    ``with_aux``: ``stage_fn`` returns ``(h, aux_scalar)`` and the call
    returns ``(outputs, aux_sum)`` — aux (e.g. the MoE load-balancing
    loss) accumulated over exactly the REAL (mask-valid) units this rank
    executed; bubble ticks contribute nothing. Summing each rank's
    ``aux_sum`` over the pipeline axis gives the total over all stages
    and microbatches.
    """
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    V = n_chunks
    lead = {leaf.shape[0] for leaf in jax.tree_util.tree_leaves(chunk_params)}
    if lead != {V}:
        raise ValueError(
            f"chunk_params leaves must be stacked [n_chunks={V}, ...]; got "
            f"leading dims {sorted(lead)}")
    if n_microbatches % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs n_microbatches ({n_microbatches}) "
            f"divisible by pipeline size ({n_stages})")
    total_ticks = V * n_microbatches + n_stages - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    h_shape = x.shape[1:]
    init_held = jnp.zeros(h_shape, x.dtype)
    init_out = jnp.zeros((n_microbatches,) + h_shape, x.dtype)

    def tick(carry, t):
        held, outputs, aux_sum = carry
        u = t - rank                      # unit index in this rank's order
        valid = (u >= 0) & (u < V * n_microbatches)
        uc = jnp.clip(u, 0, V * n_microbatches - 1)
        group, rem = uc // (V * n_stages), uc % (V * n_stages)
        c = rem // n_stages               # chunk to apply this tick
        m = group * n_stages + rem % n_stages  # microbatch of this unit

        params_c = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            chunk_params)

        inject = jax.lax.dynamic_index_in_dim(x, m, keepdims=False)
        use_inject = valid & (c == 0) & (rank == 0)
        inp = jnp.where(use_inject, inject, held)
        if with_aux:
            out, aux = fn(params_c, inp)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        else:
            out = fn(params_c, inp)
        # collect completed microbatches on the last rank's last chunk
        done = valid & (c == V - 1) & (rank == n_stages - 1)
        updated = jax.lax.dynamic_update_index_in_dim(outputs, out, m, 0)
        outputs = jnp.where(done, updated, outputs)
        # cyclic: the last rank's chunk-c output wraps to rank 0, which
        # consumes it next tick as chunk c+1's input
        held_next = ring_shift(out, axis_name, wrap=True)
        return (held_next, outputs, aux_sum), None

    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (init_held, init_out, jnp.zeros((), jnp.float32)),
        jnp.arange(total_ticks))
    return (outputs, aux_sum) if with_aux else outputs


def forward_backward_pipelining_with_interleaving(
        stage_fn: Callable, loss_head: Callable, chunk_params, x,
        n_microbatches: int, n_chunks: Optional[int] = None,
        axis_name: str = ps.PIPELINE_AXIS):
    """Interleaved pipeline + loss, returning (loss, chunk-param grads)."""
    n_microbatches = resolve_num_microbatches(n_microbatches)
    n_stages = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    if n_chunks is None:
        n_chunks = ps.get_virtual_pipeline_model_parallel_world_size() or 1
        if n_chunks == 1:
            leaf = jax.tree_util.tree_leaves(chunk_params)[0]
            n_chunks = leaf.shape[0]

    def full(params):
        outs = pipeline_apply_interleaved(stage_fn, params, x,
                                          n_microbatches, n_chunks,
                                          axis_name)
        loss = loss_head(outs)
        return jnp.where(rank == n_stages - 1, loss, 0.0)

    loss, grads = jax.value_and_grad(full)(chunk_params)
    return loss, grads


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size: int = 1):
    """Dispatch mirroring Megatron's ``get_forward_backward_func``
    (vpp state: ``apex/transformer/parallel_state.py:252-322``)."""
    if pipeline_model_parallel_size > 1:
        if (virtual_pipeline_model_parallel_size is not None
                and virtual_pipeline_model_parallel_size > 1):
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
